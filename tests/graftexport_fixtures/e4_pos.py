"""E4 planted violations: non-portable artifacts, both flavors.

``e4_callback``: a ``jax.pure_callback`` traced into the program — it
lowers to a custom call holding a pointer into THIS process's Python
heap; the blob cannot resolve it anywhere else (the production store
tolerates the serialize failure; the artifact discipline does not
tolerate the attempt).

``e4_platform``: a clean program whose manifest CLAIMS platform
"tpu" while the blob was compiled on CPU — the key would route the
artifact to replicas whose backend never produced it."""

import jax
import jax.numpy as jnp
import numpy as np

from tools.graftexport import ExportTarget


def _build_callback():
    def host_scale(x):
        return np.asarray(x) * 2.0

    def f(x):
        y = jax.pure_callback(
            host_scale,
            jax.ShapeDtypeStruct((32,), jnp.float32), x)
        return y + 1.0

    return f, (jax.ShapeDtypeStruct((32,), jnp.float32),), ()


def _build_platform():
    def f(x):
        return x * 3.0

    return f, (jax.ShapeDtypeStruct((32,), jnp.float32),), ()


TARGETS = [
    ExportTarget(name="e4_callback", build=_build_callback, kind="fn"),
    ExportTarget(name="e4_platform", build=_build_platform, kind="fn",
                 platform_claim="tpu"),
]
