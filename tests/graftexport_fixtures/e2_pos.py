"""E2 planted violation: a donation dropped by serialization.

The live trace donates ``state`` (arg 0) onto a same-shaped output —
XLA honors it, ``input_output_alias`` appears in the live optimized
module. But the SERIALIZED blob comes from a non-donating compile of
the same fn (``drop_donation_on_serialize``), modeling an export path
that rebuilt the program without its alias map. A replica loading
this artifact pays an input-sized copy per call that the compiling
replica does not."""

import jax
import jax.numpy as jnp

from tools.graftexport import ExportTarget


def _build():
    def f(state, x):
        return state + x, (x * x).sum()

    st = jax.ShapeDtypeStruct((128,), jnp.float32)
    xs = jax.ShapeDtypeStruct((128,), jnp.float32)
    return f, (st, xs), (0,)


TARGETS = [ExportTarget(name="e2_fixture", build=_build, kind="fn",
                        drop_donation_on_serialize=True)]
