"""Request-scoped tracing (ISSUE 14): the span ledger across the
serving stack.

Four layers:

- ledger units (serving/trace.py): deterministic sampling,
  always-keep-tail/failure retention, idempotent exactly-once close,
  discard-on-reject, buffered lock-free flush, jsonl record shape;
- scheduler integration: tracing OFF is the bitwise default (no span
  objects, no file, no new summary keys); tracing ON mints one span
  per ACCEPTED request and closes it on the path that settled its
  future — completed/failed/deadline/cancelled/evicted — with
  dispatch fan-in spans, phase marks, breaker-at-admit and
  feature-cache annotations, and session chains walkable via parent
  links (registry spans additionally stamped model/variant/canary);
- THE acceptance drill: seeded chaos (wedge, shed, deadline,
  raise) at pipeline_depth=2 closes exactly one span per accepted
  request, zero orphans, with outcome tags reconciling
  bucket-for-bucket against submitted == completed + failed +
  deadline_missed + cancelled;
- serve_trace read-back: phase attribution over the tail exemplars
  reproduces the metrics histogram's top-bucket membership, and a
  timeline walk reconstructs dispatch fan-in + session chain.
"""

import json
import os
import time

import numpy as np
import pytest

from raft_tpu.config import RAFTConfig
from raft_tpu.serving.registry import ModelRegistry
from raft_tpu.serving.scheduler import (PRIORITY_BATCH,
                                        PRIORITY_INTERACTIVE,
                                        DeadlineExceeded,
                                        MicroBatchScheduler,
                                        SchedulerClosed)
from raft_tpu.serving.session import VideoSession
from raft_tpu.serving.trace import (SPAN_CLASSES, TraceLedger,
                                    sample_fraction)
from raft_tpu.testing import faults
from tests.test_registry import _WarmFakeEngine
from tests.test_scheduler import _FakeEngine

Z = np.zeros((32, 32, 3), np.float32)


@pytest.fixture(autouse=True)
def _disarm_after():
    yield
    faults.disarm()


@pytest.fixture(scope="module")
def small_setup():
    import jax
    import jax.numpy as jnp

    from raft_tpu.models import RAFT

    cfg = RAFTConfig(small=True)
    img = jnp.zeros((1, 32, 32, 3))
    variables = RAFT(cfg).init(jax.random.PRNGKey(0), img, img,
                               iters=1)
    return cfg, variables


@pytest.fixture(scope="module")
def engine(small_setup):
    """One warm-start engine shared by the real-stack drills here
    (same two-bucket envelope as test_scheduler's)."""
    from raft_tpu.serving.engine import RAFTEngine
    from tests.test_scheduler import BUCKET_BATCH, SHAPES

    cfg, variables = small_setup
    return RAFTEngine(variables, cfg, iters=1,
                      envelope=[(BUCKET_BATCH, h, w)
                                for h, w in SHAPES],
                      precompile=True, warm_start=True)


def _spans(path):
    return [json.loads(line) for line in open(path)]


def _requests(path):
    return [r for r in _spans(path) if r["span"] == "request"]


class TestLedgerUnits:
    def test_exactly_once_close_and_counters(self, tmp_path):
        path = str(tmp_path / "spans.jsonl")
        led = TraceLedger(path)
        s = led.begin("request", bucket="32x32")
        assert led.open_count() == 1
        assert led.close(s, "completed", "completed") is True
        assert led.close(s, "failed", "failed") is False  # idempotent
        assert led.open_count() == 0
        assert led.snapshot()["closed"] == 1
        led.flush()
        recs = _spans(path)
        assert len(recs) == 1
        assert recs[0]["class"] == "completed"
        assert recs[0]["kind"] == "span"

    def test_discard_never_writes_never_orphans(self, tmp_path):
        led = TraceLedger(str(tmp_path / "s.jsonl"))
        s = led.begin("request")
        led.discard(s)
        assert led.open_count() == 0
        led.flush()
        assert not os.path.exists(led.path) \
            or not _spans(led.path)
        assert led.snapshot()["discarded"] == 1

    def test_sampling_is_deterministic_and_keeps_tail_and_failures(
            self, tmp_path):
        path = str(tmp_path / "s.jsonl")
        led = TraceLedger(path, sample_rate=0.0)  # drop everything...
        kept = []
        for i in range(20):
            s = led.begin("request", bucket="b")
            kept.append(led.close(s, "completed", "completed"))
        assert not any(kept)            # ...sampled out at rate 0
        t = led.begin("request", bucket="b")
        assert led.close(t, "completed", "completed", tail=True)
        f = led.begin("request", bucket="b")
        assert led.close(f, "RuntimeError", "failed")
        d = led.begin("request", bucket="b")
        assert led.close(d, "deadline_expired", "deadline_missed")
        led.flush()
        recs = _requests(path)
        assert {r["class"] for r in recs} == {"completed", "failed",
                                             "deadline_missed"}
        assert [r for r in recs if r["tail"]]
        # the sample hash is a pure function of the id
        assert sample_fraction("r-1") == sample_fraction("r-1")
        assert 0.0 <= sample_fraction("r-2") < 1.0

    def test_dispatch_span_kept_only_with_a_written_child(
            self, tmp_path):
        path = str(tmp_path / "s.jsonl")
        led = TraceLedger(path, sample_rate=0.0)
        r1 = led.begin("request", bucket="b")
        d = led.begin("dispatch", bucket="b", fan_in=1, capacity=1,
                      padding_waste=0.0, requests=[r1.trace_id])
        r1.linked = d
        led.close(r1, "completed", "completed")   # sampled out
        led.close(d, "ok")
        r2 = led.begin("request", bucket="b")
        d2 = led.begin("dispatch", bucket="b", fan_in=1, capacity=1,
                       padding_waste=0.0, requests=[r2.trace_id])
        r2.linked = d2
        led.close(r2, "completed", "completed", tail=True)  # kept
        led.close(d2, "ok")
        led.flush()
        disp = [r for r in _spans(path) if r["span"] == "dispatch"]
        assert [r["trace_id"] for r in disp] == [d2.trace_id]
        assert led.open_count() == 0

    def test_flush_is_buffered_and_resilient(self, tmp_path):
        path = str(tmp_path / "sub" / "s.jsonl")
        led = TraceLedger(path)
        led.close(led.begin("request", bucket="b"), "completed",
                  "completed")
        assert not os.path.exists(path)   # close never does I/O
        assert led.flush() == 1
        assert led.flush() == 0           # drained
        assert len(_spans(path)) == 1

    def test_bad_sample_rate_rejected(self):
        with pytest.raises(ValueError, match="sample_rate"):
            TraceLedger(None, sample_rate=1.5)

    def test_discard_restores_the_consumed_parent_link(self):
        """A rollout-raced registry submit mints (consuming the
        session's parent link), hits SchedulerClosed, discards, and
        re-routes to live — the re-routed mint must still chain."""
        led = TraceLedger(None)
        led.set_parent("r-0")
        s = led.begin("request")
        assert s.fields["parent"] == "r-0"
        led.discard(s)
        s2 = led.begin("request")
        assert s2.fields["parent"] == "r-0"

    def test_intake_stamp_and_parent_are_consumed_once(self):
        led = TraceLedger(None)
        led.stamp_intake(model="m", variant="v1", canary=False)
        led.set_parent("r-0")
        s1 = led.begin("request")
        assert s1.fields["model"] == "m" and s1.fields["parent"] == "r-0"
        s2 = led.begin("request")
        assert "model" not in s2.fields and "parent" not in s2.fields


class TestSchedulerTracing:
    def test_off_is_the_default_and_leaves_no_trace(self):
        sched = MicroBatchScheduler(_FakeEngine(), gather_window_s=0.0)
        assert sched.tracer is None
        fut = sched.submit(Z, Z)
        fut.result(timeout=30)
        assert not hasattr(fut, "trace_id")
        sched.close()

    def test_completed_spans_with_phases_and_fan_in(self, tmp_path):
        path = str(tmp_path / "spans.jsonl")
        tr = TraceLedger(path)
        sched = MicroBatchScheduler(_FakeEngine(), gather_window_s=0.05,
                                    tracer=tr, pipeline_depth=2)
        futs = [sched.submit(Z, Z) for _ in range(4)]
        for f in futs:
            f.result(timeout=30)
        sched.close()
        assert tr.open_count() == 0
        recs = _spans(path)
        reqs = [r for r in recs if r["span"] == "request"]
        disp = [r for r in recs if r["span"] == "dispatch"]
        assert len(reqs) == 4 and disp
        ids = {r["trace_id"] for r in reqs}
        assert ids == {getattr(f, "trace_id") for f in futs}
        for r in reqs:
            assert r["class"] == "completed"
            assert r["breaker_at_admit"] == "closed"
            assert set(r["phases"]) >= {"queue_ms", "assembly_ms",
                                        "device_ms", "fetch_ms"}
            assert r["dispatch"] in {d["trace_id"] for d in disp}
        # the fan-in record carries every request it coalesced
        covered = {rid for d in disp for rid in d["requests"]}
        assert covered == ids
        for d in disp:
            assert d["fan_in"] == len(d["requests"])
            assert 0.0 <= d["padding_waste"] < 1.0

    def test_outcome_classes_deadline_failed_cancelled_closed(
            self, tmp_path):
        path = str(tmp_path / "spans.jsonl")
        tr = TraceLedger(path)
        eng = _FakeEngine()
        eng.hang_shapes[(40, 40)] = 0.4     # keeps the queue busy
        eng.fail_shapes.add((48, 48))
        sched = MicroBatchScheduler(eng, gather_window_s=0.0,
                                    tracer=tr)
        blocker = sched.submit(np.zeros((40, 40, 3), np.float32),
                               np.zeros((40, 40, 3), np.float32))
        # queued behind the hang: one expires, one is cancelled
        doomed = sched.submit(Z, Z, deadline_s=0.01)
        cancelled = sched.submit(Z, Z)
        time.sleep(0.05)
        cancelled.cancel()
        failed = sched.submit(np.zeros((48, 48, 3), np.float32),
                              np.zeros((48, 48, 3), np.float32))
        blocker.result(timeout=30)
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=30)
        with pytest.raises(RuntimeError, match="device error"):
            failed.result(timeout=30)
        survivor = sched.submit(Z, Z)
        survivor.result(timeout=30)
        sched.close()
        assert tr.open_count() == 0
        by_id = {r["trace_id"]: r for r in _requests(path)}
        assert by_id[doomed.trace_id]["class"] == "deadline_missed"
        assert by_id[doomed.trace_id]["outcome"] == "deadline_expired"
        assert by_id[cancelled.trace_id]["class"] == "cancelled"
        assert by_id[failed.trace_id]["class"] == "failed"
        assert by_id[failed.trace_id]["outcome"] == "RuntimeError"
        assert by_id[survivor.trace_id]["class"] == "completed"
        for r in by_id.values():
            assert r["class"] in SPAN_CLASSES

    def test_no_drain_close_and_eviction_tag_spans(self, tmp_path):
        path = str(tmp_path / "spans.jsonl")
        tr = TraceLedger(path)
        eng = _FakeEngine()
        eng.hang_shapes[(40, 40)] = 0.6
        sched = MicroBatchScheduler(eng, gather_window_s=0.0,
                                    max_queue=2, tracer=tr)
        blocker = sched.submit(np.zeros((40, 40, 3), np.float32),
                               np.zeros((40, 40, 3), np.float32))
        time.sleep(0.1)                   # dispatcher takes the hang
        victim = sched.submit(Z, Z, priority=PRIORITY_BATCH)
        survivor = sched.submit(Z, Z, priority=PRIORITY_BATCH)
        # full queue: the interactive arrival evicts the NEWEST batch
        evictor = sched.submit(Z, Z, priority=PRIORITY_INTERACTIVE)
        assert survivor.done()            # shed-batch-first took it
        sched.close(drain=False)
        assert tr.open_count() == 0
        by_id = {r["trace_id"]: r for r in _requests(path)}
        assert by_id[survivor.trace_id]["outcome"] == "evicted"
        assert by_id[survivor.trace_id]["class"] == "failed"
        # victim + evictor were dropped by the no-drain close (or
        # served if the dispatcher got there first) — every accepted
        # span closed either way
        for fut in (blocker, victim, evictor):
            assert by_id[fut.trace_id]["class"] in SPAN_CLASSES

    def test_rejected_submits_mint_no_orphan(self, tmp_path):
        tr = TraceLedger(str(tmp_path / "s.jsonl"))
        eng = _FakeEngine()
        eng.hang_shapes[(40, 40)] = 0.5
        sched = MicroBatchScheduler(eng, gather_window_s=0.0,
                                    max_queue=1, tracer=tr)
        from raft_tpu.serving.scheduler import BackpressureError
        sched.submit(np.zeros((40, 40, 3), np.float32),
                     np.zeros((40, 40, 3), np.float32))
        time.sleep(0.1)                   # dispatcher takes the hang
        sched.submit(Z, Z)                # fills the one queue slot
        with pytest.raises(BackpressureError):
            sched.submit(Z, Z)            # shed — span discarded
        sched.close()
        snap = tr.snapshot()
        assert snap["discarded"] == 1
        assert snap["open"] == 0


class TestSessionAndRegistryTracing:
    def test_session_chain_is_walkable(self, tmp_path):
        path = str(tmp_path / "spans.jsonl")
        tr = TraceLedger(path)
        sched = MicroBatchScheduler(_WarmFakeEngine(),
                                    gather_window_s=0.0, tracer=tr)
        sess = VideoSession(sched)
        rng = np.random.RandomState(0)
        futs = [sess.submit_frame(
            rng.rand(32, 32, 3).astype(np.float32))
            for _ in range(4)]
        sess.drain()
        sched.close()
        pairs = [f for f in futs if f is not None]
        assert len(pairs) == 3
        by_id = {r["trace_id"]: r for r in _requests(path)}
        # frame N links frame N-1: the recurrence is one chain
        assert by_id[pairs[1].trace_id]["parent"] == pairs[0].trace_id
        assert by_id[pairs[2].trace_id]["parent"] == pairs[1].trace_id
        assert "parent" not in by_id[pairs[0].trace_id]

    def test_registry_stamps_model_variant_canary(self, tmp_path):
        path = str(tmp_path / "spans.jsonl")
        reg = ModelRegistry(trace_path=path, gather_window_s=0.0)
        reg.add_model("m", {}, RAFTConfig(), engine=_WarmFakeEngine())
        live_fut = reg.submit(Z, Z, model="m")
        live_fut.result(timeout=30)
        reg.deploy("m", {}, engine=_WarmFakeEngine(),
                   canary_fraction=1.0)
        can_fut = reg.submit(Z, Z, model="m")
        can_fut.result(timeout=30)
        reg.promote("m")
        reg.close()
        assert reg.tracer.open_count() == 0
        by_id = {r["trace_id"]: r for r in _requests(path)}
        live_span = by_id[live_fut.trace_id]
        can_span = by_id[can_fut.trace_id]
        assert live_span["model"] == can_span["model"] == "m"
        assert live_span["variant"] == "v1"
        assert live_span["canary"] is False
        assert can_span["variant"] == "v2"
        assert can_span["canary"] is True

    def test_cached_spans_annotate_prime_and_hit(self, tmp_path):
        pytest.importorskip("jax")
        import jax
        import jax.numpy as jnp

        from raft_tpu.models import RAFT
        from raft_tpu.serving.engine import RAFTEngine

        cfg = RAFTConfig(small=True)
        img = jnp.zeros((1, 32, 32, 3))
        variables = RAFT(cfg).init(jax.random.PRNGKey(0), img, img,
                                   iters=1)
        eng = RAFTEngine(variables, cfg, iters=1, envelope=[(1, 32, 32)],
                         precompile=False, warm_start=True,
                         feature_cache=True)
        path = str(tmp_path / "spans.jsonl")
        tr = TraceLedger(path)
        sched = MicroBatchScheduler(eng, gather_window_s=0.0,
                                    feature_cache=True, tracer=tr)
        sess = VideoSession(sched, feature_cache=True)
        rng = np.random.RandomState(0)
        futs = [sess.submit_frame(
            rng.rand(32, 32, 3).astype(np.float32))
            for _ in range(3)]
        for f in futs:
            if f is not None:
                f.result(timeout=120)
        sess.drain()
        sched.close()
        assert tr.open_count() == 0
        reqs = _requests(path)
        primes = [r for r in reqs if r.get("prime")]
        hits = [r for r in reqs if r.get("cache") == "hit"]
        assert len(primes) == 1 and primes[0]["cache"] == "prime"
        assert len(hits) == 2
        # pair spans chain through the prime — the warm recurrence is
        # one walkable chain, stream identity on every hop
        for r in reqs:
            assert r["bucket"].endswith("/cache")
            assert "stream" in r and "seq" in r
        chained = [r for r in reqs if r.get("parent")]
        assert len(chained) == 2


class TestChaosSpanAccountingIdentity:
    def test_chaos_drill_zero_orphans_and_identity(self, tmp_path,
                                                   small_setup):
        """THE acceptance drill: seeded randomized fault plans (wedge
        hangs, raises, deadline pressure) at pipeline_depth=2 over
        the real engine — spans.jsonl closes exactly ONE span per
        accepted request, zero orphans, and the outcome-tag classes
        reconcile bucket-for-bucket against the accounting identity's
        counters, round totals included."""
        cfg, variables = small_setup
        from raft_tpu.cli.serve_bench import run_chaos_drill
        from tests.test_scheduler import BUCKET_BATCH, SHAPES

        path = str(tmp_path / "spans.jsonl")
        summary = run_chaos_drill(
            variables, cfg, shapes=SHAPES, rounds=2, requests=8,
            submitters=2, bucket_batch=BUCKET_BATCH, iters=1,
            dispatch_timeout_s=0.4, hang_s=0.8, breaker_failures=1,
            breaker_backoff_s=0.15, breaker_backoff_max_s=0.6,
            recover_s=30.0, seed=11, pipeline_depth=2,
            deadline_s=20.0, trace_path=path)
        assert summary["violations"] == []
        assert summary["totals"]["wedged_dispatches"] >= 1
        ledger = summary["trace"]
        assert ledger["open"] == 0 and ledger["buffered"] == 0
        reqs = _requests(path)
        # exactly one closed span per accepted request, all rounds
        accounting = [p["tail_exemplars"]["accounting"]
                      for p in summary["per_round"]]
        submitted = sum(a["submitted"] for a in accounting)
        assert len(reqs) == submitted
        assert len({r["trace_id"] for r in reqs}) == len(reqs)
        # bucket-for-bucket reconciliation against the identity
        by_class = {c: 0 for c in SPAN_CLASSES}
        for r in reqs:
            by_class[r["class"]] += 1
        for cls in SPAN_CLASSES:
            assert by_class[cls] == sum(a[cls] for a in accounting), \
                f"span class {cls} diverged from its counter"
        # wedge collateral is attributed, not anonymous: every drill
        # future the verdicts failed has a span saying so (recovery
        # probes may add more — they are accepted requests too)
        wedged_spans = [r for r in reqs
                        if r["outcome"] == "DispatchWedged"]
        assert len(wedged_spans) >= sum(
            p["failed_wedged"] for p in summary["per_round"])
        # per-round blocks carry their OWN refs/accounting; the
        # whole-file attribution lives once at the summary level
        # (the shared ledger's file spans every round)
        for p in summary["per_round"]:
            assert "refs" in p["tail_exemplars"]
            assert "phase_attribution" not in p["tail_exemplars"]
        assert summary["tail_exemplars"]["phase_attribution"]["spans"] \
            > 0
        assert summary["tail_exemplars"]["top_bucket"]["count"] > 0

    def test_tracing_off_summary_is_unchanged(self, small_setup,
                                              engine):
        """Knob-off acceptance: an untraced drill's summary has NO
        tracing keys (the PR-13 line byte-for-byte) and builds no
        ledger or spans file."""
        cfg, variables = small_setup
        from raft_tpu.cli.serve_bench import run_drill
        from tests.test_scheduler import BUCKET_BATCH, SHAPES

        summary = run_drill(variables, cfg, shapes=SHAPES, requests=6,
                            submitters=2, bucket_batch=BUCKET_BATCH,
                            gather_window_s=0.01, engine=engine)
        assert "tail_exemplars" not in summary
        assert "trace" not in summary


class TestServeTraceReadback:
    def test_exemplars_reproduce_top_bucket_membership(
            self, tmp_path, small_setup, engine):
        """Acceptance: the metrics snapshot's tail_exemplars refs all
        resolve to RETAINED spans flagged tail, with total_ms in the
        top bucket's range — serve_trace's attribution runs over the
        same membership the histogram reports."""
        cfg, variables = small_setup
        from raft_tpu.cli.serve_bench import run_drill
        from raft_tpu.cli.serve_trace import (load_spans,
                                              phase_attribution,
                                              tail_spans,
                                              top_bucket_membership)
        from tests.test_scheduler import BUCKET_BATCH, SHAPES

        path = str(tmp_path / "spans.jsonl")
        summary = run_drill(variables, cfg, shapes=SHAPES,
                            requests=10, submitters=2,
                            bucket_batch=BUCKET_BATCH,
                            gather_window_s=0.01, engine=engine,
                            trace_path=path, trace_sample=0.0)
        blk = summary["tail_exemplars"]
        assert blk["refs"], "drill produced no tail exemplars"
        spans = load_spans(path)
        retained = {s["trace_id"]: s for s in spans
                    if s.get("span") == "request"}
        top_gt = list(blk["refs"])
        for ref in top_gt:
            # retained despite sample_rate=0.0 — always-keep-tail
            s = retained[ref["trace_id"]]
            assert s["tail"] is True
            # the ref's total is the histogram observation, the
            # span's its own close clock — same request, ms apart
            assert abs(s["total_ms"] - ref["total_ms"]) < 50.0
        membership = top_bucket_membership(spans)
        assert set(e["trace_id"] for e in top_gt) \
            <= set(membership["trace_ids"])
        attr = phase_attribution(spans)
        assert attr["spans"] == len(tail_spans(spans))
        shares = [p["share"] for p in attr["phases"].values()]
        assert abs(sum(shares) - 1.0) < 0.05
        assert blk["ledger"]["tail_kept"] >= len(top_gt)

    def test_timeline_and_report_cli(self, tmp_path, capsys):
        path = str(tmp_path / "spans.jsonl")
        tr = TraceLedger(path)
        sched = MicroBatchScheduler(_WarmFakeEngine(),
                                    gather_window_s=0.0, tracer=tr)
        sess = VideoSession(sched)
        rng = np.random.RandomState(0)
        futs = [sess.submit_frame(
            rng.rand(32, 32, 3).astype(np.float32))
            for _ in range(4)]
        sess.drain()
        sched.close()
        last = [f for f in futs if f is not None][-1]
        from raft_tpu.cli import serve_trace as st
        spans = st.load_spans(path)
        tl = st.timeline(spans, last.trace_id)
        assert tl["found"] and len(tl["chain"]) == 2
        assert tl["dispatch"]["fan_in"] >= 1
        st.main([path, "--trace", last.trace_id])
        out = capsys.readouterr().out
        assert "session chain" in out and last.trace_id in out
        st.main([path])
        out = capsys.readouterr().out
        assert "where did the p99 go" in out
        assert "queue_ms" in out
        with pytest.raises(SystemExit):
            st.main([str(tmp_path / "missing.jsonl")])

    def test_guardian_window_carries_exemplar_refs(self):
        from raft_tpu.serving.guardian import window_stats
        from tests.test_guardian import _blk

        base = _blk(completed=10, bucket=2)
        cur = _blk(completed=30, bucket=2)
        base["tail_exemplars"] = {"refs": [
            {"trace_id": "r-1", "bucket": "b", "total_ms": 5.0,
             "bucket_idx": 2}]}
        cur["tail_exemplars"] = {"refs": [
            {"trace_id": "r-1", "bucket": "b", "total_ms": 5.0,
             "bucket_idx": 2},
            {"trace_id": "r-9", "bucket": "b", "total_ms": 9.0,
             "bucket_idx": 2}]}
        w = window_stats(cur, base)
        # only exemplars NEW in the window: the decision's evidence
        # names the trace ids behind the p99 it judged
        assert [e["trace_id"] for e in w["exemplars"]] == ["r-9"]
        # untraced snapshots keep the historical window schema
        w2 = window_stats(_blk(completed=3), _blk())
        assert "exemplars" not in w2
