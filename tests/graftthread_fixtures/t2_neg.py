"""T2 negative: a declared settle-helper module — the raw calls live
inside ``settle_future`` and every other site routes through it."""

from concurrent.futures import InvalidStateError

GRAFTTHREAD = {"settle_helper": True}


def settle_future(fut, result_or_exc, raced=None):
    try:
        if isinstance(result_or_exc, BaseException):
            fut.set_exception(result_or_exc)
        else:
            fut.set_result(result_or_exc)
    except InvalidStateError:
        if raced is not None:
            raced()
        return False
    return True


def fail_all(requests, exc):
    return sum(settle_future(r.future, exc) for r in requests)
