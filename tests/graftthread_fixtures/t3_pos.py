"""T3 positive: the declared order and an inferred nested-``with``
acquisition disagree — the union graph has a cycle."""

import threading

LOCK_ORDER = (
    ("t3_pos.Board._alock", "t3_pos.Board._block"),
)


class Board:
    def __init__(self):
        self._alock = threading.Lock()
        self._block = threading.Lock()

    def snapshot(self):
        with self._alock:
            with self._block:      # matches the declaration
                return 1

    def inverted(self):
        with self._block:
            with self._alock:      # INVERSION: closes the cycle
                return 2
