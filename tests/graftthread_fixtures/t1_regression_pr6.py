"""The PR-6 compile-under-engine-lock bug, distilled pre-fix.

The original engine compiled bucket executables INSIDE the engine
lock: a minutes-long XLA compile for one cold bucket stalled every
live weight swap and every already-compiled dispatch queued behind the
lock. PR 6's review moved ``lower()/compile()`` outside (first insert
wins the duplicate-compile race); this fixture preserves the pre-fix
shape so the T1 rule is demonstrably red on it — the regression anchor
for the whole rule.
"""

import threading


class RAFTEngineBug:
    def __init__(self, fn):
        self._fn = fn
        self._lock = threading.RLock()
        self._compiled = {}

    def _get_executable(self, shape, args):
        with self._lock:
            exe = self._compiled.get(shape)
            if exe is None:
                # THE BUG: weight swaps and every compiled-bucket
                # dispatch now wait out this compile
                exe = self._fn.lower(*args).compile()
                self._compiled[shape] = exe
            return exe
