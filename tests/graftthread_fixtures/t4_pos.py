"""T4 positive: a declared listener fired while the lock is held."""

import threading

GRAFTTHREAD = {"callbacks": ("on_transition",)}


class Breaker:
    def __init__(self, listener):
        self._lock = threading.Lock()
        self.on_transition = listener
        self._state = "closed"

    def trip(self):
        with self._lock:
            self._state = "open"
            # arbitrary caller code re-entering locked state WITH the
            # lock: the deadlock the _set/_notify split exists to avoid
            self.on_transition("closed", "open")
