"""T6 negative: consequences first, futures last — the PR-7 wedge
ordering invariant."""

GRAFTTHREAD = {
    "verdicts": ("wedge_verdict", "quiet_verdict"),
    "consequences": ("drop_bucket", "record_failure"),
    "settles": ("fail_requests",),
}


class Scheduler:
    def wedge_verdict(self, key, batch, exc):
        self.engine.drop_bucket(key)
        self.breaker.record_failure(wedged=True)
        self.fail_requests(batch, exc)

    def quiet_verdict(self, key):
        # a verdict that settles nothing has nothing to order
        self.engine.drop_bucket(key)
