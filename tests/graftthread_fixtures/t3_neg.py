"""T3 negative: every nested acquisition follows the declared order."""

import threading

LOCK_ORDER = (
    ("t3_neg.Board._alock", "t3_neg.Board._block",
     "t3_neg.Board._clock"),
)


class Board:
    def __init__(self):
        self._alock = threading.Lock()
        self._block = threading.Lock()
        self._clock = threading.Lock()

    def snapshot(self):
        with self._alock:
            with self._block:
                return 1

    def deep(self):
        with self._alock:
            with self._clock:      # skipping a level is still ordered
                return 2
