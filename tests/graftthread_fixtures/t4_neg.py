"""T4 negative: the resilience.py discipline — record the transition
under the lock, fire the listener after releasing."""

import threading

GRAFTTHREAD = {"callbacks": ("on_transition",)}


class Breaker:
    def __init__(self, listener):
        self._lock = threading.Lock()
        self.on_transition = listener
        self._state = "closed"

    def trip(self):
        with self._lock:
            old, self._state = self._state, "open"
            fired = (old, "open") if old != "open" else None
        if fired is not None:
            self.on_transition(*fired)   # outside the lock: legal
