"""T5 positive: threads nobody can reap or account for."""

import threading


class Poller:
    """Not daemon-flagged, and the class has no stop path at all."""

    def arm(self, work):
        self._thread = threading.Thread(target=work)
        self._thread.start()


def run_detached(work):
    t = threading.Thread(target=work, daemon=True)
    t.start()      # the function returns without ever joining it
    return t
