"""T1 positive: blocking calls lexically inside a lock body."""

import threading
import time


class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self._compiled = {}

    def get_executable(self, fn, shape, fut, worker, mailbox):
        with self._lock:
            exe = fn.lower(shape).compile()   # XLA compile under lock
            self._compiled[shape] = exe
            time.sleep(0.1)                   # sleep under lock
            _ = fut.result()                  # Future wait under lock
            worker.join()                     # thread wait under lock
            _ = mailbox.get()                 # queue read under lock
        return exe
