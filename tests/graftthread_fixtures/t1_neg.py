"""T1 negative: the same operations, held-lock discipline respected."""

import threading
import time


class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition()
        self._compiled = {}

    def get_executable(self, fn, shape):
        with self._lock:
            exe = self._compiled.get(shape)   # dict .get: not a queue
        if exe is None:
            # compile OUTSIDE the lock; first insert wins the race
            exe = fn.lower(shape).compile()
            with self._lock:
                exe = self._compiled.setdefault(shape, exe)
        return exe

    def wait_ready(self, timeout):
        with self._cv:
            # waiting on the HELD Condition releases it — the one
            # legal blocking wait under a lock
            self._cv.wait(timeout)

    def deferred_cleanup(self):
        with self._lock:
            def later():            # a closure runs LATER, lock-free
                time.sleep(0.1)
            self._compiled.clear()
            return later
