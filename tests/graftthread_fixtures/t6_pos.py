"""T6 positive: a verdict that settles futures BEFORE its
consequences land — a woken caller races the cleanup."""

GRAFTTHREAD = {
    "verdicts": ("wedge_verdict",),
    "consequences": ("drop_bucket", "record_failure"),
    "settles": ("fail_requests",),
}


class Scheduler:
    def wedge_verdict(self, key, batch, exc):
        # BUG: callers wake to DispatchWedged while the suspect
        # executable is still routable and the breaker still closed
        self.fail_requests(batch, exc)
        self.engine.drop_bucket(key)
        self.breaker.record_failure(wedged=True)
