"""T5 negative: joined on the stop path, or quarantine-accounted."""

import threading


class Watcher:
    def __init__(self, work):
        self._thread = threading.Thread(target=work, daemon=True)
        self._thread.start()

    def close(self):
        self._thread.join(timeout=5.0)


class Quarantiner:
    """The DispatchExecutor discipline: a wedged thread can't be
    killed or joined — it is abandoned, replaced, and ACCOUNTED."""

    def __init__(self):
        self.quarantined = []
        self._thread = None

    def spawn(self, work):
        self._thread = threading.Thread(target=work, daemon=True)
        self._thread.start()

    def stop(self):
        self.quarantined.append(self._thread)


def helper(work):
    t = threading.Thread(target=work, daemon=True)
    t.start()
    t.join(timeout=1.0)        # armed AND reaped in the same function
    return t
