"""T2 positive: raw future settles — the pre-PR-11 copy-paste idiom."""

from concurrent.futures import InvalidStateError


def fail_all(requests, exc):
    n = 0
    for r in requests:
        try:
            r.future.set_exception(exc)     # raw settle
            n += 1
        except InvalidStateError:
            pass
    return n


def finish(fut, value):
    fut.set_result(value)                   # raw settle, not even guarded
