"""Multi-model serving registry acceptance (ISSUE 9): versioned
engines behind ``ModelRegistry`` — deterministic canary routing,
promote/rollback with zero stranded futures, per-model accounting,
auto-rollback on a failed deploy (the ``registry.load`` fault site) —
plus the scheduler's priority classes: shed-batch-first backpressure
and weighted dequeue under a batch flood."""

import threading
import time

import numpy as np
import pytest

from tests.test_scheduler import _pad8

import jax
import jax.numpy as jnp

from raft_tpu.config import RAFTConfig
from raft_tpu.models import RAFT
from raft_tpu.serving.engine import RAFTEngine
from raft_tpu.serving.registry import (DeployError, ModelRegistry,
                                       RolloutInProgress, UnknownModel,
                                       canary_hash_fraction)
from raft_tpu.serving.scheduler import (PRIORITY_BATCH,
                                        PRIORITY_INTERACTIVE,
                                        BackpressureError,
                                        MicroBatchScheduler)
from raft_tpu.serving.session import VideoSession
from raft_tpu.testing import faults
from tests.test_scheduler import _FakeEngine

HW = (32, 32)
BUCKET_BATCH = 4


@pytest.fixture(scope="module")
def basic_setup():
    cfg = RAFTConfig()
    model = RAFT(cfg)
    img = jnp.zeros((1, *HW, 3))
    variables = model.init(jax.random.PRNGKey(0), img, img, iters=1)
    return cfg, variables


@pytest.fixture(scope="module")
def small_setup():
    cfg = RAFTConfig(small=True)
    model = RAFT(cfg)
    img = jnp.zeros((1, *HW, 3))
    variables = model.init(jax.random.PRNGKey(1), img, img, iters=1)
    return cfg, variables


@pytest.fixture(scope="module")
def basic_engine(basic_setup):
    """The accurate live tier: one warm-start bucket, shared across
    the module (compiles once)."""
    cfg, variables = basic_setup
    return RAFTEngine(variables, cfg, iters=1,
                      envelope=[(BUCKET_BATCH, *HW)], precompile=True,
                      warm_start=True)


@pytest.fixture(scope="module")
def small_engine(small_setup):
    """The fast canary tier (a DIFFERENT architecture than basic)."""
    cfg, variables = small_setup
    return RAFTEngine(variables, cfg, iters=1,
                      envelope=[(BUCKET_BATCH, *HW)], precompile=True,
                      warm_start=True)


@pytest.fixture(autouse=True)
def _disarm_after():
    yield
    faults.disarm()


def _pair(rng, h=HW[0], w=HW[1]):
    return (rng.rand(h, w, 3).astype(np.float32) * 255,
            rng.rand(h, w, 3).astype(np.float32) * 255)


Z = np.zeros((*HW, 3), np.float32)


class _WarmFakeEngine(_FakeEngine):
    """_FakeEngine with the warm-start surface (flow_low output) so
    session-recurrence drills run without XLA."""

    warm_start = True

    def infer_batch_async(self, i1, i2, flow_init=None,
                          return_low=False, low_device=False):
        inner = super().infer_batch_async(i1, i2)

        class _P:
            bucket = inner.bucket
            h2d_bytes = inner.h2d_bytes
            t_ready = None

            def fetch(p):
                flow = inner.fetch()
                b, h, w = flow.shape[:3]
                low = np.zeros((b, _pad8(h) // 8, _pad8(w) // 8, 2),
                               np.float32)
                p.t_ready = time.monotonic()
                return flow, low

        return _P()


# -- deterministic routing hash -------------------------------------------


class TestCanaryHash:
    def test_deterministic_and_near_uniform(self):
        vals = [canary_hash_fraction("m", i) for i in range(1000)]
        assert vals == [canary_hash_fraction("m", i) for i in range(1000)]
        assert all(0.0 <= v < 1.0 for v in vals)
        # near-uniform: a 25% fraction lands within a few percent
        frac = sum(v < 0.25 for v in vals) / len(vals)
        assert abs(frac - 0.25) < 0.04
        # the model name is part of the hash: two models split their
        # token spaces independently
        other = [canary_hash_fraction("other", i) for i in range(1000)]
        assert other != vals

    def test_sticky_token_pins_assignment(self):
        reg = ModelRegistry(gather_window_s=0.0)
        reg.add_model("m", {}, RAFTConfig(), engine=_FakeEngine())
        reg.deploy("m", {}, engine=_FakeEngine(), canary_fraction=0.3)
        want = reg.routes_to_canary("m", "user-42")
        assert all(reg.routes_to_canary("m", "user-42") == want
                   for _ in range(10))
        reg.close()


# -- registry lifecycle (duck-typed engines: fast, deterministic) ---------


class TestRegistryLifecycle:
    def test_unknown_model_and_single_model_default(self):
        reg = ModelRegistry(gather_window_s=0.0)
        reg.add_model("only", {}, RAFTConfig(), engine=_FakeEngine())
        # single registered model: model= may be omitted
        assert reg.submit(Z, Z).result(10).flow.shape == (*HW, 2)
        with pytest.raises(UnknownModel):
            reg.submit(Z, Z, model="nope")
        reg.add_model("second", {}, RAFTConfig(small=True),
                      engine=_FakeEngine())
        with pytest.raises(UnknownModel):
            reg.submit(Z, Z)   # ambiguous now
        with pytest.raises(ValueError):
            reg.add_model("only", {}, RAFTConfig(),
                          engine=_FakeEngine())  # deploy(), not re-add
        reg.close()

    def test_one_rollout_at_a_time(self):
        reg = ModelRegistry(gather_window_s=0.0)
        reg.add_model("m", {}, RAFTConfig(), engine=_FakeEngine())
        reg.deploy("m", {}, engine=_FakeEngine(), canary_fraction=0.5)
        with pytest.raises(RolloutInProgress):
            reg.deploy("m", {}, engine=_FakeEngine())
        reg.rollback("m")
        with pytest.raises(RolloutInProgress):
            reg.rollback("m")    # nothing left to roll back
        with pytest.raises(ValueError):
            reg.deploy("m", {}, engine=_FakeEngine(),
                       canary_fraction=1.5)
        reg.close()

    def test_deploy_failure_auto_rolls_back(self):
        """The registry.load chaos site: a deploy that dies building
        its variant surfaces DeployError, leaves NO canary, and live
        traffic is untouched — then a clean deploy succeeds."""
        reg = ModelRegistry(gather_window_s=0.0)
        reg.add_model("m", {}, RAFTConfig(), engine=_FakeEngine())
        faults.arm([{"site": "registry.load", "kind": "raise"}])
        with pytest.raises(DeployError):
            reg.deploy("m", {}, engine=_FakeEngine(),
                       canary_fraction=0.5)
        faults.disarm()
        assert reg.health()["m"]["canary"] is None
        assert reg.submit(Z, Z).result(10).flow.shape == (*HW, 2)
        # the failed version number is burned, not reused
        v = reg.deploy("m", {}, engine=_FakeEngine(),
                       canary_fraction=0.5)
        assert v == "v3"
        reg.close()
        snap = reg.snapshot()["m"]
        assert snap["accounting_ok"]

    def test_rollback_drains_canary_zero_stranded(self):
        """rollback() stops routing first, then drains: every accepted
        future settles; post-rollback traffic is 100% live."""
        eng = _FakeEngine(infer_delay_s=0.02)
        ceng = _FakeEngine(infer_delay_s=0.02)
        reg = ModelRegistry(gather_window_s=0.0, max_batch=2)
        reg.add_model("m", {}, RAFTConfig(), engine=eng)
        reg.deploy("m", {}, engine=ceng, canary_fraction=1.0)
        futs = [reg.submit(Z, Z, route_key=i) for i in range(12)]
        reg.rollback("m")          # drain=True settles everything
        assert all(f.done() for f in futs), "rollback stranded futures"
        assert all(f.exception() is None for f in futs)
        # canary retired: subsequent traffic serves from live
        before = reg.snapshot()["m"]["live"]["submitted"]
        reg.submit(Z, Z, route_key=3).result(10)
        assert reg.snapshot()["m"]["live"]["submitted"] == before + 1
        reg.close()
        assert reg.snapshot()["m"]["accounting_ok"]

    def test_session_sticks_to_one_variant(self):
        """A VideoSession over the registry pins a sticky route token:
        the whole stream lands on ONE variant (warm-start state must
        never cross engines)."""
        reg = ModelRegistry(gather_window_s=0.0)
        reg.add_model("m", {}, RAFTConfig(), engine=_FakeEngine())
        reg.deploy("m", {}, engine=_FakeEngine(), canary_fraction=0.5)
        m = reg._models["m"]

        def run_session(**kw):
            live0 = m.live.scheduler.metrics.submitted
            can0 = m.canary.scheduler.metrics.submitted
            sess = VideoSession(reg, warm_start=False, **kw)
            for _ in range(4):
                f = sess.submit_frame(Z)
                if f is not None:
                    f.result(10)
            return (m.live.scheduler.metrics.submitted - live0,
                    m.canary.scheduler.metrics.submitted - can0)

        # deterministic keys covering both sides of the 50% split
        keys = [f"s{i}" for i in range(8)]
        sides = {k: canary_hash_fraction("m", k) < 0.5 for k in keys}
        assert len(set(sides.values())) == 2   # both variants drawn
        for k in keys:
            delta = run_session(route_key=k)
            # the session's 3 pairs landed WHOLLY on its hash's variant
            assert delta == ((0, 3) if sides[k] else (3, 0)), (k, delta)
        # the auto-assigned sticky token path: still all-one-side
        assert run_session() in ((3, 0), (0, 3))
        reg.close()

    def test_rollout_cold_restarts_session_recurrence(self):
        """A rollback that moves a warm stream off its variant must
        cold-restart the recurrence: one variant's flow_low never
        feeds another model's refinement (the pair AFTER the rollout
        submits cold, then warming resumes)."""
        reg = ModelRegistry(gather_window_s=0.0)
        reg.add_model("m", {}, RAFTConfig(), engine=_WarmFakeEngine())
        reg.deploy("m", {}, engine=_WarmFakeEngine(),
                   canary_fraction=1.0)   # every key routes canary
        sess = VideoSession(reg)          # warm_start=True default
        for _ in range(3):                # pairs 1 (cold) + 2 (warm)
            f = sess.submit_frame(Z)
            if f is not None:
                f.result(10)
        assert sess.warm_submits == 1
        reg.rollback("m")                 # stream moves to live
        f = sess.submit_frame(Z)          # pair 3: MUST cold-restart
        f.result(10)
        assert sess.warm_submits == 1, \
            "stale canary flow_low warm-started the live model"
        f = sess.submit_frame(Z)          # pair 4: warming resumes
        f.result(10)
        assert sess.warm_submits == 2
        reg.close()


# -- priority classes (scheduler layer) -----------------------------------


class TestPriorityClasses:
    def test_shed_batch_first_under_backpressure(self):
        """Full queue + interactive arrival: the newest queued batch
        entry is evicted (fails BackpressureError, counted shed AND
        failed); interactive work is never evicted; identity holds."""
        eng = _FakeEngine(infer_delay_s=0.05)
        s = MicroBatchScheduler(eng, max_queue=4, max_batch=1,
                                gather_window_s=0.0)
        bat, rejected = [], 0
        for _ in range(12):
            try:
                bat.append(s.submit(Z, Z, priority=PRIORITY_BATCH))
            except BackpressureError:
                rejected += 1
        inter = [s.submit(Z, Z, priority=PRIORITY_INTERACTIVE)
                 for _ in range(3)]
        for f in inter:
            assert f.result(30).flow.shape == (*HW, 2)
        s.close()
        evicted = sum(1 for f in bat if f.done()
                      and isinstance(f.exception(), BackpressureError))
        assert rejected > 0 and evicted > 0
        snap = s.metrics.snapshot()
        assert snap["evicted"] == evicted
        p = snap["priority"]
        assert p[PRIORITY_INTERACTIVE]["shed"] == 0
        assert p[PRIORITY_INTERACTIVE]["completed"] == 3
        assert p[PRIORITY_BATCH]["shed"] == rejected + evicted
        assert snap["submitted"] == (snap["completed"] + snap["failed"]
                                     + snap["deadline_missed"]
                                     + snap["cancelled"])

    def test_priority_less_path_never_evicts(self):
        """Default traffic at a full queue sheds NEW work only — the
        historical contract, bit for bit (no priorities, no eviction,
        no priority block in the snapshot)."""
        eng = _FakeEngine(infer_delay_s=0.05)
        s = MicroBatchScheduler(eng, max_queue=2, max_batch=1,
                                gather_window_s=0.0)
        futs = []
        with pytest.raises(BackpressureError):
            for _ in range(10):
                futs.append(s.submit(Z, Z))
        s.close()
        assert all(f.exception() is None for f in futs)
        snap = s.metrics.snapshot()
        assert snap["evicted"] == 0 and snap["priority"] == {}

    def test_weighted_dequeue_pulls_interactive_ahead(self):
        """A batch flood is queued first; interactive arrivals still
        complete ahead of most of it (weighted round-robin head)."""
        eng = _FakeEngine(infer_delay_s=0.03)
        s = MicroBatchScheduler(eng, max_queue=64, max_batch=1,
                                gather_window_s=0.0)
        order = []
        olock = threading.Lock()

        def tag(name):
            def cb(_):
                with olock:
                    order.append(name)
            return cb

        for i in range(10):
            s.submit(Z, Z, priority=PRIORITY_BATCH).add_done_callback(
                tag(f"b{i}"))
        for i in range(4):
            s.submit(Z, Z,
                     priority=PRIORITY_INTERACTIVE).add_done_callback(
                tag(f"i{i}"))
        s.close()
        assert len(order) == 14
        pos = {name: k for k, name in enumerate(order)}
        mean_i = sum(pos[f"i{i}"] for i in range(4)) / 4
        mean_b = sum(pos[f"b{i}"] for i in range(10)) / 10
        # interactive submitted LAST but completes ahead of the flood
        assert mean_i < mean_b, (order, mean_i, mean_b)
        # batch is rationed, not starved: the batch head completes
        # within one full weighted cycle (interactive_weight picks +
        # its own) of the start, whatever the submit/dispatch race
        assert pos["b0"] <= 5, order

    def test_invalid_priority_rejected(self):
        s = MicroBatchScheduler(_FakeEngine(), gather_window_s=0.0)
        with pytest.raises(ValueError):
            s.submit(Z, Z, priority="realtime")
        s.close()


# -- the ISSUE-9 acceptance drill (real stack) ----------------------------


class TestTwoModelAcceptanceDrill:
    def test_canary_rollout_drill(self, basic_setup, small_setup,
                                  basic_engine, small_engine):
        """Deploy small as canary at 25% next to live basic; assert
        the deterministic routing fraction (±5% over >= 400 requests),
        bitwise-stable live outputs during the canary window, promote
        (new arch: engine swap), then zero stranded futures and the
        per-model accounting identity across the whole rollout."""
        basic_cfg, basic_vars = basic_setup
        small_cfg, small_vars = small_setup
        rng = np.random.RandomState(7)
        xa, xb = _pair(rng)   # ONE fixed pair: bitwise references
        ref_live = basic_engine.infer_batch(xa[None], xb[None])[0]
        ref_canary = small_engine.infer_batch(xa[None], xb[None])[0]
        # the two archs must be tellable apart at fp noise scale, or
        # the classification below is meaningless
        gap = float(np.abs(ref_live - ref_canary).max())
        assert gap > 1e-2, f"ref outputs too close to classify ({gap})"

        reg = ModelRegistry(max_batch=BUCKET_BATCH,
                            gather_window_s=0.002)
        reg.add_model("basic", basic_vars, basic_cfg, iters=1,
                      engine=basic_engine)
        version = reg.deploy("basic", small_vars, small_cfg,
                             canary_fraction=0.25, engine=small_engine)
        assert version == "v2"
        predicted = [reg.routes_to_canary("basic", i)
                     for i in range(400)]

        # -- bitwise window: sequential singles (each dispatch fills
        # the bucket identically), every live result must equal the
        # pre-rollout reference BIT FOR BIT, canary results the
        # canary's
        for i in range(24):
            flow = reg.submit(xa, xb, model="basic",
                              route_key=i).result(timeout=600).flow
            want = ref_canary if predicted[i] else ref_live
            np.testing.assert_array_equal(
                flow, want,
                err_msg=f"request {i} (canary={predicted[i]}) not "
                        "bitwise its engine's reference")

        # -- routing fraction: >= 400 requests, concurrent submitters
        # (with polite backpressure backoff — the queue is bounded)
        futs = {}
        flock = threading.Lock()

        def submit_range(lo, hi):
            for i in range(lo, hi):
                while True:
                    try:
                        f = reg.submit(xa, xb, model="basic",
                                       route_key=i)
                        break
                    except BackpressureError:
                        time.sleep(0.01)
                with flock:
                    futs[i] = f

        threads = [threading.Thread(target=submit_range,
                                    args=(24, 212)),
                   threading.Thread(target=submit_range,
                                    args=(212, 400))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        served_canary = 0
        for i, f in sorted(futs.items()):
            flow = f.result(timeout=600).flow
            d_live = float(np.abs(flow - ref_live).max())
            d_can = float(np.abs(flow - ref_canary).max())
            # coalesced fills move outputs only at conv-vectorization
            # noise scale — nearest-reference classification is exact
            is_canary = d_can < d_live
            assert min(d_live, d_can) < gap / 4
            assert is_canary == predicted[i], \
                f"request {i} served by the wrong variant"
            served_canary += is_canary
        total_canary = served_canary + sum(predicted[:24])
        frac = total_canary / 400
        assert abs(frac - 0.25) <= 0.05, \
            f"canary fraction {frac} off the deployed 0.25"

        # -- promote: small is a NEW arch -> engine swap; post-promote
        # traffic serves the promoted engine
        out = reg.promote("basic")
        assert out["mode"] == "engine_swap" and out["version"] == "v2"
        for i in range(4):
            flow = reg.submit(xa, xb,
                              model="basic").result(timeout=600).flow
            d_can = float(np.abs(flow - ref_canary).max())
            assert d_can < gap / 4, "post-promote output not the " \
                                    "promoted model's"
        # zero stranded across the rollout
        assert all(f.done() for f in futs.values())
        reg.close()
        snap = reg.snapshot()["basic"]
        assert snap["accounting_ok"], snap["totals"]
        # 24 bitwise-window + 376 fraction-window + 4 post-promote;
        # the backpressure retries above mean every request was
        # eventually ACCEPTED, so completed must equal submitted —
        # zero dropped across deploy -> canary -> promote
        assert snap["totals"]["submitted"] == 404
        assert snap["totals"]["completed"] == 404
        abandoned = sum(
            s["abandoned_inflight"]
            for s in [snap["live"]] + snap["retired"])
        assert abandoned == 0
        # engine hygiene: one bucket each, no cross-model leakage, no
        # compile storm from the rollout
        assert len(basic_engine._compiled) == 1
        assert len(small_engine._compiled) == 1

    def test_priority_drill_real_stack(self, small_setup, small_engine):
        """Under full-queue backpressure on the real stack: batch
        sheds first (rejections and evictions), every interactive
        request completes."""
        cfg, variables = small_setup
        reg = ModelRegistry(max_batch=BUCKET_BATCH, max_queue=6,
                            gather_window_s=0.05)
        reg.add_model("small", variables, cfg, iters=1,
                      engine=small_engine)
        rng = np.random.RandomState(3)
        xa, xb = _pair(rng)
        bat, bat_rejected = [], 0
        for _ in range(24):
            try:
                bat.append(reg.submit(xa, xb,
                                      priority=PRIORITY_BATCH))
            except BackpressureError:
                bat_rejected += 1
        inter = []
        for _ in range(4):
            inter.append(reg.submit(xa, xb,
                                    priority=PRIORITY_INTERACTIVE))
        for f in inter:
            assert f.result(timeout=600).flow.shape == (*HW, 2), \
                "interactive request failed under batch flood"
        reg.close()
        snap = reg.snapshot()["small"]
        p = snap["live"]["priority"]
        assert bat_rejected > 0, "flood never hit backpressure"
        assert snap["live"]["evicted"] > 0, \
            "no queued batch work was evicted for interactive arrivals"
        assert p[PRIORITY_INTERACTIVE]["shed"] == 0
        assert p[PRIORITY_INTERACTIVE]["completed"] == 4
        assert p[PRIORITY_BATCH]["shed"] >= bat_rejected
        assert snap["accounting_ok"], snap["totals"]

    def test_registry_chaos_soak(self, small_setup):
        """The registry chaos drill at tiny shapes: randomized fault
        rounds (drawing registry.load AND guardian.decide — the
        guardian owns every round's rollout verdict) + the clean
        round — zero violations, some deploy attempts, per-model
        identity, and the clean round's canary judged clean and
        auto-promoted."""
        from raft_tpu.cli.serve_bench import run_registry_chaos

        cfg, variables = small_setup
        canary_vars = RAFT(cfg).init(jax.random.PRNGKey(9),
                                     jnp.zeros((1, *HW, 3)),
                                     jnp.zeros((1, *HW, 3)), iters=1)
        summary = run_registry_chaos(
            [("tier_a", variables, cfg), ("tier_b", variables, cfg)],
            shapes=[HW], rounds=2, requests=10, submitters=2,
            bucket_batch=3, iters=1, priority_mix=(1, 1),
            canary_fraction=0.5, canary_variables=canary_vars,
            dispatch_timeout_s=0.5, hang_s=1.0, breaker_failures=2,
            breaker_backoff_s=0.1, breaker_backoff_max_s=0.4,
            seed=5)
        assert summary["violations"] == []
        assert summary["deploys"]["attempted"] == 3
        # round 0's deploy is forced to fail at registry.load: the
        # auto-rollback path ran and left no canary behind (a leak is
        # a violation above)
        assert summary["deploys"]["auto_rolled_back"] >= 1
        # the clean round always deploys; at least it must land
        assert summary["deploys"]["deployed"] >= 1
        # the guardian judged at least the clean round (its promote is
        # also pinned by the violations check), and a wedged guardian
        # round would have shown up as a half-rolled-canary violation
        assert summary["guardian"]["decisions"] >= 1
        clean = summary["per_round"][-1]
        assert clean["canary"]["resolution"] == "guardian_promote"
        assert clean["guardian"]["wedged"] is False
