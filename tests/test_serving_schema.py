"""The ONE schema-assert test over every metrics.jsonl/spans.jsonl
record kind (serving/schema.py).

Until ISSUE 14 each test re-declared its slice of the record schema
inline; this drill drives the REAL emitters — a wedged scheduler with
breakers, a feature-cache flush, a full registry rollout lifecycle
(deploy/promote/rollback/failed deploy/close), guardian verdicts
(promote, rollback, failed decision, loop error), and a traced drill
writing span records — then validates every line against the single
registry and asserts coverage both ways: every emitted record
conforms, every declared event kind was actually produced.
"""

import json
import random
import time

import numpy as np
import pytest

from raft_tpu.config import RAFTConfig
from raft_tpu.serving import schema
from raft_tpu.serving.guardian import GuardianPolicy, SLOGuardian
from raft_tpu.serving.metrics import ServingMetrics
from raft_tpu.serving.registry import DeployError, ModelRegistry
from raft_tpu.serving.resilience import DispatchWedged
from raft_tpu.serving.scheduler import MicroBatchScheduler
from raft_tpu.serving.trace import TraceLedger
from raft_tpu.testing import faults
from tests.host_worker import StubEngine
from tests.test_fleet import _FleetEngine
from tests.test_guardian import _FakeRegistry, _blk
from tests.test_registry import _WarmFakeEngine
from tests.test_scheduler import _FakeEngine, _wait_for

Z = np.zeros((32, 32, 3), np.float32)


@pytest.fixture(autouse=True)
def _disarm_after():
    yield
    faults.disarm()


def _lines(path):
    return [json.loads(line) for line in open(path)]


def _drive_scheduler_events(mpath, spath):
    """serving_state / breaker_* / dispatch_wedged /
    thread_quarantined / cache_flush / serving snapshots / spans —
    the real wedge-and-recover flow from the resilience drills, with
    a feature-cache pool and a trace ledger armed."""
    eng = _FakeEngine()
    eng.feature_cache = True              # pool only — no XLA needed
    faults.arm([{"site": "serve.request", "kind": "hang",
                 "hang_s": 1.0, "count": 1}])
    sched = MicroBatchScheduler(
        eng, gather_window_s=0.0, dispatch_timeout_s=0.3,
        breaker_failures=1, breaker_backoff_s=0.2,
        breaker_backoff_max_s=0.2, breaker_rng=random.Random(0),
        feature_cache=True, metrics_path=mpath,
        tracer=TraceLedger(spath))
    wedged = sched.submit(Z, Z)
    with pytest.raises(DispatchWedged):
        wedged.result(timeout=10)
    faults.disarm()
    # half-open probe closes the breaker (breaker_closed event)
    t_end = time.monotonic() + 20.0
    while time.monotonic() < t_end:
        try:
            sched.submit(Z, Z).result(timeout=10)
            break
        except Exception:
            time.sleep(0.05)
    sched.flush_feature_cache("drill")    # cache_flush event
    sched.close(drain=True)               # snapshot + span flush


def _drive_fleet_and_host_events(mpath):
    """replica_activated / replica_retired / replica_grow_failed /
    fleet_weights_swap via a pressure-grown local fleet, then
    replica_quarantined / host_suspect / host_dead / failover /
    host_rejoined via a loopback host lane killed mid-traffic and
    rejoined — the real emitters, never synthetic records."""
    from raft_tpu.serving.hosts import HostFleet, HostWorker
    from raft_tpu.serving.transport import LoopbackTransport

    # queue pressure grows a replica (replica_activated), idleness
    # retires it (replica_retired), and the swap epoch stamps
    # fleet_weights_swap
    sched = MicroBatchScheduler(
        _FleetEngine(infer_delay_s=0.05), replicas=1,
        replica_ceiling=2, max_batch=1, gather_window_s=0.0,
        replica_idle_retire_s=0.1, metrics_path=mpath)
    for f in [sched.submit(Z, Z) for _ in range(12)]:
        f.result(timeout=30)
    sched.swap_weights({"gen": 1})
    assert _wait_for(
        lambda: sched.health()["fleet"]["active"] == 1, timeout=10.0)
    sched.close()

    # a fleet whose scale-up can't build a replica: replica_grow_failed
    class _NoGrow(_FleetEngine):
        def spawn_replica(self):
            raise RuntimeError("no replica budget")

    sched2 = MicroBatchScheduler(
        _NoGrow(infer_delay_s=0.05), replicas=1, replica_ceiling=2,
        max_batch=1, gather_window_s=0.0, metrics_path=mpath)
    for f in [sched2.submit(Z, Z) for _ in range(12)]:
        f.result(timeout=30)
    sched2.close()

    # one loopback host killed mid-traffic: the missed-beat ladder
    # (host_suspect -> host_dead), the verdict consequences
    # (replica_quarantined + failover), then the explicit rejoin
    t0 = LoopbackTransport(HostWorker(StubEngine(0.02)), name="h0")
    fleet = HostFleet({"h0": t0}, heartbeat_s=0.05,
                      heartbeat_timeout_s=0.5, suspect_after=1,
                      dead_after=2, reconnect_backoff_s=600.0,
                      rng=random.Random(0))
    fleet.admit_all()
    sched3 = MicroBatchScheduler(
        StubEngine(), max_batch=2, gather_window_s=0.0,
        breaker_failures=1, dispatch_timeout_s=10.0,
        metrics_path=mpath, host_fleet=fleet)
    futs = [sched3.submit(Z, Z) for _ in range(6)]
    fleet.poison("h0")
    for f in futs:
        f.result(timeout=30)
    assert _wait_for(
        lambda: any(blk.get("host") == "h0" and blk["quarantined"]
                    for blk in
                    sched3.health()["fleet"]["lanes"].values()),
        timeout=10.0)
    fleet.rejoin("h0", t0.reopen())
    sched3.close()


def _drive_registry_events(mpath):
    """model_state / model_deploy / model_promote / model_rollback /
    model_deploy_failed / aot_evicted / registry_closed, through real
    rollouts. The rolled-back canary carries a (fake) AOT store +
    weights fingerprint so its retirement drives the GC path — the
    aot_evicted emitter the graftwire first scan found undeclared."""

    class _FakeAot:
        def evict(self, max_bytes=None, weights=None):
            return {"removed": 1, "removed_bytes": 128}

    reg = ModelRegistry(metrics_path=mpath, gather_window_s=0.0)
    reg.add_model("m", {}, RAFTConfig(), engine=_WarmFakeEngine())
    reg.deploy("m", {}, engine=_WarmFakeEngine(), canary_fraction=0.5)
    reg.promote("m")
    canary_eng = _WarmFakeEngine()
    canary_eng._aot = _FakeAot()
    canary_eng._weights_fp = "fp-canary"
    reg.deploy("m", {}, engine=canary_eng, canary_fraction=0.5)
    reg.rollback("m")
    faults.arm([{"site": "registry.load", "kind": "raise", "count": 1}])
    with pytest.raises(DeployError):
        reg.deploy("m", {}, engine=None, canary_fraction=0.5)
    faults.disarm()
    reg.close()


def _drive_guardian_events(mpath):
    """guardian_bake_start / guardian_promote / guardian_rollback /
    guardian_decision_failed / guardian_error via the real guardian
    over scripted registries + synthetic snapshots (the
    test_guardian determinism pattern)."""
    policy = GuardianPolicy(bake_window_s=1.0, min_requests=1)
    metrics = ServingMetrics(mpath, namespace="guardian")

    # promote: clean bake past the window
    fake = _FakeRegistry()
    clock = [0.0]
    snaps = [{"m": {"live": _blk(), "canary": _blk(model="m@v2")}},
             {"m": {"live": _blk(completed=30),
                    "canary": _blk(completed=30, model="m@v2")}}]
    it1 = iter(snaps)
    g = SLOGuardian(fake, policy, clock=lambda: clock[0],
                    reader=lambda: next(it1), metrics=metrics)
    g.tick()                              # bake_start
    clock[0] = 2.0
    g.tick()                              # clean -> guardian_promote
    assert fake.actions == [("promote", "m")]

    # rollback: wedge breach in the canary window
    fake2 = _FakeRegistry()
    snaps2 = [{"m": {"live": _blk(), "canary": _blk(model="m@v3")}},
              {"m": {"live": _blk(completed=30),
                     "canary": _blk(completed=30, wedged=2,
                                    model="m@v3")}}]
    it2 = iter(snaps2 + [snaps2[-1]])
    g2 = SLOGuardian(fake2, policy, clock=lambda: clock[0],
                     reader=lambda: next(it2), metrics=metrics)
    clock[0] = 0.0
    g2.tick()
    clock[0] = 0.5
    g2.tick()                             # breach -> guardian_rollback
    assert fake2.actions == [("rollback", "m")]

    # decision_failed: the registry refuses the verdict
    fake3 = _FakeRegistry()
    fake3.raise_on_action = RuntimeError("operator got there first")
    it3 = iter([{"m": {"live": _blk(),
                       "canary": _blk(model="m@v4")}},
                {"m": {"live": _blk(completed=30),
                       "canary": _blk(completed=30, wedged=2,
                                      model="m@v4")}}])
    g3 = SLOGuardian(fake3, policy, clock=lambda: clock[0],
                     reader=lambda: next(it3), metrics=metrics)
    clock[0] = 0.0
    g3.tick()
    clock[0] = 0.5
    g3.tick()                             # guardian_decision_failed

    # guardian_error: a reader that raises inside the polling loop
    def boom():
        raise RuntimeError("reader down")

    g4 = SLOGuardian(_FakeRegistry(), policy, reader=boom,
                     poll_s=0.01, metrics=metrics).start()
    time.sleep(0.1)
    g4.stop()
    assert g4.errors >= 1


def test_every_record_kind_validates_and_is_covered(tmp_path):
    mpath = str(tmp_path / "metrics.jsonl")
    spath = str(tmp_path / "spans.jsonl")
    _drive_scheduler_events(mpath, spath)
    _drive_fleet_and_host_events(mpath)
    _drive_registry_events(mpath)
    _drive_guardian_events(mpath)

    recs = _lines(mpath) + _lines(spath)
    problems = schema.validate_lines(recs)
    assert problems == []

    seen_events = {r["event"] for r in recs
                   if r.get("kind") == "serving_event"}
    missing = set(schema.EVENT_FIELDS) - seen_events
    assert not missing, \
        f"declared event kinds never emitted by the drill: {missing}"
    undeclared = seen_events - set(schema.EVENT_FIELDS)
    assert not undeclared    # validate_lines already failed these
    kinds = {r.get("kind") for r in recs}
    assert kinds == set(schema.RECORD_KINDS)
    spans = {r["span"] for r in recs if r.get("kind") == "span"}
    assert spans == set(schema.SPAN_KINDS)


def test_static_every_record_event_literal_is_declared():
    """The static twin of the dynamic drill above (and of graftwire's
    W6 tier): walk every ``record_event(...)`` / ``_emit(...)`` call
    under raft_tpu/serving/ whose kind is a string literal (or a
    constant-prefix BinOp like ``"breaker_" + state``) and assert the
    kind resolves in EVENT_FIELDS — an emitter added without a schema
    entry fails HERE at parse time, not at the first drill that
    happens to drive it."""
    import ast
    import os

    serving_dir = os.path.dirname(os.path.abspath(schema.__file__))
    events = set(schema.EVENT_FIELDS)
    problems, literals = [], 0
    for name in sorted(os.listdir(serving_dir)):
        if not name.endswith(".py"):
            continue
        path = os.path.join(serving_dir, name)
        tree = ast.parse(open(path, encoding="utf-8").read(),
                         filename=path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            attr = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if attr not in ("record_event", "_emit"):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and \
                    isinstance(arg.value, str):
                literals += 1
                if arg.value not in events:
                    problems.append(
                        f"{name}:{node.lineno}: {arg.value!r}")
            elif isinstance(arg, ast.BinOp) and \
                    isinstance(arg.op, ast.Add) and \
                    isinstance(arg.left, ast.Constant) and \
                    isinstance(arg.left.value, str):
                literals += 1
                if not any(e.startswith(arg.left.value)
                           for e in events):
                    problems.append(f"{name}:{node.lineno}: prefix "
                                    f"{arg.left.value!r}")
    assert problems == [], \
        "record_event kinds with no EVENT_FIELDS entry: " \
        + "; ".join(problems)
    # the walk actually saw the emitters (a refactor that moves them
    # out of serving/ must update this drill, not silently skip it)
    assert literals >= 20


def test_wire_methods_registry_matches_worker_table():
    """WIRE_METHODS <-> the real HostWorker ``_m_*`` surface, pinned
    both ways: a handler added without a registry entry (or a registry
    row whose handler was dropped) fails here."""
    from raft_tpu.serving.hosts import HostWorker

    table = {m[len("_m_"):] for m in dir(HostWorker)
             if m.startswith("_m_")}
    assert table == set(schema.WIRE_METHODS)


def test_validator_rejects_drift():
    assert schema.validate_record({"kind": "mystery"})
    bad_event = {"kind": "serving_event", "event": "breaker_open",
                 "time": 0.0}
    assert any("missing" in p
               for p in schema.validate_record(bad_event))
    assert any("undeclared" in p for p in schema.validate_record(
        {"kind": "serving_event", "event": "brand_new_event",
         "time": 0.0}))
    bad_span = {"kind": "span", "span": "request", "trace_id": "r-1",
                "time": 0.0, "outcome": "completed", "class": "nope",
                "total_ms": 1.0, "tail": False, "bucket": "b",
                "phases": {}}
    assert any("class" in p for p in schema.validate_record(bad_span))
    good = dict(bad_span, **{"class": "completed"})
    assert schema.validate_record(good) == []
