"""Native codec parity: C++ flowio vs the numpy reference implementations.

Round-trips every format through both paths; skips cleanly when no
toolchain is available (the package must work without it).
"""

import numpy as np
import pytest

from raft_tpu import native
from raft_tpu.data import frame_utils


pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


def _numpy_read_flow(path):
    with open(path, "rb") as f:
        magic = np.fromfile(f, np.float32, count=1)
        assert magic[0] == np.float32(frame_utils.TAG_FLO)
        w = int(np.fromfile(f, np.int32, count=1)[0])
        h = int(np.fromfile(f, np.int32, count=1)[0])
        return np.fromfile(f, np.float32, count=2 * w * h).reshape(h, w, 2)


class TestFlo:
    def test_roundtrip(self, tmp_path, rng):
        uv = rng.randn(17, 23, 2).astype(np.float32)
        p = str(tmp_path / "a.flo")
        assert native.write_flo(p, uv)
        np.testing.assert_array_equal(native.read_flo(p), uv)
        # byte-identical to what the numpy reader sees
        np.testing.assert_array_equal(_numpy_read_flow(p), uv)

    def test_frame_utils_uses_native(self, tmp_path, rng):
        uv = rng.randn(5, 7, 2).astype(np.float32)
        p = str(tmp_path / "b.flo")
        frame_utils.write_flow(p, uv)
        np.testing.assert_array_equal(frame_utils.read_flow(p), uv)

    def test_bad_file_returns_none(self, tmp_path):
        p = tmp_path / "bad.flo"
        p.write_bytes(b"not a flo file")
        assert native.read_flo(str(p)) is None


class TestPfm:
    @pytest.mark.parametrize("color", [False, True])
    def test_matches_numpy_reader(self, tmp_path, rng, color):
        shape = (11, 13, 3) if color else (11, 13)
        data = rng.randn(*shape).astype(np.float32)
        p = str(tmp_path / "x.pfm")
        frame_utils.write_pfm(p, data)
        got = native.read_pfm(p)
        np.testing.assert_array_equal(got, data)


class TestPfmCRLF:
    def test_crlf_header_matches_numpy(self, tmp_path, rng):
        """Windows-written PFM: header lines end in \\r\\n; the payload must
        not shift by a byte."""
        data = rng.randn(6, 5).astype(np.float32)
        p = tmp_path / "crlf.pfm"
        with open(p, "wb") as f:
            f.write(b"Pf\r\n5 6\r\n-1.0\r\n")
            np.flipud(data).astype("<f").tofile(f)
        got = native.read_pfm(str(p))
        np.testing.assert_array_equal(got, data)
