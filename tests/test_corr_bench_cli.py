"""The corr_bench CLI is measurement infrastructure (it picks the model's
corr_impl default from hardware runs), so its plumbing is tested like
product code: every impl path in both modes, including the Pallas kernel in
interpret mode and the padded-pyramid gradient unpad in --grad mode.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import raft_tpu.kernels as kernels
from raft_tpu.cli import corr_bench
from raft_tpu.kernels import corr_pallas


@pytest.fixture(autouse=True)
def interpret_pallas(monkeypatch):
    monkeypatch.setattr(corr_pallas, "_INTERPRET", True)
    # pallas_available() gates on a real TPU backend; interpret mode runs
    # the same program on CPU (main() imports it from raft_tpu.kernels)
    monkeypatch.setattr(kernels, "pallas_available", lambda: True)


ARGS = ["--batch", "1", "--hw", "8", "12", "--dim", "16", "--radius", "2",
        "--levels", "2", "--iters", "2"]


def _diffs(capsys):
    out = capsys.readouterr().out
    return out, [float(line.split("max|Δ|=")[1].split()[0])
                 for line in out.splitlines() if "max|Δ|" in line]


def test_forward_all_impls(capsys):
    results, failed = corr_bench.main(
        ARGS + ["--impls", "gather", "onehot", "pallas", "alt"])
    assert not failed
    assert set(results) == {"gather", "onehot", "pallas", "alt"}
    out, diffs = _diffs(capsys)
    assert len(diffs) == 4 and max(diffs) < 1e-4, out


def test_grad_mode_parity_includes_gradients(capsys):
    """Grad-mode parity compares gradient leaves, not just the primal —
    a wrong backward (e.g. in the Pallas scatter kernel or its unpad
    slicing) must surface as a large max|Δ| here."""
    results, failed = corr_bench.main(
        ARGS + ["--grad", "--impls", "gather", "onehot", "pallas"])
    assert not failed
    assert set(results) == {"gather", "onehot", "pallas"}
    out, diffs = _diffs(capsys)
    assert len(diffs) == 3 and max(diffs) < 1e-4, out


def test_grad_mode_flags_a_broken_backward(capsys):
    """If the Pallas VJP returned zeros, parity must catch it (guards the
    failure mode where only the primal would be compared and a broken
    backward would silently win the shootout)."""

    def zero_bwd(radius, res, g):
        d_pyramid, dx, dy = corr_pallas._lookup_bwd(radius, res, g)
        return tuple(jnp.zeros_like(d) for d in d_pyramid), dx, dy

    corr_pallas._lookup.defvjp(corr_pallas._lookup_fwd, zero_bwd)
    try:
        corr_bench.main(ARGS + ["--grad", "--impls", "gather", "pallas"])
        out, diffs = _diffs(capsys)
        assert max(diffs) > 1e-3, f"zeroed backward not detected: {out}"
    finally:
        corr_pallas._lookup.defvjp(corr_pallas._lookup_fwd,
                                   corr_pallas._lookup_bwd)


def test_grad_mode_onehot_t_layout_normalized(capsys):
    """onehot_t's volume cotangents are produced in (B,Hl,Wl,N); the CLI
    must transpose them back before parity, else a correct backward reads
    as rel diff ~1 (and with the old primal-dominated denominator, a
    WRONG one read as ~1e-5)."""
    results, failed = corr_bench.main(
        ARGS + ["--grad", "--impls", "onehot", "onehot_t"])
    assert not failed
    out, diffs = _diffs(capsys)
    assert len(diffs) == 2 and max(diffs) < 1e-4, out


def test_unknown_impl_reports_failure():
    _, failed = corr_bench.main(ARGS + ["--impls", "onehot", "onehott"])
    assert failed == ["onehott"]
