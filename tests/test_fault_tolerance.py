"""The fault matrix: crash-safe checkpoints, retry/backoff, supervised
auto-resume, and loader resilience, driven by the deterministic fault
harness (raft_tpu.testing.faults).

Tier-1 on the CPU mesh with tiny configs, except the end-to-end drill
(TestSupervisedEndToEnd, ``@pytest.mark.slow`` — run explicitly): an
armed fault plan wedges the first child at step N and corrupts the
checkpoint written at step M; the supervisor restarts it, resume falls
back past the corrupt step, and the finished weights match an
uninterrupted control run bitwise.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from raft_tpu.testing import faults
from raft_tpu.training.supervisor import ATTEMPT_ENV, Supervisor
from raft_tpu.utils.ckpt_scan import latest_step_on_disk, step_dirs
from raft_tpu.utils.retry import backoff_delays, retry
from raft_tpu.utils.watchdog import WEDGED_EXIT_CODE

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "fault_train_worker.py")


@pytest.fixture(autouse=True)
def _disarm_after():
    yield
    faults.disarm()


class TestFaultPlan:
    def test_occurrence_counting_and_one_shot(self):
        faults.arm([{"site": "x", "at": 2, "kind": "raise"}])
        faults.fault_point("x")  # occurrence 1: below threshold
        with pytest.raises(faults.FaultInjected, match="occurrence 2"):
            faults.fault_point("x")
        faults.fault_point("x")  # fired entries never re-fire

    def test_disarmed_is_noop(self):
        faults.disarm()
        faults.fault_point("anything")
        assert not faults.armed("anything")

    def test_arm_from_env_and_dict_form(self, monkeypatch):
        monkeypatch.setenv("RAFT_FAULT_PLAN", json.dumps(
            {"faults": [{"site": "y", "kind": "raise"}]}))
        faults.arm_from_env()
        assert faults.armed("y")
        with pytest.raises(faults.FaultInjected):
            faults.fault_point("y")
        assert not faults.armed("y")

    def test_attempt_scoping(self, monkeypatch):
        plan = [{"site": "a", "kind": "raise", "attempt": 0},
                {"site": "b", "kind": "raise", "attempt": 1}]
        monkeypatch.setenv(ATTEMPT_ENV, "1")
        faults.arm(plan)
        assert not faults.armed("a") and faults.armed("b")
        monkeypatch.delenv(ATTEMPT_ENV)
        faults.arm(plan)  # unset env = attempt 0
        assert faults.armed("a") and not faults.armed("b")

    def test_invalid_entries_rejected(self):
        with pytest.raises(ValueError, match="kind"):
            faults.arm([{"site": "x", "kind": "explode"}])
        with pytest.raises(ValueError, match="1-based"):
            faults.arm([{"site": "x", "kind": "raise", "at": 0}])

    def test_unknown_dotted_site_rejected_with_nearest_hint(self):
        # a typo'd production site arms NOTHING — the drill then
        # silently tests less than it claims, so arm() fails loudly at
        # parse time and names the nearest real site
        with pytest.raises(ValueError, match=r"transport\.snd.*did "
                                             r"you mean 'transport\.send'"):
            faults.arm([{"site": "transport.snd", "kind": "raise"}])
        with pytest.raises(ValueError, match="KNOWN_SITES"):
            faults.arm([{"site": "serve.bogus_phase", "kind": "raise"}])
        # every production site is dotted; undotted synthetic names
        # (this file's "x"/"y"/"f" machinery drills) stay legal
        faults.arm([{"site": "x", "kind": "raise"}])
        assert faults.armed("x")
        faults.disarm()

    def test_known_sites_table_matches_armed_reality(self):
        # every declared site validates; the table carries a one-line
        # description (it doubles as the chaos-surface inventory the
        # graftwire W7 audit reads)
        for site, desc in faults.KNOWN_SITES.items():
            assert "." in site, site
            assert isinstance(desc, str) and desc, site
        faults.arm([{"site": s, "kind": "raise", "at": 10 ** 9}
                    for s in faults.KNOWN_SITES])
        faults.disarm()

    def test_fault_file_zeroes_content(self, tmp_path):
        p = tmp_path / "blob"
        p.write_bytes(b"A" * 300)
        faults.arm([{"site": "f", "kind": "corrupt"}])
        victim = faults.fault_file("f", str(p))
        assert victim == str(p)
        # size-preserving zero-fill (see fault_file docstring for why
        # not bit flips or truncation)
        assert p.read_bytes() == b"\x00" * 300
        # dir form: the largest file under the dir is the victim
        d = tmp_path / "step"
        d.mkdir()
        (d / "small").write_bytes(b"s" * 10)
        (d / "big").write_bytes(b"B" * 400)
        faults.arm([{"site": "f", "kind": "corrupt"}])
        assert faults.fault_file("f", str(d)) == str(d / "big")
        assert (d / "small").read_bytes() == b"s" * 10
        # ... unless a _METADATA file exists (Orbax step dirs): the
        # python-parsed metadata is hit so the restore fails before
        # tensorstore's async data reads can poison the reader's heap
        (d / "sub").mkdir()
        (d / "sub" / "_METADATA").write_bytes(b"m" * 20)
        faults.arm([{"site": "f", "kind": "corrupt"}])
        assert faults.fault_file("f", str(d)) == str(d / "sub" / "_METADATA")
        assert (d / "sub" / "_METADATA").read_bytes() == b"\x00" * 20


class TestRetry:
    def test_delays_deterministic_without_jitter(self):
        import itertools
        got = list(itertools.islice(
            backoff_delays(1.0, 8.0, jitter=0.0), 6))
        assert got == [1.0, 2.0, 4.0, 8.0, 8.0, 8.0]

    def test_jitter_bounds(self):
        import itertools
        import random
        got = list(itertools.islice(
            backoff_delays(1.0, 8.0, jitter=0.5, rng=random.Random(7)), 50))
        caps = [1.0, 2.0, 4.0] + [8.0] * 47
        for d, cap in zip(got, caps):
            assert 0.5 * cap <= d <= 1.5 * cap

    def test_retries_then_succeeds(self):
        calls, sleeps = [], []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        seen = []
        assert retry(flaky, attempts=4, jitter=0.0, base_s=1.0,
                     on_retry=lambda k, d, e: seen.append((k, d)),
                     sleep=sleeps.append) == "ok"
        assert len(calls) == 3 and sleeps == [1.0, 2.0]
        assert seen == [(1, 1.0), (2, 2.0)]

    def test_exhausted_reraises_last(self):
        with pytest.raises(OSError, match="always"):
            retry(lambda: (_ for _ in ()).throw(OSError("always")),
                  attempts=3, jitter=0.0, sleep=lambda d: None)

    def test_only_listed_exceptions_retried(self):
        def boom():
            raise KeyError("no")

        with pytest.raises(KeyError):
            retry(boom, attempts=5, retry_on=(OSError,),
                  sleep=lambda d: None)


class TestMsgpackIntegrity:
    """Atomic weights-only writes + the SHA-256 sidecar manifest."""

    VARS = {"params": {"w": np.arange(64, dtype=np.float32)}}
    VARS2 = {"params": {"w": np.ones(64, dtype=np.float32)}}

    def test_save_writes_manifest_and_verifies(self, tmp_path):
        from raft_tpu.tools import convert

        path = str(tmp_path / "w.msgpack")
        convert.save_converted(self.VARS, path)
        data = open(path, "rb").read()
        convert.verify_manifest(path, data)  # intact: no raise
        assert os.path.exists(convert.manifest_path(path))

    def test_missing_manifest_tolerated(self, tmp_path):
        from raft_tpu.tools import convert

        path = str(tmp_path / "legacy.msgpack")
        path_data = b"pre-hardening checkpoint"
        open(path, "wb").write(path_data)
        convert.verify_manifest(path, path_data)  # no sidecar: passes

    def test_flipped_byte_detected(self, tmp_path):
        from raft_tpu.tools import convert

        path = str(tmp_path / "w.msgpack")
        convert.save_converted(self.VARS, path)
        data = bytearray(open(path, "rb").read())
        data[len(data) // 2] ^= 0xFF
        with pytest.raises(convert.CorruptCheckpointError,
                           match="integrity"):
            convert.verify_manifest(path, bytes(data))

    def test_interrupted_rename_leaves_final_intact(self, tmp_path):
        """An interruption in the tmp->rename window must leave the
        previous final file byte-identical (and no tmp litter on the
        exception path)."""
        from raft_tpu.tools import convert

        path = str(tmp_path / "w.msgpack")
        convert.save_converted(self.VARS, path)
        before = open(path, "rb").read()
        faults.arm([{"site": "ckpt.msgpack_write", "kind": "raise"}])
        with pytest.raises(faults.FaultInjected):
            convert.save_converted(self.VARS2, path)
        assert open(path, "rb").read() == before
        convert.verify_manifest(path, before)
        assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]

    def test_bitrot_drill_caught_by_manifest(self, tmp_path):
        """kind="corrupt" smashes the COMPLETED file (post-manifest), so
        the load-time check is what must catch it."""
        from raft_tpu.tools import convert

        path = str(tmp_path / "w.msgpack")
        faults.arm([{"site": "ckpt.msgpack_write", "kind": "corrupt"}])
        convert.save_converted(self.VARS, path)
        with pytest.raises(convert.CorruptCheckpointError):
            convert.verify_manifest(path, open(path, "rb").read())

    def test_crash_mid_save_never_torn_under_final_name(self, tmp_path):
        """Real os._exit crash (no finally, no atexit) between tmp and
        rename: the final name must still hold the PREVIOUS intact save."""
        script = f"""
import sys
sys.path.insert(0, {REPO!r})
import numpy as np
from raft_tpu.testing import faults
from raft_tpu.tools.convert import save_converted
save_converted({{"params": {{"w": np.zeros(64, np.float32)}}}}, sys.argv[1])
faults.arm([{{"site": "ckpt.msgpack_write", "kind": "crash"}}])
save_converted({{"params": {{"w": np.ones(64, np.float32)}}}}, sys.argv[1])
"""
        path = str(tmp_path / "w.msgpack")
        r = subprocess.run([sys.executable, "-c", script, path],
                           capture_output=True, text=True)
        assert r.returncode == faults.CRASH_EXIT_CODE, r.stderr[-2000:]
        from flax import serialization

        from raft_tpu.tools import convert

        data = open(path, "rb").read()
        convert.verify_manifest(path, data)  # intact, manifest matches
        restored = serialization.msgpack_restore(data)
        np.testing.assert_array_equal(restored["params"]["w"],
                                      np.zeros(64, np.float32))


class TestOrbaxFallback:
    """restore_train_state falls back past a torn/corrupt latest step."""

    @pytest.fixture(scope="class")
    def state(self):
        import jax.numpy as jnp
        import optax

        from raft_tpu.training.train_step import RAFTTrainState

        # handcrafted tiny state, not create_train_state: these tests
        # exercise save/quarantine/fallback mechanics, which only see
        # the (step, params, batch_stats, opt_state) tree — a real
        # model init costs ~10 s of tier-1 budget for no extra
        # coverage (the slow-marked e2e drill restores the real
        # thing). adam, not sgd, so opt_state carries real tensors
        # through the orbax -> sandbox -> msgpack round trip.
        tx = optax.adam(1e-3)
        params = {"w": jnp.arange(64.0), "b": jnp.ones((4, 4))}
        return RAFTTrainState(step=jnp.zeros((), jnp.int32),
                              params=params, batch_stats={},
                              opt_state=tx.init(params), tx=tx)

    def test_corrupt_latest_falls_back_and_quarantines(self, tmp_path,
                                                       state, capsys):
        from raft_tpu.training import checkpoint as ckpt_lib

        d = str(tmp_path / "stage")
        s1 = state.replace(step=state.step + 1)
        s2 = state.replace(step=state.step + 2)
        # the REAL drill path: the SECOND save corrupts its own step dir
        faults.arm([{"site": "ckpt.orbax_save", "at": 2,
                     "kind": "corrupt"}])
        ckpt_lib.save_train_state(d, s1, wait=True)
        ckpt_lib.save_train_state(d, s2, wait=True)
        assert latest_step_on_disk(d) == 2

        restored = ckpt_lib.restore_train_state(d, state)
        assert int(restored.step) == 1
        # the bad step was renamed aside, not deleted, and no longer
        # counts as a restorable step
        names = os.listdir(d)
        assert any(n.endswith(".corrupt") for n in names)
        assert [s for s, _ in step_dirs(d)] == [1]
        out = capsys.readouterr().out
        assert "torn/corrupt" in out and "fallback step 1" in out

    def test_explicit_step_fails_loudly(self, tmp_path, state):
        """A caller-named step must raise, not silently substitute."""
        from raft_tpu.training import checkpoint as ckpt_lib

        d = str(tmp_path / "stage")
        faults.arm([{"site": "ckpt.orbax_save", "kind": "corrupt"}])
        ckpt_lib.save_train_state(d, state.replace(step=state.step + 5),
                                  wait=True)
        with pytest.raises(Exception):
            ckpt_lib.restore_train_state(d, state, step=5)
        # no quarantine on the explicit path: the caller decides
        assert not [n for n in os.listdir(d) if n.endswith(".corrupt")]

    def test_env_failure_does_not_quarantine(self, tmp_path, state,
                                             monkeypatch):
        """A sandbox failure that is NOT step damage (disk full writing
        the snapshot, a broken env) must surface as an error — NOT feed
        the fallback loop, which would quarantine every intact step and
        silently restart a long run from scratch."""
        from raft_tpu.training import checkpoint as ckpt_lib
        from raft_tpu.training.restore_sandbox import ENV_ERROR_EXIT

        d = str(tmp_path / "stage")
        ckpt_lib.save_train_state(d, state.replace(step=state.step + 1),
                                  wait=True)

        def fake_run(*a, **kw):
            return subprocess.CompletedProcess(
                a, ENV_ERROR_EXIT, stdout="", stderr="disk full")

        monkeypatch.setattr(ckpt_lib.subprocess, "run", fake_run)
        with pytest.raises(RuntimeError, match="disk full"):
            ckpt_lib.restore_train_state(d, state)
        assert not [n for n in os.listdir(d) if n.endswith(".corrupt")]
        assert [s for s, _ in step_dirs(d)] == [1]  # history intact

    def test_sandbox_timeout_quarantines_hung_step(self, tmp_path, state,
                                                   monkeypatch):
        """A tensorstore read that BLOCKS on damaged input (rather than
        erroring or crashing) runs before the trainer's watchdog is
        armed — the deadline must turn it into quarantine-and-fall-back
        instead of an eternal wedge."""
        from raft_tpu.training import checkpoint as ckpt_lib

        d = str(tmp_path / "stage")
        ckpt_lib.save_train_state(d, state.replace(step=state.step + 1),
                                  wait=True)
        ckpt_lib.save_train_state(d, state.replace(step=state.step + 2),
                                  wait=True)
        name2 = {s: n for s, n in step_dirs(d)}[2]
        real_run = subprocess.run

        def fake_run(cmd, **kw):
            if os.path.basename(cmd[-2]) == name2:
                raise subprocess.TimeoutExpired(cmd, kw.get("timeout"))
            return real_run(cmd, **kw)

        monkeypatch.setattr(ckpt_lib.subprocess, "run", fake_run)
        restored = ckpt_lib.restore_train_state(d, state)
        assert int(restored.step) == 1
        assert any(n.endswith(".corrupt") for n in os.listdir(d))
        assert [s for s, _ in step_dirs(d)] == [1]

    def test_oom_signal_death_does_not_quarantine(self, tmp_path, state,
                                                  monkeypatch):
        """SIGKILL/SIGTERM of the sandbox (OOM killer, process manager)
        says nothing about the step's bytes — on a memory-tight host it
        recurs for EVERY step, and quarantining on it would shred the
        entire intact history. It must surface as an error instead."""
        from raft_tpu.training import checkpoint as ckpt_lib

        d = str(tmp_path / "stage")
        ckpt_lib.save_train_state(d, state.replace(step=state.step + 1),
                                  wait=True)

        def fake_run(*a, **kw):
            return subprocess.CompletedProcess(a, -9, stdout="",
                                               stderr="oom-killed")

        monkeypatch.setattr(ckpt_lib.subprocess, "run", fake_run)
        with pytest.raises(RuntimeError, match="oom-killed"):
            ckpt_lib.restore_train_state(d, state)
        assert not [n for n in os.listdir(d) if n.endswith(".corrupt")]
        assert [s for s, _ in step_dirs(d)] == [1]  # history intact

    def test_sandbox_crash_signal_quarantines(self, tmp_path, state,
                                              monkeypatch):
        """A SIGSEGV sandbox death IS the poisoned-read crash class the
        sandbox exists to contain: quarantine and fall back."""
        from raft_tpu.training import checkpoint as ckpt_lib

        d = str(tmp_path / "stage")
        ckpt_lib.save_train_state(d, state.replace(step=state.step + 1),
                                  wait=True)
        ckpt_lib.save_train_state(d, state.replace(step=state.step + 2),
                                  wait=True)
        name2 = {s: n for s, n in step_dirs(d)}[2]
        real_run = subprocess.run

        def fake_run(cmd, **kw):
            if os.path.basename(cmd[-2]) == name2:
                return subprocess.CompletedProcess(cmd, -11, stdout="",
                                                   stderr="segfault")
            return real_run(cmd, **kw)

        monkeypatch.setattr(ckpt_lib.subprocess, "run", fake_run)
        restored = ckpt_lib.restore_train_state(d, state)
        assert int(restored.step) == 1
        assert any(n.endswith(".corrupt") for n in os.listdir(d))

    def test_all_steps_corrupt_raises_with_inventory(self, tmp_path,
                                                     state):
        from raft_tpu.training import checkpoint as ckpt_lib

        d = str(tmp_path / "stage")
        faults.arm([{"site": "ckpt.orbax_save", "kind": "corrupt"},
                    {"site": "ckpt.orbax_save", "at": 2,
                     "kind": "corrupt"}])
        ckpt_lib.save_train_state(d, state.replace(step=state.step + 1),
                                  wait=True)
        ckpt_lib.save_train_state(d, state.replace(step=state.step + 2),
                                  wait=True)
        with pytest.raises(FileNotFoundError, match="quarantined"):
            ckpt_lib.restore_train_state(d, state)


class _ListDataset:
    """Tiny tuple-sample dataset with optional bad/slow indices."""

    def __init__(self, n=8, bad=(), slow=(), slow_s=8.0):
        self.n = n
        self.bad = set(bad)
        self.slow = set(slow)
        self.slow_s = slow_s

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        if i in self.bad:
            raise ValueError(f"rotten sample {i}")
        if i in self.slow:
            time.sleep(self.slow_s)
        img = np.zeros((8, 8, 3), np.float32)
        flow = np.zeros((8, 8, 2), np.float32)
        valid = np.ones((8, 8), np.float32)
        return img, img, flow, valid


class TestLoaderResilience:
    def _loader(self, ds, **kw):
        from raft_tpu.data.loader import PrefetchLoader

        kw.setdefault("shuffle", False)
        kw.setdefault("num_workers", 2)
        kw.setdefault("clamp", False)
        return PrefetchLoader(ds, batch_size=4, **kw)

    def test_skip_policy_resamples_and_counts(self):
        loader = self._loader(_ListDataset(bad={3}), on_bad_sample="skip")
        with pytest.warns(UserWarning, match="skipped bad sample 3"):
            batches = list(loader)
        assert len(batches) == 2
        assert all(b["image1"].shape == (4, 8, 8, 3) for b in batches)
        assert loader.bad_samples >= 1

    def test_raise_policy_surfaces_decode_error(self):
        loader = self._loader(_ListDataset(bad={3}))  # default: raise
        with pytest.raises(ValueError, match="rotten sample 3"):
            list(loader)

    def test_systematically_broken_dataset_gives_up(self):
        loader = self._loader(_ListDataset(bad=set(range(8))),
                              on_bad_sample="skip")
        with pytest.warns(UserWarning):
            with pytest.raises(RuntimeError,
                               match="systematically broken"):
                list(loader)

    def test_stall_deadline_raises_named_error(self):
        from raft_tpu.data.loader import LoaderStallError

        loader = self._loader(_ListDataset(slow={0}, slow_s=8.0),
                              num_workers=1, stall_s=0.75)
        t0 = time.monotonic()
        with pytest.raises(LoaderStallError, match="stall_s"):
            list(loader)
        assert time.monotonic() - t0 < 5.0  # surfaced, not an 8s hang

    def test_no_worker_thread_leak_on_early_exit(self):
        """Workers parked in ahead.acquire() must observe stop after an
        early consumer exit instead of leaking one thread set per
        partial epoch."""
        before = set(threading.enumerate())
        loader = self._loader(_ListDataset(n=32), prefetch=1)
        it = iter(loader)
        next(it)
        it.close()  # early exit mid-epoch
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            leaked = [t for t in threading.enumerate()
                      if t not in before and t.is_alive()
                      and t.name.startswith("PrefetchLoader")]
            if not leaked:
                break
            time.sleep(0.05)
        assert not leaked, f"leaked worker threads: {leaked}"

    def test_fault_site_in_worker_respects_skip_policy(self):
        faults.arm([{"site": "loader.sample", "kind": "raise"}])
        loader = self._loader(_ListDataset(), on_bad_sample="skip",
                              num_workers=1)
        with pytest.warns(UserWarning, match="FaultInjected"):
            batches = list(loader)
        assert len(batches) == 2 and loader.bad_samples == 1


class TestSupervisorUnit:
    def _sup(self, rcs, probes, **kw):
        seq = iter(rcs)
        probe_seq = iter(probes)
        launches = []

        def launch(attempt, env):
            launches.append(env[ATTEMPT_ENV])
            return next(seq)

        sup = Supervisor(["true"], launch=launch,
                         probe_step=lambda: next(probe_seq),
                         sleep=lambda d: None, **kw)
        return sup, launches

    def test_restart_on_wedge_then_success(self):
        sup, launches = self._sup([WEDGED_EXIT_CODE, 0], [4])
        assert sup.run() == 0
        assert launches == ["0", "1"] and sup.restarts == 1

    def test_two_crashes_same_step_is_deterministic(self):
        sup, launches = self._sup([1, 1, 1], [5, 5, 5], max_restarts=10)
        assert sup.run() == 1
        assert len(launches) == 2  # gave up, didn't burn the budget

    def test_crashes_with_no_checkpoint_yet_spend_budget(self):
        """probe None == None must NOT read as 'deterministic': a crash
        before the first checkpoint commits (the OUTAGE-r04 shape) has
        no restore point to replay — it spends restart budget instead
        of abandoning the run after one restart."""
        sup, launches = self._sup([1, 1, 1], [None, None, None],
                                  max_restarts=2)
        assert sup.run() == 1
        assert len(launches) == 3  # initial + max_restarts

    def test_repeated_wedges_same_step_keep_retrying(self):
        """Wedges (exit 3) are transient by definition — two at the
        same restore point (they recur faster than the checkpoint
        cadence) must not trip the deterministic-crash rule."""
        sup, launches = self._sup([WEDGED_EXIT_CODE, WEDGED_EXIT_CODE, 0],
                                  [7, 7, 7], max_restarts=5)
        assert sup.run() == 0
        assert sup.restarts == 2

    def test_final_signal_death_maps_to_128_plus_signum(self):
        """sys.exit(-9) would be masked to an undocumented 247; the
        supervisor returns the shell convention instead."""
        sup, launches = self._sup([-9, -9], [1, 2], max_restarts=1)
        assert sup.run() == 137  # 128 + SIGKILL

    def test_progressing_failures_use_full_budget(self):
        sup, launches = self._sup([1] * 10, [1, 2, 3, 4, 5, 6],
                                  max_restarts=3)
        assert sup.run() == 1
        assert len(launches) == 4  # initial + max_restarts

    def test_usage_error_never_retried(self):
        sup, launches = self._sup([2], [99])
        assert sup.run() == 2
        assert len(launches) == 1

    def test_restart_events_appended_to_metrics(self, tmp_path):
        """The alerting substrate: every restart decision lands in
        metrics.jsonl (attempt, exit class, restored step, backoff)
        next to the trainer Logger's records."""
        mpath = str(tmp_path / "runs" / "metrics.jsonl")
        sup, _ = self._sup([WEDGED_EXIT_CODE, 1, 0], [3, 5],
                           metrics_path=mpath)
        assert sup.run() == 0
        recs = [json.loads(line) for line in open(mpath)]
        restarts = [r for r in recs if r["event"] == "supervisor_restart"]
        assert [r["attempt"] for r in restarts] == [1, 2]
        assert restarts[0]["exit_class"] == "wedge"
        assert restarts[0]["restored_step"] == 3
        assert restarts[1]["exit_class"] == "crash"
        assert restarts[1]["restored_step"] == 5
        assert all(r["backoff_s"] >= 0 and "time" in r for r in restarts)
        recovered = [r for r in recs
                     if r["event"] == "supervisor_recovered"]
        assert len(recovered) == 1 and recovered[0]["restarts"] == 2

    def test_give_up_event_recorded(self, tmp_path):
        mpath = str(tmp_path / "metrics.jsonl")
        sup, _ = self._sup([1, 1], [5, 5], max_restarts=10,
                           metrics_path=mpath)
        assert sup.run() == 1
        recs = [json.loads(line) for line in open(mpath)]
        give_up = [r for r in recs if r["event"] == "supervisor_give_up"]
        assert len(give_up) == 1
        assert give_up[0]["reason"] == "deterministic-crash"
        assert give_up[0]["restored_step"] == 5

    def test_no_metrics_path_is_quiet(self):
        """Without metrics_path the supervisor writes nothing (and
        doesn't crash trying) — the embedded/test default."""
        sup, _ = self._sup([WEDGED_EXIT_CODE, 0], [4])
        assert sup.run() == 0  # _record no-ops throughout

    def test_preemption_signal_retried(self):
        sup, launches = self._sup([-15, 0], [7])
        assert sup.run() == 0
        assert sup.restarts == 1

    def test_operator_signal_forwarded_not_restarted(self):
        """SIGTERM to the supervisor pid must reach the child and stop
        the loop — not orphan a trainer that keeps the accelerator
        claim while the job looks dead."""
        import signal as signal_mod

        forwarded = []

        class FakeChild:
            def poll(self):
                return None

            def send_signal(self, signum):
                forwarded.append(signum)

        def launch(attempt, env):
            sup._child = FakeChild()
            sup._on_signal(signal_mod.SIGTERM, None)
            sup._child = None
            return -int(signal_mod.SIGTERM)

        sup = Supervisor(["true"], launch=launch, probe_step=lambda: 1,
                         sleep=lambda d: None, max_restarts=5)
        assert sup.run() == 128 + int(signal_mod.SIGTERM)
        assert forwarded == [signal_mod.SIGTERM]
        assert sup.restarts == 0  # stopped, never restarted

    def test_stop_landing_in_spawn_window_still_forwarded(self):
        """A stop recorded between the loop-top check and the child-
        handle assignment (the handler saw _child=None) must reach the
        just-spawned child — not leave it running a full stage inside
        proc.wait()."""
        import signal as signal_mod

        sup = Supervisor(
            [sys.executable, "-c", "import time; time.sleep(30)"],
            sleep=lambda d: None)
        sup._stop_signal = int(signal_mod.SIGTERM)
        t0 = time.monotonic()
        rc = sup._spawn(0, dict(os.environ))
        assert rc == -int(signal_mod.SIGTERM)
        assert time.monotonic() - t0 < 25  # did not sit out the sleep

    def test_signal_during_backoff_cancels_restart(self):
        """A stop landing in the restart-backoff window (no child
        alive to forward to) must cut the wait short and end the loop
        — not be honored only after one more FULL child run."""
        import signal as signal_mod

        launches = []

        def launch(attempt, env):
            launches.append(attempt)
            return WEDGED_EXIT_CODE

        def sleep(d):
            sup._on_signal(signal_mod.SIGTERM, None)

        sup = Supervisor(["true"], launch=launch, probe_step=lambda: None,
                         sleep=sleep, max_restarts=5)
        assert sup.run() == 128 + int(signal_mod.SIGTERM)
        assert launches == [0]  # the stop preempted the relaunch

    def test_subprocess_wedge_exit3_restart(self, tmp_path):
        """The satellite drill: a real child process wedges (tiny
        hang_s watchdog -> exit 3), the supervisor relaunches it, and
        the second attempt succeeds. jax-free and fast."""
        script = f"""
import os, sys, time
sys.path.insert(0, {REPO!r})
from raft_tpu.utils.watchdog import HangWatch
if int(os.environ.get("RAFT_SUPERVISOR_ATTEMPT", "0")) >= 1:
    sys.exit(0)
HangWatch(0.4, label="drill").start()
time.sleep(30)
"""
        sup = Supervisor([sys.executable, "-c", script],
                         max_restarts=2, probe_step=iter([1, 2]).__next__,
                         base_s=0.05, max_s=0.1)
        t0 = time.monotonic()
        assert sup.run() == 0
        assert sup.restarts == 1
        assert time.monotonic() - t0 < 20.0


class TestWatchdogPostmortem:
    def test_wedge_dumps_all_thread_stacks(self):
        script = """
import time
from raft_tpu.utils.watchdog import HangWatch
HangWatch(0.3, label="pm").start()
time.sleep(30)
"""
        r = subprocess.run(
            [sys.executable, "-c", script], capture_output=True,
            text=True, env=dict(os.environ, PYTHONPATH=REPO))
        assert r.returncode == WEDGED_EXIT_CODE
        assert "[watchdog] pm" in r.stderr
        # faulthandler stack dump shows WHERE the process stuck (the
        # watchdog thread is "Current thread"; the wedged main thread's
        # frame is the module-level sleep line)
        assert "Current thread" in r.stderr
        assert 'File "<string>", line 5 in <module>' in r.stderr


class TestDownloadRetry:
    def test_transient_failures_then_success(self, tmp_path, monkeypatch):
        import urllib.request

        from raft_tpu.tools import download_models

        calls = []

        def fake_retrieve(url, dest):
            calls.append(url)
            if len(calls) < 3:
                raise OSError("connection reset")
            open(dest, "wb").write(b"zipbytes")

        monkeypatch.setattr(urllib.request, "urlretrieve", fake_retrieve)
        monkeypatch.setattr(time, "sleep", lambda d: None)
        dest = str(tmp_path / "models.zip")
        assert download_models.download("http://x/models.zip", dest) == dest
        assert len(calls) == 3
        assert open(dest, "rb").read() == b"zipbytes"
        assert not os.path.exists(dest + ".part")

    def test_permanent_failure_raises(self, tmp_path, monkeypatch):
        import urllib.request

        from raft_tpu.tools import download_models

        def always_fail(url, dest):
            raise OSError("refused")

        monkeypatch.setattr(urllib.request, "urlretrieve", always_fail)
        monkeypatch.setattr(time, "sleep", lambda d: None)
        with pytest.raises(OSError, match="refused"):
            download_models.download("http://x/m.zip",
                                     str(tmp_path / "m.zip"))


class TestCurriculumRestart:
    def _run(self, tmp_path, monkeypatch, stages, pre_done=(), **kw):
        from raft_tpu.training import trainer

        calls = []
        ckpt = str(tmp_path / "ckpt")
        os.makedirs(ckpt, exist_ok=True)

        def fake_train(model_cfg, cfg, resume=False, loader=None):
            calls.append((cfg.stage, resume, cfg.restore_ckpt))
            open(os.path.join(ckpt, f"{cfg.name}.msgpack"), "wb").write(b"w")

        monkeypatch.setattr(trainer, "train", fake_train)
        for stage in pre_done:
            open(os.path.join(ckpt, f"c-{stage}.msgpack"), "wb").write(b"w")
        from raft_tpu.config import RAFTConfig

        trainer.train_curriculum(stages, RAFTConfig(small=True), name="c",
                                 checkpoint_dir=ckpt, **kw)
        return calls, ckpt

    def test_completed_stage_skipped_and_chained(self, tmp_path,
                                                 monkeypatch, capsys):
        calls, ckpt = self._run(tmp_path, monkeypatch,
                                ["chairs", "things"], pre_done=["chairs"])
        # chairs not retrained; things restores chairs' existing final
        assert [c[0] for c in calls] == ["things"]
        assert calls[0][2] == os.path.join(ckpt, "c-chairs.msgpack")
        assert "skipping" in capsys.readouterr().out

    def test_in_progress_stage_gets_resume(self, tmp_path, monkeypatch):
        calls, _ = self._run(tmp_path, monkeypatch, ["chairs", "things"])
        assert [(c[0], c[1]) for c in calls] == [("chairs", True),
                                                 ("things", True)]

    def test_resume_false_retrains_everything(self, tmp_path, monkeypatch):
        calls, _ = self._run(tmp_path, monkeypatch, ["chairs"],
                             pre_done=["chairs"], resume=False)
        assert [(c[0], c[1]) for c in calls] == [("chairs", False)]

    def test_corrupt_final_retrained_not_skipped(self, tmp_path,
                                                 monkeypatch, capsys):
        """An existing final that fails its integrity manifest must not
        be trusted by the skip shortcut: the next stage's load would
        reject it at startup on every restart — a permanently wedged
        curriculum. Quarantine it and retrain the stage instead."""
        from raft_tpu.config import RAFTConfig
        from raft_tpu.tools.convert import manifest_path
        from raft_tpu.training import trainer

        calls = []
        ckpt = str(tmp_path / "ckpt")
        os.makedirs(ckpt)
        final = os.path.join(ckpt, "c-chairs.msgpack")
        open(final, "wb").write(b"rotten final")
        open(manifest_path(final), "w").write("0" * 64 + " 999\n")

        def fake_train(model_cfg, cfg, resume=False, loader=None):
            calls.append(cfg.stage)
            open(os.path.join(ckpt, f"{cfg.name}.msgpack"),
                 "wb").write(b"w")

        monkeypatch.setattr(trainer, "train", fake_train)
        trainer.train_curriculum(["chairs"], RAFTConfig(small=True),
                                 name="c", checkpoint_dir=ckpt)
        assert calls == ["chairs"]  # retrained, not skipped
        names = os.listdir(ckpt)
        # the bad final (and its stale sidecar) moved aside; the
        # retrained final sits under the real name
        assert "c-chairs.msgpack.corrupt" in names
        assert "c-chairs.msgpack.corrupt.sha256" in names
        assert open(final, "rb").read() == b"w"
        assert "retraining the stage" in capsys.readouterr().out

    def test_env_read_error_on_final_surfaces_not_quarantined(
            self, tmp_path, monkeypatch):
        """An environmental read failure (EIO on a flaky mount — here
        simulated by a directory under the final's name) is not
        evidence against the artifact: it must surface as an error,
        not quarantine an intact multi-day final and retrain."""
        from raft_tpu.config import RAFTConfig
        from raft_tpu.training import trainer

        calls = []
        ckpt = str(tmp_path / "ckpt")
        os.makedirs(os.path.join(ckpt, "c-chairs.msgpack"))
        monkeypatch.setattr(
            trainer, "train",
            lambda *a, **kw: calls.append(kw) or None)
        with pytest.raises(OSError):
            trainer.train_curriculum(["chairs"], RAFTConfig(small=True),
                                     name="c", checkpoint_dir=ckpt)
        assert calls == []  # no retrain on an environmental error
        assert not [n for n in os.listdir(ckpt) if ".corrupt" in n]


class TestTrainCLISupervise:
    def test_parser_exposes_robustness_knobs(self):
        from raft_tpu.cli.train import build_parser, configs_from_args

        args = build_parser().parse_args(
            ["--stage", "chairs", "--hang_s", "120", "--supervise",
             "--max_restarts", "7"])
        assert args.supervise and args.max_restarts == 7
        _, tcfg = configs_from_args(args)
        assert tcfg.hang_s == 120.0
        # default stays disabled (the stable contract)
        _, tcfg0 = configs_from_args(
            build_parser().parse_args(["--stage", "chairs"]))
        assert tcfg0.hang_s == 0.0

    def test_abbreviated_flags_rejected(self, capsys):
        """allow_abbrev must stay off: an accepted --superv would
        survive _strip_flag into the child argv and re-enter the
        supervisor in every child, recursing forever."""
        from raft_tpu.cli.train import build_parser

        with pytest.raises(SystemExit):
            build_parser().parse_args(["--stage", "chairs", "--superv"])
        capsys.readouterr()  # swallow argparse usage noise

    def test_supervise_builds_resumed_child(self, tmp_path, monkeypatch):
        """--supervise must relaunch THIS cli minus the supervisor flags,
        with --resume forced, probing the right stage dir."""
        import raft_tpu.training.supervisor as sup_mod
        from raft_tpu.cli import train as cli_train

        captured = {}

        class FakeSup:
            def __init__(self, argv, **kw):
                captured["argv"] = argv
                captured.update(kw)

            def run(self):
                return 0

        monkeypatch.setattr(sup_mod, "Supervisor", FakeSup)
        argv = ["--stage", "chairs", "--name", "n", "--supervise",
                "--max_restarts", "2",
                "--checkpoint_dir", str(tmp_path)]
        with pytest.raises(SystemExit) as ei:
            cli_train.main(argv)
        assert ei.value.code == 0
        child = captured["argv"]
        assert child[:3] == [sys.executable, "-m", "raft_tpu.cli.train"]
        tail = child[3:]
        assert "--supervise" not in tail and "--max_restarts" not in tail
        assert "2" not in tail  # the flag's value went with it
        assert tail[-1] == "--resume"
        assert captured["max_restarts"] == 2
        assert captured["ckpt_dir"] == os.path.join(str(tmp_path), "n",
                                                    "chairs")
        # restart events land in the SAME file the trainer's Logger
        # writes (trainer.py: Logger(join(log_dir, name))) — a
        # dashboard tailing the curves sees the restarts too
        assert captured["metrics_path"] == os.path.join(
            "runs", "n", "metrics.jsonl")


@pytest.mark.slow  # ~190 s (three subprocess training runs + a real
# 20 s watchdog wedge) — far past the tier-1 budget on the 2-core CI
# host. The tier-1 fault matrix above covers every mechanism this
# composes; run the full drill explicitly:
#   pytest tests/test_fault_tolerance.py -m slow
class TestSupervisedEndToEnd:
    def test_wedge_plus_corruption_resume_parity(self, tmp_path,
                                                 monkeypatch):
        """The acceptance drill: fault plan wedges attempt 0 at step 4
        (watchdog exit 3) after corrupting the step-3 checkpoint; the
        supervisor restarts, resume quarantines the corrupt step and
        falls back to step 1, and the finished weights are bitwise
        identical to an uninterrupted control run."""
        runs = str(tmp_path / "runs")
        base = [sys.executable, WORKER, "--log-dir", runs,
                "--num-steps", "4"]
        ctl_dir, sup_dir = str(tmp_path / "ctl"), str(tmp_path / "sup")

        # control run doubles as the compile-cache warmer for the
        # supervised children (same program, persistent cache)
        r = subprocess.run(base + ["--ckpt-dir", ctl_dir, "--name", "ctl"],
                           capture_output=True, text=True)
        assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])

        plan = [{"site": "ckpt.orbax_save", "at": 2, "kind": "corrupt",
                 "attempt": 0},
                {"site": "trainer.step", "at": 4, "kind": "hang",
                 "attempt": 0}]
        monkeypatch.setenv("RAFT_FAULT_PLAN", json.dumps(plan))
        stage_dir = os.path.join(sup_dir, "sup", "chairs")
        sup = Supervisor(
            base + ["--ckpt-dir", sup_dir, "--name", "sup",
                    "--hang-s", "20", "--resume"],
            max_restarts=3, ckpt_dir=stage_dir, base_s=0.2, max_s=0.5)
        assert sup.run() == 0
        # >= 1, not == 1: under CPU contention a resumed child can eat
        # an extra (benign) watchdog restart and still recover — the
        # parity and quarantine asserts below are the real acceptance
        assert sup.restarts >= 1

        # the corrupt step-3 checkpoint was quarantined during resume
        assert any(n.endswith(".corrupt") for n in os.listdir(stage_dir))
        # ... and rewritten intact by the resumed run
        assert latest_step_on_disk(stage_dir) == 3

        ctl = open(os.path.join(ctl_dir, "ctl.msgpack"), "rb").read()
        spv = open(os.path.join(sup_dir, "sup.msgpack"), "rb").read()
        assert ctl == spv  # restored-state parity, bitwise
