"""tools/hlo_attr.py: fusion -> source-op attribution parsing.

Hermetic: parses a synthetic after-optimizations HLO text (the format the
tool consumes is XLA's dump; the fixture mirrors the lines that matter —
fusion defs with kind/calls/metadata and fused-computation bodies).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import hlo_attr  # noqa: E402

_HLO = """\
HloModule jit_step, entry_computation_layout={()->f32[]}

%fused_computation.1 (p0: bf16[8,64]) -> bf16[8,64] {
  %p0 = bf16[8,64]{1,0} parameter(0)
  %c = bf16[8,64]{1,0} convert(%p0), metadata={op_name="jit(step)/while/body/convert"}
  ROOT %a = bf16[8,64]{1,0} add(%c, %c), metadata={op_name="jit(step)/while/body/add_any"}
}

%fused_computation.2 (p1: f32[4]) -> f32[4] {
  %p1 = f32[4]{0} parameter(0)
  ROOT %m = f32[4]{0} multiply(%p1, %p1)
}

%fused_computation.3 (p2: f32[4]) -> f32[4] {
  %p2 = f32[4]{0} parameter(0)
  %n = f32[4]{0} negate(%p2), metadata={op_name="jit(step)/while/body/neg"}
  %s = f32[4]{0} subtract(%n, %p2), metadata={op_name="jit(step)/while/body/sub"}
  ROOT %a2 = f32[4]{0} add(%s, %n), metadata={op_name="jit(step)/while/body/sub"}
}

ENTRY %main () -> f32[] {
  %x = bf16[8,64]{1,0} parameter(0)
  %add_convert_fusion.7 = bf16[8,64]{1,0} fusion(%x), kind=kLoop, calls=%fused_computation.1, metadata={op_name="jit(step)/transpose(jvp())/while/body"}
  %loop_convert_convolution_add_reduce_fusion.123 = (f32[2]{0}, f32[4]{0}) fusion(%x), kind=kOutput, calls=%fused_computation.2, metadata={op_name="jit(step)/while/body/conv_general_dilated"}
  %fusion.41 = f32[4]{0} fusion(%x), kind=kLoop, calls=%fused_computation.3
  ROOT %fusion.33 = f32[4]{0} fusion(%x), kind=kOutput, calls=%fused_computation.2
}
"""


def _write(tmp_path):
    p = tmp_path / "module_0001.jit_step.tpu_after_optimizations.txt"
    p.write_text(_HLO)
    return str(tmp_path)


def test_parse_fusions_metadata_and_kind(tmp_path):
    d = _write(tmp_path)
    fusions = hlo_attr.parse_fusions(os.path.join(
        d, "module_0001.jit_step.tpu_after_optimizations.txt"))
    assert set(fusions) == {"add_convert_fusion.7", "fusion.33", "fusion.41",
                            "loop_convert_convolution_add_reduce_fusion.123"}
    tup = fusions["loop_convert_convolution_add_reduce_fusion.123"]
    assert tup["shape"] == "(f32[2]{0}, f32[4]{0})"
    assert tup["op_name"] == "jit(step)/while/body/conv_general_dilated"
    f7 = fusions["add_convert_fusion.7"]
    assert f7["kind"] == "kLoop"
    assert f7["op_name"] == "jit(step)/transpose(jvp())/while/body"
    assert f7["calls"] == "fused_computation.1"
    assert f7["body_lines"] == 3


def test_body_fallback_when_root_has_no_metadata(tmp_path):
    d = _write(tmp_path)
    fusions = hlo_attr.parse_fusions(os.path.join(
        d, "module_0001.jit_step.tpu_after_optimizations.txt"))
    # fusion.33's def line carries no metadata and its body has none
    # either -> stays unattributed (no crash)
    assert fusions["fusion.33"]["op_name"] == "(no metadata)"
    # fusion.41's def line has no metadata but its body does -> the
    # most-frequent body op_name wins (sub appears twice, neg once)
    assert fusions["fusion.41"]["op_name"] == "(body) jit(step)/while/body/sub"


def test_missing_dump_dir_is_not_a_traceback(tmp_path, capsys):
    assert hlo_attr.main([str(tmp_path / "no-such-dir")]) == 1
    assert "after_optimizations" in capsys.readouterr().err


def test_main_substring_match_and_top(tmp_path, capsys):
    d = _write(tmp_path)
    # a 48-char-truncated paste from trace_summary (tail cut off) must
    # still match via substring
    truncated = "loop_convert_convolution_add_reduce_fusion.123"[:40]
    assert hlo_attr.main([d, "fusion.7", truncated, "--top", "2"]) == 0
    out = capsys.readouterr().out
    assert "add_convert_fusion.7" in out
    assert "jit(step)/transpose(jvp())/while/body" in out
    assert "loop_convert_convolution_add_reduce_fusion.123" in out
    assert "# top 2 fusions" in out


def test_main_missing_dump_dir_errors(tmp_path, capsys):
    assert hlo_attr.main([str(tmp_path)]) == 1
    assert "after_optimizations" in capsys.readouterr().err
