"""R2 negative: jnp math, dtype/constant np attributes, prints outside
the traced body — all allowed."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def clean(x):
    y = jnp.mean(x.astype(np.float32))   # np dtype attr is fine
    return jnp.sqrt(y) + np.pi           # np constant is fine


def host_side(x):
    print("host logging is fine here", np.mean(x))
    return clean(jnp.asarray(x))
