"""R2 positive: np.* math and print on traced values inside jit."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def leaky(x):
    m = np.mean(x)              # host math on a tracer
    print("loss is", m)         # fires at trace time only
    return jnp.sum(x) - m


def also_leaky():
    return jax.jit(lambda x: np.sqrt(x) + 1.0)
