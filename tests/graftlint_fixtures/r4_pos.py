"""R4 positive: state threaded through jit without donation."""
import jax
import jax.numpy as jnp


def make_step():
    def train_step(state, batch):
        return state, {"loss": jnp.sum(batch)}

    # the old state stays live while the new one materializes: 2x HBM
    return jax.jit(train_step)


accumulate = jax.jit(lambda opt_state, g: opt_state + g)


@jax.jit
def apply_updates(train_state, grads):
    return train_state + grads
