"""R1 negative: the sanctioned periodic-flush pattern (trainer.py).

Syncs exist but only OUTSIDE jit bodies and either outside the hot
loop or under a cadence guard. Never executed — parsed only.
"""
import jax
import jax.numpy as jnp


@jax.jit
def good_jitted(x):
    return jnp.sum(x * 2.0)


def good_hot_loop(step_inputs, state, batch, rng, sum_freq=100):
    step_fn = jax.jit(lambda s, b, r: (s, {"loss": jnp.sum(b)}))
    total = 0
    for _ in step_inputs:
        state, metrics = step_fn(state, batch, rng)
        total += 1
        if total % sum_freq == sum_freq - 1:
            # periodic flush under a cadence guard — allowed
            sums = jax.device_get(metrics)
            print(sums)
    # fetch AFTER the loop fences the whole chain — allowed
    return float(jax.device_get(metrics["loss"]))
