"""R5 positive — distilled from the PRE-FIX round-5 advisor findings:

- trainer.py:111/212 before this PR: hang_watch.stop() only on the
  normal-return path, so an exception left the armed daemon alive to
  os._exit the host process later;
- a daemon thread armed in a plain function with no try/finally.
"""
import threading

from raft_tpu.utils.watchdog import HangWatch


def prefix_trainer_shape(train_cfg, run_steps):
    hang_watch = HangWatch(train_cfg.hang_s, label="train loop")
    hang_watch.start()
    run_steps()                 # raises -> stop() never runs
    hang_watch.stop()
    return True


def prefix_bench_shape(watch_fn):
    t = threading.Thread(target=watch_fn, daemon=True)
    t.start()
    return t
