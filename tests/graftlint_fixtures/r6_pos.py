"""R6 positive — distilled from the pre-fix bench.py:175: the wedge
watchdog exited 2 while the trainer's watchdog exited
WEDGED_EXIT_CODE=3, splitting one failure mode across two codes."""
import os
import sys


def prefix_bench_shape(emit):
    emit("backend_wedged", 0.0)
    os._exit(2)


def distinctive_sys_exit():
    sys.exit(7)
