"""R3 negative: jit hoisted out of the loop, hashable static args."""
import jax


def compile_once_run_many(batches, scale):
    fn = jax.jit(lambda x: x * scale)       # one cache entry
    outs = []
    for b in batches:
        outs.append(fn(b))
    return outs


def hashable_static(x):
    fn = jax.jit(lambda a, cfg: a * cfg[0], static_argnums=(1,))
    return fn(x, (2.0, 3.0))    # tuple hashes — a valid cache key
