"""R1 positive: host syncs in a jit body and unguarded in a hot loop.

Never executed — parsed by tests/test_graftlint.py only.
"""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def bad_jitted(x):
    # concretizes the tracer at trace time
    host = np.asarray(x)
    return jnp.sum(x) + float(host.mean())


def bad_hot_loop(step_inputs, state, batch, rng):
    step_fn = jax.jit(lambda s, b, r: (s, {"loss": jnp.sum(b)}))
    for _ in step_inputs:
        state, metrics = step_fn(state, batch, rng)
        loss = float(metrics["loss"])        # unconditional D2H per step
        jax.block_until_ready(state)         # ditto
    return loss
