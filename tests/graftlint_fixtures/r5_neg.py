"""R5 negative: stop() in a finally; lifecycle owned by a class."""
import threading

from raft_tpu.utils.watchdog import HangWatch


def fixed_trainer_shape(train_cfg, run_steps):
    hang_watch = HangWatch(train_cfg.hang_s, label="train loop")
    hang_watch.start()
    try:
        run_steps()
    finally:
        hang_watch.stop()       # exception path disarms the daemon
    return True


class OwnsItsThread:
    """The HangWatch shape: arming inside a class that exposes stop()."""

    def __init__(self):
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._stop.wait,
                                        daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
