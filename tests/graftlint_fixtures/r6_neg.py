"""R6 negative: named shared constant, conventional 0/1 sys.exit."""
import os
import sys

from raft_tpu.utils.watchdog import WEDGED_EXIT_CODE


def fixed_bench_shape(emit):
    emit("backend_wedged", 0.0)
    os._exit(WEDGED_EXIT_CODE)


def main():
    return 0


if __name__ == "__main__":
    sys.exit(main())            # propagating a computed code is fine
    sys.exit(1)                 # conventional failure is fine
