"""R3 positive: jit created per loop iteration + unhashable static arg."""
import jax
import jax.numpy as jnp


def retrace_per_iteration(batches, scale):
    outs = []
    for b in batches:
        fn = jax.jit(lambda x: x * scale)   # fresh lambda every pass
        outs.append(fn(b))
    return outs


def retrace_in_comprehension(batches):
    return [jax.jit(lambda x: x + 1.0)(b) for b in batches]


def unhashable_static(x):
    fn = jax.jit(lambda a, cfg: a * cfg[0], static_argnums=(1,))
    return fn(x, [2.0, 3.0])    # list in a static position: unhashable
