"""R4 negative: donated state, and non-state first params undonated."""
from functools import partial

import jax
import jax.numpy as jnp


def make_step():
    def train_step(state, batch):
        return state, {"loss": jnp.sum(batch)}

    return jax.jit(train_step, donate_argnums=(0,))


accumulate = jax.jit(lambda opt_state, g: opt_state + g,
                     donate_argnums=(0,))


@partial(jax.jit, donate_argnums=(0,))
def apply_updates(train_state, grads):
    return train_state + grads


# weights are REUSED across calls — donation would be a bug here, and
# the rule must not demand it for non-state first params
serve = jax.jit(lambda variables, image: variables["w"] * image)
