"""The timing harness is the foundation of every performance number this
repo reports (BENCH_NOTES.md documents the three wrong schemes it
replaced), so its anti-dead-code property is pinned at the HLO level: a
backward pass inside the timed function must survive XLA optimization.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp

from raft_tpu.utils.timing import chain_timed, chained_scan


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def _marked(x):
    return x * 2.0


def _marked_fwd(x):
    return x * 2.0, x


def _marked_bwd(res, g):
    # 'atan2' is distinctive and survives into optimized HLO by name; it
    # appears nowhere else in the scanned program
    return (g * jnp.arctan2(res, res + 1.0),)


_marked.defvjp(_marked_fwd, _marked_bwd)


def _grad_fn(x):
    return jax.value_and_grad(lambda v: jnp.sum(_marked(v) ** 2))(x)


def test_backward_survives_in_compiled_hlo():
    x = jnp.ones((4, 8), jnp.float32)
    scanned = chained_scan(_grad_fn, iters=3)
    hlo = scanned.lower(x).compile().as_text()
    assert "atan2" in hlo, (
        "backward pass was dead-code-eliminated from the timed scan — "
        "grad-mode timings would silently measure forward only")


def test_primal_only_nudge_would_fail():
    """Counter-test: the naive scheme (nudge from the primal leaf only)
    really does lose the backward — guards against someone 'simplifying'
    chained_scan back to it."""
    x = jnp.ones((4, 8), jnp.float32)

    def step(c, _):
        val, _grads = _grad_fn(c)
        return c + (jnp.mean(val) * 1e-12).astype(c.dtype), ()

    naive = jax.jit(
        lambda c: jnp.ravel(jax.lax.scan(step, c, None, length=3)[0])[0])
    hlo = naive.lower(x).compile().as_text()
    assert "atan2" not in hlo, (
        "XLA stopped eliminating the unused backward; the counter-test "
        "no longer demonstrates the hazard (harmless, but re-check "
        "chained_scan's rationale)")


def test_chain_timed_runs_and_returns_positive():
    dt = chain_timed(lambda x: x * 1.5, jnp.ones((8, 8), jnp.float32),
                     iters=2)
    assert dt > 0.0 and np.isfinite(dt)
