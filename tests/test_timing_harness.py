"""The timing harness is the foundation of every performance number this
repo reports (BENCH_NOTES.md documents the three wrong schemes it
replaced), so its anti-dead-code property is pinned at the HLO level: a
backward pass inside the timed function must survive XLA optimization.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp

from raft_tpu.utils.timing import chain_timed, chained_scan


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def _marked(x):
    return x * 2.0


def _marked_fwd(x):
    return x * 2.0, x


def _marked_bwd(res, g):
    # 'atan2' is distinctive and survives into optimized HLO by name; it
    # appears nowhere else in the scanned program
    return (g * jnp.arctan2(res, res + 1.0),)


_marked.defvjp(_marked_fwd, _marked_bwd)


def _grad_fn(x):
    return jax.value_and_grad(lambda v: jnp.sum(_marked(v) ** 2))(x)


def test_backward_survives_in_compiled_hlo():
    x = jnp.ones((4, 8), jnp.float32)
    scanned = chained_scan(_grad_fn, iters=3)
    hlo = scanned.lower(x).compile().as_text()
    assert "atan2" in hlo, (
        "backward pass was dead-code-eliminated from the timed scan — "
        "grad-mode timings would silently measure forward only")


def test_primal_only_nudge_would_fail():
    """Counter-test: the naive scheme (nudge from the primal leaf only)
    really does lose the backward — guards against someone 'simplifying'
    chained_scan back to it."""
    x = jnp.ones((4, 8), jnp.float32)

    def step(c, _):
        val, _grads = _grad_fn(c)
        return c + (jnp.mean(val) * 1e-12).astype(c.dtype), ()

    naive = jax.jit(
        lambda c: jnp.ravel(jax.lax.scan(step, c, None, length=3)[0])[0])
    hlo = naive.lower(x).compile().as_text()
    assert "atan2" not in hlo, (
        "XLA stopped eliminating the unused backward; the counter-test "
        "no longer demonstrates the hazard (harmless, but re-check "
        "chained_scan's rationale)")


def test_chain_timed_runs_and_returns_positive():
    dt = chain_timed(lambda x: x * 1.5, jnp.ones((8, 8), jnp.float32),
                     iters=2)
    assert dt > 0.0 and np.isfinite(dt)


def test_invariants_lower_as_parameters_not_constants():
    """Arrays the timed fn reads must ride as jit parameters. A closure
    would embed them in the HLO as literal constants — on the remote TPU
    backend a large embedded operand is rejected outright by the compile
    endpoint (HTTP 413 at ~750 MB observed on-chip), and it bloats every
    upload before that. Pinned at the lowered-HLO level: a 4 MB invariant
    must not appear in the program text."""
    # random data: a constant-foldable pattern (ones, iota) would lower
    # as a broadcast/iota and dodge the embedding either way
    big = jnp.asarray(
        np.random.RandomState(0).rand(1 << 20).astype(np.float32))  # 4 MB
    scanned = chained_scan(lambda c, v: jnp.sum(v) * c, iters=2)
    txt = scanned.lower(jnp.float32(1.0), big).as_text()
    assert len(txt) < 100_000, (
        f"invariant embedded as an HLO constant ({len(txt)} bytes of "
        "program text) — it must be a parameter")

    # counter-test: the closure form really does embed it (big * c keeps
    # the array in the graph — a concrete-only expression like
    # jnp.sum(big) would constant-fold to a scalar during tracing)
    closed = chained_scan(lambda c: big * c, iters=2)
    txt_closed = closed.lower(jnp.float32(1.0)).as_text()
    assert len(txt_closed) > 1_000_000, (
        "XLA stopped embedding closure constants; the invariants "
        "machinery may no longer be necessary (harmless, but re-check "
        "timing.py's rationale)")
