"""trace_summary's gviz parsing + report rollup, on a synthetic table
shaped like xprof's hlo_stats output (the real conversion needs an
on-accelerator XPlane capture; the parse/report layer is what must not
break between captures) — plus the missing-xprof surface: the lazy
converter import must exit with an actionable install message, never
a raw mid-function ImportError."""

import sys

import pytest

import raft_tpu.cli.trace_summary as ts


GVIZ = {
    "cols": [{"id": "category"}, {"id": "hlo_op_name"},
             {"id": "occurrences"}, {"id": "total_self_time"},
             {"id": "total_self_time_percent"}, {"id": "bound_by"},
             {"id": "measured_memory_bw"}],
    "rows": [
        {"c": [{"v": "convolution"}, {"v": "conv.1"}, {"v": 24},
               {"v": 1000.0}, {"v": 50.0}, {"v": "compute"}, {"v": 400.0}]},
        {"c": [{"v": "fusion"}, {"v": "fusion.7"}, {"v": 12},
               {"v": 600.0}, {"v": 30.0}, {"v": "memory"}, {"v": 120.0}]},
        {"c": [{"v": "convolution"}, {"v": "conv.2"}, {"v": 24},
               {"v": 400.0}, {"v": 20.0}, {"v": "compute"}, None]},
    ],
}


def test_parse_gviz_rows():
    rows = ts.parse_gviz(GVIZ)
    assert len(rows) == 3
    assert rows[0]["hlo_op_name"] == "conv.1"
    assert rows[2]["measured_memory_bw"] is None  # tolerated by report


def test_report_rollup_and_order(capsys):
    ts.report(ts.parse_gviz(GVIZ), top=2)
    out = capsys.readouterr().out
    assert "total 2,000 us" in out
    # convolution (1400) must lead the rollup, conv.1 the top table
    roll, topn = out.split("== top 2 ops")
    assert roll.index("convolution") < roll.index("fusion")
    assert "conv.1" in topn and "conv.2" not in topn


def test_report_empty(capsys):
    ts.report([], top=5)
    assert "no device op rows" in capsys.readouterr().out


def test_no_xplane_files_exit(tmp_path):
    with pytest.raises(SystemExit, match="no .*xplane"):
        ts._load_hlo_stats(str(tmp_path))


def test_missing_xprof_exits_with_install_hint(tmp_path, monkeypatch):
    """This environment has no xprof — and even where one is
    installed, the poisoned sys.modules entry forces the import
    failure: the tool must exit with the install hint, not crash with
    a bare ImportError after the glob already succeeded."""
    trace_dir = tmp_path / "trace"
    trace_dir.mkdir()
    (trace_dir / "host.xplane.pb").write_bytes(b"\x00")
    monkeypatch.setitem(sys.modules, "xprof", None)
    with pytest.raises(SystemExit) as excinfo:
        ts._load_hlo_stats(str(trace_dir))
    msg = str(excinfo.value)
    assert "xprof" in msg.lower()
    assert "pip install" in msg        # actionable, not a traceback
