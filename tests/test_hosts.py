"""Multi-host fleet acceptance (ISSUE 18): the transport seam (wire
protocol, corruption -> clean ``TransportError``, the length guard),
the worker contracts (pre-warm-before-traffic gating, sha256-verified
idempotent artifact push), ``AOTCache.push`` retrying a corrupted
transfer into a byte-identical landing, the missed-beat liveness
ladder (injectable clock: healthy -> suspect -> dead, verdict notice
queued exactly once, breaker-paced reconnect -> full rejoin protocol),
the scheduler's failover discipline over loopback host lanes (dead
host's in-flight batch re-dispatches to survivors; every future
settles exactly once, accounting identity intact, results bitwise),
the ``hosts=0`` bitwise-PR-17 pin, and the real drills: SIGKILL one of
two subprocess workers mid-traffic (stub stack and the real
RAFTEngine/AOT-push stack), with the restarted worker rejoining via
verified artifact push and ZERO XLA compiles."""

import json
import os
import pickle
import random
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from tests.host_worker import StubEngine
from tests.test_scheduler import _wait_for

from raft_tpu.serving.aot import AOTCache
from raft_tpu.serving.hosts import (HOST_DEAD, HOST_HEALTHY,
                                    HOST_SUSPECT, HostFleet, HostWorker)
from raft_tpu.serving.metrics import ServingMetrics
from raft_tpu.serving.scheduler import ConfigError, MicroBatchScheduler
from raft_tpu.serving.transport import (MAX_MESSAGE_BYTES, _LEN,
                                        LoopbackTransport,
                                        SocketTransport, TransportError,
                                        _recv_msg, serve_forever)
from raft_tpu.testing import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm_after():
    yield
    faults.disarm()


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def _pairs(n, seed=0, h=32, w=32):
    rs = np.random.RandomState(seed)
    return [(rs.rand(h, w, 3).astype(np.float32) * 255,
             rs.rand(h, w, 3).astype(np.float32) * 255)
            for _ in range(n)]


def _stub_oracle(a, b):
    return ((a - b)[..., :2] * 0.125).astype(np.float32)


def _events(mpath):
    if not os.path.exists(mpath):
        return []
    return [json.loads(line)["event"] for line in open(mpath)
            if json.loads(line).get("kind") == "serving_event"]


def _accounting_ok(snap):
    return snap["submitted"] == (snap["completed"] + snap["failed"]
                                 + snap["deadline_missed"]
                                 + snap["cancelled"])


def _host_lane_block(sched, name):
    for blk in sched.health()["fleet"]["lanes"].values():
        if blk.get("host") == name:
            return blk
    raise AssertionError(f"no lane carries host {name}")


# -- the transport seam ----------------------------------------------------


class TestTransport:
    def test_loopback_roundtrip_error_close_reopen(self):
        t = LoopbackTransport(HostWorker(StubEngine()))
        r = t.call("ping")
        assert r == {"seq": 1, "ready": True}
        # worker-side exceptions come back as clean error replies
        with pytest.raises(TransportError, match="worker error"):
            t.call("definitely_not_a_method")
        t.close()
        assert t.closed
        with pytest.raises(TransportError, match="closed"):
            t.call("ping")
        # reopen targets the SAME worker object (state preserved)
        assert t.reopen().call("ping")["seq"] == 2

    def test_send_corruption_reads_as_transport_error(self):
        t = LoopbackTransport(HostWorker(StubEngine()))
        faults.arm([{"site": "transport.send", "kind": "corrupt",
                     "count": 1}])
        with pytest.raises(TransportError, match="corrupted"):
            t.call("ping")
        # exhausted plan: the retry is clean
        assert t.call("ping")["ready"] is True

    def test_recv_corruption_reads_as_transport_error(self):
        t = LoopbackTransport(HostWorker(StubEngine()))
        faults.arm([{"site": "transport.recv", "kind": "corrupt",
                     "count": 1}])
        with pytest.raises(TransportError, match="corrupted"):
            t.call("ping")
        assert t.call("ping")["ready"] is True

    def test_length_guard_rejects_corrupt_prefix(self):
        a, b = socket.socketpair()
        try:
            a.sendall(_LEN.pack(MAX_MESSAGE_BYTES + 1))
            with pytest.raises(TransportError, match="length"):
                _recv_msg(b)
        finally:
            a.close()
            b.close()

    def test_socket_transport_roundtrip(self):
        ready = _ReadyPort()
        threading.Thread(
            target=serve_forever, args=(0, HostWorker(StubEngine())),
            kwargs={"ready_fh": ready}, daemon=True).start()
        assert ready.evt.wait(10.0)
        t = SocketTransport("127.0.0.1", ready.port, call_timeout_s=10)
        try:
            assert t.call("ping")["ready"] is True
            assert t.call("stats")["ready"] is True
            with pytest.raises(TransportError, match="worker error"):
                t.call("nope")
            # the stream survived the error reply: still usable
            assert t.call("ping")["seq"] == 2
        finally:
            t.close()


class _ReadyPort:
    """serve_forever's ready_fh hook, captured to an event."""

    def __init__(self):
        self.evt = threading.Event()
        self.port = None

    def write(self, s):
        self.port = int(s.split()[1])

    def flush(self):
        self.evt.set()


# -- the worker: pre-warm gating + verified artifact push ------------------


class TestHostWorker:
    def test_prewarm_gates_all_traffic(self):
        t = LoopbackTransport(HostWorker(
            engine_factory=lambda: StubEngine()))
        assert t.call("ping")["ready"] is False
        for method, payload in [
                ("capacity", {"h": 32, "w": 32}),
                ("infer", {"image1": np.zeros((1, 32, 32, 3),
                                              np.float32),
                           "image2": np.zeros((1, 32, 32, 3),
                                              np.float32)})]:
            with pytest.raises(TransportError, match="not prewarmed"):
                t.call(method, payload)
        stats = t.call("prewarm")
        assert stats["compiles"] == 0
        assert t.call("ping")["ready"] is True
        flow = t.call("infer",
                      {"image1": np.ones((1, 32, 32, 3), np.float32),
                       "image2": np.zeros((1, 32, 32, 3), np.float32)})
        assert flow.shape == (1, 32, 32, 2)

    def _artifact(self, n=256, seed=7):
        import hashlib

        blob = np.random.RandomState(seed).bytes(n)
        sha = hashlib.sha256(blob).hexdigest()
        manifest = {"format": "test", "key": {"k": 1}, "sha256": sha,
                    "blob_bytes": n}
        return blob, sha, json.dumps(manifest).encode("utf-8")

    def test_put_artifact_verifies_before_any_byte_lands(self, tmp_path):
        w = HostWorker(StubEngine(), aot_root=str(tmp_path / "aot"))
        blob, sha, mb = self._artifact()
        with pytest.raises(ValueError, match="mismatch"):
            w.handle("put_artifact",
                     {"digest": "d0", "blob": blob, "manifest": mb,
                      "sha256": "0" * 64})
        with pytest.raises(ValueError, match="disagree"):
            w.handle("put_artifact",
                     {"digest": "d0", "blob": blob, "sha256": sha,
                      "manifest": json.dumps(
                          {"sha256": "f" * 64}).encode("utf-8")})
        # nothing landed from the rejected pushes
        assert not os.path.exists(
            os.path.join(w.aot_root, "objects", "d0"))
        reply = w.handle("put_artifact",
                         {"digest": "d0", "blob": blob, "manifest": mb,
                          "sha256": sha})
        assert reply == {"sha256": sha, "bytes": len(blob)}
        edir = os.path.join(w.aot_root, "objects", "d0")
        assert open(os.path.join(edir, "executable.bin"),
                    "rb").read() == blob
        assert open(os.path.join(edir, "manifest.json"),
                    "rb").read() == mb
        # idempotent re-push (the retry-after-corruption path)
        assert w.handle("put_artifact",
                        {"digest": "d0", "blob": blob, "manifest": mb,
                         "sha256": sha}) == reply

    def test_aot_push_retries_corruption_into_identical_bytes(
            self, tmp_path):
        src = AOTCache(str(tmp_path / "src"))
        blob, sha, mb = self._artifact(n=512)
        edir = os.path.join(src.objects, "d" + "0" * 63)
        os.makedirs(edir)
        with open(os.path.join(edir, "executable.bin"), "wb") as fh:
            fh.write(blob)
        with open(os.path.join(edir, "manifest.json"), "wb") as fh:
            fh.write(mb)
        # a torn entry (no manifest) must be skipped, never shipped
        os.makedirs(os.path.join(src.objects, "torn"))
        with open(os.path.join(src.objects, "torn", "executable.bin"),
                  "wb") as fh:
            fh.write(b"half")
        w = HostWorker(StubEngine(), aot_root=str(tmp_path / "dst"))
        t = LoopbackTransport(w)
        faults.arm([{"site": "transport.send", "kind": "corrupt",
                     "count": 1}])
        out = src.push(t, attempts=3, base_s=0.0,
                       rng=random.Random(0), sleep=lambda s: None)
        assert out == {"entries": 1, "bytes": len(blob), "retries": 1}
        got = os.path.join(w.aot_root, "objects", "d" + "0" * 63)
        assert open(os.path.join(got, "executable.bin"),
                    "rb").read() == blob
        assert open(os.path.join(got, "manifest.json"),
                    "rb").read() == mb
        assert not os.path.exists(
            os.path.join(w.aot_root, "objects", "torn"))


# -- the liveness ladder (injectable clock, no sleeping) -------------------


class TestHeartbeatLadder:
    def _fleet(self, transports, mpath=None, **kw):
        kw.setdefault("heartbeat_s", 1.0)
        kw.setdefault("suspect_after", 2)
        kw.setdefault("dead_after", 4)
        kw.setdefault("reconnect_backoff_s", 4.0)
        kw.setdefault("rng", random.Random(0))
        metrics = ServingMetrics(mpath) if mpath else None
        return HostFleet(transports, metrics=metrics, **kw)

    def test_ladder_verdict_once_and_breaker_paced_rejoin(
            self, tmp_path):
        mpath = str(tmp_path / "metrics.jsonl")
        clock = _Clock()
        t = LoopbackTransport(HostWorker(StubEngine()))
        fleet = self._fleet({"h0": t}, mpath, clock=clock)
        fleet.admit_all()
        h = fleet.hosts["h0"]
        assert h.ready and fleet.degradation() == "healthy"
        assert fleet.beat("h0") and h.beats == 1

        t.close()
        assert fleet.beat_all() == ["h0"]          # miss 1
        assert h.state == HOST_HEALTHY
        fleet.beat("h0")                           # miss 2 -> suspect
        assert h.state == HOST_SUSPECT
        fleet.beat("h0")                           # miss 3
        fleet.beat("h0")                           # miss 4 -> dead
        assert h.state == HOST_DEAD and not h.ready
        assert fleet.pop_notices() == [("dead", "h0")]
        assert fleet.pop_notices() == []           # verdict queued ONCE
        assert fleet.beat_all() == []              # dead hosts skipped
        assert fleet.degradation() == "partitioned"
        ev = _events(mpath)
        assert ev.count("host_suspect") == 1
        assert ev.count("host_dead") == 1

        # reconnect is PACED: inside the breaker backoff, no probe
        fleet.tick()
        assert h.state == HOST_DEAD and h.rejoins == 0
        # backoff expired (half-open): reopen -> ping -> full rejoin
        clock.advance(1000.0)
        fleet.tick()
        assert h.state == HOST_HEALTHY and h.ready and h.rejoins == 1
        assert fleet.pop_notices() == [("rejoined", "h0")]
        assert "host_rejoined" in _events(mpath)
        assert fleet.degradation() == "healthy"
        health = fleet.health()
        assert health["state"] == "healthy"
        assert health["hosts"]["h0"]["rejoins"] == 1

    def test_suspect_recovers_on_clean_beat(self):
        clock = _Clock()
        t = LoopbackTransport(HostWorker(StubEngine()))
        fleet = self._fleet({"h0": t}, clock=clock)
        fleet.admit_all()
        # transient heartbeat faults (the host.heartbeat chaos site)
        faults.arm([{"site": "host.heartbeat", "kind": "raise",
                     "count": 2}])
        fleet.beat("h0")
        fleet.beat("h0")
        h = fleet.hosts["h0"]
        assert h.state == HOST_SUSPECT and h.missed == 2
        assert fleet.beat("h0")                    # plan exhausted
        assert h.state == HOST_HEALTHY and h.missed == 0
        assert not fleet.pop_notices()             # never verdicted

    def test_degradation_states_across_two_hosts(self):
        clock = _Clock()
        t0 = LoopbackTransport(HostWorker(StubEngine()))
        t1 = LoopbackTransport(HostWorker(StubEngine()))
        fleet = self._fleet([t0, t1], clock=clock, suspect_after=1,
                            dead_after=2)
        fleet.admit_all()
        assert fleet.degradation() == "healthy"
        t0.close()
        fleet.beat("h0")
        fleet.beat("h0")
        assert fleet.hosts["h0"].state == HOST_DEAD
        assert fleet.degradation() == "degraded"   # h1 still serves
        t1.close()
        fleet.beat("h1")
        fleet.beat("h1")
        assert fleet.degradation() == "partitioned"

    def test_threshold_validation(self):
        t = LoopbackTransport(HostWorker(StubEngine()))
        with pytest.raises(ValueError, match="suspect_after"):
            HostFleet([t], suspect_after=3, dead_after=3)


# -- scheduler integration: loopback failover drill ------------------------


class TestFleetFailoverLoopback:
    def _stack(self, mpath, reconnect_backoff_s=600.0):
        local = StubEngine()
        t0 = LoopbackTransport(HostWorker(StubEngine(0.02)), name="h0")
        t1 = LoopbackTransport(HostWorker(StubEngine(0.02)), name="h1")
        fleet = HostFleet(
            {"h0": t0, "h1": t1}, heartbeat_s=0.05,
            heartbeat_timeout_s=0.5, suspect_after=1, dead_after=2,
            reconnect_backoff_s=reconnect_backoff_s,
            rng=random.Random(0))
        fleet.admit_all()
        sched = MicroBatchScheduler(
            local, max_batch=2, gather_window_s=0.0,
            dispatch_timeout_s=10.0, breaker_failures=2,
            metrics_path=mpath, host_fleet=fleet)
        return sched, fleet, t0

    def test_dead_host_fails_over_all_futures_settle_bitwise(
            self, tmp_path):
        mpath = str(tmp_path / "metrics.jsonl")
        sched, fleet, t0 = self._stack(mpath)
        try:
            pairs = _pairs(30)
            futs = []
            for i, (a, b) in enumerate(pairs):
                futs.append(sched.submit(a, b))
                if i == 9:
                    fleet.poison("h0")   # kill mid-traffic
            for (a, b), f in zip(pairs, futs):
                flow = np.asarray(f.result(timeout=60).flow)
                assert np.array_equal(flow, _stub_oracle(a, b))
            assert _wait_for(
                lambda: fleet.hosts["h0"].state == HOST_DEAD, 10.0)
            assert _wait_for(
                lambda: _host_lane_block(sched, "h0")["quarantined"],
                10.0)
            h = sched.health()
            assert h["state"] == "degraded"
            assert h["hosts"]["state"] == "degraded"
            assert h["hosts"]["hosts"]["h0"]["state"] == "dead"
            assert h["hosts"]["hosts"]["h1"]["state"] == "healthy"
            assert _host_lane_block(sched, "h1")["active"]

            snap = sched.metrics.snapshot()
            assert snap["submitted"] == 30 == snap["completed"]
            assert snap["failed"] == 0
            assert snap["abandoned_inflight"] == 0   # zero stranded
            assert _accounting_ok(snap)
            ev = _events(mpath)
            assert "host_dead" in ev
            assert "failover" in ev
            assert "replica_quarantined" in ev
            assert snap["hosts"]["h0"]["state"] == "dead"

            # explicit rejoin over a fresh transport to the SAME
            # worker: full protocol, lane reactivates, serves again
            fleet.rejoin("h0", t0.reopen())
            assert _wait_for(
                lambda: _host_lane_block(sched, "h0")["active"],
                10.0)
            futs2 = [sched.submit(a, b) for a, b in pairs[:8]]
            for (a, b), f in zip(pairs, futs2):
                assert np.array_equal(np.asarray(f.result(60).flow),
                                      _stub_oracle(a, b))
            assert fleet.hosts["h0"].rejoins == 1
            assert "host_rejoined" in _events(mpath)
            assert _wait_for(
                lambda: sched.health()["state"] == "healthy", 10.0)
        finally:
            sched.close()

    def test_infer_fault_fails_over_and_recovers(self, tmp_path):
        """The host.infer chaos site drawn end-to-end: a worker-side
        infer raise crosses the wire as TransportError mid-dispatch,
        the live batch fails over by requeue to the surviving lanes
        (scheduler's before-any-heartbeat-verdict path), and once the
        one-shot fault exhausts every future still settles bitwise
        with the accounting identity intact."""
        mpath = str(tmp_path / "metrics.jsonl")
        sched, fleet, _t0 = self._stack(mpath)
        try:
            faults.arm([{"site": "host.infer", "kind": "raise",
                         "count": 1}])
            pairs = _pairs(30)
            futs = [sched.submit(a, b) for a, b in pairs]
            for (a, b), f in zip(pairs, futs):
                flow = np.asarray(f.result(timeout=60).flow)
                assert np.array_equal(flow, _stub_oracle(a, b))
            assert not faults.armed("host.infer")   # the drill DREW it
            snap = sched.metrics.snapshot()
            assert snap["submitted"] == 30 == snap["completed"]
            assert snap["failed"] == 0
            assert snap["abandoned_inflight"] == 0   # zero stranded
            assert _accounting_ok(snap)
            assert "failover" in _events(mpath)
        finally:
            sched.close()

    def test_hosts_zero_is_bitwise_pr17(self, tmp_path):
        """The migration pin: no fleet -> no hosts surface at all."""
        sched = MicroBatchScheduler(StubEngine(), gather_window_s=0.0)
        try:
            assert sched.host_fleet is None
            assert "hosts" not in sched.health()
            a, b = _pairs(1)[0]
            flow = np.asarray(sched.submit(a, b).result(60).flow)
            assert np.array_equal(flow, _stub_oracle(a, b))
            assert "hosts" not in sched.metrics.snapshot()
        finally:
            sched.close()

    def test_ragged_with_host_fleet_raises_config_error(self):
        eng = StubEngine()
        eng.ragged = True
        t = LoopbackTransport(HostWorker(StubEngine()))
        fleet = HostFleet({"h0": t})
        with pytest.raises(ConfigError, match="host_fleet"):
            MicroBatchScheduler(eng, ragged=True, host_fleet=fleet)


# -- subprocess workers: the SIGKILL crash drill ---------------------------


def _spawn_stub_worker(infer_delay_s=0.05):
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tests", "host_worker.py"),
         "--stub", "--infer-delay-s", str(infer_delay_s)],
        cwd=REPO, stdout=subprocess.PIPE, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO})
    return proc, _read_port(proc)


def _read_port(proc, timeout=120.0):
    out = []

    def _read():
        out.append(proc.stdout.readline())

    th = threading.Thread(target=_read, daemon=True)
    try:
        th.start()
        th.join(timeout)
        assert out and out[0].startswith("PORT "), \
            f"worker never reported a port: {out!r}"
        return int(out[0].split()[1])
    finally:
        if th.is_alive():
            proc.kill()        # EOF unblocks the pending readline
            th.join(5)


class TestSubprocessKillDrill:
    def test_sigkill_one_of_two_workers_failover_then_rejoin(
            self, tmp_path):
        """The acceptance drill on real processes and sockets (stub
        engines: deterministic, jax-free math): SIGKILL one of two
        subprocess workers mid-traffic -> its lane quarantines with
        ``host_dead`` + ``failover`` in metrics.jsonl, every in-flight
        request settles exactly once (bitwise, accounting identity,
        zero stranded), and a RESTARTED worker rejoins through a new
        transport and takes traffic again."""
        mpath = str(tmp_path / "metrics.jsonl")
        procs = {}
        sched = None
        try:
            procs["h0"], p0 = _spawn_stub_worker()
            procs["h1"], p1 = _spawn_stub_worker()
            fleet = HostFleet(
                {"h0": SocketTransport("127.0.0.1", p0,
                                       call_timeout_s=30, name="h0"),
                 "h1": SocketTransport("127.0.0.1", p1,
                                       call_timeout_s=30, name="h1")},
                heartbeat_s=0.05, heartbeat_timeout_s=1.0,
                suspect_after=1, dead_after=2,
                reconnect_backoff_s=600.0, rng=random.Random(0))
            fleet.admit_all()
            sched = MicroBatchScheduler(
                StubEngine(), max_batch=2, gather_window_s=0.0,
                dispatch_timeout_s=30.0, breaker_failures=2,
                metrics_path=mpath, host_fleet=fleet)
            pairs = _pairs(30)
            futs = []
            for i, (a, b) in enumerate(pairs):
                futs.append(sched.submit(a, b))
                if i == 9:
                    procs["h0"].kill()             # SIGKILL mid-batch
            for (a, b), f in zip(pairs, futs):
                flow = np.asarray(f.result(timeout=120).flow)
                assert np.array_equal(flow, _stub_oracle(a, b))
            assert _wait_for(
                lambda: fleet.hosts["h0"].state == HOST_DEAD, 20.0)
            assert _wait_for(
                lambda: _host_lane_block(sched, "h0")["quarantined"],
                20.0)
            snap = sched.metrics.snapshot()
            assert snap["submitted"] == 30 == snap["completed"]
            assert snap["failed"] == 0
            assert snap["abandoned_inflight"] == 0
            assert _accounting_ok(snap)
            ev = _events(mpath)
            assert "host_dead" in ev and "failover" in ev

            # restart the worker (fresh process, NEW port) and rejoin
            procs["h0b"], p0b = _spawn_stub_worker()
            fleet.rejoin("h0", SocketTransport("127.0.0.1", p0b,
                                               call_timeout_s=30,
                                               name="h0"))
            assert fleet.hosts["h0"].rejoins == 1
            assert "host_rejoined" in _events(mpath)
            futs2 = [sched.submit(a, b) for a, b in pairs[:10]]
            for (a, b), f in zip(pairs, futs2):
                assert np.array_equal(np.asarray(f.result(120).flow),
                                      _stub_oracle(a, b))
            assert _wait_for(
                lambda: sched.health()["state"] == "healthy", 20.0)
        finally:
            if sched is not None:
                sched.close()
            for p in procs.values():
                p.kill()
                p.wait(timeout=10)


# -- the real stack: AOT push, zero-compile prewarm, bitwise oracle --------


class TestRealStackKillDrill:
    def test_push_prewarm_kill_failover_rejoin_zero_compiles(
            self, tmp_path):
        """ISSUE 18 acceptance end to end on the REAL stack: the
        parent's artifact store ships to two subprocess RAFTEngine
        workers (sha256-verified), both prewarm with ZERO XLA compiles
        (pure AOT loads), remote flow is bitwise the single-engine
        oracle, a SIGKILL mid-traffic fails over with every request
        settling exactly once, and the restarted worker rejoins
        through a verified re-push — again zero compiles."""
        import jax
        import jax.numpy as jnp

        from raft_tpu.config import RAFTConfig
        from raft_tpu.models import RAFT
        from raft_tpu.serving.engine import RAFTEngine

        cfg = RAFTConfig(small=True)
        model = RAFT(cfg)
        img = jnp.zeros((1, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), img, img, iters=1)
        rs = np.random.RandomState(3)
        i1 = (rs.rand(32, 32, 3) * 255).round().astype(np.float32)
        i2 = (rs.rand(32, 32, 3) * 255).round().astype(np.float32)

        art = str(tmp_path / "artifacts")
        primary = RAFTEngine(variables, cfg, iters=1,
                             envelope=[(1, 32, 32)], precompile=True,
                             aot_cache=art)
        oracle = np.asarray(primary.infer_batch(i1[None], i2[None]))[0]
        wpath = str(tmp_path / "weights.pkl")
        with open(wpath, "wb") as fh:
            pickle.dump(variables, fh)

        mpath = str(tmp_path / "metrics.jsonl")
        procs = {}
        sched = None
        try:
            def spawn(tag):
                proc = subprocess.Popen(
                    [sys.executable,
                     os.path.join(REPO, "tests", "host_worker.py"),
                     "--weights", wpath,
                     "--aot-root", str(tmp_path / f"aot_{tag}"),
                     "--iters", "1", "--height", "32", "--width", "32"],
                    cwd=REPO, stdout=subprocess.PIPE, text=True,
                    env={**os.environ, "JAX_PLATFORMS": "cpu",
                         "PYTHONPATH": REPO})
                return proc, _read_port(proc)

            procs["h0"], p0 = spawn("h0")
            procs["h1"], p1 = spawn("h1")
            fleet = HostFleet(
                {"h0": SocketTransport("127.0.0.1", p0,
                                       call_timeout_s=300, name="h0"),
                 "h1": SocketTransport("127.0.0.1", p1,
                                       call_timeout_s=300, name="h1")},
                aot_cache=AOTCache(art), heartbeat_s=0.1,
                heartbeat_timeout_s=5.0, suspect_after=1, dead_after=2,
                reconnect_backoff_s=600.0, rng=random.Random(0))
            stats = fleet.admit_all()
            for name in ("h0", "h1"):
                assert stats[name]["compiles"] == 0, stats[name]
                assert stats[name]["aot_hits"] >= 1, stats[name]
                assert fleet.hosts[name].push_entries >= 1
                assert fleet.hosts[name].push_bytes > 0

            sched = MicroBatchScheduler(
                primary, max_batch=1, gather_window_s=0.0,
                dispatch_timeout_s=120.0, breaker_failures=1,
                metrics_path=mpath, host_fleet=fleet)
            futs = []
            for i in range(16):
                futs.append(sched.submit(i1, i2))
                if i == 5:
                    procs["h0"].kill()             # SIGKILL mid-batch
            for f in futs:
                flow = np.asarray(f.result(timeout=600).flow)
                assert np.array_equal(flow, oracle)   # bitwise
            assert _wait_for(
                lambda: fleet.hosts["h0"].state == HOST_DEAD, 30.0)
            assert _wait_for(
                lambda: _host_lane_block(sched, "h0")["quarantined"],
                30.0)
            snap = sched.metrics.snapshot()
            assert snap["submitted"] == 16 == snap["completed"]
            assert snap["failed"] == 0
            assert snap["abandoned_inflight"] == 0
            assert _accounting_ok(snap)
            ev = _events(mpath)
            assert "host_dead" in ev and "failover" in ev

            # restart on a fresh port: full rejoin protocol — re-push
            # (idempotent on the worker) + prewarm, again ZERO compiles
            procs["h0b"], p0b = spawn("h0b")
            rstats = fleet.rejoin(
                "h0", SocketTransport("127.0.0.1", p0b,
                                      call_timeout_s=300, name="h0"))
            assert rstats["compiles"] == 0, rstats
            assert rstats["aot_hits"] >= 1, rstats
            assert "host_rejoined" in _events(mpath)
            futs2 = [sched.submit(i1, i2) for _ in range(6)]
            for f in futs2:
                assert np.array_equal(
                    np.asarray(f.result(timeout=600).flow), oracle)
            assert _wait_for(
                lambda: sched.health()["state"] == "healthy", 30.0)
        finally:
            if sched is not None:
                sched.close()
            for p in procs.values():
                p.kill()
                p.wait(timeout=10)
