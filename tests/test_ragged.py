"""Ragged single-executable serving: one compiled program for mixed
spatial shapes.

Three layers, matching the feature's construction:

- kernel (kernels/corr_ragged_pallas): the descriptor, the per-row
  feature mask, and the self-masking equivalence — a masked row's
  correlation lookup IS the row's own smaller-volume lookup, bitwise
  (every backend's zeros-outside-the-volume semantics does the ragged
  work for free once the feature tails are zeroed);
- engine (RAFTEngine(ragged=True)): one capacity-class executable
  serves any shape mix; per-row crops; row independence (a request's
  result does not depend on what it coalesced with); the
  ragged-vs-bucketed oracle pin — BITWISE at bucket-batch-1 integer
  inputs for every swept shape, each at its own capacity box (the
  established bitwise-safe geometry: XLA CPU conv bits move with total
  batch, and the identity mask adds zero numeric perturbation);
- scheduler (MicroBatchScheduler(ragged=True)): cross-shape
  coalescing fills one micro-batch from the whole mixed-shape queue —
  served == submitted, ONE executable, the accounting identity, the
  padding-waste/capacity-fill gauges, warm video sessions, and the
  chaos drill passing through the ragged drop/recompile cycle.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.config import RAFTConfig
from raft_tpu.kernels.corr_ragged_pallas import (
    build_corr_pyramid_ragged, corr_lookup_ragged, make_descriptor,
    mask_features)
from raft_tpu.models import RAFT
from raft_tpu.models.corr import build_corr_pyramid
from raft_tpu.serving.engine import RAFTEngine
from raft_tpu.serving.scheduler import MicroBatchScheduler
from raft_tpu.serving.session import VideoSession

#: the mixed-shape sweep: three distinct request shapes, all fitting
#: the (32, 40) capacity box
SWEEP = [(32, 32), (24, 40), (32, 40)]
CAP_HW = (32, 40)


@pytest.fixture(scope="module")
def small_setup():
    cfg = RAFTConfig(small=True)
    model = RAFT(cfg)
    img = jnp.zeros((1, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), img, img, iters=1)
    return cfg, variables


@pytest.fixture(scope="module")
def ragged_engine(small_setup):
    """ONE capacity class for the whole module's mixed traffic —
    every test below must leave the ragged table at exactly this one
    entry."""
    cfg, variables = small_setup
    return RAFTEngine(variables, cfg, iters=1, ragged=True,
                      capacity_classes=[(2,) + CAP_HW],
                      precompile=True, warm_start=True)


def _pair(rng, h, w):
    """Integer-valued frames — the bitwise-safe parity inputs."""
    return (rng.randint(0, 256, (h, w, 3)).astype(np.float32),
            rng.randint(0, 256, (h, w, 3)).astype(np.float32))


class TestRaggedKernel:
    def test_descriptor_fields_and_validation(self):
        d = make_descriptor([(4, 4), (3, 5)], (4, 5), batch=3)
        assert list(d.h8) == [4, 3, 0]
        assert list(d.w8) == [4, 5, 0]
        assert list(d.hw_offset) == [0, 20, 40]
        assert list(d.valid_len) == [4 * 5, 3 * 5, 0]
        with pytest.raises(ValueError, match="exceeds the capacity"):
            make_descriptor([(5, 5)], (4, 5), batch=1)
        with pytest.raises(ValueError, match="rows > batch"):
            make_descriptor([(1, 1), (1, 1)], (4, 5), batch=1)

    def test_mask_is_identity_at_full_extent_and_zeros_tails(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(2, 6, 8, 3).astype(np.float32))
        m = mask_features(x, jnp.asarray([6, 4], jnp.int32),
                          jnp.asarray([8, 5], jnp.int32))
        m = np.asarray(m)
        # full-extent row: the select is the exact identity
        assert np.array_equal(m[0], np.asarray(x)[0])
        # sub-capacity row: valid region untouched, tails exactly zero
        assert np.array_equal(m[1, :4, :5], np.asarray(x)[1, :4, :5])
        assert (m[1, 4:, :] == 0).all() and (m[1, :, 5:] == 0).all()

    def test_masked_lookup_matches_own_volume_bitwise(self):
        """The self-masking theorem the ragged path rests on: a row's
        masked-capacity-box lookup equals the lookup over the row's
        OWN volume, bitwise — windows drifting past the valid extent
        read the masked zeros exactly where the own volume's
        zeros-padding would have applied. Power-of-two extents keep
        every pyramid level pool-aligned, so all levels pin exact."""
        rng = np.random.RandomState(1)
        hl, wl, C, radius, levels = 8, 8, 16, 3, 4
        HL, WL = 16, 16
        f1 = rng.randn(1, hl, wl, C).astype(np.float32)
        f2 = rng.randn(1, hl, wl, C).astype(np.float32)
        # embed in the capacity box; the zero fill IS the mask for
        # embedded-from-zero rows, and mask_features re-asserts it
        f1b = np.zeros((1, HL, WL, C), np.float32)
        f2b = np.zeros((1, HL, WL, C), np.float32)
        f1b[0, :hl, :wl] = f1[0]
        f2b[0, :hl, :wl] = f2[0]
        vh = jnp.asarray([hl], jnp.int32)
        vw = jnp.asarray([wl], jnp.int32)

        own = build_corr_pyramid(jnp.asarray(f1), jnp.asarray(f2),
                                 levels)
        box = build_corr_pyramid_ragged(jnp.asarray(f1b),
                                        jnp.asarray(f2b), vh, vw,
                                        levels)
        # coords: identity grid + a drift that pushes some windows
        # past the valid boundary (where both sides must read zeros)
        gy, gx = np.meshgrid(np.arange(hl), np.arange(wl),
                             indexing="ij")
        drift = rng.uniform(-4, 6, (1, hl, wl, 2)).astype(np.float32)
        own_coords = (np.stack([gx, gy], -1)[None].astype(np.float32)
                      + drift)
        gy, gx = np.meshgrid(np.arange(HL), np.arange(WL),
                             indexing="ij")
        box_coords = np.stack([gx, gy], -1)[None].astype(np.float32)
        box_coords[0, :hl, :wl] = own_coords[0]

        for impl in ("gather", "onehot", "softsel"):
            got = np.asarray(corr_lookup_ragged(
                box, jnp.asarray(box_coords), radius, impl=impl))
            # compare against the SAME impl on the own volume —
            # backends differ in fp association between themselves
            ref_impl = np.asarray(corr_lookup_ragged(
                own, jnp.asarray(own_coords), radius, impl=impl))
            assert np.array_equal(got[:, :hl, :wl], ref_impl), \
                f"masked box lookup != own-volume lookup ({impl})"

    def test_full_extent_pyramid_bitwise_plain(self):
        rng = np.random.RandomState(2)
        f1 = jnp.asarray(rng.randn(1, 8, 8, 16).astype(np.float32))
        f2 = jnp.asarray(rng.randn(1, 8, 8, 16).astype(np.float32))
        full = jnp.asarray([8], jnp.int32)
        plain = build_corr_pyramid(f1, f2, 4)
        masked = build_corr_pyramid_ragged(f1, f2, full, full, 4)
        for p, m in zip(plain, masked):
            assert np.array_equal(np.asarray(p), np.asarray(m))


class TestRaggedEngine:
    def test_one_executable_serves_mixed_shapes(self, ragged_engine):
        rng = np.random.RandomState(0)
        pairs = [_pair(rng, h, w) for h, w in SWEEP[:2]]
        flows, lows = ragged_engine.infer_ragged(pairs,
                                                 return_low=True)
        assert [f.shape for f in flows] == [(32, 32, 2), (24, 40, 2)]
        assert [l.shape for l in lows] == [(4, 4, 2), (3, 5, 2)]
        # the third distinct shape rides the SAME executable
        flows2 = ragged_engine.infer_ragged(
            [_pair(rng, *SWEEP[2]), _pair(rng, *SWEEP[0])])
        assert [f.shape for f in flows2] == [(32, 40, 2), (32, 32, 2)]
        assert ragged_engine.executable_count() == 1
        assert ragged_engine.ragged_classes() == [(2,) + CAP_HW]

    def test_row_independence_across_shapes(self, ragged_engine):
        """Cross-shape coalescing must not perturb a request: row i of
        a mixed dispatch is bitwise row i dispatched alone through the
        same class (masked rows are data-independent)."""
        rng = np.random.RandomState(1)
        pa = _pair(rng, 32, 32)
        pb = _pair(rng, 24, 40)
        mixed, mixed_lows = ragged_engine.infer_ragged(
            [pa, pb], return_low=True)
        solo_a = ragged_engine.infer_ragged([pa], return_low=True)
        solo_b = ragged_engine.infer_ragged([pb], return_low=True)
        assert np.array_equal(mixed[0], solo_a[0][0])
        assert np.array_equal(mixed[1], solo_b[0][0])
        assert np.array_equal(np.asarray(mixed_lows[0]),
                              np.asarray(solo_a[1][0]))
        assert ragged_engine.executable_count() == 1

    def test_warm_start_round_trip(self, ragged_engine):
        rng = np.random.RandomState(2)
        pairs = [_pair(rng, 32, 32), _pair(rng, 24, 40)]
        flows, lows = ragged_engine.infer_ragged(pairs,
                                                 return_low=True)
        warm = ragged_engine.infer_ragged(pairs, flow_inits=lows)
        cold = ragged_engine.infer_ragged(pairs)
        # a nonzero warm start moves the refinement start
        assert not np.array_equal(warm[0], cold[0])
        # mixed warm/cold rows coalesce too (None = cold row)
        part = ragged_engine.infer_ragged(pairs,
                                          flow_inits=[lows[0], None])
        assert np.array_equal(part[1], cold[1])
        assert ragged_engine.executable_count() == 1

    @pytest.mark.parametrize("shape", SWEEP)
    def test_parity_vs_bucketed_every_swept_shape(self, small_setup,
                                                  shape):
        """The oracle pin: at bucket-batch-1 integer inputs, each
        swept shape served through its own capacity box is BITWISE the
        bucketed path at the same box — descriptor, assembly, identity
        mask and per-row crops add zero numeric perturbation. (At a
        full-extent row the select mask is the identity; sub-capacity
        masked semantics are pinned at the kernel layer above.)"""
        cfg, variables = small_setup
        h, w = shape
        rng = np.random.RandomState(3)
        i1, i2 = _pair(rng, h, w)
        rag = RAFTEngine(variables, cfg, iters=1, ragged=True,
                         capacity_classes=[(1, h, w)],
                         precompile=True, warm_start=True)
        buck = RAFTEngine(variables, cfg, iters=1,
                          envelope=[(1, h, w)], precompile=True,
                          warm_start=True)
        rflows, rlows = rag.infer_ragged([(i1, i2)], return_low=True)
        bflow, blow = buck.infer_batch(i1[None], i2[None],
                                       return_low=True)
        assert np.array_equal(rflows[0], bflow[0])
        assert np.array_equal(np.asarray(rlows[0]), np.asarray(blow[0]))
        # warm round: same flow_init, same result — the recurrence
        # state round-trips identically through both paths
        rwarm = rag.infer_ragged([(i1, i2)], flow_inits=[rlows[0]])
        bwarm = buck.infer_batch(i1[None], i2[None], flow_init=blow)
        assert np.array_equal(rwarm[0], bwarm[0])
        assert rag.executable_count() == 1
        assert buck.executable_count() == 1

    def test_drop_bucket_and_lazy_recompile(self, small_setup):
        cfg, variables = small_setup
        eng = RAFTEngine(variables, cfg, iters=1, ragged=True,
                         capacity_classes=[(2,) + CAP_HW],
                         precompile=False, warm_start=True)
        # precompile=False: placeholder present, nothing compiled
        assert eng.ragged_classes() == [(2,) + CAP_HW]
        assert eng.drop_bucket((2,) + CAP_HW, ragged=True)
        assert not eng.drop_bucket((2,) + CAP_HW, ragged=True)
        assert eng.executable_count() == 0
        # the half-open probe's lazy recompile path
        assert eng.ensure_ragged(2, *CAP_HW) == (2,) + CAP_HW
        assert eng.executable_count() == 1

    def test_routing_and_grain(self, small_setup):
        cfg, variables = small_setup
        eng = RAFTEngine(variables, cfg, iters=1, ragged=True,
                         capacity_classes=[(2,) + CAP_HW],
                         precompile=False, warm_start=True,
                         ragged_grain=64)
        # shapes fitting the declared class coalesce under its box
        assert eng.ragged_class_for(32, 32) == CAP_HW
        assert eng.ragged_class_for(30, 38) == CAP_HW
        assert eng.ragged_capacity(*CAP_HW) == 2
        # outside every class: grain-rounded box (the compile-cache
        # DoS bound — arbitrary resolutions land on grain multiples)
        assert eng.ragged_class_for(100, 200) == (128, 256)
        assert eng.route_ragged(3, 100, 200) == (3, 128, 256)
        # batch outgrowing the class keeps the declared geometry
        assert eng.route_ragged(4, 30, 38) == (4,) + CAP_HW

    def test_dispatch_routes_on_the_coalescing_box(self, small_setup):
        """Regression (review finding): with multiple classes, routing
        on the BATCH's max extents can pick a different class than
        routing on the coalescing-key box — the scheduler's wedge
        verdict would then drop a healthy class while the hung one
        kept serving. The scheduler passes ``box=`` so both decisions
        run on identical inputs; this pins the divergence the box
        parameter exists to close (routing only — no compiles)."""
        cfg, variables = small_setup
        eng = RAFTEngine(variables, cfg, iters=1, ragged=True,
                         capacity_classes=[(4, 64, 64), (1, 56, 80)],
                         precompile=False, warm_start=True)
        # a 48x64 request keys to the (64, 64) box (area-min)...
        assert eng.ragged_class_for(48, 64) == (64, 64)
        # ...and routing ON THE BOX honors that key (only (4,64,64)
        # fits 64 in H)
        assert eng.route_ragged(1, 64, 64) == (4, 64, 64)
        # ...but routing on the request's own extents would pick the
        # volume-min (1,56,80) class — the divergence box= closes
        assert eng.route_ragged(1, 48, 64) == (1, 56, 80)

    def test_validation(self, small_setup, ragged_engine):
        cfg, variables = small_setup
        with pytest.raises(ValueError, match="feature_cache"):
            RAFTEngine(variables, cfg, ragged=True, warm_start=True,
                       feature_cache=True)
        with pytest.raises(ValueError, match="capacity_classes"):
            RAFTEngine(variables, cfg, capacity_classes=[(1, 32, 32)])
        with pytest.raises(ValueError, match="ragged_grain"):
            RAFTEngine(variables, cfg, ragged=True, ragged_grain=12)
        with pytest.raises(ValueError, match="multiples of 8"):
            RAFTEngine(variables, cfg, ragged=True,
                       capacity_classes=[(1, 30, 32)],
                       precompile=False)
        buck = RAFTEngine(variables, cfg, iters=1, precompile=False,
                          envelope=[(1, 32, 32)])
        with pytest.raises(ValueError, match="ragged=True"):
            buck.infer_ragged([(np.zeros((32, 32, 3)),
                                np.zeros((32, 32, 3)))])
        with pytest.raises(ValueError, match="ragged=True"):
            MicroBatchScheduler(buck, ragged=True)
        with pytest.raises(ValueError, match="empty"):
            ragged_engine.infer_ragged([])
        with pytest.raises(ValueError, match="flow_init shape"):
            ragged_engine.infer_ragged(
                [(np.zeros((32, 32, 3)), np.zeros((32, 32, 3)))],
                flow_inits=[np.zeros((5, 5, 2), np.float32)])

    def test_ragged_feature_cache_rejected_at_the_boundary(self,
                                                           small_setup):
        """The unsupported combination must fail on ITSELF — an
        actionable not-yet-supported error naming the ROADMAP brick —
        at every boundary, BEFORE any compile spends seconds:
        constructor (whatever warm_start says), chaos-drill library
        call (which used to compile its ragged engine first and only
        then trip run_drill's check as a raw traceback), and the CLI
        parse."""
        cfg, variables = small_setup
        for warm in (False, True):
            with pytest.raises(ValueError, match="ROADMAP"):
                RAFTEngine(variables, cfg, ragged=True,
                           feature_cache=True, warm_start=warm)
        from raft_tpu.cli.serve_bench import run_chaos_drill
        with pytest.raises(ValueError, match="ROADMAP"):
            run_chaos_drill(variables, cfg, shapes=[(32, 32)],
                            ragged=True, feature_cache=True)
        from raft_tpu.cli.serve_bench import main as serve_bench_main
        with pytest.raises(SystemExit, match="ROADMAP"):
            serve_bench_main(["--ragged", "--feature-cache"])


class TestRaggedScheduler:
    def test_cross_shape_coalescing_one_executable(self, ragged_engine):
        """The tentpole's serving claim: mixed-shape traffic fills
        micro-batches from the WHOLE queue and one executable serves
        it all — served == submitted, accounting identity, the
        capacity-fill/cross-shape/padding gauges live."""
        rng = np.random.RandomState(0)
        with MicroBatchScheduler(ragged_engine, max_batch=2,
                                 gather_window_s=0.05,
                                 ragged=True) as sched:
            futs = []

            def caller(sid):
                r = np.random.RandomState(100 + sid)
                for k in range(3):
                    h, w = SWEEP[(sid + k) % len(SWEEP)]
                    futs.append(sched.submit(*_pair(r, h, w),
                                             want_low=True))

            threads = [threading.Thread(target=caller, args=(s,))
                       for s in (0, 1)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            res = [f.result(timeout=600) for f in futs]
            assert len(res) == 6
            assert all(r.flow.ndim == 3 and r.flow_low is not None
                       for r in res)
            rec = sched.metrics.snapshot(
                executables=ragged_engine.executable_count())
            health = sched.health()
        assert rec["executables"] == 1
        accounted = (rec["completed"] + rec["failed"]
                     + rec["deadline_missed"] + rec["cancelled"])
        assert rec["submitted"] == accounted == 6
        rag = rec["ragged"]
        assert rag["dispatches"] > 0
        assert rag["cross_shape_dispatches"] > 0
        assert 0 < rag["capacity_fill"] <= 1
        assert 0 <= rec["padding_waste"]["waste_ratio"] < 1
        # class-keyed bucket label, ragged-suffixed
        label = "2x32x40/ragged"
        assert label in rec["buckets"]
        assert rec["buckets"][label]["real_px"] > 0
        assert health["state"] == "healthy"
        # the module invariant: every drill above left ONE class
        assert ragged_engine.ragged_classes() == [(2,) + CAP_HW]

    def test_video_session_through_ragged(self, ragged_engine):
        """Warm-start sessions ride the ragged path unchanged: every
        pair's ``flow_low`` comes back at the request's own 1/8
        geometry (the recurrence substrate — actual warm reuse at
        these tiny grids is blowout-limited at random weights, the
        same caveat the plain-path session test documents), and the
        whole stream stays on the one class executable."""
        rng = np.random.RandomState(1)
        with MicroBatchScheduler(ragged_engine, max_batch=2,
                                 gather_window_s=0.0,
                                 ragged=True) as sched:
            sess = VideoSession(sched)
            futs = [sess.submit_frame(
                rng.randint(0, 256, (24, 40, 3)).astype(np.float32))
                for _ in range(4)]
            assert futs[0] is None and all(f is not None
                                           for f in futs[1:])
            res = [f.result(timeout=600) for f in futs[1:]]
            assert all(r.flow.shape == (24, 40, 2) for r in res)
            assert all(r.flow_low is not None
                       and r.flow_low.shape == (3, 5, 2) for r in res)
        assert ragged_engine.executable_count() == 1

    def test_run_drill_summary_fields(self, ragged_engine,
                                      small_setup):
        from raft_tpu.cli.serve_bench import run_drill

        cfg, variables = small_setup
        s = run_drill(variables, cfg, shapes=SWEEP, requests=6,
                      submitters=2, bucket_batch=2, iters=1,
                      gather_window_s=0.02, ragged=True,
                      engine=ragged_engine, seed=0)
        assert s["ragged"] is True
        assert s["served"] == s["accepted"] == 6
        assert s["accounting_ok"] and s["stranded"] == 0
        assert s["executables"] == s["documented_buckets"] == 1
        assert 0 < s["capacity_fill"] <= 1
        assert 0 <= s["cross_shape_coalesce_rate"] <= 1
        assert 0 <= s["padding_waste_ratio"] < 1

    def test_chaos_passthrough(self, small_setup):
        """The resilience stack treats a capacity class like any
        bucket: wedge verdicts drop the RAGGED executable, the
        half-open probe recompiles it, accounting stays exact, and
        the clean round recovers to the documented ONE executable."""
        from raft_tpu.cli.serve_bench import run_chaos_drill

        cfg, variables = small_setup
        s = run_chaos_drill(
            variables, cfg, shapes=SWEEP[:2], rounds=1, requests=4,
            submitters=2, bucket_batch=2, iters=1,
            dispatch_timeout_s=0.4, hang_s=0.8, breaker_failures=2,
            breaker_backoff_s=0.1, breaker_backoff_max_s=0.4,
            recover_s=8.0, ragged=True, seed=0)
        assert s["violations"] == []
        assert s["documented_buckets"] == 1
        assert s["executables"] == 1
