"""graftexport: the serialized-executable audit gate (tools/graftexport/).

Three layers, mirroring the sibling tier tests:

- per-rule fixture tests: each rule E1-E6 has a fixture program under
  ``tests/graftexport_fixtures/`` with a PLANTED violation (a manifest
  missing the weights/jaxlib key components, a serialization path that
  drops the donation alias map, a closure-captured multi-MB weight
  literal, a host callback + a dishonest platform claim, a tampered
  signature block, a naive loader that survives corruption) —
  detection must fire, and both suppression channels (a Waiver on the
  target; a baseline entry) must round-trip;
- mechanism tests: waiver-justification enforcement, the lintcache-
  backed warm cache, stale-baseline failure, CLI usage errors, and the
  REQUIRED_KEY_FIELDS mirror pin (the jax-free literal in spec.py must
  equal the live set in serving/aot.py — the warm path answers without
  importing either);
- the repo gate: ``python -m tools.graftexport --json`` over the REAL
  serve programs (plain f32, u8 warm-start, feature-cache, ragged)
  round-tripped through the production AOTCache must exit 0 with no
  findings, the committed baseline must stay EMPTY, and the warm gate
  must answer in under 45 s WITHOUT importing jax (pinned with a
  poisoned ``jax`` shim on PYTHONPATH).
"""

import dataclasses
import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "graftexport_fixtures")
BASELINE = os.path.join(REPO, "tools", "graftexport", "baseline.json")

if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tests.conftest import mesh_subprocess_env  # noqa: E402
from tools.graftexport import (ExportTarget, Waiver,  # noqa: E402
                               apply_baseline, audit_targets,
                               load_baseline, load_fixture_targets,
                               write_baseline)
from tools.graftexport.core import cached_audit, main  # noqa: E402

RULES = ("E1", "E2", "E3", "E4", "E5", "E6")

_AUDIT_CACHE = {}


def fixture(name):
    return os.path.join(FIXTURES, name)


def audit_fixture(name):
    """(targets, findings) for one fixture module, audited once per
    test session — detection, waiver, and baseline tests all read the
    same run (each audit is a real compile + serialize round trip)."""
    if name not in _AUDIT_CACHE:
        targets = load_fixture_targets(fixture(name))
        findings, _ = audit_targets(targets)
        _AUDIT_CACHE[name] = (targets, findings)
    return _AUDIT_CACHE[name]


class TestRuleFixtures:
    @pytest.mark.parametrize("rule", RULES)
    def test_planted_violation_detected(self, rule):
        _, findings = audit_fixture(f"{rule.lower()}_pos.py")
        assert any(f.rule == rule for f in findings), \
            f"{rule} fixture produced no {rule} finding: {findings}"

    @pytest.mark.parametrize("rule", RULES)
    def test_waiver_suppresses_with_justification(self, rule):
        """The pragma analog: a Waiver(rule, detail-substring, reason)
        on the target declaration silences exactly that finding."""
        targets, findings = audit_fixture(f"{rule.lower()}_pos.py")
        details = [f.detail for f in findings if f.rule == rule]
        assert details
        waived_targets = [
            dataclasses.replace(
                t, waivers=t.waivers + tuple(
                    Waiver(rule, d, "fixture round-trip")
                    for d in details))
            for t in targets]
        refindings, _ = audit_targets(waived_targets)
        assert not any(f.rule == rule for f in refindings), \
            f"waiver did not suppress: {refindings}"
        # a waiver naming a DIFFERENT rule must not suppress
        wrong = "E1" if rule != "E1" else "E2"
        wrong_targets = [
            dataclasses.replace(
                t, waivers=tuple(Waiver(wrong, d, "wrong rule")
                                 for d in details))
            for t in targets]
        refindings, _ = audit_targets(wrong_targets)
        assert any(f.rule == rule for f in refindings)

    @pytest.mark.parametrize("rule", RULES)
    def test_baseline_roundtrip_then_stale(self, rule, tmp_path):
        """Grandfathering consumes the entry; a fixed finding leaves a
        STALE entry that must fail (it would otherwise silently
        grandfather the next reintroduction)."""
        targets, findings = audit_fixture(f"{rule.lower()}_pos.py")
        bl = tmp_path / "baseline.json"
        write_baseline(str(bl), findings)
        new, stale = apply_baseline(findings, load_baseline(str(bl)))
        assert new == [] and stale == []
        # "fixed": nothing found, every entry unconsumed -> stale
        new, stale = apply_baseline(
            [], load_baseline(str(bl)),
            audited_targets=[t.name for t in targets])
        assert new == [] and len(stale) == len(findings)
        # an entry for a target OUTSIDE this run is merely unchecked
        new, stale = apply_baseline(
            [], load_baseline(str(bl)),
            audited_targets=["some_other_target"])
        assert new == [] and stale == []

    def test_clean_fixture_is_silent(self):
        """The negative: a complete key, donations that survive the
        round trip, small literals, portable custom calls, a matching
        signature, every probe routed to miss — all rules silent."""
        _, findings = audit_fixture("clean.py")
        assert findings == [], \
            "; ".join(f.render() for f in findings)


class TestMechanisms:
    def test_waiver_requires_justification(self):
        with pytest.raises(ValueError, match="justification"):
            Waiver("E4", "anything", "   ")

    def test_cached_audit_hits_and_matches(self, tmp_path):
        """Second run through the lintcache file must serve from cache
        (no rebuild) and return identical findings."""
        targets = load_fixture_targets(fixture("e1_pos.py"))
        from tools.graftexport.rules import ALL_RULES
        path = str(tmp_path / "cache.json")
        f1, _, hits1 = cached_audit(targets, ALL_RULES, path)
        assert hits1 == {"e1_fixture": False}
        f2, _, hits2 = cached_audit(targets, ALL_RULES, path)
        assert hits2 == {"e1_fixture": True}
        assert [f.key() for f in f2] == [f.key() for f in f1]
        # a different rule set is a different key: no false hit
        donation_only = [m for m in ALL_RULES if m.RULE == "E2"]
        f3, _, hits3 = cached_audit(targets, donation_only, path)
        assert hits3 == {"e1_fixture": False}
        assert f3 == []     # E2 alone can't see the key omission

    def test_required_key_fields_mirror_the_live_store(self):
        """targets/spec carry a jax-free literal MIRROR of the store's
        required key set (the warm cache path must not import jax OR
        raft_tpu); this pin is what makes the mirror safe — drift
        between the literal and ``aot.REQUIRED_KEY_FIELDS`` fails here
        before the gate can desynchronize from the store it audits."""
        from raft_tpu.serving import aot
        from tools.graftexport import spec
        assert spec.REQUIRED_KEY_FIELDS == aot.REQUIRED_KEY_FIELDS

    def test_cli_usage_errors(self, tmp_path):
        assert main(["--rules", "E9"]) == 2
        assert main(["--rules", "E1", "--write-baseline",
                     str(tmp_path / "b.json")]) == 2
        assert main(["--fixture",
                     str(tmp_path / "missing.py")]) == 2
        broken = tmp_path / "broken_fixture.py"
        broken.write_text("import no_such_module_xyz\n")
        assert main(["--fixture", str(broken)]) == 2

    def test_cli_fixture_json_and_baseline_flow(self, tmp_path, capsys):
        """CLI end-to-end on the cheapest fixture: findings as JSON,
        then grandfathered via --write-baseline, then unchecked (not
        stale) for a run over different targets."""
        rc = main(["--fixture", fixture("e1_pos.py"), "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert any(f["rule"] == "E1" for f in out)
        assert all({"target", "rule", "name", "detail", "message"}
                   <= set(f) for f in out)
        bl = tmp_path / "bl.json"
        rc = main(["--fixture", fixture("e1_pos.py"),
                   "--write-baseline", str(bl)])
        assert rc == 0 and bl.exists()
        capsys.readouterr()
        rc = main(["--fixture", fixture("e1_pos.py"),
                   "--baseline", str(bl)])
        assert rc == 0        # grandfathered
        rc = main(["--fixture", fixture("clean.py"),
                   "--baseline", str(bl)])
        capsys.readouterr()
        assert rc == 0        # different targets: unchecked, not stale


class TestRepoGate:
    """The actual gate: the real serve artifacts must audit clean."""

    def _run_gate(self, cache_dir, pythonpath_prefix=""):
        env = mesh_subprocess_env(
            local_devices=1,
            extra_env={"RAFT_GRAFTEXPORT_CACHE":
                       os.path.join(cache_dir, "cache.json")})
        if pythonpath_prefix:
            env["PYTHONPATH"] = pythonpath_prefix + os.pathsep + \
                env.get("PYTHONPATH", "")
        return subprocess.run(
            [sys.executable, "-m", "tools.graftexport", "--json"],
            cwd=REPO, capture_output=True, text=True, timeout=600,
            env=env)

    def test_repo_audit_clean_and_warm_without_jax(self, tmp_path):
        """Cold run round-trips the four serve programs through the
        production AOTCache and must gate clean; the SECOND run
        answers from the lintcache entry keyed on the raft_tpu source
        hash + rule set — pinned under the 45 s warm budget AND proven
        jax-free by a poisoned ``jax`` shim on PYTHONPATH (importing
        it raises, so a warm path that touched jax would crash)."""
        r = self._run_gate(str(tmp_path))
        assert r.returncode == 0, \
            f"graftexport findings:\n{r.stdout}\n{r.stderr}"
        assert json.loads(r.stdout) == []
        poison = tmp_path / "poison"
        poison.mkdir()
        (poison / "jax.py").write_text(
            "raise ImportError('graftexport warm path imported jax')\n")
        t0 = time.monotonic()
        r2 = self._run_gate(str(tmp_path),
                            pythonpath_prefix=str(poison))
        warm_s = time.monotonic() - t0
        assert r2.returncode == 0, \
            f"warm gate failed:\n{r2.stdout}\n{r2.stderr}"
        assert json.loads(r2.stdout) == []
        assert "cache" in r2.stderr, r2.stderr
        assert warm_s < 45, f"warm gate took {warm_s:.1f}s"

    def test_baseline_stays_empty(self):
        """The first scan's findings were FIXED at the site — aot.py
        grew the key-completeness refusal and the manifest/hash
        verification the load path now routes through — never
        grandfathered. The baseline ships EMPTY and stays that way:
        new findings are fixed or waived with justification."""
        with open(BASELINE) as f:
            entries = json.load(f)["findings"]
        assert entries == [], (
            "graftexport baseline regrew — fix or waive the finding "
            f"instead of grandfathering it: {entries}")

    def test_targets_mirror_the_engine_program_table(self):
        """The audited targets must cover the REAL program table the
        engine serves from — one target per serve recipe (plain f32,
        u8 warm-start, feature-cache, ragged), each built through
        ``RAFTEngine(aot_cache=...)`` so the audited entry is written
        by the production store path, not a test stand-in."""
        from tools.graftexport.targets import export_targets
        targets = {t.name: t for t in export_targets()}
        assert set(targets) == {"serve", "serve_u8_warm",
                                "serve_cached", "serve_ragged"}
        assert all(t.kind == "engine" for t in targets.values())

    def test_meta_gate_runs_six_tiers(self):
        """``python -m tools.graft`` fans out over SIX tiers now —
        graftexport plus the wire tier behind it. Pinned against the
        tier table (the full six-tier run is the pre-commit command;
        the expensive tiers have their own gate tests)."""
        from tools.graft import TIER_ARGS, TIERS
        assert "graftexport" in TIER_ARGS
        assert "graftwire" in TIER_ARGS
        assert len(TIERS) == 6
        # usage errors stay usage errors
        r = subprocess.run(
            [sys.executable, "-m", "tools.graft", "--tiers", "nope"],
            cwd=REPO, capture_output=True, text=True, timeout=60)
        assert r.returncode == 2
