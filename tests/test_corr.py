"""Correlation volume/lookup parity vs a torch oracle.

The oracle reproduces the reference semantics (corr.py:12-60) from torch
primitives: all-pairs matmul / sqrt(dim), avg_pool2d pyramid, and per-level
grid_sample at coords/2^i + window offsets — including the reference's
channel-order quirk where the x coordinate gets the OUTER meshgrid offset
(corr.py:39-43; same x-major order as the CUDA kernel's
``(iy-1) + rd*(ix-1)`` scatter, correlation_kernel.cu:92-95).
"""

import numpy as np
import pytest
import torch
import torch.nn.functional as F

import jax.numpy as jnp

from raft_tpu.models.corr import (
    AlternateCorrBlock,
    CorrBlock,
    all_pairs_correlation,
    build_corr_pyramid,
    corr_lookup,
)


def torch_corr_oracle(fmap1, fmap2, coords, num_levels, radius):
    """Reference-semantics corr lookup, NCHW torch. Returns (B, L*K^2, H, W)."""
    B, C, H, W = fmap1.shape
    f1 = fmap1.reshape(B, C, H * W)
    f2 = fmap2.reshape(B, C, H * W)
    corr = torch.matmul(f1.transpose(1, 2), f2) / np.sqrt(C)
    corr = corr.reshape(B * H * W, 1, H, W)

    pyramid = [corr]
    for _ in range(num_levels - 1):
        corr = F.avg_pool2d(corr, 2, stride=2)
        pyramid.append(corr)

    r = radius
    coords_p = coords.permute(0, 2, 3, 1)  # (B, H, W, 2) xy
    out = []
    for i, c in enumerate(pyramid):
        d = torch.linspace(-r, r, 2 * r + 1)
        # reference quirk: meshgrid(dy, dx) added to (x, y) -> x gets the
        # outer offset
        delta = torch.stack(torch.meshgrid(d, d, indexing="ij"), dim=-1)
        centroid = coords_p.reshape(B * H * W, 1, 1, 2) / 2 ** i
        pos = centroid + delta.reshape(1, 2 * r + 1, 2 * r + 1, 2)
        hw = c.shape[-2:]
        gx = 2 * pos[..., 0] / (hw[1] - 1) - 1
        gy = 2 * pos[..., 1] / (hw[0] - 1) - 1
        grid = torch.stack([gx, gy], dim=-1)
        samp = F.grid_sample(c, grid, align_corners=True)
        out.append(samp.reshape(B, H, W, -1))
    return torch.cat(out, dim=-1).permute(0, 3, 1, 2)


@pytest.fixture(scope="module")
def fmaps(request):
    # smallest level is (H/8, W/8); keep >= 2 px so the torch oracle's
    # grid_sample normalization (divide by dim-1) stays finite.
    rng = np.random.RandomState(7)
    B, H, W, C = 2, 16, 24, 8
    f1 = rng.randn(B, H, W, C).astype(np.float32)
    f2 = rng.randn(B, H, W, C).astype(np.float32)
    return f1, f2


class TestAllPairs:
    def test_vs_torch_matmul(self, fmaps):
        f1, f2 = fmaps
        B, H, W, C = f1.shape
        got = np.asarray(all_pairs_correlation(jnp.asarray(f1), jnp.asarray(f2)))
        t1 = torch.from_numpy(f1).permute(0, 3, 1, 2).reshape(B, C, H * W)
        t2 = torch.from_numpy(f2).permute(0, 3, 1, 2).reshape(B, C, H * W)
        want = (torch.matmul(t1.transpose(1, 2), t2) / np.sqrt(C)).reshape(
            B, H * W, H, W).numpy()
        np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


class TestCorrLookup:
    @pytest.mark.parametrize("radius", [3, 4])
    def test_vs_reference_oracle(self, fmaps, radius):
        f1, f2 = fmaps
        B, H, W, C = f1.shape
        rng = np.random.RandomState(3)
        # coords near the grid with some displacement, some OOB
        base = np.stack(np.meshgrid(np.arange(W), np.arange(H),
                                    indexing="xy"), axis=-1)
        coords = (base[None] + rng.uniform(-3, 3, size=(B, H, W, 2))
                  ).astype(np.float32)

        block = CorrBlock(jnp.asarray(f1), jnp.asarray(f2), 4, radius)
        got = np.asarray(block(jnp.asarray(coords)))  # (B, H, W, L*K^2)

        t1 = torch.from_numpy(f1).permute(0, 3, 1, 2)
        t2 = torch.from_numpy(f2).permute(0, 3, 1, 2)
        tc = torch.from_numpy(coords).permute(0, 3, 1, 2)
        want = torch_corr_oracle(t1, t2, tc, 4, radius)
        want = want.permute(0, 2, 3, 1).numpy()

        np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-3)

    def test_alternate_path_matches_main(self, fmaps):
        f1, f2 = fmaps
        B, H, W, C = f1.shape
        rng = np.random.RandomState(5)
        base = np.stack(np.meshgrid(np.arange(W), np.arange(H),
                                    indexing="xy"), axis=-1)
        coords = jnp.asarray(
            (base[None] + rng.uniform(-2, 2, size=(B, H, W, 2))
             ).astype(np.float32))

        main = CorrBlock(jnp.asarray(f1), jnp.asarray(f2), 4, 4)(coords)
        alt = AlternateCorrBlock(jnp.asarray(f1), jnp.asarray(f2), 4, 4,
                                 chunk=32)(coords)
        np.testing.assert_allclose(np.asarray(alt), np.asarray(main),
                                   atol=1e-4, rtol=1e-3)

    def test_pyramid_shapes_odd(self):
        """Odd sizes floor-divide down the pyramid like avg_pool2d."""
        f = jnp.ones((1, 55, 13, 4))
        pyr = build_corr_pyramid(f, f, 4)
        assert [p.shape[2:] for p in pyr] == [
            (55, 13), (27, 6), (13, 3), (6, 1)]

    def test_channel_order_x_major(self):
        """Peak at displacement (dx=+1, dy=0) lights channel (1+r)*K + r."""
        H, W, C = 8, 8, 4
        f1 = np.zeros((1, H, W, C), np.float32)
        f2 = np.zeros((1, H, W, C), np.float32)
        f1[0, 4, 4] = 1.0
        f2[0, 4, 5] = 1.0  # feature moved +1 in x
        r = 4
        block = CorrBlock(jnp.asarray(f1), jnp.asarray(f2), 1, r)
        base = np.stack(np.meshgrid(np.arange(W), np.arange(H),
                                    indexing="xy"), axis=-1)[None]
        out = np.asarray(block(jnp.asarray(base.astype(np.float32))))
        K = 2 * r + 1
        expect_ch = (1 + r) * K + r  # du=+1 outer, dv=0 inner
        assert out[0, 4, 4].argmax() == expect_ch
