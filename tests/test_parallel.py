"""Mesh/sharding/distributed-runtime tests on the 8-virtual-device mesh
(SURVEY.md §4(d): multi-chip tests without hardware)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.parallel import distributed as dist
from raft_tpu.parallel.mesh import (batch_sharding, make_mesh, replicated,
                                    shard_batch)


class TestMesh:
    def test_axes_and_shape(self):
        mesh = make_mesh(8, spatial=2)
        assert mesh.axis_names == ("data", "spatial")
        assert mesh.devices.shape == (4, 2)

    def test_shard_batch_layouts(self, rng):
        mesh = make_mesh(8, spatial=2)
        batch = {
            "image1": rng.rand(4, 64, 16, 3).astype(np.float32),
            "valid": np.ones((4, 64, 16), np.float32),
        }
        sharded = shard_batch(batch, mesh)
        # batch dim split 4-way, height split 2-way
        db = sharded["image1"].sharding.shard_shape((4, 64, 16, 3))
        assert db == (1, 32, 16, 3)
        dv = sharded["valid"].sharding.shard_shape((4, 64, 16))
        assert dv == (1, 32, 16)

    def test_psum_over_data_axis(self):
        """XLA inserts the gradient reduction; emulate with explicit jit."""
        mesh = make_mesh(8)

        @jax.jit
        def mean_loss(x):
            return jnp.mean(x ** 2)

        x = jax.device_put(np.arange(32, dtype=np.float32).reshape(8, 4),
                           batch_sharding_2d(mesh))
        g = jax.jit(jax.grad(mean_loss))(x)
        np.testing.assert_allclose(np.asarray(g).ravel(),
                                   2 * np.arange(32) / 32, rtol=1e-6)


def batch_sharding_2d(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P("data", None))


class TestShardingEquivalence:
    @pytest.mark.parametrize("impl", ["onehot", "softsel", "onehot_t"])
    def test_spatial_sharding_matches_single_device(self, rng, impl):
        """The (data x spatial) sharded train step must produce the same
        loss/metrics as an unsharded run — XLA's inserted collectives
        (psum, halo exchanges) are an implementation detail, not semantics
        — for EVERY XLA lookup variant (onehot_t in particular reshapes
        (B,H,W,*) into (...,H*W) layouts GSPMD must partition without
        gathers).

        Images are 64x64 so each spatial shard holds 4 feature rows —
        the minimum extent XLA partitions correctly inside the scanned
        refinement loop (see mesh.MAX_FEATURE_HALO): smaller shards hit
        an XLA bug where in-scan conv halo exchanges return wrong rows,
        which shard_batch now rejects (test below).
        """
        from raft_tpu.config import RAFTConfig, TrainConfig
        from raft_tpu.training.train_step import (create_train_state,
                                                  make_train_step)

        model_cfg = RAFTConfig(small=True, corr_impl=impl)
        train_cfg = TrainConfig(stage="chairs", num_steps=10, batch_size=4,
                                iters=2)
        batch_np = {
            "image1": rng.rand(4, 64, 64, 3).astype(np.float32) * 255,
            "image2": rng.rand(4, 64, 64, 3).astype(np.float32) * 255,
            "flow": rng.randn(4, 64, 64, 2).astype(np.float32),
            "valid": np.ones((4, 64, 64), np.float32),
        }
        key = jax.random.PRNGKey(0)

        losses = {}
        for spatial in (1, 2):
            mesh = make_mesh(4 if spatial == 1 else 8, spatial=spatial)
            state = create_train_state(model_cfg, train_cfg,
                                       jax.random.PRNGKey(7),
                                       image_hw=(64, 64))
            # two-pass mesh sweep: a jit (and its compile) per mesh
            # config IS the test
            step = jax.jit(make_train_step(model_cfg, train_cfg))  # graftlint: disable=R3
            with mesh:
                state = jax.device_put(state, replicated(mesh))
                sharded = shard_batch(batch_np, mesh)
                _, metrics = step(state, sharded, key)
                losses[spatial] = float(metrics["loss"])  # graftlint: disable=R1
        assert losses[1] == pytest.approx(losses[2], rel=1e-4)

    def test_shard_batch_rejects_sub_halo_spatial_extent(self, rng):
        """32x32 images over spatial=2 leave 2 feature rows per shard —
        inside the scanned update block XLA miscompiles conv halos at
        that extent (halo 3 of the 7x7 motion conv >= shard rows), so
        shard_batch must refuse rather than return wrong numbers."""
        mesh = make_mesh(8, spatial=2)
        batch = {"image1": rng.rand(4, 32, 32, 3).astype(np.float32)}
        with pytest.raises(ValueError, match="feature rows per shard"):
            shard_batch(batch, mesh)


class TestDistributed:
    def test_initialize_single_host_noop(self):
        dist.initialize()  # must not raise on single process
        assert jax.process_count() == 1

    def test_process_batch_slice(self):
        s = dist.process_batch_slice(16)
        assert s == slice(0, 16)

    def test_host_local_batch_global_arrays(self, rng):
        mesh = make_mesh(8, spatial=1)
        batch = {
            "image1": rng.rand(8, 8, 8, 3).astype(np.float32),
            "flow": rng.randn(8, 8, 8, 2).astype(np.float32),
            "valid": np.ones((8, 8, 8), np.float32),
        }
        out = dist.host_local_batch(batch, mesh)
        assert out["image1"].shape == (8, 8, 8, 3)
        np.testing.assert_array_equal(np.asarray(out["flow"]), batch["flow"])

    def test_replicated_state(self, rng):
        mesh = make_mesh(8)
        x = jax.device_put(rng.randn(4, 4).astype(np.float32),
                           replicated(mesh))
        assert x.sharding.is_fully_replicated


class TestSpatialMemoryScaling:
    def test_corr_volume_memory_shards_over_spatial_axis(self):
        """SURVEY §5 long-context claim, made falsifiable: growing the
        'spatial' axis must shrink per-device temp memory of the compiled
        forward (the (HW)^2 correlation pyramid is the dominant temp).
        Measured on this shape: ~5.4 / 3.8 / 2.5 MiB for spatial=1/2/4."""
        from raft_tpu.config import RAFTConfig
        from raft_tpu.models import RAFT

        model = RAFT(RAFTConfig(small=True))
        B, H, W = 2, 128, 128
        img = jnp.zeros((1, 64, 64, 3))
        variables = model.init(jax.random.PRNGKey(0), img, img, iters=1)

        def fwd(v, i1, i2):
            return model.apply(v, i1, i2, iters=2, test_mode=True)[1]

        temps = {}
        for spatial in (1, 4):
            mesh = make_mesh(2 * spatial, spatial=spatial)
            vs = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                               sharding=replicated(mesh)),
                variables)
            ss = jax.ShapeDtypeStruct((B, H, W, 3), jnp.float32,
                                      sharding=batch_sharding(mesh))
            # per-mesh AOT compile is the measurement under test
            compiled = jax.jit(fwd).lower(vs, ss, ss).compile()  # graftlint: disable=R3
            temps[spatial] = compiled.memory_analysis().temp_size_in_bytes
        assert temps[4] < 0.7 * temps[1], temps
