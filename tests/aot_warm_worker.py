"""Worker for the cross-process AOT warm-start test (not a test module).

Run twice against one ``--cache`` dir by tests/test_aot_cache.py: the
cold leg compiles the bucket through ``RAFTEngine(aot_cache=...)`` and
stores the serialized executable; the warm leg — a FRESH interpreter,
the restarting-replica scenario serving/aot.py exists for — must load
it back with ZERO XLA compiles (asserted via the engine's own compile
counter, never timing: the jax persistent compile cache would make a
timing pin lie) and produce bitwise-identical flow. Stats go to stdout
as one ``AOT_WORKER {json}`` line; the flow goes to ``--out`` as .npy.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")

import jax  # noqa: E402

try:
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:
    pass
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

import json  # noqa: E402

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from raft_tpu.config import RAFTConfig  # noqa: E402
from raft_tpu.models import RAFT  # noqa: E402
from raft_tpu.serving.engine import RAFTEngine  # noqa: E402


def main(cache_dir: str, out_npy: str, registry: bool = False) -> int:
    cfg = RAFTConfig(small=True)
    model = RAFT(cfg)
    probe = jnp.zeros((1, 32, 32, 3))
    # PRNGKey(0) init is deterministic across processes — both legs
    # derive the SAME weights, hence the same content-addressed key
    variables = model.init(jax.random.PRNGKey(0), probe, probe, iters=1)

    if registry:
        # the restarting-supervisor path: registry threads artifact_dir
        # into the engines it builds; with a warm dir the live variant
        # AND a re-deploy of known weights load instead of compiling
        from raft_tpu.serving.registry import ModelRegistry

        reg = ModelRegistry(gather_window_s=0.0)
        try:
            reg.add_model("m", variables, cfg, iters=1,
                          envelope=[(1, 32, 32)], artifact_dir=cache_dir)
            live = reg._models["m"].live.engine.aot_stats()
            reg.deploy("m", variables, cfg, iters=1,
                       envelope=[(1, 32, 32)], artifact_dir=cache_dir,
                       canary_fraction=0.25)
            canary = reg._models["m"].canary.engine.aot_stats()
            reg.rollback("m")
        finally:
            reg.close()
        print("AOT_WORKER " + json.dumps({"live": live,
                                          "canary": canary}),
              flush=True)
        return 0

    eng = RAFTEngine(variables, cfg, iters=1, envelope=[],
                     precompile=False, aot_cache=cache_dir)
    host = np.random.RandomState(7)
    i1 = host.rand(1, 32, 32, 3).astype(np.float32) * 255
    i2 = host.rand(1, 32, 32, 3).astype(np.float32) * 255
    flow = np.asarray(eng.infer_batch(i1, i2))
    np.save(out_npy, flow)
    print("AOT_WORKER " + json.dumps(eng.aot_stats()), flush=True)
    return 0


if __name__ == "__main__":
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--cache", required=True)
    p.add_argument("--out", default="")
    p.add_argument("--registry", action="store_true")
    a = p.parse_args()
    sys.exit(main(a.cache, a.out, registry=a.registry))
