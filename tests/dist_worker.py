"""Worker for the 2-process distributed smoke test (not a test module).

Each process contributes its local CPU device to a 2-process
``jax.distributed`` cluster, builds the global mesh, feeds only its rows of
the global batch through ``host_local_batch``, and runs ONE jitted train
step — the multi-host path (parallel/distributed.py:40-82) end to end.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")

import jax  # noqa: E402

try:
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:
    pass
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np  # noqa: E402

from raft_tpu.config import RAFTConfig, TrainConfig  # noqa: E402
from raft_tpu.parallel import distributed as dist  # noqa: E402
from raft_tpu.parallel.mesh import make_mesh, replicated  # noqa: E402
from raft_tpu.training.train_step import (create_train_state,  # noqa: E402
                                          make_train_step)


def batch_geometry(spatial: int):
    """(B, H, W) for a given spatial factor — shared with the in-process
    comparator so both sides can't drift. spatial>1 shards feature rows;
    H must clear the 7x7-conv halo fence
    (parallel/mesh.validate_spatial_extent)."""
    return 2, (64 if spatial > 1 else 32), 32


def make_global_batch(B, H, W):
    """Deterministic global batch — shared with the in-process comparator
    (tests/test_distributed_multiprocess.py) so both sides consume
    byte-identical data."""
    host = np.random.RandomState(0)
    return {
        "image1": host.rand(B, H, W, 3).astype(np.float32) * 255,
        "image2": host.rand(B, H, W, 3).astype(np.float32) * 255,
        "flow": host.randn(B, H, W, 2).astype(np.float32),
        "valid": np.ones((B, H, W), np.float32),
    }


def main(process_id: int, port: str, spatial: int = 1) -> None:
    dist.initialize(f"localhost:{port}", 2, process_id)
    assert jax.process_count() == 2, jax.process_count()

    mesh = make_mesh(spatial=spatial)  # all devices across both processes
    B, H, W = batch_geometry(spatial)
    model_cfg = RAFTConfig(small=True)
    train_cfg = TrainConfig(stage="chairs", num_steps=10, batch_size=B,
                            iters=1)
    rng = jax.random.PRNGKey(0)
    state = create_train_state(model_cfg, train_cfg, rng, image_hw=(H, W))
    step = jax.jit(make_train_step(model_cfg, train_cfg))

    gbatch = make_global_batch(B, H, W)
    sl = dist.process_batch_slice(B)
    local = {k: v[sl] for k, v in gbatch.items()}
    with mesh:
        state = jax.device_put(state, replicated(mesh))
        sharded = dist.host_local_batch(local, mesh)
        _, metrics = step(state, sharded, rng)
    print(f"RESULT pid={process_id} loss={float(metrics['loss']):.6f} "
          f"procs={jax.process_count()} devices={len(jax.devices())}",
          flush=True)


if __name__ == "__main__":
    main(int(sys.argv[1]), sys.argv[2],
         int(sys.argv[3]) if len(sys.argv) > 3 else 1)
