"""End-to-end demo CLI test: real frames in, PNG visualizations out.

Drives ``cli/demo.py`` (demo.py:42-63 analog) with random-init small-model
weights over two real Sintel frames — covers weight loading, the padder,
the jitted forward, flow_viz, and the headless PNG writer in one pass.
"""

import glob
import os.path as osp

import numpy as np
import pytest

import jax


# bundled Sintel frames (repo root); the reference checkout's copy is the
# fallback so the test still runs from an unbundled source tree
_BUNDLED = osp.join(osp.dirname(osp.dirname(osp.abspath(__file__))),
                    "demo-frames")
REF_FRAMES = (_BUNDLED if osp.isdir(_BUNDLED)
              else "/root/reference/demo-frames")

if not osp.isdir(REF_FRAMES):  # pragma: no cover
    pytest.skip("demo frames not available", allow_module_level=True)


def test_demo_writes_flow_pngs(tmp_path):
    from PIL import Image

    import jax.numpy as jnp
    from raft_tpu.cli.demo import main
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models import RAFT
    from raft_tpu.tools.convert import save_converted

    # two downscaled frames keep CPU runtime low while staying real images
    frames = sorted(glob.glob(osp.join(REF_FRAMES, "*.png")))[:2]
    fdir = tmp_path / "frames"
    fdir.mkdir()
    for f in frames:
        Image.open(f).resize((128, 64)).save(fdir / osp.basename(f))

    model = RAFT(RAFTConfig(small=True))
    img = jnp.zeros((1, 64, 128, 3))
    variables = model.init(jax.random.PRNGKey(0), img, img, iters=1)
    weights = tmp_path / "w.msgpack"
    save_converted(variables, str(weights))

    out = tmp_path / "out"
    main(["--model", str(weights), "--path", str(fdir), "--out", str(out),
          "--small", "--iters", "2"])

    pngs = sorted(glob.glob(str(out / "*.png")))
    assert len(pngs) == 1  # 2 frames -> 1 pair
    arr = np.asarray(Image.open(pngs[0]))
    assert arr.ndim == 3 and arr.shape[2] == 3
    assert arr.std() > 0  # non-degenerate visualization
