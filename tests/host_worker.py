"""Subprocess body for the multi-host fleet drills: one
:class:`~raft_tpu.serving.hosts.HostWorker` served over the socket
transport (:func:`~raft_tpu.serving.transport.serve_forever`), the
reference worker process behind ``SocketTransport``.

Two modes:

- ``--stub`` (tier-1 cheap): a deterministic numpy stub engine — no
  jax, no compiles; outputs are a pure function of the inputs so the
  parent computes the bitwise oracle itself.
- ``--weights W.pkl --aot-root DIR`` (the real-stack kill drill): the
  engine is built LAZILY at ``prewarm`` time — after the parent's
  ``AOTCache.push`` has landed verified artifacts under ``--aot-root``
  — as a real ``RAFTEngine(aot_cache=..., precompile=True)``, so the
  joining host warms by LOADING pushed executables: the ``prewarm``
  reply's counters pin ZERO XLA compiles.

Prints ``PORT <n>`` on stdout once bound (``--port 0`` = ephemeral);
the parent reads it to build the transport. The parent SIGKILLs this
process mid-batch in the crash drill — there is no graceful shutdown
path on purpose.
"""

import argparse
import pickle
import sys

import numpy as np

from raft_tpu.serving.hosts import HostWorker
from raft_tpu.serving.transport import serve_forever


def _pad8(x):
    return -(-x // 8) * 8


class StubEngine:
    """Deterministic scheduler-facing engine: flow = per-pixel
    (i1 - i2) of the first two channels, scaled — a pure function of
    the inputs, so any process (parent oracle, either host) produces
    BITWISE-identical output. ``infer_delay_s`` widens the in-flight
    window the kill drill aims at."""

    warm_start = False
    wire = "f32"

    def __init__(self, infer_delay_s: float = 0.0):
        self.infer_delay_s = float(infer_delay_s)
        self._compiled = {}

    def bucket_capacity(self, h, w):
        fits = [s[0] for s in self._compiled
                if s[1] == _pad8(h) and s[2] == _pad8(w)]
        return max(fits) if fits else None

    def ensure_bucket(self, b, h, w):
        shape = (b, _pad8(h), _pad8(w))
        self._compiled[shape] = object()
        return shape

    def route_bucket(self, b, h, w):
        return (b, _pad8(h), _pad8(w))

    def drop_bucket(self, shape):
        return self._compiled.pop(shape, None) is not None

    def executable_count(self):
        return len(self._compiled)

    def infer_batch(self, i1, i2, **kw):
        if self.infer_delay_s:
            import time

            time.sleep(self.infer_delay_s)
        i1 = np.asarray(i1, np.float32)
        i2 = np.asarray(i2, np.float32)
        return ((i1 - i2)[..., :2] * 0.125).astype(np.float32)


def _real_factory(weights_path: str, aot_root: str, iters: int,
                  h: int, w: int):
    def build():
        from raft_tpu.config import RAFTConfig
        from raft_tpu.serving.engine import RAFTEngine

        with open(weights_path, "rb") as fh:
            variables = pickle.load(fh)
        cfg = RAFTConfig(small=True)
        # precompile over the envelope: with the pushed artifacts in
        # place every lower/compile is an AOT LOAD (aot_hits), pinned
        # by the prewarm reply's compiles==0
        return RAFTEngine(variables, cfg, iters=iters,
                          envelope=[(1, h, w)], precompile=True,
                          aot_cache=aot_root)
    return build


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--stub", action="store_true")
    ap.add_argument("--infer-delay-s", type=float, default=0.0)
    ap.add_argument("--weights")
    ap.add_argument("--aot-root")
    ap.add_argument("--iters", type=int, default=1)
    ap.add_argument("--height", type=int, default=32)
    ap.add_argument("--width", type=int, default=32)
    args = ap.parse_args(argv)

    if args.stub:
        worker = HostWorker(StubEngine(args.infer_delay_s),
                            aot_root=args.aot_root)
    else:
        if not (args.weights and args.aot_root):
            ap.error("real mode needs --weights and --aot-root")
        worker = HostWorker(
            engine_factory=_real_factory(args.weights, args.aot_root,
                                         args.iters, args.height,
                                         args.width),
            aot_root=args.aot_root)
    serve_forever(args.port, worker, ready_fh=sys.stdout)


if __name__ == "__main__":
    main()
