"""Golden tests for raft_tpu.ops against PyTorch oracles.

The oracles are torch *primitives* (grid_sample, interpolate, avg_pool2d,
unfold) — the same primitives the reference model is built from — so passing
these pins our NHWC ops to the reference's numerics.
"""

import numpy as np
import pytest
import torch
import torch.nn.functional as F

import jax.numpy as jnp

from raft_tpu.ops import (
    InputPadder,
    avg_pool2x2,
    bilinear_sampler,
    convex_upsample,
    coords_grid,
    upflow8,
)


def nchw(x_nhwc):
    return torch.from_numpy(np.asarray(x_nhwc)).permute(0, 3, 1, 2).contiguous()


def to_nhwc(t_nchw):
    return t_nchw.permute(0, 2, 3, 1).numpy()


class TestCoordsGrid:
    def test_matches_meshgrid(self):
        g = np.asarray(coords_grid(2, 3, 5))
        assert g.shape == (2, 3, 5, 2)
        # channel 0 = x (col), channel 1 = y (row)
        assert np.all(g[0, :, :, 0] == np.arange(5)[None, :])
        assert np.all(g[0, :, :, 1] == np.arange(3)[:, None])
        assert np.all(g[0] == g[1])


class TestBilinearSampler:
    @pytest.mark.parametrize("case", ["interior", "edges", "oob"])
    def test_vs_grid_sample(self, rng, case):
        B, H, W, C = 2, 13, 17, 6
        img = rng.randn(B, H, W, C).astype(np.float32)
        if case == "interior":
            xs = rng.uniform(0.5, W - 1.5, size=(B, 7, 9))
            ys = rng.uniform(0.5, H - 1.5, size=(B, 7, 9))
        elif case == "edges":
            xs = rng.uniform(-0.49, W - 0.51, size=(B, 7, 9))
            ys = rng.uniform(-0.49, H - 0.51, size=(B, 7, 9))
        else:  # far out of bounds
            xs = rng.uniform(-5, W + 5, size=(B, 7, 9))
            ys = rng.uniform(-5, H + 5, size=(B, 7, 9))
        coords = np.stack([xs, ys], axis=-1).astype(np.float32)

        got = np.asarray(bilinear_sampler(jnp.asarray(img), jnp.asarray(coords)))

        # torch oracle: pixel coords -> normalized [-1, 1], align_corners=True
        timg = nchw(img)
        gx = 2 * torch.from_numpy(coords[..., 0]) / (W - 1) - 1
        gy = 2 * torch.from_numpy(coords[..., 1]) / (H - 1) - 1
        grid = torch.stack([gx, gy], dim=-1)
        want = to_nhwc(F.grid_sample(timg, grid, align_corners=True))

        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


class TestUpflow8:
    def test_vs_interpolate(self, rng):
        flow = rng.randn(2, 6, 7, 2).astype(np.float32)
        got = np.asarray(upflow8(jnp.asarray(flow)))
        want = to_nhwc(
            8 * F.interpolate(nchw(flow), size=(48, 56), mode="bilinear",
                              align_corners=True)
        )
        np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-5)


class TestAvgPool:
    @pytest.mark.parametrize("hw", [(8, 8), (7, 9), (13, 6)])
    def test_vs_avg_pool2d(self, rng, hw):
        H, W = hw
        x = rng.randn(3, H, W, 5).astype(np.float32)
        got = np.asarray(avg_pool2x2(jnp.asarray(x)))
        want = to_nhwc(F.avg_pool2d(nchw(x), 2, stride=2))
        np.testing.assert_allclose(got, want, atol=1e-6, rtol=1e-6)

    def test_extra_leading_dims(self, rng):
        x = rng.randn(2, 4, 10, 12, 1).astype(np.float32)
        got = np.asarray(avg_pool2x2(jnp.asarray(x)))
        assert got.shape == (2, 4, 5, 6, 1)


class TestConvexUpsample:
    def test_vs_torch_unfold(self, rng):
        """Oracle reproduces core/raft.py:72-83 from torch primitives."""
        B, H, W = 2, 5, 6
        flow = rng.randn(B, H, W, 2).astype(np.float32)
        mask = rng.randn(B, H, W, 576).astype(np.float32)

        got = np.asarray(convex_upsample(jnp.asarray(flow), jnp.asarray(mask)))

        tflow = nchw(flow)
        tmask = nchw(mask).view(B, 1, 9, 8, 8, H, W)
        tmask = torch.softmax(tmask, dim=2)
        up = F.unfold(8 * tflow, [3, 3], padding=1).view(B, 2, 9, 1, 1, H, W)
        up = torch.sum(tmask * up, dim=2)
        up = up.permute(0, 1, 4, 2, 5, 3).reshape(B, 2, 8 * H, 8 * W)
        want = to_nhwc(up)

        np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


class TestInputPadder:
    @pytest.mark.parametrize("mode,hw", [("sintel", (436, 1024)),
                                         ("kitti", (375, 1242)),
                                         ("sintel", (440, 1024))])
    def test_pad_unpad_roundtrip(self, rng, mode, hw):
        H, W = hw
        img = rng.randn(1, H, W, 3).astype(np.float32)
        padder = InputPadder(img.shape, mode=mode)
        padded = padder.pad(jnp.asarray(img))
        assert padded.shape[1] % 8 == 0 and padded.shape[2] % 8 == 0
        back = np.asarray(padder.unpad(padded))
        np.testing.assert_array_equal(back, img)

    def test_matches_torch_replicate(self, rng):
        img = rng.randn(1, 11, 14, 3).astype(np.float32)
        padder = InputPadder(img.shape, mode="sintel")
        got = np.asarray(padder.pad(jnp.asarray(img)))
        l, r, t, b = padder._pad
        want = to_nhwc(F.pad(nchw(img), [l, r, t, b], mode="replicate"))
        np.testing.assert_array_equal(got, want)

    def test_kitti_pads_bottom_only(self):
        padder = InputPadder((1, 375, 1242, 3), mode="kitti")
        l, r, t, b = padder._pad
        assert t == 0 and b == 1

    def test_batched_matches_per_frame_oracle(self, rng):
        """convex_upsample_batched must be numerically interchangeable with
        the per-frame oracle: it is the same softmax + fp32 convex
        combination, only laid out pixels-on-lanes for the TPU memory tile
        (the per-iteration form burned ~35% of the measured train step)."""
        from raft_tpu.ops.flow_ops import convex_upsample_batched

        T, B, H, W = 3, 2, 5, 6
        flow = rng.randn(T, B, H, W, 2).astype(np.float32)
        mask = rng.randn(T, B, H, W, 576).astype(np.float32)

        got = np.asarray(convex_upsample_batched(jnp.asarray(flow),
                                                 jnp.asarray(mask)))
        assert got.shape == (T, B, 8 * H, 8 * W, 2)
        for t in range(T):
            want = np.asarray(convex_upsample(jnp.asarray(flow[t]),
                                              jnp.asarray(mask[t])))
            np.testing.assert_allclose(got[t], want, atol=1e-5, rtol=1e-5)

    def test_upflow8_batched_matches_per_frame(self, rng):
        from raft_tpu.ops.flow_ops import upflow8_batched

        T, B, H, W = 2, 2, 4, 5
        flow = rng.randn(T, B, H, W, 2).astype(np.float32)
        got = np.asarray(upflow8_batched(jnp.asarray(flow)))
        assert got.shape == (T, B, 8 * H, 8 * W, 2)
        for t in range(T):
            want = np.asarray(upflow8(jnp.asarray(flow[t])))
            np.testing.assert_allclose(got[t], want, atol=1e-5, rtol=1e-5)
