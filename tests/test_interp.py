"""forward_interpolate (warm-start) tests vs its mathematical definition
(core/utils/utils.py:26-54 semantics): forward-warp then nearest-fill."""

import numpy as np

from raft_tpu.ops.interp import forward_interpolate


class TestForwardInterpolate:
    def test_zero_flow_is_identity(self):
        flow = np.zeros((6, 8, 2), np.float32)
        np.testing.assert_array_equal(forward_interpolate(flow), flow)

    def test_uniform_shift_survives_warp(self):
        """A constant flow warps onto a shifted grid; nearest interpolation
        back onto the integer grid reproduces the constant field."""
        flow = np.full((8, 10, 2), 1.0, np.float32)
        out = forward_interpolate(flow)
        np.testing.assert_allclose(out, 1.0)

    def test_shape_and_dtype(self, rng):
        flow = rng.randn(5, 7, 2).astype(np.float32) * 2
        out = forward_interpolate(flow)
        assert out.shape == (5, 7, 2) and out.dtype == np.float32
        assert np.isfinite(out).all()
