"""2-process ``jax.distributed`` smoke test (VERDICT r1 next-step #9).

Spawns two real OS processes (tests/dist_worker.py), each with one local
CPU device, wired into one cluster via ``dist.initialize``; each feeds its
half of the global batch through ``host_local_batch`` and runs one jitted
train step. Asserts both processes compute the SAME loss, and that it
matches a single-process run of the identical global batch on a 2-device
mesh — the only previously-untested path in parallel/distributed.py.
"""

import os
import re
import socket
import subprocess
import sys

import numpy as np
import pytest

import jax


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _single_process_loss() -> float:
    """Same batch/seeds as dist_worker, on an in-process 2-device mesh."""
    from raft_tpu.config import RAFTConfig, TrainConfig
    from raft_tpu.parallel.mesh import make_mesh, replicated, shard_batch
    from raft_tpu.training.train_step import (create_train_state,
                                              make_train_step)

    B, H, W = 2, 32, 32
    model_cfg = RAFTConfig(small=True)
    train_cfg = TrainConfig(stage="chairs", num_steps=10, batch_size=B,
                            iters=1)
    rng = jax.random.PRNGKey(0)
    state = create_train_state(model_cfg, train_cfg, rng, image_hw=(H, W))
    step = jax.jit(make_train_step(model_cfg, train_cfg))
    host = np.random.RandomState(0)
    batch = {
        "image1": host.rand(B, H, W, 3).astype(np.float32) * 255,
        "image2": host.rand(B, H, W, 3).astype(np.float32) * 255,
        "flow": host.randn(B, H, W, 2).astype(np.float32),
        "valid": np.ones((B, H, W), np.float32),
    }
    mesh = make_mesh(2)
    with mesh:
        state = jax.device_put(state, replicated(mesh))
        _, metrics = step(state, shard_batch(batch, mesh), rng)
    return float(metrics["loss"])


def test_two_process_train_step_matches_single_process():
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "dist_worker.py")
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    procs = [subprocess.Popen([sys.executable, worker, str(i), str(port)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True, env=env)
             for i in range(2)]
    outs = [p.communicate(timeout=540)[0] for p in procs]
    losses = []
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-2000:]}"
        m = re.search(r"RESULT pid=\d+ loss=([\d.]+) procs=2 devices=2", out)
        assert m, f"worker {i} output malformed:\n{out[-2000:]}"
        losses.append(float(m.group(1)))

    assert losses[0] == losses[1]
    # same global computation as one process on a 2-device mesh
    assert losses[0] == pytest.approx(_single_process_loss(), rel=1e-5)
