"""2-process ``jax.distributed`` smoke test (VERDICT r1 next-step #9).

Spawns two real OS processes (tests/dist_worker.py), each with one local
CPU device, wired into one cluster via ``dist.initialize``; each feeds its
half of the global batch through ``host_local_batch`` and runs one jitted
train step. Asserts both processes compute the SAME loss, and that it
matches a single-process run of the identical global batch on a 2-device
mesh — the only previously-untested path in parallel/distributed.py.
"""

import os
import re
import socket
import subprocess
import sys

import numpy as np
import pytest

import jax


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _single_process_loss(n_devices: int = 2, spatial: int = 1) -> float:
    """Same batch/seeds as dist_worker, on an in-process mesh."""
    from raft_tpu.config import RAFTConfig, TrainConfig
    from raft_tpu.parallel.mesh import make_mesh, replicated, shard_batch
    from raft_tpu.training.train_step import (create_train_state,
                                              make_train_step)
    from tests.dist_worker import batch_geometry, make_global_batch

    B, H, W = batch_geometry(spatial)
    model_cfg = RAFTConfig(small=True)
    train_cfg = TrainConfig(stage="chairs", num_steps=10, batch_size=B,
                            iters=1)
    rng = jax.random.PRNGKey(0)
    state = create_train_state(model_cfg, train_cfg, rng, image_hw=(H, W))
    step = jax.jit(make_train_step(model_cfg, train_cfg))
    batch = make_global_batch(B, H, W)
    mesh = make_mesh(n_devices, spatial=spatial)
    with mesh:
        state = jax.device_put(state, replicated(mesh))
        _, metrics = step(state, shard_batch(batch, mesh), rng)
    return float(metrics["loss"])


def _run_two_process(spatial: int, local_devices: int) -> list:
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "dist_worker.py")
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    if local_devices > 1:
        env["XLA_FLAGS"] = (f"--xla_force_host_platform_device_count="
                            f"{local_devices}")
    cmd_tail = [str(port)] + ([str(spatial)] if spatial > 1 else [])
    procs = [subprocess.Popen([sys.executable, worker, str(i)] + cmd_tail,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True, env=env)
             for i in range(2)]
    outs = [p.communicate(timeout=540)[0] for p in procs]
    losses = []
    total = 2 * local_devices
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-2000:]}"
        m = re.search(rf"RESULT pid=\d+ loss=([\d.]+) procs=2 "
                      rf"devices={total}", out)
        assert m, f"worker {i} output malformed:\n{out[-2000:]}"
        losses.append(float(m.group(1)))
    return losses


def test_two_process_train_step_matches_single_process():
    losses = _run_two_process(spatial=1, local_devices=1)
    assert losses[0] == losses[1]
    # same global computation as one process on a 2-device mesh
    assert losses[0] == pytest.approx(_single_process_loss(2), rel=1e-5)


def test_two_process_spatial_mesh_matches_single_process():
    """The multi-host pod shape: data axis across processes (the DCN-side
    gradient psum), spatial axis across each process's TWO local devices
    (the ICI-side halo exchanges) — mesh (data=2, spatial=2), each host
    feeding only its batch rows at full height through host_local_batch
    (which must split them over its local spatial shards)."""
    losses = _run_two_process(spatial=2, local_devices=2)
    assert losses[0] == losses[1]
    assert losses[0] == pytest.approx(
        _single_process_loss(4, spatial=2), rel=1e-5)
