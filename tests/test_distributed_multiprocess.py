"""2-process ``jax.distributed`` smoke test (VERDICT r1 next-step #9).

Spawns two real OS processes (tests/dist_worker.py), each with one local
CPU device, wired into one cluster via ``dist.initialize``; each feeds its
half of the global batch through ``host_local_batch`` and runs one jitted
train step. Asserts both processes compute the SAME loss, and that it
matches a single-process run of the identical global batch on a 2-device
mesh — the only previously-untested path in parallel/distributed.py.

The whole module is gated on an environment probe: some hosts (and some
jaxlib builds) wire the 2-process cluster up fine but cannot run the
cross-process collectives the train step needs (observed: XLA
"Multiprocess computations aren't implemented on the CPU backend").
That is an environment verdict, not a code regression — the probe runs
the minimal failing op (a 2-process ``sync_global_devices`` barrier)
once per session and SKIPS the tests with the captured reason when the
backend can't start, so tier-1 reads clean instead of carrying two
known-environment failures every run.
"""

import functools
import os
import re
import socket
import subprocess
import sys

import numpy as np
import pytest

import jax

from tests.conftest import mesh_subprocess_env


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


#: the minimal 2-process collective: initialize + a global barrier
#: (sync_global_devices rides broadcast_one_to_all -> an all-reduce —
#: the exact op class the real workers die on when the backend lacks
#: multiprocess support). Tiny on purpose: no model, no train step.
_PROBE_SRC = """
import sys
import jax
jax.distributed.initialize(f"localhost:{sys.argv[2]}", num_processes=2,
                           process_id=int(sys.argv[1]))
from jax.experimental import multihost_utils
multihost_utils.sync_global_devices("probe")
print("PROBE_OK", flush=True)
"""


@functools.lru_cache(maxsize=1)
def _multiprocess_backend_probe():
    """(ok, reason): can this host actually run 2-process
    ``jax.distributed`` collectives on the configured backend? Cached
    for the session — one ~10s probe gates the whole module."""
    port = _free_port()
    env = mesh_subprocess_env(local_devices=1)
    procs = [subprocess.Popen(
        [sys.executable, "-c", _PROBE_SRC, str(i), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(2)]
    try:
        outs = [p.communicate(timeout=180)[0] for p in procs]
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        return False, "probe barrier timed out (cluster never formed)"
    if all(p.returncode == 0 and "PROBE_OK" in out
           for p, out in zip(procs, outs)):
        return True, ""
    bad = next(out for p, out in zip(procs, outs)
               if p.returncode != 0 or "PROBE_OK" not in out)
    lines = [ln for ln in bad.strip().splitlines() if ln.strip()]
    errs = [ln for ln in lines if "Error" in ln or "error:" in ln]
    return False, (errs[-1] if errs else lines[-1] if lines
                   else "no output").strip()


@pytest.fixture(autouse=True)
def _require_multiprocess_backend():
    ok, reason = _multiprocess_backend_probe()
    if not ok:
        pytest.skip(
            "2-process jax.distributed collectives unavailable on "
            f"this host: {reason}")


def _single_process_loss(n_devices: int = 2, spatial: int = 1) -> float:
    """Same batch/seeds as dist_worker, on an in-process mesh."""
    from raft_tpu.config import RAFTConfig, TrainConfig
    from raft_tpu.parallel.mesh import make_mesh, replicated, shard_batch
    from raft_tpu.training.train_step import (create_train_state,
                                              make_train_step)
    from tests.dist_worker import batch_geometry, make_global_batch

    B, H, W = batch_geometry(spatial)
    model_cfg = RAFTConfig(small=True)
    train_cfg = TrainConfig(stage="chairs", num_steps=10, batch_size=B,
                            iters=1)
    rng = jax.random.PRNGKey(0)
    state = create_train_state(model_cfg, train_cfg, rng, image_hw=(H, W))
    step = jax.jit(make_train_step(model_cfg, train_cfg))
    batch = make_global_batch(B, H, W)
    mesh = make_mesh(n_devices, spatial=spatial)
    with mesh:
        state = jax.device_put(state, replicated(mesh))
        _, metrics = step(state, shard_batch(batch, mesh), rng)
    return float(metrics["loss"])


def _run_two_process(spatial: int, local_devices: int) -> list:
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "dist_worker.py")
    env = mesh_subprocess_env(local_devices=local_devices)
    cmd_tail = [str(port)] + ([str(spatial)] if spatial > 1 else [])
    procs = [subprocess.Popen([sys.executable, worker, str(i)] + cmd_tail,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True, env=env)
             for i in range(2)]
    outs = [p.communicate(timeout=540)[0] for p in procs]
    losses = []
    total = 2 * local_devices
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-2000:]}"
        m = re.search(rf"RESULT pid=\d+ loss=([\d.]+) procs=2 "
                      rf"devices={total}", out)
        assert m, f"worker {i} output malformed:\n{out[-2000:]}"
        losses.append(float(m.group(1)))
    return losses


def test_two_process_train_step_matches_single_process():
    losses = _run_two_process(spatial=1, local_devices=1)
    assert losses[0] == losses[1]
    # same global computation as one process on a 2-device mesh
    assert losses[0] == pytest.approx(_single_process_loss(2), rel=1e-5)


def test_two_process_spatial_mesh_matches_single_process():
    """The multi-host pod shape: data axis across processes (the DCN-side
    gradient psum), spatial axis across each process's TWO local devices
    (the ICI-side halo exchanges) — mesh (data=2, spatial=2), each host
    feeding only its batch rows at full height through host_local_batch
    (which must split them over its local spatial shards)."""
    losses = _run_two_process(spatial=2, local_devices=2)
    assert losses[0] == losses[1]
    assert losses[0] == pytest.approx(
        _single_process_loss(4, spatial=2), rel=1e-5)
