"""Rematerialized refinement loop: identical outputs, working grads."""

import numpy as np

import jax
import jax.numpy as jnp

from raft_tpu.config import RAFTConfig, TrainConfig
from raft_tpu.training.train_step import create_train_state, make_train_step


class TestRemat:
    def test_forward_identical_and_grads_finite(self, rng):
        img1 = jnp.asarray(rng.rand(1, 32, 32, 3).astype(np.float32) * 255)
        img2 = jnp.asarray(rng.rand(1, 32, 32, 3).astype(np.float32) * 255)

        from raft_tpu.models import RAFT

        outs = {}
        for key, kw in (("off", dict(remat=False)),
                        ("full", dict(remat=True)),
                        ("dots", dict(remat=True, remat_policy="dots"))):
            model = RAFT(RAFTConfig(small=True, **kw))
            variables = model.init(jax.random.PRNGKey(0), img1, img2,
                                   iters=1)
            _, up = model.apply(variables, img1, img2, iters=3,
                                test_mode=True)
            outs[key] = np.asarray(up)
        np.testing.assert_allclose(outs["full"], outs["off"], atol=1e-5,
                                   rtol=1e-5)
        np.testing.assert_allclose(outs["dots"], outs["off"], atol=1e-5,
                                   rtol=1e-5)

    def test_train_step_with_remat(self, rng):
        model_cfg = RAFTConfig(small=True, remat=True)
        train_cfg = TrainConfig(stage="chairs", num_steps=10, batch_size=2,
                                iters=2)
        state = create_train_state(model_cfg, train_cfg,
                                   jax.random.PRNGKey(0), image_hw=(32, 32))
        step = jax.jit(make_train_step(model_cfg, train_cfg))
        batch = {
            "image1": jnp.asarray(
                rng.rand(2, 32, 32, 3).astype(np.float32) * 255),
            "image2": jnp.asarray(
                rng.rand(2, 32, 32, 3).astype(np.float32) * 255),
            "flow": jnp.asarray(rng.randn(2, 32, 32, 2).astype(np.float32)),
            "valid": jnp.ones((2, 32, 32), jnp.float32),
        }
        state, metrics = step(state, batch, jax.random.PRNGKey(1))
        assert np.isfinite(float(metrics["loss"]))
        assert int(state.step) == 1
