"""Validator metric math with stubbed forwards — pins EPE aggregation and
the KITTI F1-all definition (evaluate.py:118-124,148-163) without weights
or datasets on disk."""

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu.config import RAFTConfig
from raft_tpu.evaluation import evaluate as ev


class FakeKITTI:
    """Two sparse-GT frames with hand-picked flows."""

    def __init__(self, *a, **k):
        h, w = 16, 16
        gt = np.zeros((h, w, 2), np.float32)
        gt[0, 0] = [10.0, 0.0]
        valid = np.zeros((h, w), np.float32)
        valid[0, 0] = 1.0   # one valid pixel per frame
        valid[0, 1] = 1.0   # gt zero here
        self.samples = [
            (np.zeros((h, w, 3), np.float32), np.zeros((h, w, 3), np.float32),
             gt, valid),
            (np.zeros((h, w, 3), np.float32), np.zeros((h, w, 3), np.float32),
             np.zeros((h, w, 2), np.float32), valid),
        ]

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        return self.samples[i]


def fake_forward_returning(flow_value):
    """make_forward stub: prediction = constant flow everywhere."""

    def make_forward(config, iters):
        def fwd(variables, i1, i2):
            B, H, W, _ = i1.shape
            flow = jnp.broadcast_to(
                jnp.asarray(flow_value, jnp.float32), (B, H, W, 2))
            return flow, flow

        return fwd, fwd

    return make_forward


class TestKITTIF1:
    def test_f1_counts_large_relative_outliers(self, monkeypatch):
        # prediction [6, 0] everywhere:
        # frame 1 pixel (0,0): gt [10,0] -> epe 4 > 3, epe/mag 0.4 > .05 ✓out
        #          pixel (0,1): gt 0 -> epe 6 > 3, ratio inf ✓ outlier
        # frame 2 both pixels gt 0 -> epe 6 ✓ outliers
        monkeypatch.setattr(ev, "make_forward", fake_forward_returning([6, 0]))
        monkeypatch.setattr(ev.ds, "KITTI", FakeKITTI)
        res = ev.validate_kitti({}, RAFTConfig(small=True))
        assert res["kitti-f1"] == pytest.approx(100.0)
        assert res["kitti-epe"] == pytest.approx((5.0 + 6.0) / 2)

    def test_f1_spares_small_relative_error(self, monkeypatch):
        # prediction [9.8, 0]: pixel (0,0) epe 0.2 (inlier);
        # pixel (0,1) gt 0 -> epe 9.8 outlier => half the valid pixels per
        # frame 1; frame 2: both outliers
        monkeypatch.setattr(ev, "make_forward",
                            fake_forward_returning([9.8, 0]))
        monkeypatch.setattr(ev.ds, "KITTI", FakeKITTI)
        res = ev.validate_kitti({}, RAFTConfig(small=True))
        assert res["kitti-f1"] == pytest.approx(100.0 * 3 / 4)


class FakeSintel:
    def __init__(self, *a, split="training", dstype="clean", **k):
        h, w = 8, 8
        gt = np.full((h, w, 2), 2.0, np.float32)
        self.samples = [(np.zeros((h, w, 3), np.float32),
                         np.zeros((h, w, 3), np.float32), gt,
                         np.ones((h, w), np.float32))] * 2

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        return self.samples[i]


class TestSintelEPE:
    def test_epe_mean_of_per_image_means(self, monkeypatch):
        monkeypatch.setattr(ev, "make_forward",
                            fake_forward_returning([2.0, 2.0]))
        monkeypatch.setattr(ev.ds, "MpiSintel", FakeSintel)
        res = ev.validate_sintel({}, RAFTConfig(small=True))
        # prediction==gt in u, off by 0 in v? pred [2,2] vs gt [2,2]: epe 0
        assert res["clean"] == pytest.approx(0.0)
        assert res["final"] == pytest.approx(0.0)


class TestShapeBucketing:
    def test_kitti_sizes_share_one_bucket_and_crop_restores(self):
        """All real KITTI-15 frame sizes must land in ONE padded shape
        (one jit compile for the whole dataset), and crop+unpad must
        restore the original geometry with the interior untouched."""
        rng = np.random.RandomState(0)
        shapes = [(375, 1242), (370, 1224), (374, 1238), (376, 1241)]
        buckets = set()
        for h, w in shapes:
            img = rng.rand(h, w, 3).astype(np.float32)
            i1, i2, padder, crop = ev._to_device_pair(img, img, "kitti",
                                                      bucket=64)
            buckets.add(i1.shape)
            # crop+unpad round-trips the padded image back to the original
            back = padder.unpad(ev._crop(i1, crop))
            np.testing.assert_array_equal(np.asarray(back)[0], img)
            flow = jnp.zeros((1, i1.shape[1], i1.shape[2], 2))
            out = padder.unpad(ev._crop(flow, crop))
            assert out.shape == (1, h, w, 2)
        assert buckets == {(1, 384, 1280, 3)}

    def test_no_bucket_keeps_exact_padded_shape(self):
        img = np.zeros((375, 1242, 3), np.float32)
        i1, _, _, crop = ev._to_device_pair(img, img, "kitti", bucket=None)
        assert i1.shape == (1, 376, 1248, 3)
        assert crop == (376, 1248)

    def test_bucketed_metric_delta_is_bounded_kitti_size(self):
        """_to_device_pair documents O(1e-2) px movement from the bucket's
        edge-fill beyond the ÷8 pad. MEASURE it on a KITTI-sized real
        image: the EPE-against-GT delta between the bucketed and
        unbucketed paths must stay below the promised tolerance.

        Needs TRAINED weights (tests/fixtures/raft-small-cputrained
        .msgpack, produced by tools/train_reference_ckpt.py + convert):
        at random init the model emits ~140 px garbage whose lookups
        wander deep into the pad region — measured delta there is ~3 px,
        which says nothing about the claim, since the claim (like eval
        itself) is about weights whose flow tracks the image."""
        import os.path as osp

        import cv2
        import jax

        from raft_tpu.models import RAFT
        from raft_tpu.tools.convert import load_converted

        fixture = osp.join(osp.dirname(__file__), "fixtures",
                           "raft-small-cputrained.msgpack")
        if not osp.exists(fixture):
            pytest.skip("trained-weights fixture not present")

        h, w = 375, 1242
        frame = cv2.cvtColor(
            cv2.imread(osp.join(osp.dirname(__file__), "..", "demo-frames",
                                "frame_0016.png")), cv2.COLOR_BGR2RGB)
        img1 = cv2.resize(frame, (w, h)).astype(np.float32)
        img2 = np.roll(img1, 3, axis=1)  # a rigid 3-px shift as "motion"

        cfg = RAFTConfig(small=True)
        model = RAFT(cfg)
        variables = load_converted(fixture, cfg)

        def run(bucket):
            i1, i2, padder, crop = ev._to_device_pair(img1, img2, "kitti",
                                                      bucket=bucket)
            _, flow = model.apply(variables, i1, i2, iters=4,
                                  test_mode=True)
            return np.asarray(padder.unpad(ev._crop(flow, crop)))[0]

        flow_nb = run(None)
        flow_b = run(64)
        assert np.abs(flow_nb).max() > 0.1, "degenerate flow — not probative"
        gt = np.zeros((h, w, 2), np.float32)
        gt[..., 0] = -3.0
        epe_nb = float(np.linalg.norm(flow_nb - gt, axis=-1).mean())
        epe_b = float(np.linalg.norm(flow_b - gt, axis=-1).mean())
        # the promise: bucketing moves the dataset metric by < 0.01 px
        assert abs(epe_b - epe_nb) < 1e-2, (epe_b, epe_nb)
        # pointwise movement is NOT localized: the fill region shifts the
        # encoders' instance-norm statistics, which couple every pixel to
        # the fill content (measured: up to ~6 px near the fill, ~2.5 px
        # even in the top rows — while the dataset metric above moves
        # <1e-2). Pin the catastrophe bound: movement stays a fraction of
        # the flow scale, nowhere near the O(100 px) of an actual
        # bucket-routing bug (wrong crop, leaked fill rows)
        assert np.abs(flow_b - flow_nb).max() < 10.0
        assert flow_b.shape == flow_nb.shape == (h, w, 2)


class FakeSintelVaried:
    """5 frames (odd count -> trailing partial batch) with per-image GT."""

    def __init__(self, *a, split="training", dstype="clean", **k):
        h, w = 8, 8
        rng = np.random.RandomState(3)
        self.samples = []
        for _ in range(5):
            img = rng.rand(h, w, 3).astype(np.float32) * 255
            gt = rng.randn(h, w, 2).astype(np.float32)
            self.samples.append((img, img.copy(), gt,
                                 np.ones((h, w), np.float32)))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        return self.samples[i]


def image_dependent_forward(config, iters):
    """Stub whose prediction depends on each image's content, so batching
    bugs (sample mix-ups, trailing-pad leakage) change the metric."""
    def fwd(variables, i1, i2):
        flow = jnp.stack([jnp.mean(i1, axis=-1) * 0.01,
                          jnp.mean(i2, axis=-1) * 0.02], axis=-1)
        return flow[:, ::8, ::8], flow

    return fwd, fwd


class TestBatchedEvalEquivalence:
    def test_sintel_metrics_independent_of_batch_size(self, monkeypatch):
        monkeypatch.setattr(ev, "make_forward", image_dependent_forward)
        monkeypatch.setattr(ev.ds, "MpiSintel", FakeSintelVaried)
        r1 = ev.validate_sintel({}, RAFTConfig(small=True), batch_size=1)
        r3 = ev.validate_sintel({}, RAFTConfig(small=True), batch_size=3)
        assert r1["clean"] == pytest.approx(r3["clean"], rel=1e-6)
        assert r1["final"] == pytest.approx(r3["final"], rel=1e-6)
        assert r1["clean"] > 0  # non-degenerate


class FakeSintelTestSplit:
    """Test-split items: (img1, img2, (sequence, frame)). Two sequences so
    the warm-start chain must reset at the boundary."""

    def __init__(self, *a, split="training", dstype="clean", **k):
        h, w = 16, 16
        img = np.zeros((h, w, 3), np.float32)
        self.samples = [
            (img, img, ("alley_1", 0)),
            (img, img, ("alley_1", 1)),
            (img, img, ("market_6", 0)),
        ]

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        return self.samples[i]


class TestSintelSubmission:
    def test_real_model_real_frames_warm_start_end_to_end(self, tmp_path):
        """The FULL warm-start submission loop with nothing stubbed: real
        MpiSintel directory walk over genuine Sintel frames (the bundled
        demo-frames), the real small model, real forward_interpolate
        chaining, real .flo output files (VERDICT r2 weak #8 — datasets
        can't be staged in this sandbox, but the bundled frames ARE
        MPI-Sintel data)."""
        import os
        import os.path as osp

        import jax
        from PIL import Image

        from raft_tpu.data import frame_utils
        from raft_tpu.models import RAFT

        src = osp.join(osp.dirname(__file__), "..", "demo-frames")
        # stage BOTH dstypes: the writer requires a complete test tree
        # (matching the reference, whose os.listdir raises on a missing
        # pass) and our empty-scan guard does the same
        for dstype in ("clean", "final"):
            scene = tmp_path / "Sintel" / "test" / dstype / "ambush_2"
            os.makedirs(scene)
            for i, name in enumerate(["frame_0016.png", "frame_0017.png",
                                      "frame_0018.png"]):
                img = Image.open(osp.join(src, name))
                # small crop keeps CPU runtime sane; still real pixels
                img.crop((0, 0, 192, 128)).save(scene / f"frame_{i:04d}.png")

        cfg = RAFTConfig(small=True)
        variables = RAFT(cfg).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)),
            jnp.zeros((1, 64, 64, 3)), iters=1)
        out = tmp_path / "submission"
        ev.create_sintel_submission(variables, cfg, iters=2,
                                    warm_start=True,
                                    output_path=str(out),
                                    data_root=str(tmp_path))
        flos = sorted((out / "clean" / "ambush_2").glob("*.flo"))
        assert [f.name for f in flos] == ["frame0001.flo", "frame0002.flo"]
        flow = frame_utils.read_gen(str(flos[0]))
        assert flow.shape == (128, 192, 2)
        assert np.isfinite(flow).all() and np.abs(flow).max() > 0.01

    def test_warm_start_chain_and_files(self, monkeypatch, tmp_path):
        """Warm start must use flow_init for consecutive frames of one
        sequence, reset at sequence boundaries (evaluate.py:30-41), and
        write frame%04d.flo named from 1 (evaluate.py:47-49)."""
        calls = {"cold": 0, "warm": 0}

        def make_forward(config, iters):
            def fwd(variables, i1, i2):
                calls["cold"] += 1
                B, H, W, _ = i1.shape
                flow = jnp.ones((B, H, W, 2), jnp.float32)
                return flow[:, ::8, ::8] * 0.5, flow

            def fwd_init(variables, i1, i2, flow_init):
                calls["warm"] += 1
                B, H, W, _ = i1.shape
                flow = jnp.full((B, H, W, 2), 2.0, jnp.float32)
                return flow[:, ::8, ::8] * 0.5, flow

            return fwd, fwd_init

        monkeypatch.setattr(ev, "make_forward", make_forward)
        monkeypatch.setattr(ev.ds, "MpiSintel", FakeSintelTestSplit)
        out = str(tmp_path / "sub")
        ev.create_sintel_submission({}, RAFTConfig(small=True),
                                    warm_start=True, output_path=out)

        # per dstype: frame0 cold, frame1 warm (same seq), frame0 cold (new)
        assert calls == {"cold": 4, "warm": 2}
        for dstype in ("clean", "final"):
            for seq, frame in [("alley_1", 1), ("alley_1", 2),
                               ("market_6", 1)]:
                p = tmp_path / "sub" / dstype / seq / f"frame{frame:04d}.flo"
                assert p.exists(), p
        from raft_tpu.data import frame_utils
        uv = frame_utils.read_flow(
            str(tmp_path / "sub" / "clean" / "alley_1" / "frame0002.flo"))
        np.testing.assert_allclose(uv, 2.0)  # warm-start forward's output


class TestMissingDatasets:
    """Unstaged data must surface as FileNotFoundError, not an empty
    reduction: the trainer's mid-run validation (trainer.run_validation)
    catches exactly that type to skip — an escaping ValueError killed a
    real on-chip 450-step run at its step-200 validation."""

    def test_validate_chairs_raises_filenotfound(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="FlyingChairs"):
            ev.validate_chairs(None, RAFTConfig(small=True),
                               data_root=str(tmp_path))

    def test_validate_sintel_raises_filenotfound(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="Sintel"):
            ev.validate_sintel(None, RAFTConfig(small=True),
                               data_root=str(tmp_path))

    def test_validate_kitti_raises_filenotfound(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="KITTI"):
            ev.validate_kitti(None, RAFTConfig(small=True),
                              data_root=str(tmp_path))

    def test_run_validation_skips_all_missing(self, tmp_path, capsys):
        from raft_tpu.training.trainer import run_validation

        results = run_validation(None, RAFTConfig(small=True),
                                 ["chairs", "sintel", "kitti"],
                                 str(tmp_path))
        assert results == {}
        out = capsys.readouterr().out
        assert out.count("skipped") == 3

    def test_fetch_dataset_empty_mix_raises(self, tmp_path):
        from raft_tpu.data.datasets import fetch_dataset

        with pytest.raises(FileNotFoundError, match="chairs"):
            fetch_dataset("chairs", (368, 496), data_root=str(tmp_path))
