"""graftaudit: the compiled-artifact audit gate (tools/graftaudit/).

Three layers, mirroring test_graftlint:

- per-rule fixture tests: each rule H1-H6 has a fixture program under
  ``tests/graftaudit_fixtures/`` with a PLANTED violation (a debug
  callback, a promotion-widened dot, an unbucketed shape sweep, an
  unusable donation, a busted byte budget, a closure-baked weight) —
  detection must fire, and both suppression channels (a Waiver on the
  target, the pragma analog; a baseline entry) must round-trip;
- mechanism tests: shrink-only budgets, stale-baseline failure,
  waiver-justification enforcement;
- the repo gate: ``python -m tools.graftaudit --json`` over the REAL
  train step / serving path / engine canaries must exit 0 with no
  findings — new jaxpr/HLO-tier violations anywhere in those programs
  fail tier-1. The committed baseline must stay EMPTY (the seed audit
  came back clean; the fp32 correlation island is a justified waiver
  on the target declaration, not a baselined finding).

Unlike graftlint (pure-stdlib ast) this suite traces real jax programs;
fixtures are kept tiny so the whole file prices in well under the
audit's own <120 s gate budget.
"""

import dataclasses
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "graftaudit_fixtures")
BASELINE = os.path.join(REPO, "tools", "graftaudit", "baseline.json")
BUDGETS = os.path.join(REPO, "tools", "graftaudit", "budgets.json")

if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.graftaudit import (Waiver, apply_baseline,  # noqa: E402
                              audit_targets, load_baseline,
                              load_fixture_targets, shrink_budgets,
                              write_baseline)
from tools.graftaudit.core import main  # noqa: E402

RULES = ("H1", "H2", "H3", "H4", "H5", "H6")

_AUDIT_CACHE = {}


def fixture(name):
    return os.path.join(FIXTURES, name)


def audit_fixture(name):
    """(targets, budgets, findings) for one fixture module, audited
    once per test session — detection, waiver, and baseline tests all
    read the same run."""
    if name not in _AUDIT_CACHE:
        targets, budgets = load_fixture_targets(fixture(name))
        findings, _, _ = audit_targets(targets, budgets=budgets)
        _AUDIT_CACHE[name] = (targets, budgets, findings)
    return _AUDIT_CACHE[name]


class TestRuleFixtures:
    @pytest.mark.parametrize("rule", RULES)
    def test_planted_violation_detected(self, rule):
        _, _, findings = audit_fixture(f"{rule.lower()}_pos.py")
        assert any(f.rule == rule for f in findings), \
            f"{rule} fixture produced no {rule} finding: {findings}"

    @pytest.mark.parametrize("rule", RULES)
    def test_waiver_suppresses_with_justification(self, rule):
        """The pragma analog: a Waiver(rule, detail-substring, reason)
        on the target declaration silences exactly that finding."""
        targets, budgets, findings = audit_fixture(f"{rule.lower()}_pos.py")
        details = [f.detail for f in findings if f.rule == rule]
        assert details
        waived_targets = [
            dataclasses.replace(
                t, waivers=t.waivers + tuple(
                    Waiver(rule, d, "fixture round-trip")
                    for d in details))
            for t in targets]
        refindings, _, _ = audit_targets(waived_targets, budgets=budgets)
        assert not any(f.rule == rule for f in refindings), \
            f"waiver did not suppress: {refindings}"
        # a waiver naming a DIFFERENT rule must not suppress
        wrong = "H1" if rule != "H1" else "H2"
        wrong_targets = [
            dataclasses.replace(
                t, waivers=tuple(Waiver(wrong, d, "wrong rule")
                                 for d in details))
            for t in targets]
        refindings, _, _ = audit_targets(wrong_targets, budgets=budgets)
        assert any(f.rule == rule for f in refindings)

    @pytest.mark.parametrize("rule", RULES)
    def test_baseline_roundtrip_then_stale(self, rule, tmp_path):
        """Grandfathering consumes the entry; a fixed finding leaves a
        STALE entry that must fail (it would otherwise silently
        grandfather the next reintroduction)."""
        targets, _, findings = audit_fixture(f"{rule.lower()}_pos.py")
        bl = tmp_path / "baseline.json"
        write_baseline(str(bl), findings)
        new, stale = apply_baseline(findings, load_baseline(str(bl)))
        assert new == [] and stale == []
        # "fixed": nothing found, every entry unconsumed -> stale
        new, stale = apply_baseline(
            [], load_baseline(str(bl)),
            audited_targets=[t.name for t in targets])
        assert new == [] and len(stale) == len(findings)
        # an entry for a target OUTSIDE this run is merely unchecked
        new, stale = apply_baseline(
            [], load_baseline(str(bl)),
            audited_targets=["some_other_target"])
        assert new == [] and stale == []

    def test_clean_fixture_is_silent(self):
        """The negative: bf16 cast at the site, donation that threads
        through, weights as args, documented bucket count — all rules
        silent."""
        _, _, findings = audit_fixture("clean.py")
        assert findings == [], \
            "; ".join(f.render() for f in findings)


class TestMechanisms:
    def test_waiver_requires_justification(self):
        with pytest.raises(ValueError, match="justification"):
            Waiver("H2", "anything", "   ")

    def test_budgets_shrink_only(self):
        budgets = {"targets": {"t": [
            {"band": "whole-step", "match": "", "max_bytes": 1000},
        ]}}
        # improvement observed: ceiling comes DOWN (to observed plus
        # ~10% headroom, whatever the float rounding)
        out = shrink_budgets(budgets, {"t": {"whole-step": 100}})
        assert 100 <= out["targets"]["t"][0]["max_bytes"] <= 115
        assert out["targets"]["t"][0]["observed_bytes"] == 100
        # regression observed: ceiling must NOT go up
        out = shrink_budgets(budgets, {"t": {"whole-step": 5000}})
        assert out["targets"]["t"][0]["max_bytes"] == 1000
        # unmeasured band: untouched
        out = shrink_budgets(budgets, {})
        assert out["targets"]["t"][0]["max_bytes"] == 1000

    def test_entry_param_shapes_handle_dim_and_layout_commas(self):
        """H4's index->shape mapping must split the header on top-level
        commas only — dims/layouts carry commas of their own."""
        from tools import hlo_lib
        hdr = ("HloModule m, entry_computation_layout="
               "{(f32[4,4]{1,0}, f32[8]{0}, (f32[2,2]{1,0}))->f32[]}\n")
        assert hlo_lib.parse_entry_param_shapes(hdr) == \
            ["f32[4,4]{1,0}", "f32[8]{0}", "(f32[2,2]{1,0})"]

    def test_hlo_lib_parses_both_hlo_dialects(self):
        """``Compiled.as_text()`` prefixes names with % and types its
        computation headers; ``--xla_dump_to`` files drop both. The
        budget re-anchor workflow reads dump dirs, so both must parse
        to the same structure."""
        from tools import hlo_lib
        as_text = (
            "HloModule m, entry_computation_layout={(f32[4]{0})->f32[]}\n"
            "%fused (p: f32[4]) -> f32[4] {\n"
            '  %p = f32[4]{0} parameter(0)\n'
            '  ROOT %t = f32[4]{0} tanh(f32[4]{0} %p), '
            'metadata={op_name="jit(f)/tanh"}\n'
            "}\n"
            "ENTRY %main (a: f32[4]) -> f32[] {\n"
            "  %a = f32[4]{0} parameter(0)\n"
            "  ROOT %f = f32[4]{0} fusion(f32[4]{0} %a), kind=kLoop, "
            'calls=%fused, metadata={op_name="jit(f)/tanh"}\n'
            "}\n")
        dump = (
            "HloModule m, entry_computation_layout={(f32[4]{0})->f32[]}\n"
            "fused {\n"
            '  p = f32[4]{0} parameter(0)\n'
            '  ROOT t = f32[4]{0} tanh(p), '
            'metadata={op_name="jit(f)/tanh"}\n'
            "}\n"
            "ENTRY main {\n"
            "  a = f32[4]{0} parameter(0)\n"
            "  ROOT f = f32[4]{0} fusion(a), kind=kLoop, "
            'calls=fused, metadata={op_name="jit(f)/tanh"}\n'
            "}\n")
        measured = []
        for text in (as_text, dump):
            fus = hlo_lib.parse_fusions_text(text)
            assert set(fus) == {"f"} and \
                fus["f"]["op_name"] == "jit(f)/tanh" and \
                fus["f"]["body_lines"] == 2, fus
            total, ops = hlo_lib.band_traffic(text, "")
            assert ops == 1   # the fusion def; body ops not re-billed
            measured.append(total)
        # both dialects must price the same instruction the SAME —
        # the dump's bare operand names resolve against the defs, so a
        # dump-based budget re-anchor stays consistent with the
        # as_text-measured gate
        assert measured[0] == measured[1] == 32, measured

    def test_unmeasurable_budget_band_is_a_finding_not_a_pass(self):
        """A committed budget whose measurement vanished (cost_analysis
        key drift, target no longer compiled) must fail loudly — a
        silent 0 would pass the gate forever."""
        from tools.graftaudit import Artifacts, Target
        from tools.graftaudit.rules import traffic
        t = Target(name="t", build=lambda: None)
        budgets = {"targets": {"t": [
            {"band": "whole-step", "match": "", "max_bytes": 10},
        ]}}
        art = Artifacts(hlo_text="ENTRY %main () -> f32[] {\n}\n",
                        cost={})   # no 'bytes accessed'
        findings = traffic.check(t, art, budgets)
        assert [f.name for f in findings] == ["traffic-unmeasurable"]
        # ...and --budget-update must leave the band alone, not shrink
        # its ceiling toward a phantom 0
        assert traffic.observe(t, art, budgets) == {}
        assert shrink_budgets(
            budgets, {"t": traffic.observe(t, art, budgets)}
        )["targets"]["t"][0]["max_bytes"] == 10
        # same for an op_name band whose match hits NO instruction
        # (metadata drift): 0 matched ops is not "0 bytes, under
        # budget"
        budgets = {"targets": {"t": [
            {"band": "scan-body", "match": "/gone/", "max_bytes": 10},
        ]}}
        art = Artifacts(hlo_text=(
            "HloModule m\n"
            "ENTRY main {\n"
            "  a = f32[4]{0} parameter(0)\n"
            '  ROOT t = f32[4]{0} tanh(a), '
            'metadata={op_name="jit(f)/tanh"}\n'
            "}\n"))
        findings = traffic.check(t, art, budgets)
        assert [f.name for f in findings] == ["traffic-unmeasurable"]
        assert "/gone/" in findings[0].message

    def test_cli_usage_errors(self, tmp_path):
        assert main(["--rules", "H9"]) == 2
        assert main(["--rules", "H1", "--write-baseline",
                     str(tmp_path / "b.json")]) == 2
        assert main(["--targets", "no_such",
                     "--write-baseline",
                     str(tmp_path / "b.json")]) == 2
        assert main(["--fixture",
                     str(tmp_path / "missing.py")]) == 2
        # a fixture that blows up at module scope (ImportError,
        # NameError, a jax error) is "unloadable", exit 2 — never a
        # raw traceback
        broken = tmp_path / "broken_fixture.py"
        broken.write_text("import no_such_module_xyz\n")
        assert main(["--fixture", str(broken)]) == 2

    def test_cli_fixture_json_and_baseline_flow(self, tmp_path, capsys):
        """CLI end-to-end on the cheapest fixture: findings as JSON,
        then grandfathered via --write-baseline, then stale once the
        'violation' would be fixed."""
        rc = main(["--fixture", fixture("h3_pos.py"), "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert any(f["rule"] == "H3" for f in out)
        assert all({"target", "rule", "name", "detail", "message"}
                   <= set(f) for f in out)
        bl = tmp_path / "bl.json"
        rc = main(["--fixture", fixture("h3_pos.py"),
                   "--write-baseline", str(bl)])
        assert rc == 0 and bl.exists()
        capsys.readouterr()
        rc = main(["--fixture", fixture("h3_pos.py"),
                   "--baseline", str(bl)])
        assert rc == 0        # grandfathered
        rc = main(["--fixture", fixture("clean.py"),
                   "--baseline", str(bl)])
        capsys.readouterr()
        assert rc == 0        # different targets: unchecked, not stale


class TestRepoGate:
    """The actual gate: the real programs must audit clean."""

    def test_repo_audit_clean(self):
        r = subprocess.run(
            [sys.executable, "-m", "tools.graftaudit", "--json"],
            cwd=REPO, capture_output=True, text=True, timeout=600)
        assert r.returncode == 0, \
            f"graftaudit findings:\n{r.stdout}\n{r.stderr}"
        assert json.loads(r.stdout) == []

    def test_baseline_stays_burned_down(self):
        """The seed audit came back clean (donation honored 405/405,
        no callbacks, no multi-MB literals, engine at its documented
        bucket count; the fp32 correlation island is a justified
        Waiver on the target). It must stay that way: new findings are
        fixed or waived with justification at the target, never
        grandfathered."""
        with open(BASELINE) as f:
            entries = json.load(f)["findings"]
        assert entries == [], (
            "baseline regrew — fix or waive the finding instead of "
            f"grandfathering it: {entries}")

    def test_budgets_are_committed_and_anchored(self):
        with open(BUDGETS) as f:
            budgets = json.load(f)
        bands = budgets["targets"]
        assert {"train_step", "serve"} <= set(bands)
        for entries in bands.values():
            for e in entries:
                assert e["max_bytes"] > 0
                # anchored: every committed band carries the observed
                # number its ceiling was shrunk toward
                assert e["observed_bytes"] <= e["max_bytes"]
        # the round-5 scan-body band is pinned by name
        assert any(e["band"] == "scan-body"
                   for e in bands["train_step"])
