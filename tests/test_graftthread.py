"""graftthread: the thread-safety static-analysis gate (tools/graftthread/).

Mirrors test_graftlint's three layers, plus the lock-graph units T3
needs:

- per-rule fixture tests: each rule T1-T6 has a positive fixture (must
  fire) and a negative fixture (must stay silent) under
  ``tests/graftthread_fixtures/``; the T1 positive set includes
  ``t1_regression_pr6.py`` — the PR-6 compile-under-engine-lock bug
  distilled pre-fix, the acceptance regression for the rule;
- mechanism tests: per-line pragmas, baseline grandfathering +
  stale-entry failure, the declaration convention's error surface
  (E2), the shared content-hash parse cache;
- lock-order units: cycle detection over SYNTHETIC declaration graphs
  (no files involved), plus the cross-file union pass;
- the repo gate: ``python -m tools.graftthread --json`` (default
  paths: the serving stack + supervisor + utils, shipped baseline)
  must exit 0 in under the 30 s warm budget, and the shipped baseline
  must be EMPTY — initial findings were fixed (settle_future
  migration, HangWatch join) or pragma-waived with justification,
  never grandfathered.

graftthread is pure-stdlib ``ast``; nothing here touches jax.
"""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "graftthread_fixtures")
BASELINE = os.path.join(REPO, "tools", "graftthread", "baseline.json")

if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.graftthread import (DEFAULT_PATHS, apply_baseline,  # noqa: E402
                               lint_file, lint_paths, load_baseline,
                               write_baseline)
from tools.graftthread.core import collect_files, main  # noqa: E402
from tools.graftthread.rules import lock_order  # noqa: E402

RULES = ("T1", "T2", "T3", "T4", "T5", "T6")


def fixture(name):
    return os.path.join(FIXTURES, name)


def rules_hit(path):
    return {f.rule for f in lint_file(path)}


class TestRuleFixtures:
    @pytest.mark.parametrize("rule", RULES)
    def test_positive_fixture_fires(self, rule):
        path = fixture(f"{rule.lower()}_pos.py")
        assert rule in rules_hit(path), \
            f"{rule} positive fixture produced no {rule} finding"

    @pytest.mark.parametrize("rule", RULES)
    def test_negative_fixture_is_silent(self, rule):
        path = fixture(f"{rule.lower()}_neg.py")
        findings = lint_file(path)
        assert not findings, \
            f"{rule} negative fixture is not clean: " \
            + "; ".join(f.render() for f in findings)

    def test_pr6_compile_under_lock_regression_is_red(self):
        """The acceptance criterion: T1 demonstrably red on the PR-6
        compile-under-engine-lock shape — and the FIXED real engine
        (compile outside the lock) stays green."""
        findings = [f for f in lint_file(fixture("t1_regression_pr6.py"))
                    if f.rule == "T1"]
        assert findings, "T1 must fire on the pre-fix engine shape"
        assert any("lower" in f.message or "compile" in f.message
                   for f in findings)
        engine = os.path.join(REPO, "raft_tpu", "serving", "engine.py")
        assert "T1" not in rules_hit(engine)

    @pytest.mark.parametrize("rule", RULES)
    def test_pragma_suppresses_each_rule(self, rule, tmp_path):
        """Detection -> pragma round trip per rule: the positive
        fixture with a pragma on every finding line goes silent for
        that rule; a pragma naming a DIFFERENT rule does not."""
        src_path = fixture(f"{rule.lower()}_pos.py")
        findings = [f for f in lint_file(src_path) if f.rule == rule]
        lines = open(src_path, encoding="utf-8").read().splitlines()
        for f in findings:
            lines[f.line - 1] += f"  # graftthread: disable={rule}"
        # SAME basename: T3's declared lock names qualify by module
        p = tmp_path / f"{rule.lower()}_pos.py"
        p.write_text("\n".join(lines) + "\n")
        assert rule not in {f.rule for f in lint_file(str(p))}
        # a pragma for an unrelated rule must NOT suppress
        wrong = "T1" if rule != "T1" else "T2"
        for i, line in enumerate(lines):
            lines[i] = line.replace(f"disable={rule}",
                                    f"disable={wrong}")
        p.write_text("\n".join(lines) + "\n")
        assert rule in {f.rule for f in lint_file(str(p))}

    @pytest.mark.parametrize("rule", RULES)
    def test_baseline_roundtrip_each_rule(self, rule, tmp_path):
        """Detection -> baseline round trip per rule: grandfathered
        findings don't fail, a fixed finding leaves a stale entry."""
        findings = lint_file(fixture(f"{rule.lower()}_pos.py"))
        assert findings
        bl = tmp_path / "baseline.json"
        write_baseline(str(bl), findings)
        new, stale = apply_baseline(findings, load_baseline(str(bl)))
        assert new == [] and stale == []
        new, stale = apply_baseline([], load_baseline(str(bl)))
        assert new == [] and len(stale) == len(findings)


class TestDeclarations:
    def test_bad_declaration_is_a_finding(self, tmp_path):
        p = tmp_path / "bad.py"
        p.write_text("GRAFTTHREAD = {'not_a_key': ()}\n")
        findings = lint_file(str(p))
        assert any(f.rule == "E2" and "not_a_key" in f.message
                   for f in findings)
        p.write_text("LOCK_ORDER = 'oops'\n")
        assert any(f.rule == "E2" for f in lint_file(str(p)))
        # non-literal values must not crash the scan
        p.write_text("GRAFTTHREAD = {'locks': make_locks()}\n")
        assert any(f.rule == "E2" for f in lint_file(str(p)))

    def test_declared_lock_and_alias(self, tmp_path):
        """An attr that doesn't LOOK like a lock participates once
        declared; an alias folds a Condition onto its underlying
        lock (so the same-receiver wait exemption still applies)."""
        p = tmp_path / "decl.py"
        p.write_text(
            "import time\n"
            "GRAFTTHREAD = {'locks': ('_gate',)}\n"
            "class S:\n"
            "    def f(self):\n"
            "        with self._gate:\n"
            "            time.sleep(1)\n")
        assert "T1" in {f.rule for f in lint_file(str(p))}
        # without the declaration, _gate is not lockish: silent
        p.write_text(
            "import time\n"
            "class S:\n"
            "    def f(self):\n"
            "        with self._gate:\n"
            "            time.sleep(1)\n")
        assert lint_file(str(p)) == []

    def test_alias_resolves_wait_exemption_both_spellings(self,
                                                          tmp_path):
        """A Condition over a lock (aliases={'_decided': '_lock'}) is
        the SAME lock: waiting on it is legal whichever spelling
        acquired it — `with self._decided: self._decided.wait()` AND
        the equally-legal `with self._lock: self._decided.wait()`."""
        p = tmp_path / "alias.py"
        body = ("GRAFTTHREAD = {{'locks': ('_decided',),"
                " 'aliases': {{'_decided': '_lock'}}}}\n"
                "class G:\n"
                "    def f(self):\n"
                "        with self.{held}:\n"
                "            self._decided.wait(1.0)\n")
        for held in ("_decided", "_lock"):
            p.write_text(body.format(held=held))
            assert "T1" not in {f.rule for f in lint_file(str(p))}, \
                f"alias wait exemption failed for `with self.{held}`"
        # a wait on an UNRELATED object under the lock still flags
        p.write_text(
            "class G:\n"
            "    def f(self, ev):\n"
            "        with self._lock:\n"
            "            ev.wait(1.0)\n")
        assert "T1" in {f.rule for f in lint_file(str(p))}

    def test_syntax_error_is_a_finding_not_a_crash(self, tmp_path):
        p = tmp_path / "broken.py"
        p.write_text("def f(:\n")
        findings = lint_file(str(p))
        assert len(findings) == 1 and findings[0].rule == "E1"


class TestLockGraph:
    """T3's cycle detector over synthetic declaration graphs — no
    files, just edges (the unit layer the ISSUE names)."""

    @staticmethod
    def edge(src, dst, path="synthetic.py", line=1, origin="declared"):
        return {"src": src, "dst": dst, "path": path, "line": line,
                "origin": origin}

    def test_chain_is_acyclic(self):
        edges = [self.edge("a", "b"), self.edge("b", "c"),
                 self.edge("a", "c")]
        assert lock_order.find_cycles(edges) == []

    def test_two_cycle(self):
        edges = [self.edge("a", "b"), self.edge("b", "a")]
        cycles = lock_order.find_cycles(edges)
        assert len(cycles) == 1 and set(cycles[0]) == {"a", "b"}

    def test_self_loop(self):
        assert lock_order.find_cycles([self.edge("a", "a")]) == [["a"]]

    def test_long_cycle_across_modules(self):
        """The shape T3 exists for: scheduler→breaker→metrics declared
        order, plus one drifted inferred edge closing the loop."""
        edges = [
            self.edge("sched._state", "sched._cv"),
            self.edge("sched._cv", "metrics._lock"),
            self.edge("sched._state", "breaker._lock"),
            self.edge("metrics._lock", "sched._state", "drift.py", 40,
                      "inferred"),
        ]
        cycles = lock_order.find_cycles(edges)
        assert len(cycles) == 1
        assert set(cycles[0]) == {"sched._state", "sched._cv",
                                  "metrics._lock"}
        (finding, anchor), = lock_order.cycle_findings(edges)
        assert finding.rule == "T3"
        assert "inferred at drift.py:40" in finding.message

    def test_disjoint_components_each_detected(self):
        edges = [self.edge("a", "b"), self.edge("b", "a"),
                 self.edge("x", "y"), self.edge("y", "x")]
        assert len(lock_order.find_cycles(edges)) == 2

    def test_cross_file_cycle_only_closes_in_union(self, tmp_path):
        """Per-file scans see no cycle; the global lint_paths pass over
        both files' edges does — the reason the driver runs T3 over
        the UNION graph."""
        a = tmp_path / "moda.py"
        a.write_text("LOCK_ORDER = (('moda.one', 'modb.two'),)\n")
        b = tmp_path / "modb.py"
        b.write_text("LOCK_ORDER = (('modb.two', 'moda.one'),)\n")
        assert lint_file(str(a)) == [] and lint_file(str(b)) == []
        findings = lint_paths([str(a), str(b)])
        assert [f.rule for f in findings] == ["T3"]
        # pragma on the anchor CHAIN line suppresses it (cycle
        # findings anchor at the lexicographically-first edge site)
        a.write_text(
            "LOCK_ORDER = (\n"
            "    ('moda.one', 'modb.two'),"
            "  # graftthread: disable=T3\n"
            ")\n")
        assert "T3" not in {f.rule
                            for f in lint_paths([str(a), str(b)])}


class TestMechanisms:
    def test_pragma_inside_string_literal_does_not_suppress(
            self, tmp_path):
        p = tmp_path / "s.py"
        p.write_text('def f(fut):\n'
                     '    fut.set_result(1); '
                     's = "# graftthread: disable=all"\n')
        assert {f.rule for f in lint_file(str(p))} == {"T2"}

    def test_pragma_disable_all(self, tmp_path):
        p = tmp_path / "s.py"
        p.write_text('def f(fut):\n'
                     '    fut.set_result(1)'
                     '  # graftthread: disable=all (drill-only fake)\n')
        assert lint_file(str(p)) == []

    def test_stale_baseline_entry_fails_the_gate(self, tmp_path,
                                                 capsys):
        p = tmp_path / "legacy.py"
        p.write_text("def f(fut):\n    fut.set_result(1)\n")
        bl = tmp_path / "baseline.json"
        write_baseline(str(bl), lint_file(str(p)))
        assert main([str(p), "--baseline", str(bl),
                     "--no-cache"]) == 0      # grandfathered
        p.write_text("def f(fut):\n    pass\n")
        assert main([str(p), "--baseline", str(bl),
                     "--no-cache"]) == 1      # stale entry must burn
        assert "stale baseline" in capsys.readouterr().err

    def test_write_baseline_refuses_rule_filter(self, tmp_path):
        bl = tmp_path / "baseline.json"
        rc = main([fixture("t2_pos.py"), "--rules", "T1",
                   "--write-baseline", str(bl), "--no-cache"])
        assert rc == 2 and not bl.exists()

    def test_walk_excludes_fixture_dir_but_explicit_file_wins(self):
        walked = collect_files([os.path.join(REPO, "tests")])
        assert not any("graftthread_fixtures" in p for p in walked)
        explicit = collect_files([fixture("t1_pos.py")])
        assert explicit == [fixture("t1_pos.py")]

    def test_graftlint_walk_excludes_graftthread_fixtures(self):
        """The new fixture tree is intentionally-violating code for
        THIS tier — graftlint's walk must skip it too (t5 fixtures
        would otherwise trip R5 on the tests/ gate path)."""
        from tools.graftlint.core import collect_files as lint_collect
        walked = lint_collect([os.path.join(REPO, "tests")])
        assert not any("graftthread_fixtures" in p for p in walked)

    def test_rules_filter_and_unknown_rule_errors(self, capsys):
        rc = main([fixture("t2_pos.py"), "--rules", "T1",
                   "--no-cache"])
        assert rc == 0          # T2 violations invisible to a T1 run
        rc = main([fixture("t2_pos.py"), "--rules", "T9",
                   "--no-cache"])
        assert rc == 2


class TestParseCache:
    """The shared tools/lintcache machinery under graftthread: content
    hashed, rules-aware, invalidated by any edit to the checker
    package — and the global T3 pass re-runs on cache HITS too."""

    BAD = "def f(fut):\n    fut.set_result(1)\n"

    def test_cache_replays_then_content_hash_invalidates(self,
                                                         tmp_path):
        p = tmp_path / "c.py"
        p.write_text(self.BAD)
        cache = tmp_path / "cache.json"
        first = lint_paths([str(p)], cache_path=str(cache))
        assert {f.rule for f in first} == {"T2"} and cache.exists()
        # prove the second run is a HIT: doctor the stored finding
        data = json.loads(cache.read_text())
        (key,) = data["files"]
        data["files"][key]["findings"][0]["message"] = "FROM-CACHE"
        cache.write_text(json.dumps(data))
        assert [f.message for f in
                lint_paths([str(p)], cache_path=str(cache))] \
            == ["FROM-CACHE"]
        # any edit changes the content hash: the entry is dead
        p.write_text(self.BAD + "# touched\n")
        fresh = lint_paths([str(p)], cache_path=str(cache))
        assert [f.message for f in fresh] != ["FROM-CACHE"]
        assert {f.rule for f in fresh} == {"T2"}
        assert len(json.loads(cache.read_text())["files"]) == 1

    def test_cached_edges_still_feed_global_cycle_pass(self, tmp_path):
        """A cache hit must not hide a cross-file cycle: edges are
        cached per file, but the union cycle check runs every time."""
        a = tmp_path / "moda.py"
        a.write_text("LOCK_ORDER = (('moda.one', 'modb.two'),)\n")
        b = tmp_path / "modb.py"
        b.write_text("LOCK_ORDER = (('modb.two', 'moda.one'),)\n")
        cache = tmp_path / "cache.json"
        cold = lint_paths([str(a), str(b)], cache_path=str(cache))
        warm = lint_paths([str(a), str(b)], cache_path=str(cache))
        assert [f.rule for f in cold] == ["T3"]
        assert [(f.rule, f.path, f.line) for f in warm] \
            == [(f.rule, f.path, f.line) for f in cold]

    def test_jobs_parallel_matches_serial(self, tmp_path):
        files = []
        for i, body in enumerate([self.BAD, "x = 1\n", self.BAD,
                                  "def f(:\n"]):
            p = tmp_path / f"f{i}.py"
            p.write_text(body)
            files.append(str(p))
        assert lint_paths(files, jobs=3) == lint_paths(files)

    def test_signature_invalidates_whole_cache(self, tmp_path):
        p = tmp_path / "c.py"
        p.write_text(self.BAD)
        cache = tmp_path / "cache.json"
        lint_paths([str(p)], cache_path=str(cache))
        data = json.loads(cache.read_text())
        data["sig"] = "some-older-graftthread"
        (key,) = data["files"]
        data["files"][key]["findings"][0]["message"] = "FROM-STALE"
        cache.write_text(json.dumps(data))
        findings = lint_paths([str(p)], cache_path=str(cache))
        assert [f.message for f in findings] != ["FROM-STALE"]
        assert json.loads(cache.read_text())["sig"] != \
            "some-older-graftthread"


class TestRepoGate:
    """The actual gate: `python -m tools.graftthread --json` (default
    paths + shipped baseline) clean, warm, and under budget."""

    def test_repo_clean_with_empty_baseline_under_budget(self):
        t0 = time.monotonic()
        r = subprocess.run(
            [sys.executable, "-m", "tools.graftthread", "--json"],
            cwd=REPO, capture_output=True, text=True, timeout=120)
        dt = time.monotonic() - t0
        assert r.returncode == 0, \
            f"new graftthread findings:\n{r.stdout}\n{r.stderr}"
        assert json.loads(r.stdout) == []
        # warm budget (the ISSUE's 30 s bound; pure-ast scan of ~15
        # files — the margin is enormous unless something regresses
        # into parsing the world)
        assert dt < 30.0, f"gate took {dt:.1f}s (budget 30s)"

    def test_baseline_is_empty_and_stays_empty(self):
        """The shipped baseline starts EMPTY (graftaudit discipline):
        every initial finding was FIXED (the settle_future migration,
        the HangWatch join) or pragma-waived with written
        justification at the site — never grandfathered. A baseline
        entry appearing means someone took the shortcut this gate
        exists to block."""
        with open(BASELINE) as f:
            entries = json.load(f)["findings"]
        assert entries == [], (
            "graftthread baseline regrew — fix or pragma the finding "
            f"instead of grandfathering it: {entries}")

    def test_default_paths_cover_the_serving_stack(self):
        files = collect_files([os.path.join(REPO, p)
                               for p in DEFAULT_PATHS])
        names = {os.path.basename(p) for p in files}
        assert {"scheduler.py", "registry.py", "resilience.py",
                "guardian.py", "engine.py", "metrics.py",
                "supervisor.py", "watchdog.py"} <= names

    def test_real_declarations_build_the_documented_graph(self):
        """The serving modules' LOCK_ORDER declarations load into the
        global graph (the comment discipline, machine-readable), the
        graph is acyclic, and one planted inversion is caught."""
        import ast as ast_mod

        from tools.graftthread.declarations import ThreadAnalysis
        edges = []
        for rel in ("scheduler", "registry", "guardian", "resilience",
                    "metrics", "engine"):
            path = os.path.join(REPO, "raft_tpu", "serving",
                                f"{rel}.py")
            src = open(path, encoding="utf-8").read()
            edges += lock_order.edges(
                ThreadAnalysis(ast_mod.parse(src), src, path))
        srcs = {e["src"] for e in edges}
        assert "scheduler.MicroBatchScheduler._state_lock" in srcs
        assert "registry.ModelRegistry._lock" in srcs
        assert "guardian.SLOGuardian._tick_lock" in srcs
        assert lock_order.find_cycles(edges) == []
        planted = edges + [{
            "src": "metrics.ServingMetrics._lock",
            "dst": "scheduler.MicroBatchScheduler._state_lock",
            "path": "drift.py", "line": 1, "origin": "inferred"}]
        assert lock_order.find_cycles(planted)

    def test_json_mode_is_machine_readable(self):
        r = subprocess.run(
            [sys.executable, "-m", "tools.graftthread",
             os.path.join("tests", "graftthread_fixtures",
                          "t2_pos.py"),
             "--json", "--no-cache"],
            cwd=REPO, capture_output=True, text=True, timeout=120)
        assert r.returncode == 1
        findings = json.loads(r.stdout)
        assert findings and all(
            set(f) >= {"path", "line", "col", "rule", "name", "message"}
            for f in findings)
        assert any(f["rule"] == "T2" for f in findings)
