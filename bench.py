"""Headline benchmark: RAFT-basic training throughput on one TPU chip.

Mirrors the reference's FlyingChairs stage (``train_standard.sh:3``: crop
368x496, 12 refinement iterations, AdamW + OneCycle, sequence loss) as a
jit-compiled bf16 train step, and reports sustained image-pairs/sec.

Baseline: the reference publishes no numbers (BASELINE.md). The committed
target is "beat 2xV100 FlyingChairs wall-clock" — public RAFT training logs
put the 2-GPU recipe at ~2 steps/s with batch 10, i.e. ~20 img-pairs/s, so
``vs_baseline`` is value/20 for the whole 2-GPU reference rig (not per GPU).

Survivability rules (learned from rounds 1-2):
- start at batch 6 (batch 10 OOMs on the 15.75 GB v5e-1); only retry
  smaller batches on OOM/RESOURCE_EXHAUSTED — any other failure (e.g.
  backend init) is fatal and emits the failure JSON immediately;
- a wall-clock deadline bounds total attempts so one bad compile can't
  eat the driver's window;
- timing forces a CONCRETE VALUE FETCH (float() of the loss and of a
  param leaf of the final train state) after a chained run of N steps.
  On the remote 'axon' backend even ``jax.block_until_ready`` returns
  before execution finishes (round 2 measured 1.7 ms/step "blocked" =
  1013 TFLOP/s on a 197 TFLOP/s chip — impossible); a host-side float()
  of data that transitively depends on every step cannot lie. Each step
  consumes the previous step's (donated) state, so the chain serializes
  on real data dependencies and the final fetch waits for all of it.

Prints exactly ONE JSON line:
    {"metric": ..., "value": N, "unit": "img_pairs_per_sec", "vs_baseline": N}
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

# honor JAX_PLATFORMS=cpu + persistent compile cache (multi-minute
# remote compiles are skipped on repeat runs)
from raft_tpu.utils.platform import setup_cli  # noqa: E402
# one failure mode, one exit code: the wedge watchdog below must exit
# with the SAME distinctive code as the trainer's watchdog so runbooks
# branch once (round-5 advisor: bench exited 2, trainer 3)
from raft_tpu.utils.watchdog import WEDGED_EXIT_CODE  # noqa: E402

setup_cli()

BASELINE_PAIRS_PER_SEC = 20.0  # est. 2xV100 reference recipe (see docstring)
IMAGE_HW = (368, 496)          # train_standard.sh chairs crop (--hw overrides)
ITERS = 12                     # train.py:232

# a crash-retry re-exec carries its elapsed seconds forward so
# --deadline-s bounds TOTAL wall-clock across the re-exec, not per process
START = time.monotonic() - float(os.environ.get("RAFT_BENCH_ELAPSED") or 0.0)


LAST_PROGRESS = time.monotonic()


def log(msg):
    global LAST_PROGRESS
    LAST_PROGRESS = time.monotonic()
    print(f"[bench +{time.monotonic() - START:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


def is_worker_crash(exc: Exception) -> bool:
    """Transient tunnel-worker death (worth ONE bounded retry) vs real bugs.

    Observed ~3x in round 3: the TPU worker crashes right after a client
    process exits and the NEXT process's first collective fails with
    "UNAVAILABLE: TPU worker process crashed or restarted". It recovers
    in ~1-2 min unattended; without a retry that transient zeroes the
    whole driver bench (BENCH_r01..r03 all recorded 0.0)."""
    s = f"{type(exc).__name__}: {exc}".lower()
    return ("worker process crashed" in s or "worker process restarted" in s
            or ("unavailable" in s and ("crashed" in s or "restarted" in s
                                        or "socket closed" in s
                                        or "connection reset" in s)))


def is_oom(exc: Exception) -> bool:
    """HBM exhaustion (worth retrying smaller) vs everything else (fatal).

    Scoped-VMEM compile errors also say "Ran out of memory" but are
    batch-INdependent kernel-tiling failures — retrying smaller batches
    burned 3 multi-minute remote compiles on one in session B.
    """
    s = f"{type(exc).__name__}: {exc}".lower()
    if "scoped vmem" in s or "memory space vmem" in s:
        return False
    return ("resource_exhausted" in s or "out of memory" in s
            or re.search(r"\boom\b", s) is not None)


def build(batch_size, remat, overrides, image_hw=IMAGE_HW,
          fused_loss=False):
    from raft_tpu.config import RAFTConfig, stage_config
    from raft_tpu.training.train_step import (create_train_state,
                                              make_train_step)

    model_cfg = RAFTConfig(small=False, mixed_precision=True, remat=remat,
                           **overrides)
    train_cfg = stage_config("chairs", batch_size=batch_size,
                             fused_loss=fused_loss)
    rng = jax.random.PRNGKey(0)
    state = create_train_state(model_cfg, train_cfg, rng, image_hw=image_hw)
    step = jax.jit(make_train_step(model_cfg, train_cfg), donate_argnums=(0,))

    h, w = image_hw
    host = np.random.RandomState(0)
    batch = {
        "image1": jnp.asarray(
            host.rand(batch_size, h, w, 3).astype(np.float32) * 255.0),
        "image2": jnp.asarray(
            host.rand(batch_size, h, w, 3).astype(np.float32) * 255.0),
        "flow": jnp.asarray(
            host.randn(batch_size, h, w, 2).astype(np.float32)),
        "valid": jnp.ones((batch_size, h, w), jnp.float32),
    }
    return state, step, batch, rng


def run(batch_size, remat, warmup, steps, overrides, image_hw=IMAGE_HW,
        fused_loss=False):
    from raft_tpu.utils.timing import force_train as force
    warmup, steps = max(1, warmup), max(1, steps)  # force() needs metrics
    log(f"building batch={batch_size} remat={remat} hw={image_hw} "
        f"overrides={overrides} fused_loss={fused_loss}")
    state, step, batch, rng = build(batch_size, remat, overrides, image_hw,
                                    fused_loss)
    log("compiling + warmup")
    for _ in range(warmup):
        state, metrics = step(state, batch, rng)
    loss = force(state, metrics)
    log(f"warmup done, loss={loss:.3f}; timing {steps} chained steps")
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch, rng)
    loss = force(state, metrics)     # waits for the full chain
    dt = (time.perf_counter() - t0) / steps
    log(f"avg step {dt * 1e3:.1f} ms over {steps} steps (value-fetch "
        f"fenced), final loss={loss:.3f}")
    return batch_size / dt


def start_hang_watch(shape_tag, hang_s, interval=30.0, stop=None):
    """Daemon that converts a silent mid-run wedge into a recorded 0.0.

    A wedge can develop AFTER the backend probe passed (observed 15:51
    UTC: bare bench green 15:45-15:50, the very next process's compile
    hung forever — the tunnel's half-up mode). log() stamps
    LAST_PROGRESS; if nothing progressed for ``hang_s`` the daemon
    prints the failure JSON the driver expects and hard-exits, instead
    of hanging until the driver's own timeout records nothing at all.
    """
    import threading

    if hang_s <= 0:  # explicit disable
        return None

    def _watch():
        while True:
            time.sleep(interval)
            if stop is not None and stop.is_set():
                return
            stale = time.monotonic() - LAST_PROGRESS
            if stale > hang_s:
                print(f"[bench] no progress for {stale:.0f}s — backend "
                      "wedged (half-up tunnel); emitting failure JSON",
                      file=sys.stderr, flush=True)
                emit(f"raft_basic_train_{shape_tag}_backend_wedged", 0.0)
                os._exit(WEDGED_EXIT_CODE)
                return  # unreachable in production; ends the thread when
                # tests stub os._exit

    # process-lifetime by design: the watchdog must survive every
    # exception path of the bench to convert a wedge into the failure
    # JSON — there is deliberately no stop/finally here
    t = threading.Thread(target=_watch, daemon=True)
    t.start()  # graftlint: disable=R5
    return t


def emit(metric, value):
    print(json.dumps({
        "metric": metric,
        "value": round(value, 3),
        "unit": "img_pairs_per_sec",
        "vs_baseline": round(value / BASELINE_PAIRS_PER_SEC, 3),
    }), flush=True)


# JSON-supplied defaults are validated against these before use — a typo'd
# BENCH_DEFAULTS.json must fail HERE with a log line, not deep inside a
# multi-minute remote compile
_DEFAULTS_SCHEMA = {
    "batches": lambda v: (isinstance(v, list) and v
                          and all(isinstance(b, int) and b > 0 for b in v)),
    "remat": lambda v: isinstance(v, bool),
    "remat_policy": lambda v: v in ("full", "dots"),
    "corr_impl": lambda v: v in ("gather", "onehot", "onehot_t", "softsel", "pallas"),
    "corr_dtype": lambda v: v in ("float32", "bfloat16"),
    "fused_loss": lambda v: isinstance(v, bool),
    "scan_unroll": lambda v: (isinstance(v, int)
                              and not isinstance(v, bool) and v >= 1),
    "gru_impl": lambda v: v in ("xla", "fused"),
}


def _apply_measured_defaults(args, passed):
    """Fold in ``BENCH_DEFAULTS.json`` (written by the on-chip config-ladder
    runbook) so a bare ``python bench.py`` runs the best MEASURED config,
    not a guess — the driver invokes bench with no flags. Flags the user
    actually passed (``passed``, from the suppressed-defaults re-parse)
    always win, including ``--no-remat`` and values that happen to equal
    the parser default."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_DEFAULTS.json")
    if not os.path.exists(path):
        return
    try:
        with open(path) as f:
            stored = json.load(f)
    except (OSError, ValueError) as exc:
        log(f"ignoring unreadable BENCH_DEFAULTS.json: {exc}")
        return
    applied = {}
    for k, check in _DEFAULTS_SCHEMA.items():
        if k not in stored or k in passed:
            continue
        if not check(stored[k]):
            log(f"ignoring BENCH_DEFAULTS.json: bad {k}={stored[k]!r}")
            return
        applied[k] = stored[k]
    for k, v in applied.items():
        setattr(args, k, v)
    if args.remat_policy and not args.remat and "remat_policy" not in passed:
        # a JSON-sourced policy is meaningless once the user turned remat
        # off (--no-remat); dropping it beats erroring on a flag the user
        # never typed
        args.remat_policy = None
        applied.pop("remat_policy", None)
    if applied:
        log(f"BENCH_DEFAULTS.json applied: {applied}")


def _apply_crash_resume(args):
    """Fold in ``RAFT_BENCH_BATCHES`` (set only by this script's own
    crash-retry re-exec) so the fresh process picks the ladder back up at
    the rung that crashed instead of re-attempting rungs the OOM loop
    already eliminated. Runs AFTER _apply_measured_defaults — the re-exec
    list must win over both parser and JSON defaults."""
    resume = os.environ.get("RAFT_BENCH_BATCHES")
    if not resume:
        return
    if not os.environ.get("RAFT_BENCH_CRASH_RETRIED"):
        # only this script's own re-exec sets both vars; a stale manual
        # export of the batches list alone must not override --batches
        log(f"ignoring RAFT_BENCH_BATCHES={resume!r} without "
            "RAFT_BENCH_CRASH_RETRIED (not a crash-retry re-exec)")
        return
    try:
        batches = [int(b) for b in resume.split()]
    except ValueError:
        log(f"ignoring malformed RAFT_BENCH_BATCHES={resume!r}")
        return
    # same positivity bar _DEFAULTS_SCHEMA holds a JSON batches list to —
    # a stale manual export of "0" must not make run(0, ...) emit a
    # "successful" 0.0
    if batches and all(b > 0 for b in batches):
        args.batches = batches
        log(f"crash-retry resume: batches={args.batches}")
    else:
        log(f"ignoring non-positive RAFT_BENCH_BATCHES={resume!r}")


def _build_parser(suppress=False):
    """``suppress=True`` builds the twin parser whose namespace contains
    ONLY flags the user actually typed — how _apply_measured_defaults
    distinguishes 'left at default' from 'explicitly passed the default'."""
    kw = dict(argument_default=argparse.SUPPRESS) if suppress else {}
    p = argparse.ArgumentParser(**kw)

    def default(v):
        return argparse.SUPPRESS if suppress else v

    p.add_argument("--batches", type=int, nargs="+",
                   default=default([8, 6, 4, 2]))
    p.add_argument("--remat", action=argparse.BooleanOptionalAction,
                   default=default(False))
    p.add_argument("--remat-policy", default=default(None),
                   choices=["full", "dots"],
                   help="remat granularity (with --remat); 'dots' saves "
                        "conv/GEMM outputs, recomputes elementwise")
    p.add_argument("--warmup", type=int, default=default(2))
    p.add_argument("--steps", type=int, default=default(20))
    p.add_argument("--deadline-s", type=float, default=default(2400.0),
                   help="no new attempt starts after this wall-clock budget")
    p.add_argument("--hang-s", type=float, default=default(720.0),
                   help="emit the failure JSON and exit if no progress "
                        "for this long (half-up tunnel: compile/execute "
                        "hangs AFTER the probe passed); longest healthy "
                        "gap observed is ~280 s of host-side data build; "
                        "<=0 disables the watchdog")
    p.add_argument("--corr-impl", default=default(None),
                   choices=["gather", "onehot", "onehot_t", "softsel", "softsel_t", "pallas"],
                   help="override RAFTConfig.corr_impl")
    p.add_argument("--gru-impl", default=default(None),
                   choices=["xla", "fused"],
                   help="update-block implementation (RAFTConfig."
                        "gru_impl): 'fused' = lane-major scan-body "
                        "motion encoder + SepConvGRU with Pallas "
                        "gate/blend epilogues; promotion to default is "
                        "decided by these whole-step rungs, never by "
                        "isolated kernel benches")
    p.add_argument("--fused-loss", action=argparse.BooleanOptionalAction,
                   default=default(False),
                   help="sequence loss in the upsampler's subpixel domain "
                        "(TrainConfig.fused_loss): same values, no "
                        "(T,B,8H,8W,2) stack materialization")
    p.add_argument("--scan-unroll", type=int, default=default(1),
                   help="lax.scan unroll factor for the refinement loop "
                        "(RAFTConfig.scan_unroll); >1 lets XLA pipeline "
                        "across iteration boundaries")
    p.add_argument("--corr-dtype", default=default("bfloat16"),
                   choices=["float32", "bfloat16"],
                   help="correlation-volume storage dtype. Default "
                        "bfloat16: halves the dominant lookup traffic and "
                        "was cleared at trained weights (EPE delta 0.0027 "
                        "px mean < the 0.01 gate, PARITY.md round 3); "
                        "float32 is the bit-parity setting")
    p.add_argument("--hw", type=int, nargs=2, default=default(list(IMAGE_HW)),
                   help="crop H W (divisible by 8); defaults to the "
                        "chairs-stage crop, e.g. 400 720 for things")
    return p


def main():
    p = _build_parser()
    args = p.parse_args()
    passed = vars(_build_parser(suppress=True).parse_args()).keys()
    _apply_measured_defaults(args, passed)
    _apply_crash_resume(args)
    if args.remat_policy and not args.remat:
        p.error("--remat-policy requires --remat (without it the policy "
                "is a silent no-op and the run measures a baseline step)")
    if args.hw[0] % 8 or args.hw[1] % 8:
        p.error(f"--hw {args.hw[0]} {args.hw[1]}: both must be divisible "
                "by 8 (catch it here, not after a multi-minute compile)")
    if args.scan_unroll < 1:
        p.error(f"--scan-unroll {args.scan_unroll}: must be >= 1 (catch "
                "it here, not after the backend probe)")
    h, w = args.hw
    stage = "chairs_" if (h, w) == IMAGE_HW else ""
    shape_tag = f"{stage}{h}x{w}"

    # Arm the no-progress watchdog BEFORE any backend dial: the
    # in-process jax.devices() below can itself block ~25 min
    # uninterruptibly on a wedged claim (the round-2 1,506 s loss), and
    # the probe attempts' own bounded timeouts (≤570 s worst case
    # between log stamps) stay under the default threshold.
    start_hang_watch(shape_tag, args.hang_s)

    # Probe the backend in a TIME-BOUNDED subprocess first: a wedged
    # tunnel claim blocks jax.devices() in-process for ~25 min with no
    # way to interrupt it (round-2 driver log lost 1,506 s to exactly
    # this). A killed probe subprocess costs 4 min and leaves this
    # process clean to emit the failure JSON immediately.
    import subprocess

    # boundedness is the point, not platform policing — an explicit
    # JAX_PLATFORMS=cpu run passes the probe instantly. The probe must
    # route through respect_cpu_request: the image's sitecustomize
    # force-registers the axon plugin, and a bare subprocess would dial
    # the tunnel even under JAX_PLATFORMS=cpu.
    repo = os.path.dirname(os.path.abspath(__file__))
    # the probe must EXECUTE a jitted op, not merely enumerate: the
    # tunnel's half-up mode (OUTAGE_r05.log 08:27, 15:51 UTC) answers
    # jax.devices() but hangs any compile/execute forever — an
    # enumeration-only probe reads that as a healthy window and the
    # bench then wedges until the driver's timeout (tools/chip_probe.sh
    # learned the same lesson)
    probe = (f"import sys; sys.path.insert(0, {repo!r}); "
             "from raft_tpu.utils.platform import respect_cpu_request; "
             "respect_cpu_request(); "
             "import jax, jax.numpy as jnp; d = jax.devices(); assert d; "
             "jax.jit(lambda a: (a * 2).sum())(jnp.ones((8, 128)))"
             ".block_until_ready(); "
             "print(d[0].platform)")
    # Two probe attempts 90 s apart: the worker's observed crash-on-exit
    # mode (dies right after the PREVIOUS client exits, self-recovers in
    # ~1-2 min) would otherwise zero the bench exactly when the driver
    # runs it right after another on-chip process.
    probe_err = None
    for attempt in (1, 2):
        try:
            r = subprocess.run([sys.executable, "-c", probe], timeout=240,
                               capture_output=True, text=True)
            if r.returncode != 0:
                raise RuntimeError(r.stderr.strip().splitlines()[-1]
                                   if r.stderr.strip() else "probe failed")
            probe_err = None
            break
        except subprocess.TimeoutExpired:
            # a timeout means the tunnel is down or the claim is wedged —
            # the multi-hour outage mode, which 90 more seconds won't fix;
            # don't burn another 240 s probe on it (the crash-on-exit mode
            # this retry targets fails FAST with a nonzero exit instead)
            probe_err = "backend probe timed out after 240s (tunnel down " \
                        "or claim wedged)"
            log(probe_err)
            break
        except Exception as exc:
            probe_err = f"backend probe failed: {exc}"
        log(probe_err)
        if attempt == 1:
            log("probe retry in 90s (worker crash-on-exit self-recovers "
                "in ~1-2 min)")
            time.sleep(90)
    if probe_err is not None:
        emit(f"raft_basic_train_{shape_tag}_backend_init_failed", 0.0)
        return 1
    try:
        devs = jax.devices()
        log(f"devices: {devs}")
    except Exception as exc:
        log(f"backend init failed: {exc}")
        emit(f"raft_basic_train_{shape_tag}_backend_init_failed", 0.0)
        return 1

    last_err = None
    # whole-run budget: one transient-crash re-exec (0 if already retried)
    crash_retries_left = 0 if os.environ.get("RAFT_BENCH_CRASH_RETRIED") else 1
    for rung_i, batch_size in enumerate(args.batches):
        if time.monotonic() - START > args.deadline_s:
            log("deadline reached before attempt")
            break
        overrides = {}
        if args.corr_impl:
            overrides["corr_impl"] = args.corr_impl
        if args.corr_dtype:
            overrides["corr_dtype"] = args.corr_dtype
        if args.remat_policy:
            overrides["remat_policy"] = args.remat_policy
        if args.scan_unroll != 1:
            overrides["scan_unroll"] = args.scan_unroll
        if args.gru_impl:
            overrides["gru_impl"] = args.gru_impl
        try:
            value = run(batch_size, args.remat, args.warmup, args.steps,
                        overrides, tuple(args.hw),
                        fused_loss=args.fused_loss)
        except Exception as exc:
            last_err = exc
            if is_oom(exc):
                log(f"batch {batch_size} OOM, trying smaller")
                continue
            if (is_worker_crash(exc) and crash_retries_left > 0):
                # A mid-run crash can wedge this process's PJRT client, so
                # an in-process retry would fail instantly: wait out the
                # ~1-2 min self-recovery, then REPLACE the process for a
                # clean client. The env flag bounds it to one re-exec.
                crash_retries_left = 0
                log(f"TPU worker crash ({type(exc).__name__}); waiting "
                    "120s, then re-exec with a fresh client")
                time.sleep(120)
                # resume the ladder at the rung that crashed — rungs the
                # OOM loop already eliminated must not be re-compiled in
                # the fresh process (ADVICE r4); slice by position so a
                # repeated rung doesn't resume at its first occurrence
                remaining = args.batches[rung_i:]
                env = dict(os.environ, RAFT_BENCH_CRASH_RETRIED="1",
                           RAFT_BENCH_ELAPSED=str(time.monotonic() - START),
                           RAFT_BENCH_BATCHES=" ".join(map(str, remaining)))
                os.execve(sys.executable,
                          [sys.executable, os.path.abspath(__file__)]
                          + sys.argv[1:], env)
            log(f"fatal (non-OOM): {type(exc).__name__}: {exc}")
            break
        tag = "_remat" if args.remat else ""
        if args.remat_policy == "dots":  # parse guard implies --remat
            tag += "_dots"
        if args.corr_impl:
            tag += f"_{args.corr_impl}"
        if args.corr_dtype:
            tag += f"_corr{args.corr_dtype}"
        if args.fused_loss:
            tag += "_fusedloss"
        if args.scan_unroll != 1:
            tag += f"_unroll{args.scan_unroll}"
        if args.gru_impl:
            tag += f"_gru{args.gru_impl}"
        emit(f"raft_basic_train_{shape_tag}_bf16_b{batch_size}"
             f"_iters{ITERS}_1chip{tag}", value)
        return 0

    log(f"no successful run; last error: {last_err}")
    emit(f"raft_basic_train_{shape_tag}_failed", 0.0)
    return 1


if __name__ == "__main__":
    sys.exit(main())
