"""Headline benchmark: RAFT-basic training throughput on one TPU chip.

Mirrors the reference's FlyingChairs stage (``train_standard.sh:3``: crop
368x496, 12 refinement iterations, AdamW + OneCycle, sequence loss) as a
jit-compiled bf16 train step, and reports sustained image-pairs/sec.

Baseline: the reference publishes no numbers (BASELINE.md). The committed
target is "beat 2xV100 FlyingChairs wall-clock" — public RAFT training logs
put the 2-GPU recipe at ~2 steps/s with batch 10, i.e. ~20 img-pairs/s, so
``vs_baseline`` is value/20 for the whole 2-GPU reference rig (not per GPU).

Prints exactly ONE JSON line:
    {"metric": ..., "value": N, "unit": "img_pairs_per_sec", "vs_baseline": N}
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

# persistent compile cache: repeat bench runs skip the multi-minute compile
jax.config.update("jax_compilation_cache_dir", "/tmp/raft_tpu_jax_cache_tpu")
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

BASELINE_PAIRS_PER_SEC = 20.0  # est. 2xV100 reference recipe (see docstring)
IMAGE_HW = (368, 496)          # train_standard.sh chairs crop
ITERS = 12                     # train.py:232
WARMUP_STEPS = 3
TIMED_STEPS = 12


def build(batch_size):
    from raft_tpu.config import RAFTConfig, stage_config
    from raft_tpu.training.train_step import (create_train_state,
                                              make_train_step)

    model_cfg = RAFTConfig(small=False, mixed_precision=True)
    train_cfg = stage_config("chairs", batch_size=batch_size)
    rng = jax.random.PRNGKey(0)
    state = create_train_state(model_cfg, train_cfg, rng, image_hw=IMAGE_HW)
    step = jax.jit(make_train_step(model_cfg, train_cfg), donate_argnums=(0,))

    h, w = IMAGE_HW
    host = np.random.RandomState(0)
    batch = {
        "image1": jnp.asarray(
            host.rand(batch_size, h, w, 3).astype(np.float32) * 255.0),
        "image2": jnp.asarray(
            host.rand(batch_size, h, w, 3).astype(np.float32) * 255.0),
        "flow": jnp.asarray(
            host.randn(batch_size, h, w, 2).astype(np.float32)),
        "valid": jnp.ones((batch_size, h, w), jnp.float32),
    }
    return state, step, batch, rng


def run(batch_size):
    state, step, batch, rng = build(batch_size)
    for _ in range(WARMUP_STEPS):
        state, metrics = step(state, batch, rng)
    jax.block_until_ready(metrics)
    t0 = time.perf_counter()
    for _ in range(TIMED_STEPS):
        state, metrics = step(state, batch, rng)
    jax.block_until_ready(metrics)
    dt = time.perf_counter() - t0
    return batch_size * TIMED_STEPS / dt


def main():
    value = None
    used_batch = None
    for batch_size in (10, 6, 4, 2, 1):
        try:
            value = run(batch_size)
            used_batch = batch_size
            break
        except Exception as exc:  # OOM at this shape -> try smaller batch
            print(f"batch {batch_size} failed: {exc}", file=sys.stderr)
    if value is None:
        print(json.dumps({
            "metric": "raft_basic_train_chairs_368x496_failed",
            "value": 0.0, "unit": "img_pairs_per_sec", "vs_baseline": 0.0,
        }))
        return
    print(json.dumps({
        "metric": (f"raft_basic_train_chairs_368x496_bf16_b{used_batch}"
                   f"_iters{ITERS}_1chip"),
        "value": round(value, 3),
        "unit": "img_pairs_per_sec",
        "vs_baseline": round(value / BASELINE_PAIRS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
