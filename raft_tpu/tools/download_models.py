"""Fetch the reference pretrained checkpoints and convert them to flax.

Analog of ``download_models.sh`` (wget models.zip + unzip) with the extra
step this framework needs: every ``.pth`` is converted through
``tools/convert.py`` into a flax msgpack next to it, so eval/demo/serving
never touch torch at runtime.

The checkpoint zip ships raft-chairs/things/sintel/kitti (basic) and
raft-small; ``--small`` matching is inferred from the filename.

Zero-egress environments: pass ``--zip`` pointing at an already-downloaded
models.zip (or a directory of .pth files via ``--models-dir``) to skip the
network step.
"""

from __future__ import annotations

import argparse
import os
import os.path as osp
import sys
import zipfile

MODELS_URL = "https://www.dropbox.com/s/4j4z58wuv8o0mfz/models.zip"


def download(url: str, dest: str) -> str:
    """Fetch ``url`` to ``dest`` with jittered exponential backoff (the
    Dropbox mirror drops connections under load — a transient error
    must not fail the whole fetch+convert run) and an atomic landing:
    the bytes arrive under ``.part`` and only a complete fetch is
    renamed into place, so a died download can't be mistaken for a zip."""
    import urllib.request

    from raft_tpu.utils.retry import retry

    part = dest + ".part"

    def _fetch():
        print(f"downloading {url} -> {dest}")
        try:
            urllib.request.urlretrieve(url, part)
        except urllib.error.HTTPError as e:
            if 400 <= e.code < 500:
                # a 404/403 is deterministic — HTTPError subclasses
                # OSError, so without this re-wrap a stale URL would
                # eat all four attempts' backoff before surfacing
                raise RuntimeError(
                    f"{url}: HTTP {e.code} {e.reason} — not retrying "
                    "a client error; is the mirror URL stale?") from e
            raise
        os.replace(part, dest)

    # URLError, timeouts, and connection resets are all OSError
    retry(_fetch, attempts=4, base_s=2.0, max_s=30.0, retry_on=(OSError,),
          on_retry=lambda k, d, e: print(
              f"  attempt {k} failed ({e}); retrying in {d:.0f}s",
              file=sys.stderr))
    return dest


def convert_all(models_dir: str) -> int:
    from raft_tpu.config import RAFTConfig
    from raft_tpu.tools.convert import load_pth, save_converted

    n = 0
    for name in sorted(os.listdir(models_dir)):
        if not name.endswith(".pth"):
            continue
        src = osp.join(models_dir, name)
        dst = src[:-4] + ".msgpack"
        cfg = RAFTConfig(small="small" in name)
        try:
            variables = load_pth(src, cfg)
        except Exception as e:
            print(f"  {name}: conversion FAILED ({e})", file=sys.stderr)
            continue
        save_converted(variables, dst)
        print(f"  {name} -> {osp.basename(dst)} "
              f"({'small' if cfg.small else 'basic'})")
        n += 1
    return n


def main(argv=None):
    p = argparse.ArgumentParser(
        description="download + convert reference RAFT checkpoints")
    p.add_argument("--out", default="models", help="output directory")
    p.add_argument("--zip", default=None,
                   help="use an existing models.zip instead of downloading")
    p.add_argument("--models-dir", default=None,
                   help="use an existing directory of .pth files")
    args = p.parse_args(argv)

    if args.models_dir:
        models_dir = args.models_dir
    else:
        os.makedirs(args.out, exist_ok=True)
        zpath = args.zip or download(MODELS_URL,
                                     osp.join(args.out, "models.zip"))
        with zipfile.ZipFile(zpath) as z:
            z.extractall(args.out)
        # the reference zip nests everything under models/
        nested = osp.join(args.out, "models")
        models_dir = nested if osp.isdir(nested) else args.out

    n = convert_all(models_dir)
    print(f"converted {n} checkpoints in {models_dir}")
    return 0 if n else 1


if __name__ == "__main__":
    sys.exit(main())
