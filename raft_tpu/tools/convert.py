"""PyTorch ``.pth`` checkpoint → flax variables converter.

The reference saves ``nn.DataParallel`` state dicts — every key carries a
``module.`` prefix (train.py:187; consumers wrap in DataParallel *before*
loading, evaluate.py:178-179). This converter:

- strips the ``module.`` prefix,
- transposes conv kernels OIHW → HWIO,
- maps norm params (weight/bias → scale/bias) and BatchNorm running stats
  into the ``batch_stats`` collection,
- drops ``num_batches_tracked`` and the duplicated ``downsample.1`` norm
  entries (torch registers the same norm module under both ``normN`` and
  ``downsample.1`` — extractor.py:44-45,103-104).

The mapping is derived by walking the *flax* variable tree and computing each
param's torch key, so missing/mismatched keys fail loudly.
"""

from __future__ import annotations

import hashlib
import os
import re
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.config import RAFTConfig
from raft_tpu.models import RAFT


def _flax_path_to_torch_key(path: Tuple[str, ...], collection: str) -> str:
    """('fnet','layer1_0','conv1','kernel') -> 'fnet.layer1.0.conv1.weight'."""
    parts = []
    for comp in path[:-1]:
        m = re.fullmatch(r"layer(\d)_(\d)", comp)
        if m:
            parts.append(f"layer{m.group(1)}.{m.group(2)}")
        elif comp == "downsample_conv":
            parts.append("downsample.0")
        elif comp == "mask_conv1":
            parts.append("mask.0")
        elif comp == "mask_conv2":
            parts.append("mask.2")
        elif comp == "norm":
            continue  # flax Norm wrapper level, absent in torch
        else:
            parts.append(comp)

    leaf = path[-1]
    if collection == "batch_stats":
        leaf = {"mean": "running_mean", "var": "running_var"}[leaf]
    else:
        leaf = {"kernel": "weight", "scale": "weight", "bias": "bias"}[leaf]
    return ".".join(parts + [leaf])


def _convert_value(path: Tuple[str, ...], value: np.ndarray,
                   target_shape) -> np.ndarray:
    if path[-1] == "kernel":
        value = np.transpose(value, (2, 3, 1, 0))  # OIHW -> HWIO
    value = np.asarray(value, dtype=np.float32)
    if tuple(value.shape) != tuple(target_shape):
        raise ValueError(
            f"shape mismatch at {'/'.join(path)}: torch {value.shape} "
            f"vs flax {tuple(target_shape)}")
    return value


def torch_key_map(variables: Dict[str, Any]) -> Dict[str, Tuple[str, Tuple[str, ...]]]:
    """torch key -> (collection, flax path) for every param in ``variables``."""
    mapping = {}
    for collection in ("params", "batch_stats"):
        if collection not in variables:
            continue
        flat = jax.tree_util.tree_flatten_with_path(variables[collection])[0]
        for keypath, leaf in flat:
            path = tuple(k.key for k in keypath)
            tkey = _flax_path_to_torch_key(path, collection)
            mapping[tkey] = (collection, path)
    return mapping


def convert_state_dict(state_dict: Dict[str, np.ndarray],
                       variables: Dict[str, Any]) -> Dict[str, Any]:
    """Fill a flax variable tree with values from a torch state dict.

    ``state_dict`` values may be torch tensors or numpy arrays.
    """
    sd = {}
    for k, v in state_dict.items():
        k = k.removeprefix("module.")
        if k.endswith("num_batches_tracked"):
            continue
        if ".downsample.1." in k:
            continue  # duplicate of normN (see module docstring)
        if hasattr(v, "detach"):
            v = v.detach().cpu().numpy()
        sd[k] = np.asarray(v)

    mapping = torch_key_map(variables)

    missing = sorted(set(mapping) - set(sd))
    unexpected = sorted(set(sd) - set(mapping))
    if missing:
        raise KeyError(f"state dict missing {len(missing)} keys, e.g. "
                       f"{missing[:5]}")
    if unexpected:
        raise KeyError(f"state dict has {len(unexpected)} unmapped keys, "
                       f"e.g. {unexpected[:5]}")

    out = {c: {} for c in variables}
    flat_out: Dict[str, Dict[Tuple[str, ...], jnp.ndarray]] = {
        c: {} for c in variables}
    for tkey, (collection, path) in mapping.items():
        target = variables[collection]
        for comp in path:
            target = target[comp]
        flat_out[collection][path] = jnp.asarray(
            _convert_value(path, sd[tkey], target.shape))

    for collection, flat in flat_out.items():
        tree: Dict[str, Any] = {}
        for path, value in flat.items():
            node = tree
            for comp in path[:-1]:
                node = node.setdefault(comp, {})
            node[path[-1]] = value
        out[collection] = tree
    # preserve any collections without torch counterparts (shouldn't happen)
    for c in variables:
        if c not in out or not out[c]:
            out[c] = variables[c]
    return out


def load_pth(path: str, config: RAFTConfig,
             image_hw: Tuple[int, int] = (64, 64)) -> Dict[str, Any]:
    """Load a reference ``.pth`` into flax variables for ``config``."""
    import torch

    state_dict = torch.load(path, map_location="cpu", weights_only=True)
    model = RAFT(config)
    img = jnp.zeros((1, *image_hw, 3))
    variables = model.init(jax.random.PRNGKey(0), img, img, iters=1)
    return convert_state_dict(state_dict, variables)


class CorruptCheckpointError(RuntimeError):
    """A checkpoint file failed its sidecar SHA-256 integrity check."""


def manifest_path(path: str) -> str:
    return path + ".sha256"


def write_manifest(path: str, data: bytes) -> None:
    """Atomic sidecar integrity manifest: ``<sha256hex>  <nbytes>``."""
    line = f"{hashlib.sha256(data).hexdigest()}  {len(data)}\n"
    tmp = f"{manifest_path(path)}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(line)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, manifest_path(path))


def verify_manifest(path: str, data: bytes) -> None:
    """Raise :class:`CorruptCheckpointError` when ``path``'s sidecar
    manifest mismatches ``data`` (flipped bytes, truncation, or a stale
    manifest from an interrupted save — all refuse-to-load conditions).
    A missing sidecar passes: pre-hardening checkpoints stay loadable."""
    try:
        with open(manifest_path(path), encoding="utf-8") as f:
            want_digest, want_size = f.read().split()
    except FileNotFoundError:
        return
    except ValueError as e:
        raise CorruptCheckpointError(
            f"unparsable integrity manifest {manifest_path(path)}: "
            f"{e}") from e
    got = hashlib.sha256(data).hexdigest()
    if got != want_digest or len(data) != int(want_size):
        raise CorruptCheckpointError(
            f"{path} failed its integrity check (manifest "
            f"{want_digest[:12]}…/{want_size}B vs actual "
            f"{got[:12]}…/{len(data)}B) — the file is corrupt or torn; "
            "refusing to load silently-wrong weights")


def save_converted(variables: Dict[str, Any], out_path: str) -> None:
    """Serialize converted variables with flax msgpack — crash-safely.

    The payload lands under a tmp name and is fsync'd before an atomic
    rename, so a crash mid-save can never leave a truncated file under
    the final name (the pre-hardening bug: a died ``save_weights``
    produced a half-written ``.msgpack`` a later resume loaded). A
    sidecar SHA-256 manifest written after the rename lets
    :func:`load_converted` detect byte corruption.
    """
    from flax import serialization

    from raft_tpu.testing import faults

    data = serialization.to_bytes(variables)
    tmp = f"{out_path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        # the classic torn-write window: tmp durable, rename pending
        faults.fault_point("ckpt.msgpack_write")
        os.replace(tmp, out_path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    write_manifest(out_path, data)
    # post-save bit-rot drill: damage the COMPLETED artifact so the
    # load-time manifest check is what has to catch it
    faults.fault_file("ckpt.msgpack_write", out_path)


def load_converted(path: str, config: RAFTConfig,
                   image_hw: Tuple[int, int] = (64, 64)) -> Dict[str, Any]:
    from flax import serialization

    model = RAFT(config)
    img = jnp.zeros((1, *image_hw, 3))
    variables = model.init(jax.random.PRNGKey(0), img, img, iters=1)
    with open(path, "rb") as f:
        data = f.read()
    verify_manifest(path, data)
    return serialization.from_bytes(variables, data)


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(
        description="Convert reference RAFT .pth checkpoints to flax msgpack")
    p.add_argument("input", help="path to .pth file")
    p.add_argument("output", help="path to write .msgpack")
    p.add_argument("--small", action="store_true")
    args = p.parse_args(argv)

    cfg = RAFTConfig(small=args.small)
    variables = load_pth(args.input, cfg)
    save_converted(variables, args.output)
    print(f"converted {args.input} -> {args.output}")


if __name__ == "__main__":
    main()
