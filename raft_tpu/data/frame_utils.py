"""Flow-file I/O: Middlebury .flo, .pfm, KITTI 16-bit png.

Equivalent of ``/root/reference/core/utils/frame_utils.py``. Formats:
- ``.flo``: float32 tag 202021.25, int32 w/h, interleaved (u, v) rows
  (frame_utils.py:10-31,70-99).
- ``.pfm``: PF/Pf header, scale sign = endianness, rows bottom-up
  (frame_utils.py:33-68).
- KITTI png: uint16 BGR->RGB, flow = (px - 2^15)/64, third channel = valid
  (frame_utils.py:102-120).
"""

from __future__ import annotations

import re
from os.path import splitext

import numpy as np
from PIL import Image

import cv2

cv2.setNumThreads(0)
cv2.ocl.setUseOpenCL(False)

TAG_FLO = 202021.25


def read_flow(path: str) -> np.ndarray:
    """Read a Middlebury .flo file -> (H, W, 2) float32."""
    from raft_tpu import native

    out = native.read_flo(path)  # GIL-free fast path when built
    if out is not None:
        return out
    with open(path, "rb") as f:
        magic = np.fromfile(f, np.float32, count=1)
        if magic.size == 0 or magic[0] != np.float32(TAG_FLO):
            raise ValueError(f"{path}: invalid .flo magic {magic}")
        w = int(np.fromfile(f, np.int32, count=1)[0])
        h = int(np.fromfile(f, np.int32, count=1)[0])
        data = np.fromfile(f, np.float32, count=2 * w * h)
    return data.reshape(h, w, 2)


def write_flow(path: str, uv: np.ndarray) -> None:
    """Write (H, W, 2) float32 flow as .flo."""
    from raft_tpu import native

    uv = np.asarray(uv, np.float32)
    assert uv.ndim == 3 and uv.shape[2] == 2, uv.shape
    if native.write_flo(path, uv):
        return
    h, w = uv.shape[:2]
    with open(path, "wb") as f:
        np.array([TAG_FLO], np.float32).tofile(f)
        np.array([w], np.int32).tofile(f)
        np.array([h], np.int32).tofile(f)
        uv.astype(np.float32).tofile(f)


def read_pfm(path: str) -> np.ndarray:
    from raft_tpu import native

    out = native.read_pfm(path)
    if out is not None:
        return out
    with open(path, "rb") as f:
        header = f.readline().rstrip()
        if header == b"PF":
            color = True
        elif header == b"Pf":
            color = False
        else:
            raise ValueError(f"{path}: not a PFM file")

        dims = re.match(rb"^(\d+)\s(\d+)\s$", f.readline())
        if not dims:
            raise ValueError(f"{path}: malformed PFM header")
        width, height = map(int, dims.groups())

        scale = float(f.readline().rstrip())
        endian = "<" if scale < 0 else ">"
        data = np.fromfile(f, endian + "f")

    shape = (height, width, 3) if color else (height, width)
    return np.flipud(data.reshape(shape)).copy()


def write_pfm(path: str, data: np.ndarray, scale: float = 1.0) -> None:
    data = np.asarray(data, np.float32)
    color = data.ndim == 3
    with open(path, "wb") as f:
        f.write(b"PF\n" if color else b"Pf\n")
        f.write(f"{data.shape[1]} {data.shape[0]}\n".encode())
        f.write(f"{-scale}\n".encode())  # little-endian
        np.flipud(data).astype("<f").tofile(f)


def read_flow_kitti(path: str):
    """KITTI flow png -> ((H, W, 2) float32 flow, (H, W) valid)."""
    img = cv2.imread(path, cv2.IMREAD_ANYDEPTH | cv2.IMREAD_COLOR)
    img = img[:, :, ::-1].astype(np.float32)  # BGR -> RGB
    flow, valid = img[:, :, :2], img[:, :, 2]
    flow = (flow - 2 ** 15) / 64.0
    return flow, valid


def write_flow_kitti(path: str, uv: np.ndarray) -> None:
    uv = 64.0 * np.asarray(uv) + 2 ** 15
    valid = np.ones([uv.shape[0], uv.shape[1], 1])
    uv = np.concatenate([uv, valid], axis=-1).astype(np.uint16)
    cv2.imwrite(path, uv[..., ::-1])


def read_disp_kitti(path: str):
    disp = cv2.imread(path, cv2.IMREAD_ANYDEPTH) / 256.0
    valid = disp > 0.0
    flow = np.stack([-disp, np.zeros_like(disp)], -1)
    return flow, valid


def read_gen(path: str):
    """Extension-dispatched reader (frame_utils.py:123-137)."""
    ext = splitext(path)[-1].lower()
    if ext in (".png", ".jpeg", ".ppm", ".jpg"):
        return Image.open(path)
    if ext in (".bin", ".raw"):
        return np.load(path)
    if ext == ".flo":
        return read_flow(path).astype(np.float32)
    if ext == ".pfm":
        flow = read_pfm(path).astype(np.float32)
        return flow if flow.ndim == 2 else flow[:, :, :-1]
    return []
