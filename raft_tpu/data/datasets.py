"""Flow datasets: Sintel, FlyingChairs, FlyingThings3D, KITTI, HD1K.

Equivalent of ``/root/reference/core/datasets.py`` as pure-Python indexers
yielding **numpy NHWC** samples (no torch): img1/img2 (H, W, 3) float32,
flow (H, W, 2) float32, valid (H, W) float32. Mixing uses the same
list-replication trick (``__rmul__``, datasets.py:93-96) and the same stage
recipes, e.g. sintel-stage mix 100·sc + 100·sf + 200·k + 5·h + things
(datasets.py:218-221).

FlyingChairs needs the upstream ``chairs_split.txt`` (1=train, 2=val). A
copy is bundled at ``raft_tpu/data/chairs_split.txt`` (a data manifest,
NOTICE-attributed) and found automatically after the working directory
and dataset root are searched; pass ``split_file`` to override (the
reference loads it from the working directory, datasets.py:129).
"""

from __future__ import annotations

import os
import os.path as osp
from glob import glob
from typing import Optional

import numpy as np

from raft_tpu.data import frame_utils
from raft_tpu.data.augmentor import FlowAugmentor, SparseFlowAugmentor


class FlowDataset:
    def __init__(self, aug_params=None, sparse: bool = False):
        self.augmentor = None
        self.sparse = sparse
        if aug_params is not None:
            if sparse:
                self.augmentor = SparseFlowAugmentor(**aug_params)
            else:
                self.augmentor = FlowAugmentor(**aug_params)

        self.is_test = False
        self.flow_list = []
        self.image_list = []
        self.extra_info = []

    def reseed(self, seed: int):
        if self.augmentor is not None:
            self.augmentor.reseed(seed)

    def __getitem__(self, index):
        if self.is_test:
            img1 = np.array(frame_utils.read_gen(self.image_list[index][0])
                            ).astype(np.uint8)[..., :3]
            img2 = np.array(frame_utils.read_gen(self.image_list[index][1])
                            ).astype(np.uint8)[..., :3]
            return (img1.astype(np.float32), img2.astype(np.float32),
                    self.extra_info[index])

        index = index % len(self.image_list)
        valid = None
        if self.sparse:
            flow, valid = frame_utils.read_flow_kitti(self.flow_list[index])
        else:
            flow = frame_utils.read_gen(self.flow_list[index])

        img1 = np.array(frame_utils.read_gen(self.image_list[index][0]))
        img2 = np.array(frame_utils.read_gen(self.image_list[index][1]))
        flow = np.array(flow).astype(np.float32)
        img1 = img1.astype(np.uint8)
        img2 = img2.astype(np.uint8)

        if img1.ndim == 2:  # grayscale
            img1 = np.tile(img1[..., None], (1, 1, 3))
            img2 = np.tile(img2[..., None], (1, 1, 3))
        else:
            img1 = img1[..., :3]
            img2 = img2[..., :3]

        if self.augmentor is not None:
            if self.sparse:
                img1, img2, flow, valid = self.augmentor(img1, img2, flow,
                                                         valid)
            else:
                img1, img2, flow = self.augmentor(img1, img2, flow)

        img1 = img1.astype(np.float32)
        img2 = img2.astype(np.float32)
        flow = flow.astype(np.float32)

        if valid is None:
            # synthetic-data validity cutoff (datasets.py:88)
            valid = ((np.abs(flow[..., 0]) < 1000)
                     & (np.abs(flow[..., 1]) < 1000))
        return img1, img2, flow, np.asarray(valid, np.float32)

    def __rmul__(self, v: int):
        self.flow_list = v * self.flow_list
        self.image_list = v * self.image_list
        return self

    def __add__(self, other):
        return ConcatDataset([self, other])

    def __len__(self):
        return len(self.image_list)


def require_nonempty(dataset, name: str, root: str) -> None:
    """Dataset scans glob the disk and come back empty when the data is not
    staged; surface that as FileNotFoundError so callers (notably
    ``trainer.run_validation``) can skip cleanly instead of crashing on an
    empty reduction downstream."""
    if len(dataset) == 0:
        raise FileNotFoundError(
            f"{name}: no samples found under '{root}' — dataset not staged")


class ConcatDataset:
    """Minimal torch ConcatDataset analog for the mixing arithmetic."""

    def __init__(self, datasets):
        flat = []
        for d in datasets:
            if isinstance(d, ConcatDataset):
                flat.extend(d.datasets)
            else:
                flat.append(d)
        self.datasets = flat
        self.cum = np.cumsum([len(d) for d in flat])

    def reseed(self, seed: int):
        for i, d in enumerate(self.datasets):
            d.reseed(seed + i)

    def __len__(self):
        return int(self.cum[-1])

    def __add__(self, other):
        return ConcatDataset([self, other])

    def __radd__(self, other):
        return ConcatDataset([other, self])

    def __getitem__(self, index):
        ds = int(np.searchsorted(self.cum, index, side="right"))
        prev = 0 if ds == 0 else int(self.cum[ds - 1])
        return self.datasets[ds][index - prev]


class MpiSintel(FlowDataset):
    def __init__(self, aug_params=None, split="training",
                 root="datasets/Sintel", dstype="clean"):
        super().__init__(aug_params)
        flow_root = osp.join(root, split, "flow")
        image_root = osp.join(root, split, dstype)

        if split == "test":
            self.is_test = True

        if osp.isdir(image_root):
            for scene in sorted(os.listdir(image_root)):
                image_list = sorted(glob(osp.join(image_root, scene, "*.png")))
                for i in range(len(image_list) - 1):
                    self.image_list += [[image_list[i], image_list[i + 1]]]
                    self.extra_info += [(scene, i)]
                if split != "test":
                    self.flow_list += sorted(
                        glob(osp.join(flow_root, scene, "*.flo")))


class FlyingChairs(FlowDataset):
    def __init__(self, aug_params=None, split="train",
                 root="datasets/FlyingChairs_release/data",
                 split_file: Optional[str] = None):
        super().__init__(aug_params)

        images = sorted(glob(osp.join(root, "*.ppm")))
        flows = sorted(glob(osp.join(root, "*.flo")))
        if not flows:
            return
        assert len(images) // 2 == len(flows)

        if split_file is None:
            # bundled manifest last: explicit/dataset-local copies win
            for cand in ("chairs_split.txt",
                         osp.join(root, "chairs_split.txt"),
                         osp.join(root, "..", "chairs_split.txt"),
                         osp.join(osp.dirname(osp.abspath(__file__)),
                                  "chairs_split.txt")):
                if osp.exists(cand):
                    split_file = cand
                    break
        if split_file is None:
            raise FileNotFoundError(
                "chairs_split.txt not found; download from upstream RAFT and "
                "pass split_file= or place it in the dataset root")
        split_list = np.loadtxt(split_file, dtype=np.int32)
        for i in range(len(flows)):
            xid = split_list[i]
            if (split == "training" and xid == 1) or \
                    (split == "validation" and xid == 2):
                self.flow_list += [flows[i]]
                self.image_list += [[images[2 * i], images[2 * i + 1]]]


class FlyingThings3D(FlowDataset):
    def __init__(self, aug_params=None, root="datasets/FlyingThings3D",
                 dstype="frames_cleanpass"):
        super().__init__(aug_params)

        for cam in ["left"]:
            for direction in ["into_future", "into_past"]:
                image_dirs = sorted(glob(osp.join(root, dstype, "TRAIN/*/*")))
                image_dirs = sorted([osp.join(f, cam) for f in image_dirs])
                flow_dirs = sorted(
                    glob(osp.join(root, "optical_flow/TRAIN/*/*")))
                flow_dirs = sorted(
                    [osp.join(f, direction, cam) for f in flow_dirs])

                for idir, fdir in zip(image_dirs, flow_dirs):
                    images = sorted(glob(osp.join(idir, "*.png")))
                    flows = sorted(glob(osp.join(fdir, "*.pfm")))
                    for i in range(len(flows) - 1):
                        if direction == "into_future":
                            self.image_list += [[images[i], images[i + 1]]]
                            self.flow_list += [flows[i]]
                        else:
                            self.image_list += [[images[i + 1], images[i]]]
                            self.flow_list += [flows[i + 1]]


class KITTI(FlowDataset):
    def __init__(self, aug_params=None, split="training",
                 root="datasets/KITTI"):
        super().__init__(aug_params, sparse=True)
        if split == "testing":
            self.is_test = True

        root = osp.join(root, split)
        images1 = sorted(glob(osp.join(root, "image_2/*_10.png")))
        images2 = sorted(glob(osp.join(root, "image_2/*_11.png")))

        for img1, img2 in zip(images1, images2):
            frame_id = img1.split("/")[-1]
            self.extra_info += [[frame_id]]
            self.image_list += [[img1, img2]]

        if split == "training":
            self.flow_list = sorted(glob(osp.join(root, "flow_occ/*_10.png")))


class HD1K(FlowDataset):
    def __init__(self, aug_params=None, root="datasets/HD1k"):
        super().__init__(aug_params, sparse=True)

        seq_ix = 0
        while True:
            flows = sorted(glob(osp.join(
                root, "hd1k_flow_gt", "flow_occ/%06d_*.png" % seq_ix)))
            images = sorted(glob(osp.join(
                root, "hd1k_input", "image_2/%06d_*.png" % seq_ix)))
            if len(flows) == 0:
                break
            for i in range(len(flows) - 1):
                self.flow_list += [flows[i]]
                self.image_list += [[images[i], images[i + 1]]]
            seq_ix += 1


def fetch_dataset(stage: str, image_size, data_root: str = "datasets",
                  train_ds: str = "C+T+K+S+H"):
    """Stage-keyed training dataset mix (datasets.py:199-228).

    Raises FileNotFoundError when the assembled mix has zero samples (the
    class scans glob the disk and come back empty when data isn't staged);
    an empty mix would otherwise surface as an opaque loader IndexError.
    """
    mix = _fetch_dataset(stage, image_size, data_root, train_ds)
    require_nonempty(mix, f"stage {stage!r}", data_root)
    return mix


def _fetch_dataset(stage: str, image_size, data_root: str,
                   train_ds: str):
    def p(name):
        return osp.join(data_root, name)

    if stage == "chairs":
        aug = {"crop_size": image_size, "min_scale": -0.1, "max_scale": 1.0,
               "do_flip": True}
        return FlyingChairs(aug, split="training",
                            root=p("FlyingChairs_release/data"))

    if stage == "things":
        aug = {"crop_size": image_size, "min_scale": -0.4, "max_scale": 0.8,
               "do_flip": True}
        clean = FlyingThings3D(aug, root=p("FlyingThings3D"),
                               dstype="frames_cleanpass")
        final = FlyingThings3D(aug, root=p("FlyingThings3D"),
                               dstype="frames_finalpass")
        return ConcatDataset([clean, final])

    if stage == "sintel":
        aug = {"crop_size": image_size, "min_scale": -0.2, "max_scale": 0.6,
               "do_flip": True}
        things = FlyingThings3D(aug, root=p("FlyingThings3D"),
                                dstype="frames_cleanpass")
        sintel_clean = MpiSintel(aug, split="training", root=p("Sintel"),
                                 dstype="clean")
        sintel_final = MpiSintel(aug, split="training", root=p("Sintel"),
                                 dstype="final")
        if train_ds == "C+T+K+S+H":
            kitti = KITTI({"crop_size": image_size, "min_scale": -0.3,
                           "max_scale": 0.5, "do_flip": True},
                          root=p("KITTI"))
            hd1k = HD1K({"crop_size": image_size, "min_scale": -0.5,
                         "max_scale": 0.2, "do_flip": True}, root=p("HD1k"))
            return ConcatDataset([100 * sintel_clean, 100 * sintel_final,
                                  200 * kitti, 5 * hd1k, things])
        return ConcatDataset([100 * sintel_clean, 100 * sintel_final, things])

    if stage == "kitti":
        aug = {"crop_size": image_size, "min_scale": -0.2, "max_scale": 0.4,
               "do_flip": False}
        return KITTI(aug, split="training", root=p("KITTI"))

    raise ValueError(f"unknown stage {stage!r}")
