from raft_tpu.data.datasets import (  # noqa: F401
    HD1K,
    KITTI,
    FlowDataset,
    FlyingChairs,
    FlyingThings3D,
    MpiSintel,
    fetch_dataset,
)
from raft_tpu.data.loader import PrefetchLoader, fetch_dataloader  # noqa: F401
