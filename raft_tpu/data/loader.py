"""Threaded prefetching batch loader (the torch DataLoader replacement).

The reference uses ``torch.utils.data.DataLoader(num_workers=4, shuffle=True,
drop_last=True)`` (datasets.py:230-231). Here: a thread pool decodes and
augments samples while the accelerator steps — cv2/PIL/numpy release the GIL
for the heavy parts, and the optional C++ codec (raft_tpu.native) bypasses it
entirely. Each worker thread gets its own reseeded RNG, mirroring the
reference's per-worker reseeding (datasets.py:45-51).

Batches are dicts of stacked numpy arrays, ready for ``jax.device_put``.
"""

from __future__ import annotations

import os
import queue
import threading
import warnings
from typing import Dict, Iterator, Optional

import numpy as np

from raft_tpu.testing import faults

#: on_bad_sample="skip" gives up after this many consecutive failures
#: for ONE slot — a dataset where every draw fails is systematically
#: broken, and resampling forever would spin a worker thread
_MAX_RESAMPLES = 8


class LoaderStallError(RuntimeError):
    """The consumer's stall deadline (``stall_s``) expired waiting for a
    batch: a worker is stuck inside decode/augment (hung codec, dead
    NFS mount). Named so callers and runbooks can tell "data pipeline
    hung" from a wedged accelerator — without the deadline this was an
    eternal silent hang in ``cond.wait_for``."""


def _collate(samples, wire_dtype: str = "float32",
             check: bool = False) -> Dict[str, np.ndarray]:
    img1, img2, flow, valid = zip(*samples)
    if wire_dtype == "uint8":
        # Low-bandwidth wire format: images and valid travel as uint8 and
        # the jitted train step casts them back on device. Lossless by the
        # augmentor contract — every augmentation runs on uint8 images and
        # the final float32 astype only widens (augmentor.py), and valid is
        # a 0/1 mask — while cutting host->device bytes 50 -> 19 MB per
        # chairs-b8 batch. Measured on the round-5 tunnel backend (axon,
        # where in-flight H2D crawls at ~60 MB/s): 1228 -> 606 ms/step
        # (BENCH_NOTES.md round 5). flow is real-valued ground truth and
        # stays float32. Cast per sample BEFORE the stack so the full-size
        # float32 batch never materializes on the loader thread.
        if check:
            for name, s in (("image1", img1[0]), ("image2", img2[0])):
                s = np.asarray(s)
                if not (s.min() >= 0 and s.max() <= 255
                        and np.array_equal(s, np.floor(s))):
                    raise ValueError(
                        "wire_dtype='uint8' requires integral [0,255] "
                        f"images (the augmentor contract) — {name} has "
                        f"values in [{s.min():.3g}, {s.max():.3g}]; use "
                        "wire_dtype='float32' for this dataset")
            v = np.asarray(valid[0])
            if not np.isin(v, (0.0, 1.0)).all():
                raise ValueError(
                    "wire_dtype='uint8' requires a 0/1 valid mask — got "
                    f"values in [{v.min():.3g}, {v.max():.3g}] (fractional "
                    "weights would be truncated); use wire_dtype='float32'")
        img1 = [np.asarray(x, np.uint8) for x in img1]
        img2 = [np.asarray(x, np.uint8) for x in img2]
        valid = [np.asarray(v, np.uint8) for v in valid]
    return {
        "image1": np.stack(img1),
        "image2": np.stack(img2),
        "flow": np.stack(flow),
        "valid": np.stack(valid),
    }


class PrefetchLoader:
    """Shuffled, batched, prefetching iterator over a FlowDataset."""

    def __init__(self, dataset, batch_size: int, shuffle: bool = True,
                 num_workers: int = 4, drop_last: bool = True,
                 seed: int = 1234, prefetch: int = 4, clamp: bool = True,
                 wire_dtype: str = "float32",
                 on_bad_sample: str = "raise", stall_s: float = 0.0):
        if wire_dtype not in ("float32", "uint8"):
            raise ValueError(f"wire_dtype={wire_dtype!r}: choose float32 "
                             "or uint8 (see _collate)")
        if on_bad_sample not in ("raise", "skip"):
            raise ValueError(f"on_bad_sample={on_bad_sample!r}: choose "
                             "'raise' (surface decode errors) or 'skip' "
                             "(resample with a counted warning)")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.wire_dtype = wire_dtype
        self.on_bad_sample = on_bad_sample
        # consumer-side deadline per batch; 0 keeps the legacy wait-
        # forever behavior (the stable contract for callers that own
        # their own watchdog)
        self.stall_s = float(stall_s)
        self.bad_samples = 0  # running skip count across epochs
        self._bad_lock = threading.Lock()
        # clamp to the host: more worker threads than spare cores only
        # buys GIL/queue contention (measured on the 1-core deployment
        # host: 1 worker 52.2 pairs/s vs 4 workers 44.6, cli/loader_bench;
        # clamp=False is the bench's escape hatch for re-validating that).
        # sched_getaffinity sees cgroup/taskset pinning that cpu_count
        # misses — the constrained-host case is the one the clamp is for.
        try:
            cores = len(os.sched_getaffinity(0))
        except (AttributeError, OSError):
            cores = os.cpu_count() or 1
        spare = max(1, cores - 1)
        self.num_workers = (max(1, min(num_workers, spare)) if clamp
                            else max(1, num_workers))
        if self.num_workers != num_workers:
            warnings.warn(
                f"PrefetchLoader: clamped num_workers {num_workers} -> "
                f"{self.num_workers} ({cores} usable cores; extra "
                "threads only add GIL contention)", stacklevel=2)
        self.drop_last = drop_last
        self.seed = seed
        self.prefetch = prefetch
        self.epoch = 0

    def __len__(self):
        n = len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return -(-n // self.batch_size)

    def _sample(self, index: int, resample: np.random.RandomState):
        """One dataset fetch under the ``on_bad_sample`` policy:
        'raise' surfaces decode errors to the consumer verbatim; 'skip'
        draws a replacement index (counted, warned) so one rotten file
        doesn't kill a multi-day run."""
        tries = 0
        while True:
            try:
                faults.fault_point("loader.sample")  # crash-safety drill
                return self.dataset[index]
            except Exception as exc:
                if self.on_bad_sample != "skip":
                    raise
                tries += 1
                if tries >= _MAX_RESAMPLES:
                    raise RuntimeError(
                        f"on_bad_sample='skip' gave up after "
                        f"{_MAX_RESAMPLES} consecutive bad samples "
                        f"(last: {type(exc).__name__}: {exc}) — the "
                        "dataset looks systematically broken, not "
                        "spotty") from exc
                with self._bad_lock:
                    self.bad_samples += 1
                    n = self.bad_samples
                warnings.warn(
                    f"PrefetchLoader: skipped bad sample {index} "
                    f"({type(exc).__name__}: {exc}); resampling "
                    f"({n} skipped so far)", stacklevel=2)
                index = int(resample.randint(len(self.dataset)))

    def _epoch_indices(self) -> np.ndarray:
        idx = np.arange(len(self.dataset))
        if self.shuffle:
            np.random.RandomState(self.seed + self.epoch).shuffle(idx)
        if self.drop_last:
            idx = idx[:len(self) * self.batch_size]
        return idx

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        indices = self._epoch_indices()
        batches = [indices[i:i + self.batch_size]
                   for i in range(0, len(indices), self.batch_size)]
        self.epoch += 1

        task_q: "queue.Queue" = queue.Queue()
        results: Dict[int, object] = {}
        cond = threading.Condition()
        stop = threading.Event()
        # bound how far workers run ahead of consumption
        ahead = threading.Semaphore(self.prefetch + self.num_workers)

        for bi, batch_idx in enumerate(batches):
            task_q.put((bi, batch_idx))

        def worker(worker_id: int):
            # per-worker reseed (datasets.py:45-51 analog)
            if hasattr(self.dataset, "reseed"):
                self.dataset.reseed(self.seed + worker_id * 7919 + self.epoch)
            resample = np.random.RandomState(
                self.seed + worker_id * 104729 + self.epoch)
            while not stop.is_set():
                ahead.acquire()
                if stop.is_set():
                    return  # woken by the consumer's shutdown release
                try:
                    bi, batch_idx = task_q.get_nowait()
                except queue.Empty:
                    ahead.release()
                    return
                try:
                    batch = _collate([self._sample(int(i), resample)
                                      for i in batch_idx],
                                     self.wire_dtype,
                                     check=(bi == 0))
                except Exception as e:  # surface decode errors to consumer
                    batch = e
                with cond:
                    results[bi] = batch
                    cond.notify_all()

        threads = [threading.Thread(target=worker, args=(w,), daemon=True,
                                    name=f"PrefetchLoader-w{w}")
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()

        try:
            for next_bi in range(len(batches)):
                with cond:
                    if not cond.wait_for(lambda: next_bi in results,
                                         timeout=self.stall_s or None):
                        raise LoaderStallError(
                            f"batch {next_bi} not produced within "
                            f"stall_s={self.stall_s:.0f}s — a worker is "
                            "stuck in decode/augment; see "
                            "PrefetchLoader(stall_s=, on_bad_sample=)")
                    batch = results.pop(next_bi)
                ahead.release()
                if isinstance(batch, Exception):
                    raise batch
                yield batch
        finally:
            stop.set()
            # wake every worker parked in ahead.acquire(): on an early
            # consumer exit (break, exception, stall) nobody would ever
            # release again, stranding them there past `stop` forever —
            # one leaked thread set per partial epoch in a long-lived
            # process. Workers re-check `stop` right after acquiring.
            for _ in threads:
                ahead.release()
            with cond:
                results.clear()


def fetch_dataloader(stage: str, image_size, batch_size: int,
                     data_root: str = "datasets", num_workers: int = 4,
                     seed: int = 1234, wire_dtype: str = "float32",
                     on_bad_sample: str = "raise",
                     stall_s: float = 0.0) -> PrefetchLoader:
    """Stage-preset loader, the fetch_dataloader analog (datasets.py:199).

    Default stays float32 (the stable public contract — batches safe for
    host arithmetic); the in-repo trainer passes ``wire_dtype="uint8"``
    explicitly for the low-bandwidth wire format the jitted step casts
    back on device (see _collate).
    """
    from raft_tpu.data.datasets import fetch_dataset

    dataset = fetch_dataset(stage, image_size, data_root)
    print(f"Training with {len(dataset)} image pairs")
    return PrefetchLoader(dataset, batch_size, shuffle=True,
                          num_workers=num_workers, drop_last=True, seed=seed,
                          wire_dtype=wire_dtype,
                          on_bad_sample=on_bad_sample, stall_s=stall_s)
