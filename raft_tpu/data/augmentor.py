"""Photometric + spatial augmentation for dense and sparse flow.

Equivalent of ``/root/reference/core/utils/augmentor.py`` with the same
probabilities and parameter distributions. torchvision is not a dependency:
``ColorJitter(brightness, contrast, saturation, hue)`` is re-implemented on
cv2/numpy — factors drawn U[1-x, 1+x] (hue U[-h, h]) and applied in a random
permutation order, the same sampling scheme torchvision uses. Differences
are sub-quantization-level (uint8 rounding order), not distributional.

Provenance note: the color jitter, its LUT/fused-SIMD fast paths, and the
grayscale/blend ops are original. ``eraser_transform`` and
``spatial_transform`` (both classes), by contrast, intentionally follow the
reference's statement ORDER, not just its distributions: the sequence of
``self.rng`` draws (scale, stretch, flip, crop, eraser rectangles) is the
augmentation parity surface — reordering two draws changes every downstream
sample — and the surrounding numpy slicing is largely forced by that. Those
two methods are honest close ports (augmentor.py:52-120, 161-246) under
LICENSE.RAFT; the RNG plumbing (explicit per-worker ``RandomState`` instead
of process-global ``np.random``) is redesigned.

All randomness flows through an ``np.random.RandomState`` so loader workers
can reseed deterministically (the reference reseeds per worker process,
datasets.py:45-51).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import cv2

cv2.setNumThreads(0)
cv2.ocl.setUseOpenCL(False)


def _blend_scalar(a: np.ndarray, b: float, factor: float) -> np.ndarray:
    """``_blend`` against a scalar, as a 256-entry LUT applied by cv2.

    The table holds the same float expression evaluated per possible uint8
    value, so results match the float blend to the uint8 cast; ``cv2.LUT``
    applies it with SIMD, ~4x the numpy fancy-index gather — the color
    jitter is the host pipeline's hottest loop (cli/loader_bench.py), and
    the 1-core deployment host makes per-sample CPU the binding resource.
    """
    lut = np.clip(factor * np.arange(256, dtype=np.float32)
                  + (1.0 - factor) * np.float32(b), 0, 255).astype(np.uint8)
    if not a.flags.c_contiguous:
        a = np.ascontiguousarray(a)
    return cv2.LUT(a, lut)


def _grayscale(img: np.ndarray) -> np.ndarray:
    """ITU-R 601-2 luma as uint8, via cv2's fixed-point SIMD path.

    PIL's ``convert('L')`` (what torchvision's ColorJitter blends against)
    also produces a rounded uint8 luma with the same 299/587/114 weights;
    the ≤1 LSB rounding-scheme difference is distributionally irrelevant
    for augmentation.
    """
    return cv2.cvtColor(img, cv2.COLOR_RGB2GRAY)


def adjust_brightness(img, factor):
    return _blend_scalar(img, 0.0, factor)


def adjust_contrast(img, factor):
    mean = float(cv2.mean(_grayscale(img))[0])
    return _blend_scalar(img, mean, factor)


def adjust_saturation(img, factor):
    # fused f*img + (1-f)*gray with saturating rounded cast — the same
    # blend PIL's ImageEnhance.Color performs, in one SIMD pass
    gray3 = cv2.cvtColor(_grayscale(img), cv2.COLOR_GRAY2RGB)
    return cv2.addWeighted(img, factor, gray3, 1.0 - factor, 0.0)


def adjust_hue(img, factor):
    """factor in [-0.5, 0.5] — fraction of the hue circle (PIL semantics)."""
    hsv = cv2.cvtColor(img, cv2.COLOR_RGB2HSV)
    # cv2 uint8 hue range is [0, 180): express the add-mod as a 256x3
    # per-channel LUT (identity on S/V) — one SIMD pass instead of a
    # strided numpy gather+add+mod on the interleaved H plane; identical
    # by construction since every H value is < 180
    shift = int(factor * 180.0) % 180
    lut = np.empty((256, 3), np.uint8)
    lut[:, 0] = (np.arange(256) + shift) % 180
    lut[:, 1] = lut[:, 2] = np.arange(256)
    hsv = cv2.LUT(hsv, lut.reshape(1, 256, 3))
    return cv2.cvtColor(hsv, cv2.COLOR_HSV2RGB)


class ColorJitter:
    """torchvision-style jitter: random factors, random op order."""

    def __init__(self, brightness=0.0, contrast=0.0, saturation=0.0, hue=0.0):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation
        self.hue = hue

    def __call__(self, img: np.ndarray,
                 rng: np.random.RandomState) -> np.ndarray:
        ops = []
        if self.brightness > 0:
            f = rng.uniform(max(0, 1 - self.brightness), 1 + self.brightness)
            ops.append(lambda x, f=f: adjust_brightness(x, f))
        if self.contrast > 0:
            f = rng.uniform(max(0, 1 - self.contrast), 1 + self.contrast)
            ops.append(lambda x, f=f: adjust_contrast(x, f))
        if self.saturation > 0:
            f = rng.uniform(max(0, 1 - self.saturation), 1 + self.saturation)
            ops.append(lambda x, f=f: adjust_saturation(x, f))
        if self.hue > 0:
            f = rng.uniform(-self.hue, self.hue)
            ops.append(lambda x, f=f: adjust_hue(x, f))
        for i in rng.permutation(len(ops)):
            img = ops[i](img)
        return img


class FlowAugmentor:
    """Dense-GT augmentation (augmentor.py:15-120)."""

    def __init__(self, crop_size: Tuple[int, int], min_scale: float = -0.2,
                 max_scale: float = 0.5, do_flip: bool = True,
                 rng: Optional[np.random.RandomState] = None):
        self.crop_size = crop_size
        self.min_scale = min_scale
        self.max_scale = max_scale
        self.spatial_aug_prob = 0.8
        self.stretch_prob = 0.8
        self.max_stretch = 0.2

        self.do_flip = do_flip
        self.h_flip_prob = 0.5
        self.v_flip_prob = 0.1

        self.photo_aug = ColorJitter(0.4, 0.4, 0.4, 0.5 / 3.14)
        self.asymmetric_color_aug_prob = 0.2
        self.eraser_aug_prob = 0.5

        self.rng = rng if rng is not None else np.random.RandomState()

    def reseed(self, seed: int):
        self.rng = np.random.RandomState(seed)

    def color_transform(self, img1, img2):
        if self.rng.rand() < self.asymmetric_color_aug_prob:
            img1 = self.photo_aug(img1, self.rng)
            img2 = self.photo_aug(img2, self.rng)
        else:
            stack = np.concatenate([img1, img2], axis=0)
            stack = self.photo_aug(stack, self.rng)
            img1, img2 = np.split(stack, 2, axis=0)
        return img1, img2

    def eraser_transform(self, img1, img2, bounds=(50, 100)):
        """Occlusion: rectangles of img2 -> mean color (augmentor.py:52-65)."""
        ht, wd = img1.shape[:2]
        if self.rng.rand() < self.eraser_aug_prob:
            # integer-exact channel means (cv2 sums the uint8s exactly, as
            # np.mean does — just without materializing a float frame)
            mean_color = np.asarray(cv2.mean(img2)[:3])
            for _ in range(self.rng.randint(1, 3)):
                x0 = self.rng.randint(0, wd)
                y0 = self.rng.randint(0, ht)
                dx = self.rng.randint(bounds[0], bounds[1])
                dy = self.rng.randint(bounds[0], bounds[1])
                img2[y0:y0 + dy, x0:x0 + dx, :] = mean_color
        return img1, img2

    def spatial_transform(self, img1, img2, flow):
        ht, wd = img1.shape[:2]
        min_scale = np.maximum(
            (self.crop_size[0] + 8) / float(ht),
            (self.crop_size[1] + 8) / float(wd))

        scale = 2 ** self.rng.uniform(self.min_scale, self.max_scale)
        scale_x = scale_y = scale
        if self.rng.rand() < self.stretch_prob:
            scale_x *= 2 ** self.rng.uniform(-self.max_stretch,
                                             self.max_stretch)
            scale_y *= 2 ** self.rng.uniform(-self.max_stretch,
                                             self.max_stretch)
        scale_x = np.clip(scale_x, min_scale, None)
        scale_y = np.clip(scale_y, min_scale, None)

        # flow's scalar multiplies (resize rescale, flip signs) are DEFERRED
        # to after the crop: each surviving element then sees the identical
        # sequence of float multiplies (order preserved), so the result is
        # bit-exact while the multiplies materialize crop-size arrays
        # instead of full-frame ones — the loader's per-sample CPU is the
        # binding resource on the 1-core host (cli/loader_bench.py)
        flow_scales = []
        if self.rng.rand() < self.spatial_aug_prob:
            img1 = cv2.resize(img1, None, fx=scale_x, fy=scale_y,
                              interpolation=cv2.INTER_LINEAR)
            img2 = cv2.resize(img2, None, fx=scale_x, fy=scale_y,
                              interpolation=cv2.INTER_LINEAR)
            flow = cv2.resize(flow, None, fx=scale_x, fy=scale_y,
                              interpolation=cv2.INTER_LINEAR)
            flow_scales.append(np.array([scale_x, scale_y], np.float32))

        if self.do_flip:
            if self.rng.rand() < self.h_flip_prob:
                img1 = img1[:, ::-1]
                img2 = img2[:, ::-1]
                flow = flow[:, ::-1]
                flow_scales.append(np.array([-1.0, 1.0], np.float32))
            if self.rng.rand() < self.v_flip_prob:
                img1 = img1[::-1, :]
                img2 = img2[::-1, :]
                flow = flow[::-1, :]
                flow_scales.append(np.array([1.0, -1.0], np.float32))

        y0 = self.rng.randint(0, img1.shape[0] - self.crop_size[0])
        x0 = self.rng.randint(0, img1.shape[1] - self.crop_size[1])

        img1 = img1[y0:y0 + self.crop_size[0], x0:x0 + self.crop_size[1]]
        img2 = img2[y0:y0 + self.crop_size[0], x0:x0 + self.crop_size[1]]
        flow = flow[y0:y0 + self.crop_size[0], x0:x0 + self.crop_size[1]]
        for s in flow_scales:
            flow = flow * s
        return img1, img2, flow

    def __call__(self, img1, img2, flow):
        img1, img2 = self.color_transform(img1, img2)
        img1, img2 = self.eraser_transform(img1, img2)
        img1, img2, flow = self.spatial_transform(img1, img2, flow)
        return (np.ascontiguousarray(img1), np.ascontiguousarray(img2),
                np.ascontiguousarray(flow))


class SparseFlowAugmentor:
    """Sparse-GT augmentation for KITTI/HD1K (augmentor.py:122-246)."""

    def __init__(self, crop_size: Tuple[int, int], min_scale: float = -0.2,
                 max_scale: float = 0.5, do_flip: bool = False,
                 rng: Optional[np.random.RandomState] = None):
        self.crop_size = crop_size
        self.min_scale = min_scale
        self.max_scale = max_scale
        self.spatial_aug_prob = 0.8

        self.do_flip = do_flip

        self.photo_aug = ColorJitter(0.3, 0.3, 0.3, 0.3 / 3.14)
        self.eraser_aug_prob = 0.5

        self.rng = rng if rng is not None else np.random.RandomState()

    def reseed(self, seed: int):
        self.rng = np.random.RandomState(seed)

    def color_transform(self, img1, img2):
        # sparse path is always symmetric (augmentor.py:142-146)
        stack = np.concatenate([img1, img2], axis=0)
        stack = self.photo_aug(stack, self.rng)
        img1, img2 = np.split(stack, 2, axis=0)
        return img1, img2

    def eraser_transform(self, img1, img2):
        ht, wd = img1.shape[:2]
        if self.rng.rand() < self.eraser_aug_prob:
            # integer-exact channel means (cv2 sums the uint8s exactly, as
            # np.mean does — just without materializing a float frame)
            mean_color = np.asarray(cv2.mean(img2)[:3])
            for _ in range(self.rng.randint(1, 3)):
                x0 = self.rng.randint(0, wd)
                y0 = self.rng.randint(0, ht)
                dx = self.rng.randint(50, 100)
                dy = self.rng.randint(50, 100)
                img2[y0:y0 + dy, x0:x0 + dx, :] = mean_color
        return img1, img2

    def resize_sparse_flow_map(self, flow, valid, fx=1.0, fy=1.0):
        """Nearest-point scatter rescale of sparse flow (augmentor.py:161-193)."""
        ht, wd = flow.shape[:2]
        coords = np.meshgrid(np.arange(wd), np.arange(ht))
        coords = np.stack(coords, axis=-1).reshape(-1, 2).astype(np.float32)

        flow = flow.reshape(-1, 2).astype(np.float32)
        valid = valid.reshape(-1).astype(np.float32)

        coords0 = coords[valid >= 1]
        flow0 = flow[valid >= 1]

        ht1 = int(round(ht * fy))
        wd1 = int(round(wd * fx))

        coords1 = coords0 * [fx, fy]
        flow1 = flow0 * [fx, fy]

        xx = np.round(coords1[:, 0]).astype(np.int32)
        yy = np.round(coords1[:, 1]).astype(np.int32)

        v = (xx > 0) & (xx < wd1) & (yy > 0) & (yy < ht1)
        xx, yy, flow1 = xx[v], yy[v], flow1[v]

        flow_img = np.zeros([ht1, wd1, 2], dtype=np.float32)
        valid_img = np.zeros([ht1, wd1], dtype=np.int32)
        flow_img[yy, xx] = flow1
        valid_img[yy, xx] = 1
        return flow_img, valid_img

    def spatial_transform(self, img1, img2, flow, valid):
        ht, wd = img1.shape[:2]
        min_scale = np.maximum(
            (self.crop_size[0] + 1) / float(ht),
            (self.crop_size[1] + 1) / float(wd))

        scale = 2 ** self.rng.uniform(self.min_scale, self.max_scale)
        scale_x = np.clip(scale, min_scale, None)
        scale_y = np.clip(scale, min_scale, None)

        if self.rng.rand() < self.spatial_aug_prob:
            img1 = cv2.resize(img1, None, fx=scale_x, fy=scale_y,
                              interpolation=cv2.INTER_LINEAR)
            img2 = cv2.resize(img2, None, fx=scale_x, fy=scale_y,
                              interpolation=cv2.INTER_LINEAR)
            flow, valid = self.resize_sparse_flow_map(flow, valid,
                                                      fx=scale_x, fy=scale_y)

        if self.do_flip:
            if self.rng.rand() < 0.5:  # h-flip only (augmentor.py:213-218)
                img1 = img1[:, ::-1]
                img2 = img2[:, ::-1]
                flow = flow[:, ::-1] * np.array([-1.0, 1.0], np.float32)
                valid = valid[:, ::-1]

        margin_y, margin_x = 20, 50
        y0 = self.rng.randint(0, img1.shape[0] - self.crop_size[0] + margin_y)
        x0 = self.rng.randint(-margin_x,
                              img1.shape[1] - self.crop_size[1] + margin_x)
        y0 = np.clip(y0, 0, img1.shape[0] - self.crop_size[0])
        x0 = np.clip(x0, 0, img1.shape[1] - self.crop_size[1])

        img1 = img1[y0:y0 + self.crop_size[0], x0:x0 + self.crop_size[1]]
        img2 = img2[y0:y0 + self.crop_size[0], x0:x0 + self.crop_size[1]]
        flow = flow[y0:y0 + self.crop_size[0], x0:x0 + self.crop_size[1]]
        valid = valid[y0:y0 + self.crop_size[0], x0:x0 + self.crop_size[1]]
        return img1, img2, flow, valid

    def __call__(self, img1, img2, flow, valid):
        img1, img2 = self.color_transform(img1, img2)
        img1, img2 = self.eraser_transform(img1, img2)
        img1, img2, flow, valid = self.spatial_transform(img1, img2, flow,
                                                         valid)
        return (np.ascontiguousarray(img1), np.ascontiguousarray(img2),
                np.ascontiguousarray(flow), np.ascontiguousarray(valid))
