"""Typed configuration for raft_tpu.

The reference threads a *mutable* argparse Namespace through every layer and
lets the model write ``corr_levels``/``corr_radius`` back into it
(``/root/reference/core/raft.py:29-45``, ``core/update.py:65,82``).  Here the
config is a frozen dataclass: model presets own their constants, stage presets
mirror the shell-script curricula (``train_standard.sh``/``train_mixed.sh``),
and nothing is mutated downstream.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class RAFTConfig:
    """Architecture hyper-parameters.

    Constants mirror the reference parity surface (SURVEY.md §2):
    basic: hdim=cdim=128, corr_levels=4, corr_radius=4, fnet 256ch instance
    norm, cnet 256ch batch norm (``core/raft.py:36-39,54-55``);
    small: hdim=96, cdim=64, radius=3, fnet 128 instance, cnet 160 no-norm
    (``core/raft.py:30-33,49-50``).
    """

    small: bool = False
    dropout: float = 0.0
    alternate_corr: bool = False
    mixed_precision: bool = False
    corr_levels: int = 4
    # lookup backend for the materialized pyramid: 'gather' (flattened-index
    # take), 'onehot' (one-hot selection GEMMs), 'onehot_t' (one-hot
    # selection over the TRANSPOSED pixels-on-lanes volume — see
    # models/corr.build_corr_pyramid_t), 'softsel' (bilinear lerp folded
    # into the selection GEMMs), or 'pallas' (vectorized mask-select
    # kernel; interpret-mode fallback off-TPU). Accuracy at trained
    # weights is uniform across all five — basic max <=1.24e-5 px vs the
    # live torch reference, TRAINED_PARITY_backends.json (r5) — so
    # backend choice is decided on speed alone. On-chip (v5e-1) status
    # after the r5 ladder (ONCHIP_r05.log, 2026-08-01): softsel is the
    # measured whole-step WINNER — 26.98 pairs/s alone, 27.99 composed
    # with the fused loss, vs onehot's 24.99 at the same b8 chairs
    # geometry — despite losing the isolated-lookup row (6.7 ms vs
    # onehot 4.9, s_bf16): its lerp-as-GEMM form trades lookup time for
    # a fusion/layout win across the whole step. Its trained-weights
    # accuracy ON CHIP is pinned at basic max 1.2e-4 px / small 4.0e-4
    # (TRAINED_PARITY_softsel_onchip.json). onehot is the isolated-
    # lookup fastest and stays the library default (conservative;
    # r3-pinned 10.8 ms fwd / 14.0 fwd+grad at chairs geometry) — the
    # bench/trainer reach softsel via BENCH_DEFAULTS.json. gather: 294 ms
    # fwd r3, scatter backward disqualifying. onehot_t: whole-step wash
    # vs onehot (24.32 vs 24.23, ONCHIP_r03e.log — kept for its
    # pixels-on-lanes layout, which spatial sharding prefers).
    # softsel_t (softsel's lerp fold on that transposed layout): isolated
    # lookup identical to softsel (6.76 vs 6.77 ms fwd+grad bf16),
    # whole-step single-chip NEGATIVE at chairs (31.39 vs 32.26,
    # 2026-08-01) — kept, like onehot_t, for the spatial-sharding regime
    # where the N-minor layout is the one that shards cleanly. pallas:
    # lost its last hypothesized regime on 2026-08-01 — serving geometry
    # 55x128 b1: 8.57 ms vs onehot 5.41 (pallas_regime row) on top of
    # r3's 15.1/27.5 vs 10.8/14.0 — DEMOTED to documented insurance for
    # memory-constrained shapes; not reachable from any default.
    # Re-benchmark with `python -m raft_tpu.cli.corr_bench` (+ --grad).
    corr_impl: str = "onehot"
    # storage dtype of the materialized correlation pyramid. The reference
    # computes correlation in an fp32 island (core/raft.py:102-103) and so
    # do we — the all-pairs GEMM always runs fp32 — but the *stored* volume
    # is this dtype. The step is memory-bound and the volume is its largest
    # tensor, read by all `iters` lookups fwd+bwd, so 'bfloat16' halves
    # that traffic. Forward: window *selection* is exact in bf16 (one
    # nonzero term per output) and the bilinear lerp runs fp32, so the
    # only forward loss is the volume's storage rounding (~0.4% rel/entry,
    # drift profile pinned in TestCorrDtypeBf16; bf16 volumes also run the
    # selection GEMMs at native bf16 MXU rate). Backward: the pyramid's
    # cotangent is necessarily bf16 too and is summed across the scanned
    # iterations at bf16 — an extra rounding the fmap gradients inherit
    # (pinned in the same test class). Caveat measured at model level
    # (test_corr_dtype_bf16_model_drift): the refinement recurrence
    # amplifies ANY volume-scale perturbation when the weights don't
    # contract it — at random init, bf16 rounding and an equivalent fp32
    # noise control both compound identically — so confirm end-to-end
    # parity at trained weights (EPE on a converted checkpoint) before
    # relying on it for leaderboard numbers; for training, treat as
    # experimental until a loss-curve comparison exists.
    # Default fp32 = bit-level reference parity. Applies only to the
    # materialized pyramid — rejected with alternate_corr, which stores
    # fmap pyramids, not a volume (see __post_init__).
    corr_dtype: str = "float32"
    # rematerialize the refinement-iteration body in the backward pass:
    # trades ~30% recompute for dropping the per-iteration activation stack
    # (observed ~1.5 GB/buffer at chairs shapes), the jax.checkpoint lever
    # HBM-bound training wants (SURVEY.md §7 "HBM bandwidth")
    remat: bool = False
    # remat granularity when remat=True: 'full' recomputes the whole
    # iteration body; 'dots' (jax.checkpoint_policies.checkpoint_dots)
    # saves matmul/conv outputs and recomputes only elementwise — most of
    # the memory win at a fraction of the recompute, since the body is
    # conv/GEMM-dominated
    remat_policy: str = "full"
    # update-block implementation for the refinement scan body: 'xla'
    # keeps the reference-shaped NHWC convs (the parity surface); 'fused'
    # runs the basic model's motion encoder + SepConvGRU in the
    # lane-major (B, H·W, C) layout — each 1x5/5x1/3x3 conv becomes a
    # per-tap shifted GEMM accumulation whose operands put the whole
    # 46x62 spatial plane on sublanes and the 128 channels on lanes
    # (tile-dense MXU work instead of a fragmented small conv; tiny-cin
    # taps like the 7x7-on-flow stay broadcast FMAs per PROFILE lesson
    # 5) — with the sigmoid/tanh gate math and the (1-z)*h + z*q blend
    # fused into Pallas epilogues (kernels/gru_pallas, interpret-mode
    # fallback off-TPU) so gate intermediates stop round-tripping HBM
    # 12x per step. Parameter tree and fp32 math are identical to 'xla'
    # (oracle-pinned in tests/test_gru_fused.py); checkpoints are
    # interchangeable. Default stays 'xla' until the whole-step A/B
    # rungs (tools/onchip_round6.sh g_gru* -> BENCH_DEFAULTS.json) show
    # a measured win — isolated kernel benches steered the repo wrong
    # for two rounds (PROFILE round 5, softsel) and do not promote.
    gru_impl: str = "xla"
    # lax.scan unroll factor for the refinement loop: >1 replicates the
    # iteration body so XLA can software-pipeline across iteration
    # boundaries (overlap iteration i's GRU convs with i+1's lookup
    # GEMMs) at the cost of unroll x compile time and code size. Math is
    # identical for any value (pinned in tests/test_model.py). Measured
    # on chip 2026-08-01 (ONCHIP_r05.log), direction depends on the
    # pass structure: TRAINING NEGATIVE — unroll2 21.7 pairs/s vs 24.99
    # at unroll1 (b8 chairs), composed fused+softsel+unroll4 26.98 vs
    # 27.99 — the replicated body plus its saved residuals blow the
    # VMEM/code budget instead of pipelining. Serving looked positive
    # pre-rework (54.8 ms at unroll2 vs 59.1), but after the upsampler
    # shift-mulacc rework it is a wash (54.8 vs 55.0) — the unroll had
    # been hiding upsampler latency that no longer exists. Keep 1.
    scan_unroll: int = 1
    # encode the two frames in TWO fnet calls instead of one batch-concat
    # call. The reference's concat trick (core/raft.py:96) is free on one
    # device but REDISTRIBUTES under a batch-sharded mesh: concatenating
    # two (B, H, W, 3) arrays sharded over 'data' into (2B, ...) moves
    # every row to a new shard — XLA materializes the full concat on
    # every device (a dynamic-update-slice + all-reduce of the images)
    # and collective-permutes the fmap halves back, per step/dispatch
    # (graftshard S2 caught this on the first mesh scan). fnet is
    # instance-norm (per-sample statistics, always — see fnet_norm), so
    # two calls are mathematically identical; only XLA CPU conv
    # vectorization bits move with the total conv batch (the established
    # batch-width caveat). Default False = bit-exact single-device
    # behavior; `parallel.partitioner.mesh_model_config` turns it on
    # whenever the 'data' axis is >1.
    split_encode: bool = False

    def __post_init__(self):
        if not (isinstance(self.scan_unroll, int)
                and not isinstance(self.scan_unroll, bool)
                and self.scan_unroll >= 1):
            raise ValueError(
                f"scan_unroll={self.scan_unroll!r}: must be an int >= 1")
        if self.corr_impl not in ("gather", "onehot", "onehot_t", "softsel",
                                  "softsel_t", "pallas"):
            raise ValueError(
                f"corr_impl={self.corr_impl!r}: choose gather, onehot, "
                "onehot_t, softsel, softsel_t, or pallas (the "
                "memory-efficient alternate path is selected by "
                "alternate_corr=True, with corr_impl picking its "
                "XLA/pallas backend)")
        if self.gru_impl not in ("xla", "fused"):
            raise ValueError(
                f"gru_impl={self.gru_impl!r}: choose 'xla' (reference "
                "NHWC update block) or 'fused' (lane-major scan-body "
                "path with Pallas gate/blend epilogues)")
        if self.gru_impl == "fused" and self.small:
            raise ValueError(
                "gru_impl='fused' covers the basic model's "
                "BasicMotionEncoder + SepConvGRU; the small model's "
                "3x3 ConvGRU has no fused path — drop one of the two "
                "settings")
        if self.remat_policy not in ("full", "dots"):
            raise ValueError(
                f"remat_policy={self.remat_policy!r}: choose 'full' or "
                "'dots'")
        if self.corr_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"corr_dtype={self.corr_dtype!r}: choose 'float32' "
                "(bit-level reference parity) or 'bfloat16' (halved "
                "volume traffic; see the corr_dtype comment)")
        if self.alternate_corr and self.corr_dtype != "float32":
            raise ValueError(
                "corr_dtype applies to the materialized correlation "
                "pyramid only; alternate_corr never builds one, so "
                f"corr_dtype={self.corr_dtype!r} would silently do "
                "nothing — remove one of the two settings.")

    @property
    def hidden_dim(self) -> int:
        return 96 if self.small else 128

    @property
    def context_dim(self) -> int:
        return 64 if self.small else 128

    @property
    def corr_radius(self) -> int:
        return 3 if self.small else 4

    @property
    def fnet_dim(self) -> int:
        return 128 if self.small else 256

    @property
    def cnet_dim(self) -> int:
        return self.hidden_dim + self.context_dim

    @property
    def fnet_norm(self) -> str:
        return "instance"

    @property
    def cnet_norm(self) -> str:
        return "none" if self.small else "batch"

    @property
    def corr_planes(self) -> int:
        return self.corr_levels * (2 * self.corr_radius + 1) ** 2

    @property
    def compute_dtype(self):
        import jax.numpy as jnp

        return jnp.bfloat16 if self.mixed_precision else jnp.float32


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """One curriculum stage. Defaults follow ``train.py:217-239``."""

    name: str = "raft"
    stage: str = "chairs"
    restore_ckpt: Optional[str] = None
    lr: float = 4e-4
    num_steps: int = 100000
    batch_size: int = 10
    image_size: Tuple[int, int] = (368, 496)
    wdecay: float = 1e-4
    epsilon: float = 1e-8
    clip: float = 1.0
    gamma: float = 0.8
    iters: int = 12
    add_noise: bool = False
    seed: int = 1234
    val_freq: int = 5000
    sum_freq: int = 100
    validation: Tuple[str, ...] = ()
    # TPU-specific
    num_workers: int = 4
    checkpoint_dir: str = "checkpoints"
    data_root: str = "datasets"
    log_dir: str = "runs"
    # (start, stop): capture a jax.profiler trace over these step indices
    # into log_dir — replaces the reference's manual cuda.synchronize
    # timing (SURVEY.md §5 tracing/profiling)
    profile_steps: Optional[Tuple[int, int]] = None
    # compute the sequence loss in the convex upsampler's subpixel domain
    # (basic model): identical values, but the (T,B,8H,8W,2) prediction
    # stack and its cotangent never materialize — see
    # training/loss.sequence_loss_subpixel. Tri-state: None (default) =
    # AUTO — fused wherever it exists (basic), standard loss for the
    # small model (which has no fused path), silently. True = explicit
    # request (warns if the model can't honor it); False = force the
    # reference-exact full-resolution loss (pinned by
    # tools/train_dynamics_parity.py for bit-level torch matching).
    # Auto is ON by measurement (2026-08-01, v5e-1, chairs-b8 softsel
    # bf16): fused 31-32 pairs/s vs unfused 20.2 after the shift-mulacc
    # upsampler rework (27.0 before it — the rework sped the
    # fused/serving paths and cost the unfused stack path).
    fused_loss: Optional[bool] = None
    # no-progress watchdog (utils/watchdog.HangWatch): hard-exit code 3
    # if the training loop makes no progress for this many seconds — the
    # remote tunnel's half-up mode blocks compile/execute forever with
    # nothing to catch, and a wedged run otherwise sleeps out its whole
    # runbook timeout (measured: 25 min of a live window lost, OUTAGE_r05
    # 15:51). 0 disables (default). Set it ABOVE the longest legitimate
    # gap: beats happen at each sum_freq metric flush (a real D2H fetch
    # — async dispatch alone proves nothing), after validation, and at
    # cleanup entry, so first-step compile plus a full sum_freq window,
    # a full validation pass, and the final async-checkpoint flush each
    # count as one gap.
    hang_s: float = 0.0
    # loader resilience (data/loader.PrefetchLoader): "skip" resamples
    # a rotten file with a counted warning instead of killing the run
    # (a supervised restart would replay the same index into the same
    # decode error — a deterministic crash the supervisor rightly gives
    # up on); "raise" keeps the strict legacy behavior.
    on_bad_sample: str = "raise"
    # deadline in seconds for the consumer's wait on each batch: a hung
    # decode surfaces as data/loader.LoaderStallError instead of an
    # eternal hang (0 disables). Unlike hang_s this is recoverable
    # in-process — size it above the slowest legitimate batch.
    stall_s: float = 0.0


# Stage presets mirroring train_standard.sh:3-6 (2-GPU fp32 recipe).
STANDARD_STAGES = {
    "chairs": dict(stage="chairs", lr=4e-4, num_steps=100000, batch_size=10,
                   image_size=(368, 496), wdecay=1e-4, gamma=0.8,
                   validation=("chairs",)),
    "things": dict(stage="things", lr=1.25e-4, num_steps=100000, batch_size=6,
                   image_size=(400, 720), wdecay=1e-4, gamma=0.8,
                   validation=("sintel",)),
    "sintel": dict(stage="sintel", lr=1.25e-4, num_steps=100000, batch_size=6,
                   image_size=(368, 768), wdecay=1e-5, gamma=0.85,
                   validation=("sintel",)),
    "kitti": dict(stage="kitti", lr=1e-4, num_steps=50000, batch_size=6,
                  image_size=(288, 960), wdecay=1e-5, gamma=0.85,
                  validation=("kitti",)),
}

# Stage presets mirroring train_mixed.sh:3-6 (1-GPU mixed-precision recipe).
MIXED_STAGES = {
    "chairs": dict(stage="chairs", lr=2.5e-4, num_steps=120000, batch_size=8,
                   image_size=(368, 496), wdecay=1e-4, gamma=0.8,
                   validation=("chairs",)),
    "things": dict(stage="things", lr=1e-4, num_steps=120000, batch_size=5,
                   image_size=(400, 720), wdecay=1e-4, gamma=0.8,
                   validation=("sintel",)),
    "sintel": dict(stage="sintel", lr=1e-4, num_steps=120000, batch_size=5,
                   image_size=(368, 768), wdecay=1e-5, gamma=0.85,
                   validation=("sintel",)),
    "kitti": dict(stage="kitti", lr=1e-4, num_steps=50000, batch_size=5,
                  image_size=(288, 960), wdecay=1e-5, gamma=0.85,
                  validation=("kitti",)),
}

# Iteration counts per use-site (BASELINE.md): train 12, demo 20,
# eval sintel 32 / kitti 24 / chairs 24, export bakes 20.
ITERS_TRAIN = 12
ITERS_DEMO = 20
ITERS_EVAL = {"sintel": 32, "kitti": 24, "chairs": 24}
ITERS_EXPORT = 20

MAX_FLOW = 400.0  # train.py:42 — exclude extreme displacements from the loss


def stage_config(stage: str, mixed: bool = False, **overrides) -> TrainConfig:
    presets = MIXED_STAGES if mixed else STANDARD_STAGES
    kw = dict(presets[stage])
    kw.update(overrides)
    return TrainConfig(**kw)
