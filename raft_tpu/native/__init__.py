"""ctypes binding for the native data-plane library (``flowio.cpp``).

Build happens lazily with plain ``g++`` (no pip, no pybind11 — neither is
available in the image); failures degrade to the numpy implementations in
``raft_tpu.data.frame_utils``, so the package works anywhere and gets the
GIL-free fast path where a toolchain exists.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "flowio.cpp")
_SO = os.path.join(_HERE, "_flowio.so")

_lib = None
_lock = threading.Lock()
_build_failed = False


def _build() -> Optional[str]:
    if os.path.exists(_SO) and (os.path.getmtime(_SO)
                                >= os.path.getmtime(_SRC)):
        return _SO
    # Compile to a per-process temp path, then os.rename (atomic on POSIX):
    # concurrent processes never observe a half-written .so, and a rebuild
    # replaces the inode without touching a library another process mapped.
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.rename(tmp, _SO)
        return _SO
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded library, or None when unavailable (numpy fallback)."""
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    with _lock:
        if _lib is not None:
            return _lib
        so = _build()
        if so is None:
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            _build_failed = True
            return None
        i32 = ctypes.c_int32
        p_i32 = ctypes.POINTER(i32)
        p_f32 = ctypes.POINTER(ctypes.c_float)
        lib.flo_header.argtypes = [ctypes.c_char_p, p_i32, p_i32]
        lib.flo_read.argtypes = [ctypes.c_char_p, p_f32, i32, i32]
        lib.flo_write.argtypes = [ctypes.c_char_p, p_f32, i32, i32]
        lib.pfm_header.argtypes = [ctypes.c_char_p, p_i32, p_i32, p_i32,
                                   p_i32, ctypes.POINTER(ctypes.c_int64)]
        lib.pfm_read.argtypes = [ctypes.c_char_p, p_f32, i32, i32, i32, i32,
                                 ctypes.c_int64]
        for fn in (lib.flo_header, lib.flo_read, lib.flo_write,
                   lib.pfm_header, lib.pfm_read):
            fn.restype = i32
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def read_flo(path: str) -> Optional[np.ndarray]:
    """Native .flo read; None on any failure (caller falls back)."""
    lib = get_lib()
    if lib is None:
        return None
    w = ctypes.c_int32()
    h = ctypes.c_int32()
    if lib.flo_header(path.encode(), ctypes.byref(w), ctypes.byref(h)) != 0:
        return None
    out = np.empty((h.value, w.value, 2), np.float32)
    if lib.flo_read(path.encode(), _f32p(out), w, h) != 0:
        return None
    return out


def write_flo(path: str, uv: np.ndarray) -> bool:
    lib = get_lib()
    if lib is None:
        return False
    uv = np.ascontiguousarray(uv, np.float32)
    h, w = uv.shape[:2]
    return lib.flo_write(path.encode(), _f32p(uv), w, h) == 0


def read_pfm(path: str) -> Optional[np.ndarray]:
    lib = get_lib()
    if lib is None:
        return None
    w = ctypes.c_int32()
    h = ctypes.c_int32()
    ch = ctypes.c_int32()
    le = ctypes.c_int32()
    off = ctypes.c_int64()
    if lib.pfm_header(path.encode(), ctypes.byref(w), ctypes.byref(h),
                      ctypes.byref(ch), ctypes.byref(le),
                      ctypes.byref(off)) != 0:
        return None
    shape = ((h.value, w.value, 3) if ch.value == 3
             else (h.value, w.value))
    out = np.empty(shape, np.float32)
    if lib.pfm_read(path.encode(), _f32p(out), w, h, ch, le, off) != 0:
        return None
    return out


# NOTE: a fused native collate (crop+cast+stack, "assemble_batch") lived
# here through round 1 but was never on the loader's path — the stock
# augmentors crop per-sample BEFORE collate (a random resize precedes the
# crop, so cropping cannot move to collate time). Measurement settled it:
# augmentation is 98% of per-sample pipeline cost, collate ~8%
# (cli/loader_bench.py on the 1-core deployment host), so the fused path
# was deleted rather than wired in.
