// Native data-plane helpers for the raft_tpu loader.
//
// Role: the reference's data path leans on torch DataLoader worker
// *processes* (core/datasets.py:230-231) to hide decode/augment cost; our
// loader uses threads (raft_tpu/data/loader.py), so the byte-moving inner
// loops live here, outside the GIL: Middlebury .flo codec
// (frame_utils.py:10-31,70-99 semantics) and PFM decode
// (frame_utils.py:33-68). A fused native collate was measured and removed:
// augmentation dominates the pipeline at 98% of per-sample cost vs 8% for
// collate (see cli/loader_bench.py), so there is nothing for it to win.
//
// Built with plain g++ into _flowio.so; bound via ctypes (no pybind11 in
// the image). Every entry point returns 0 on success / negative errno-style
// codes so the Python wrapper can fall back to the numpy implementations.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr float kFloTag = 202021.25f;

constexpr int kOk = 0;
constexpr int kErrOpen = -1;
constexpr int kErrFormat = -2;
constexpr int kErrShort = -3;

struct FileCloser {
  FILE* f;
  ~FileCloser() {
    if (f) fclose(f);
  }
};

}  // namespace

extern "C" {

// Reads the (w, h) header of a .flo file. Returns kOk and fills dims.
int flo_header(const char* path, int32_t* w, int32_t* h) {
  FILE* f = fopen(path, "rb");
  FileCloser closer{f};
  if (!f) return kErrOpen;
  float tag;
  if (fread(&tag, 4, 1, f) != 1 || tag != kFloTag) return kErrFormat;
  if (fread(w, 4, 1, f) != 1 || fread(h, 4, 1, f) != 1) return kErrShort;
  if (*w <= 0 || *h <= 0 || *w > 1 << 16 || *h > 1 << 16) return kErrFormat;
  return kOk;
}

// Reads .flo payload into out (h*w*2 floats, caller-allocated).
int flo_read(const char* path, float* out, int32_t w, int32_t h) {
  FILE* f = fopen(path, "rb");
  FileCloser closer{f};
  if (!f) return kErrOpen;
  if (fseek(f, 12, SEEK_SET) != 0) return kErrShort;
  size_t n = static_cast<size_t>(w) * h * 2;
  if (fread(out, 4, n, f) != n) return kErrShort;
  return kOk;
}

int flo_write(const char* path, const float* uv, int32_t w, int32_t h) {
  FILE* f = fopen(path, "wb");
  FileCloser closer{f};
  if (!f) return kErrOpen;
  if (fwrite(&kFloTag, 4, 1, f) != 1) return kErrShort;
  if (fwrite(&w, 4, 1, f) != 1 || fwrite(&h, 4, 1, f) != 1) return kErrShort;
  size_t n = static_cast<size_t>(w) * h * 2;
  if (fwrite(uv, 4, n, f) != n) return kErrShort;
  return kOk;
}

// Parses a PFM header; returns byte offset of the payload, fills dims,
// channels (1 or 3) and little_endian flag.
int pfm_header(const char* path, int32_t* w, int32_t* h, int32_t* channels,
               int32_t* little_endian, int64_t* payload_offset) {
  FILE* f = fopen(path, "rb");
  FileCloser closer{f};
  if (!f) return kErrOpen;
  char magic[3] = {0};
  if (fscanf(f, "%2s", magic) != 1) return kErrFormat;
  if (strcmp(magic, "PF") == 0) {
    *channels = 3;
  } else if (strcmp(magic, "Pf") == 0) {
    *channels = 1;
  } else {
    return kErrFormat;
  }
  float scale;
  if (fscanf(f, "%d %d %f", w, h, &scale) != 3) return kErrFormat;
  // the header ends at the first newline after the scale; tolerate CRLF
  // (a lone fgetc would leave the '\n' in the stream and shift the
  // payload by one byte — silently corrupt floats)
  int ch;
  do {
    ch = fgetc(f);
    if (ch == EOF) return kErrShort;
  } while (ch != '\n');
  if (*w <= 0 || *h <= 0) return kErrFormat;
  *little_endian = scale < 0 ? 1 : 0;
  *payload_offset = ftell(f);
  return kOk;
}

// Reads PFM payload, swaps endianness if needed, flips rows (PFM stores
// bottom-up) into out (h*w*channels floats).
int pfm_read(const char* path, float* out, int32_t w, int32_t h,
             int32_t channels, int32_t little_endian,
             int64_t payload_offset) {
  FILE* f = fopen(path, "rb");
  FileCloser closer{f};
  if (!f) return kErrOpen;
  if (fseek(f, static_cast<long>(payload_offset), SEEK_SET) != 0)
    return kErrShort;
  size_t row = static_cast<size_t>(w) * channels;
  std::vector<float> buf(row);
  for (int32_t y = h - 1; y >= 0; --y) {  // flip vertically while reading
    if (fread(buf.data(), 4, row, f) != row) return kErrShort;
    if (!little_endian) {
      for (size_t i = 0; i < row; ++i) {
        uint32_t v;
        memcpy(&v, &buf[i], 4);
        v = __builtin_bswap32(v);
        memcpy(&buf[i], &v, 4);
      }
    }
    memcpy(out + static_cast<size_t>(y) * row, buf.data(), row * 4);
  }
  return kOk;
}

}  // extern "C"
