"""Per-stream video sessions: warm-started recurrence over the scheduler.

RAFT's refinement is a recurrence, and consecutive frames of one stream
are nearly the same problem — the reference's Sintel submission writer
carries the previous pair's low-res flow into the next pair's start
(``warm_start``, evaluation/evaluate.py) and converges in fewer
effective iterations. This lifts that into serving (the serving analog
of compiler-first O(1) autoregressive state reuse for SSM inference,
arXiv 2603.09555): a :class:`VideoSession` is a thin per-stream state
holder — frames go in one at a time, each consecutive pair becomes one
scheduler request, and the returned ``flow_low`` is
forward-interpolated (ops/interp, the reference's host-side scipy path)
into the next request's ``flow_init``.

The per-stream recurrence is sequential by nature (pair N+1's warm
start needs pair N's flow), but it never serializes the DEVICE: each
request still coalesces with other streams' and one-shot callers' work
in the scheduler queue, and a zero ``flow_init`` is bit-for-bit a cold
start, so warm and cold rows share one bucket executable.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class VideoSession:
    """One video stream's warm-start state.

    NOT thread-safe — a stream has one frame order; run each session
    from its own submitter (cross-stream parallelism lives in the
    scheduler's queue). ``warm_start=False`` degrades to per-pair cold
    starts (still coalesced) without touching caller code.
    """

    def __init__(self, scheduler, *, warm_start: bool = True,
                 device_state: bool = False,
                 deadline_s: Optional[float] = None):
        """``device_state=True`` keeps the recurrence state
        (``flow_low``) ON DEVICE between pairs: the scheduler returns a
        device array, the forward warp runs as a jitted scatter
        (ops/interp.forward_interpolate_device — holes stay zero, i.e.
        locally cold, instead of scipy's global nearest fill), and the
        next submit passes the device array straight back — the
        per-frame D2H→H2D round trip disappears from the hot path.
        Shape-change and cold-restart paths still materialize to host
        (they reset the state to None and restart the recurrence);
        ``drain()`` always returns a host array. Default OFF: the host
        scipy path is bitwise what it always was."""
        self._sched = scheduler
        self.warm_start = bool(warm_start)
        self.device_state = bool(device_state)
        self.deadline_s = deadline_s
        self.frames = 0
        self.warm_submits = 0
        self._prev_frame: Optional[np.ndarray] = None
        self._pending = None                    # previous pair's Future
        self._flow_low: Optional[np.ndarray] = None

    def _harvest(self) -> None:
        """Settle the previous pair — the recurrence is sequential per
        stream: pair N+1 warm-starts from pair N's flow_low. A failed
        or deadline-missed pair cold-restarts the recurrence (the
        failure already surfaced on that pair's own future)."""
        if self._pending is None:
            return
        try:
            self._flow_low = self._pending.result().flow_low
        except Exception:
            self._flow_low = None
        self._pending = None

    def submit_frame(self, frame, *,
                     deadline_s: Optional[float] = None):
        """Feed the next frame; returns the Future for the
        (previous, current) pair — None for the first frame of a
        stream (or after a mid-stream resolution change, which
        restarts the recurrence: ``flow_low`` lives in the old frame
        geometry)."""
        frame = np.asarray(frame, np.float32)
        self.frames += 1
        prev, self._prev_frame = self._prev_frame, frame
        if prev is None:
            return None
        if prev.shape != frame.shape:
            self._pending, self._flow_low = None, None
            return None
        flow_init = None
        if self.warm_start:
            self._harvest()
            if self._flow_low is not None and self.device_state \
                    and not isinstance(self._flow_low, np.ndarray):
                from raft_tpu.ops.interp import \
                    forward_interpolate_device

                # device-resident recurrence: warp on device, feed the
                # handle straight back — no bytes cross the PCIe/host
                # boundary between pairs. A non-finite flow scatters
                # nothing (every point fails the validity window), so
                # a garbage pair degrades to a cold start here the way
                # the host path's isfinite guard does — without a sync.
                flow_init = forward_interpolate_device(self._flow_low)
                self.warm_submits += 1
            elif self._flow_low is not None:
                from raft_tpu.ops.interp import forward_interpolate

                flow_init = forward_interpolate(
                    np.asarray(self._flow_low))
                if np.isfinite(flow_init).all():
                    self.warm_submits += 1
                else:
                    # every forward-warped point left the frame (a
                    # garbage pair, or motion larger than the frame):
                    # griddata had nothing to interpolate from and
                    # returns NaN ('nearest' ignores fill_value) —
                    # cold-start instead of poisoning the stream
                    flow_init = None
        fut = self._sched.submit(
            prev, frame,
            deadline_s=self.deadline_s if deadline_s is None
            else deadline_s,
            flow_init=flow_init, want_low=self.warm_start,
            low_device=self.device_state)
        self._pending = fut
        return fut

    def drain(self) -> Optional[np.ndarray]:
        """Wait out the last pair; returns the stream's final
        ``flow_low`` (None if the stream is cold) — always materialized
        to host, whatever ``device_state`` says."""
        self._harvest()
        if self._flow_low is not None \
                and not isinstance(self._flow_low, np.ndarray):
            self._flow_low = np.asarray(self._flow_low)
        return self._flow_low
