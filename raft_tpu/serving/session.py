"""Per-stream video sessions: warm-started recurrence over the scheduler.

RAFT's refinement is a recurrence, and consecutive frames of one stream
are nearly the same problem — the reference's Sintel submission writer
carries the previous pair's low-res flow into the next pair's start
(``warm_start``, evaluation/evaluate.py) and converges in fewer
effective iterations. This lifts that into serving (the serving analog
of compiler-first O(1) autoregressive state reuse for SSM inference,
arXiv 2603.09555): a :class:`VideoSession` is a thin per-stream state
holder — frames go in one at a time, each consecutive pair becomes one
scheduler request, and the returned ``flow_low`` is
forward-interpolated (ops/interp, the reference's host-side scipy path)
into the next request's ``flow_init``.

The per-stream recurrence is sequential by nature (pair N+1's warm
start needs pair N's flow), but it never serializes the DEVICE: each
request still coalesces with other streams' and one-shot callers' work
in the scheduler queue, and a zero ``flow_init`` is bit-for-bit a cold
start, so warm and cold rows share one bucket executable.
"""

from __future__ import annotations

import itertools
import random
import time
from typing import Callable, Optional

import numpy as np

from raft_tpu.utils.retry import backoff_delays

# graftthread: no declarations — this module owns NO locks by design
# (a session is single-submitter by contract; cross-stream concurrency
# lives in the scheduler's queue), so there is nothing to order, fire,
# or verdict here. Keep it that way: adding a lock to session state
# means the contract broke.

#: sticky route tokens for sessions over a ModelRegistry: one token per
#: session, fixed for its lifetime, so the deterministic canary hash
#: routes the WHOLE stream to one variant — a warm-start flow_init must
#: never cross engines mid-stream
_SESSION_IDS = itertools.count(1)


class VideoSession:
    """One video stream's warm-start state.

    NOT thread-safe — a stream has one frame order; run each session
    from its own submitter (cross-stream parallelism lives in the
    scheduler's queue). ``warm_start=False`` degrades to per-pair cold
    starts (still coalesced) without touching caller code.
    """

    def __init__(self, scheduler, *, warm_start: bool = True,
                 device_state: bool = False,
                 feature_cache: bool = False,
                 deadline_s: Optional[float] = None,
                 model: Optional[str] = None,
                 priority: Optional[str] = None,
                 route_key: Optional[str] = None,
                 retry_budget: int = 0,
                 retry_base_s: float = 0.05,
                 retry_max_s: float = 2.0,
                 retry_jitter: float = 0.5,
                 retry_rng: Optional[random.Random] = None,
                 retry_sleep: Optional[Callable[[float], None]] = None):
        """``device_state=True`` keeps the recurrence state
        (``flow_low``) ON DEVICE between pairs: the scheduler returns a
        device array, the forward warp runs as a jitted scatter
        (ops/interp.forward_interpolate_device — holes stay zero, i.e.
        locally cold, instead of scipy's global nearest fill), and the
        next submit passes the device array straight back — the
        per-frame D2H→H2D round trip disappears from the hot path.
        Shape-change and cold-restart paths still materialize to host
        (they reset the state to None and restart the recurrence);
        ``drain()`` always returns a host array. Default OFF: the host
        scipy path is bitwise what it always was.

        ``scheduler`` may also be a
        :class:`~raft_tpu.serving.registry.ModelRegistry`: ``model``
        then names the variant family to serve from, ``priority``
        defaults to ``"interactive"`` (a session is a user waiting on
        frames), and the session pins a sticky ``route_key`` so the
        deterministic canary hash keeps the WHOLE stream on one
        engine — warm-start state never crosses model variants
        mid-stream. Against a plain scheduler all three stay unset and
        the submit call is byte-identical to before.

        ``feature_cache=True`` (needs a scheduler/registry whose
        engine and scheduler armed the cross-frame feature cache)
        moves the WHOLE recurrence + encoder state device-side: each
        frame submits alone through ``submit_cached`` (the pair's
        first frame never re-ships or re-encodes — its features live
        in the per-stream device pool), the stream's first frame (and
        any cold restart) is a PRIME submit whose future the session
        harvests internally, and a submit-time
        ``FeatureCacheMiss`` (slot evicted/flushed/invalidated)
        cold-restarts cleanly: re-prime the previous frame, wait it
        out, resubmit the pair. ``warm_start``/``device_state`` are
        superseded (state lives pool-side); ``drain()`` returns None
        (the recurrence state never materializes to host). Rollout
        moves (the ``variant_version`` poll) and shape changes
        cold-restart BOTH the recurrence and the cache slot.

        ``retry_budget`` > 0 makes the session absorb transient
        submit-time rejections itself: a ``BackpressureError`` (full
        queue or registry admission budget) or ``CircuitOpen`` retries
        through ``utils/retry.backoff_delays`` (``retry_base_s`` /
        ``retry_max_s`` / ``retry_jitter``; ``retry_rng`` and
        ``retry_sleep`` injectable for deterministic drills), capped
        at ``retry_budget`` retries over the SESSION's lifetime — a
        stream stuck behind a persistent overload must run out, not
        hammer. Any retried pair cold-restarts the recurrence (by the
        time a retry lands, the warm state is stale by at least one
        backoff), and budget exhaustion surfaces the ORIGINAL
        exception to the caller. Default 0: rejections surface
        immediately, the historical contract."""
        self._sched = scheduler
        self.warm_start = bool(warm_start)
        self.device_state = bool(device_state)
        self.feature_cache = bool(feature_cache)
        if feature_cache and not hasattr(scheduler, "submit_cached"):
            raise ValueError(
                "feature_cache=True needs a scheduler/ModelRegistry "
                "with submit_cached (a feature_cache=True scheduler "
                "over a feature_cache=True engine)")
        self.deadline_s = deadline_s
        self._variant_version: Optional[str] = None
        self._submit_kw = {}
        if getattr(scheduler, "is_registry", False):
            from raft_tpu.serving.scheduler import PRIORITY_INTERACTIVE

            self._submit_kw["route_key"] = (
                route_key if route_key is not None
                else f"session-{next(_SESSION_IDS)}")
            self._submit_kw["priority"] = (
                priority if priority is not None else PRIORITY_INTERACTIVE)
            if model is not None:
                self._submit_kw["model"] = model
        elif model is not None or route_key is not None:
            # checked before the priority branch: a plain scheduler
            # must reject these loudly whatever else is set — silently
            # dropping model= would serve the wrong model's output
            raise ValueError(
                "model=/route_key= need a ModelRegistry scheduler")
        elif priority is not None:
            self._submit_kw["priority"] = priority
        self.retry_budget = int(retry_budget)
        self.retries_used = 0
        self._retryable: tuple = ()
        if self.retry_budget > 0:
            from raft_tpu.serving.resilience import CircuitOpen
            from raft_tpu.serving.scheduler import BackpressureError

            self._retryable = (BackpressureError, CircuitOpen)
            self._mk_delays = lambda: backoff_delays(
                retry_base_s, retry_max_s, jitter=retry_jitter,
                rng=retry_rng)
            self._retry_sleep = (retry_sleep if retry_sleep is not None
                                 else time.sleep)
        self.frames = 0
        self.warm_submits = 0
        self._prev_frame: Optional[np.ndarray] = None
        self._pending = None                    # previous pair's Future
        self._flow_low: Optional[np.ndarray] = None
        #: feature-cache stream identity: ALWAYS unique per session
        #: object — pool slots are per-session recurrence state, and
        #: two sessions sharing an explicit sticky ``route_key`` must
        #: NOT share a slot (their independent frame counters would
        #: collide on seq and silently correlate one video's frame
        #: against the other's cached features)
        self._stream = f"stream-{next(_SESSION_IDS)}"
        #: request tracing (serving/trace.py): the previous submit's
        #: trace id — frame N's span links frame N−1's, so the
        #: stream's whole recurrence (primes and re-primes included)
        #: is one walkable chain. None whenever the scheduler runs
        #: untraced.
        self._last_trace: Optional[str] = None

    def _trace_parent(self):
        """Arm the next submit's parent link (tracing armed); returns
        the ledger or None — the same duck-typed read off a plain
        scheduler or a registry."""
        tr = getattr(self._sched, "tracer", None)
        if tr is not None and self._last_trace is not None:
            tr.set_parent(self._last_trace)
        return tr

    def _trace_unparent(self):
        """Clear an armed-but-unconsumed parent link after a submit
        REJECTED before the mint (backpressure/breaker at intake) —
        a stale stamp on the thread must never chain an unrelated
        later span into this stream."""
        tr = getattr(self._sched, "tracer", None)
        if tr is not None:
            tr.set_parent(None)

    def _harvest(self) -> None:
        """Settle the previous pair — the recurrence is sequential per
        stream: pair N+1 warm-starts from pair N's flow_low. A failed
        or deadline-missed pair cold-restarts the recurrence (the
        failure already surfaced on that pair's own future)."""
        if self._pending is None:
            return
        try:
            self._flow_low = self._pending.result().flow_low
        except Exception:
            self._flow_low = None
        self._pending = None

    def submit_frame(self, frame, *,
                     deadline_s: Optional[float] = None):
        """Feed the next frame; returns the Future for the
        (previous, current) pair — None for the first frame of a
        stream (or after a mid-stream resolution change, which
        restarts the recurrence: ``flow_low`` lives in the old frame
        geometry)."""
        if self.feature_cache:
            return self._submit_frame_cached(frame, deadline_s)
        frame = np.asarray(frame, np.float32)
        self.frames += 1
        prev, self._prev_frame = self._prev_frame, frame
        if prev is None:
            return None
        if prev.shape != frame.shape:
            self._pending, self._flow_low = None, None
            return None
        if self._variant_moved():
            self._pending, self._flow_low = None, None
        flow_init = None
        if self.warm_start:
            self._harvest()
            if self._flow_low is not None and self.device_state \
                    and not isinstance(self._flow_low, np.ndarray):
                from raft_tpu.ops.interp import \
                    forward_interpolate_device

                # device-resident recurrence: warp on device, feed the
                # handle straight back — no bytes cross the PCIe/host
                # boundary between pairs. A non-finite flow scatters
                # nothing (every point fails the validity window), so
                # a garbage pair degrades to a cold start here the way
                # the host path's isfinite guard does — without a sync.
                flow_init = forward_interpolate_device(self._flow_low)
            elif self._flow_low is not None:
                from raft_tpu.ops.interp import forward_interpolate

                flow_init = forward_interpolate(
                    np.asarray(self._flow_low))
                if not np.isfinite(flow_init).all():
                    # every forward-warped point left the frame (a
                    # garbage pair, or motion larger than the frame):
                    # griddata had nothing to interpolate from and
                    # returns NaN ('nearest' ignores fill_value) —
                    # cold-start instead of poisoning the stream
                    flow_init = None
        effective_deadline = (self.deadline_s if deadline_s is None
                              else deadline_s)
        tr = self._trace_parent()
        try:
            try:
                fut = self._sched.submit(
                    prev, frame, deadline_s=effective_deadline,
                    flow_init=flow_init, want_low=self.warm_start,
                    low_device=self.device_state, **self._submit_kw)
            except self._retryable as exc:
                fut = self._retry_submit(prev, frame,
                                         effective_deadline, exc)
            else:
                if flow_init is not None:
                    self.warm_submits += 1
        except BaseException:
            self._trace_unparent()
            raise
        self._pending = fut
        if tr is not None:
            self._last_trace = getattr(fut, "trace_id", None)
        return fut

    def _variant_moved(self) -> bool:
        """Registry rollout guard (no-op off a registry): poll the
        variant a request with this stream's sticky ``route_key``
        would serve from; True when a deploy/promote/rollback moved it
        since the last pair — warm state produced by one variant must
        never feed another model's refinement, so the caller
        cold-restarts. The first poll only establishes the baseline.
        (A change landing between this read and the submit is a
        one-pair race; the NEXT pair cold-restarts — and on the
        feature-cache path the pool's weights-version stamp backstops
        even that window.)"""
        if "route_key" not in self._submit_kw:
            return False
        ver = self._sched.variant_version(
            self._submit_kw.get("model"),
            self._submit_kw["route_key"])
        moved = (self._variant_version is not None
                 and ver != self._variant_version)
        self._variant_version = ver
        return moved

    def _harvest_cached(self) -> None:
        """Settle the previous cached dispatch (pair or prime) — its
        completion installs the pool slot the NEXT pair correlates
        against, so the wait is what makes warmth knowable. A failure
        already surfaced on that future, and the pool's seq-exact
        validity turns its missed store into a clean submit-time miss:
        nothing to reset here."""
        if self._pending is None:
            return
        try:
            self._pending.result()
        except Exception:
            pass
        self._pending = None

    def _submit_frame_cached(self, frame, deadline_s):
        """The feature-cache form of ``submit_frame``: one frame ships
        per submit; pairs correlate against the device pool's slot for
        this stream. Cold starts (first frame, shape change, rollout
        move) PRIME: the frame's features install the slot and the
        caller gets None — exactly the first-frame contract."""
        from raft_tpu.serving.feature_cache import FeatureCacheMiss

        frame = np.asarray(frame, np.float32)
        self.frames += 1
        seq = self.frames
        prev, self._prev_frame = self._prev_frame, frame
        # the PR-9 rollout discipline, extended to encoder state: a
        # deploy/promote/rollback that moves this stream's variant
        # cold-restarts — the slot lives in the OLD variant's pool and
        # its features in the old weights (the pool's weights-version
        # stamp + StaleFeatureError backstop the race window)
        cold = (prev is None or prev.shape != frame.shape
                or self._variant_moved())
        effective_deadline = (self.deadline_s if deadline_s is None
                              else deadline_s)
        if cold:
            # stream (re)start: prime THIS frame — there is no pair
            # (or the recurrence must restart in the new geometry/
            # variant). Harvest the in-flight previous dispatch FIRST:
            # its completion store must not land after (and clobber)
            # the prime's fresh slot. The prime's own future is
            # harvested before the next submit; the caller gets None.
            self._harvest_cached()
            self._pending = self._cached_submit(
                frame, seq=seq, prime=True,
                deadline_s=effective_deadline)
            return None
        # pair owed: wait out the previous dispatch — its completion
        # installs this pair's first-frame features (the sequential-
        # harvest contract: per-stream order, never serializing the
        # device across streams)
        self._harvest_cached()
        fut = None
        for attempt in range(3):
            try:
                fut = self._cached_submit(
                    frame, seq=seq, prime=False,
                    deadline_s=effective_deadline)
                self.warm_submits += 1
                break
            except FeatureCacheMiss:
                # slot gone (LRU-evicted, flushed by a weight swap, or
                # a failed/expired pair left a seq hole): clean
                # cold-restart — re-prime the pair's FIRST frame, wait
                # it out, resubmit the pair against the fresh slot.
                # One extra round trip, paid only on restarts. Bounded
                # retries because under capacity starvation ANOTHER
                # stream's store can evict the fresh slot between the
                # re-prime and the resubmit probe; past the bound the
                # miss surfaces — the pool genuinely is too small for
                # the live stream population, and hammering would only
                # deepen the churn. A failed re-prime surfaces its own
                # error immediately.
                if attempt == 2:
                    raise
                self._cached_submit(
                    prev, seq=seq - 1, prime=True,
                    deadline_s=effective_deadline).result()
        self._pending = fut
        return fut

    def _cached_submit(self, frame, *, seq: int, prime: bool,
                       deadline_s):
        """One cached submit through the session's retry budget: a
        transient ``BackpressureError``/``CircuitOpen`` retries with
        jittered backoff up to the shared per-session cap, exhaustion
        re-raises the ORIGINAL rejection — the cached analog of
        ``_retry_submit``. No forced cold restart here: warmth is
        decided pool-side at dispatch, and the slot's seq/version
        validity already guards anything a backoff could stale.
        Tracing armed: every submit (pairs, primes, re-primes) links
        the stream's previous trace — a cold restart stays ON the
        chain, visible by its ``prime`` annotation, so serve_trace
        can attribute the re-prime round trip to the pair it
        delayed."""
        tr = self._trace_parent()
        try:
            fut = None
            try:
                fut = self._sched.submit_cached(
                    frame, stream=self._stream, seq=seq, prime=prime,
                    deadline_s=deadline_s, **self._submit_kw)
            except self._retryable as exc:
                delays = self._mk_delays()
                while self.retries_used < self.retry_budget:
                    self.retries_used += 1
                    self._retry_sleep(next(delays))
                    try:
                        self._trace_parent()
                        fut = self._sched.submit_cached(
                            frame, stream=self._stream, seq=seq,
                            prime=prime, deadline_s=deadline_s,
                            **self._submit_kw)
                        break
                    except self._retryable:
                        continue
                if fut is None:
                    raise exc
        except BaseException:
            self._trace_unparent()
            raise
        if tr is not None:
            self._last_trace = getattr(fut, "trace_id", None)
        return fut

    def _retry_submit(self, prev, frame,
                      deadline_s: Optional[float], original):
        """Absorb a retryable submit rejection within the session's
        retry budget: jittered backoff, then resubmit the pair COLD —
        by the time a retry lands the warm state is a backoff stale,
        and a cold row is bitwise a fresh stream start. The budget is
        per session and hard; exhaustion re-raises the ORIGINAL
        rejection (the retries' own rejections carry no new
        information)."""
        # cold-restart the recurrence: the retried pair submits with
        # no flow_init, and the NEXT pair must not warm off state from
        # before the disruption either
        self._flow_low = None
        delays = self._mk_delays()
        while self.retries_used < self.retry_budget:
            self.retries_used += 1
            self._retry_sleep(next(delays))
            try:
                self._trace_parent()  # the retried (cold) pair stays
                #                       on the stream's trace chain
                return self._sched.submit(
                    prev, frame, deadline_s=deadline_s,
                    flow_init=None, want_low=self.warm_start,
                    low_device=self.device_state, **self._submit_kw)
            except self._retryable:
                continue
        raise original

    def drain(self) -> Optional[np.ndarray]:
        """Wait out the last pair; returns the stream's final
        ``flow_low`` (None if the stream is cold) — always materialized
        to host, whatever ``device_state`` says. On the feature-cache
        path it also releases the stream's pool slot (a finished
        stream's device arrays must not occupy capacity live streams
        need) and returns None (state never materialized to host)."""
        if self.feature_cache:
            self._harvest_cached()
            inv = getattr(self._sched, "invalidate_stream", None)
            if inv is not None:
                if "route_key" in self._submit_kw:
                    inv(self._stream,
                        model=self._submit_kw.get("model"),
                        route_key=self._submit_kw["route_key"])
                else:
                    inv(self._stream)
            return None
        self._harvest()
        if self._flow_low is not None \
                and not isinstance(self._flow_low, np.ndarray):
            self._flow_low = np.asarray(self._flow_low)
        return self._flow_low
