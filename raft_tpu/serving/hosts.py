"""Multi-host replica fleet: remote workers behind the transport seam.

PR 17's fleet is threads over host devices in ONE process; this module
is the other half the ROADMAP names — replicas on separate *hosts*.
Three pieces:

- :class:`HostWorker`: the worker-side object (one per host process —
  ``tests/host_worker.py`` serves one over a socket; tier-1 drills
  hold one behind a :class:`~raft_tpu.serving.transport
  .LoopbackTransport`). It enforces **pre-warm-before-traffic**: until
  artifacts are pushed (sha256-verified, written blob-then-manifest-
  last into its own AOT store) and ``prewarm`` has built its engine,
  every routing/infer method refuses — a joining host takes zero
  requests until its artifacts verify.
- :class:`RemoteEngine`: an engine-shaped proxy over a transport. The
  scheduler's fleet lanes drive it exactly like a local engine (the
  sync ``infer_batch`` path — the blocking RPC rides the lane's
  supervised executor, so the fleet watchdog covers transport hangs).
- :class:`HostFleet`: liveness + membership. Per host: heartbeat
  probes (``host.heartbeat`` fault site), a missed-beat ladder
  ``healthy → suspect → dead`` with injectable-clock thresholds, a
  per-host :class:`~raft_tpu.serving.resilience.CircuitBreaker` whose
  jittered backoff (``utils/retry.backoff_delays`` under the hood)
  paces reconnect probes, and artifact push + prewarm on (re)join.
  Dead-host verdicts are queued as *notices*; the scheduler drains
  them on its dispatcher tick and applies the PR-7
  consequences-before-futures discipline (quarantine the lane, poison
  the transport, THEN fail over the in-flight batch by requeue — see
  ``MicroBatchScheduler._wedge_host``).

Degradation states (surfaced in :meth:`HostFleet.health` and the
scheduler's ``health()["hosts"]``): ``healthy`` (every host ready and
beating), ``degraded`` (some host suspect/dead/not-ready while others
serve), ``partitioned`` (NO host reachable — the fleet is cut off;
local lanes, if any, keep serving).

metrics.jsonl events: ``host_suspect``, ``host_dead``,
``host_rejoined`` (emitted here), ``failover`` (emitted by the
scheduler with the requeue count). All additive — ``hosts=0`` builds
none of this.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..testing.faults import fault_point
from .resilience import BREAKER_HALF_OPEN, CircuitBreaker
from .transport import TransportError

#: graftthread lock declarations. ``HostFleet._lock`` guards the
#: notices deque + membership snapshots only — NEVER held across a
#: transport call (heartbeat/push/prewarm RPCs run lock-free, so a
#: hung host can stall one probe, not the fleet's bookkeeping). It is
#: a leaf under the scheduler's locks: the dispatcher drains notices
#: while holding nothing.
LOCK_ORDER = (
    ("hosts.HostFleet._lock",),
)

#: heartbeat verdicts ride the scheduler's verdict discipline — the
#: fleet only *queues* them (``pop_notices``); consequences land in
#: ``scheduler._wedge_host`` before any future is touched.
GRAFTTHREAD = {
    "locks": ("_lock",),
}

#: graftwire declarations. Every worker method is idempotent BY
#: CONTRACT (the TransportError-always-retryable design): ping/stats/
#: capacity are reads; put_artifact re-verifies and no-ops on a digest
#: already installed; prewarm re-warms to the same engine; ensure/
#: route/drop converge on the same bucket table; infer is pure;
#: update_weights sets the tree to the SAME value on re-send. A new
#: method that is NOT safe to re-send must ship a request_id in its
#: payload instead of a row here — W2 holds every call site to one or
#: the other. ``_emit`` wraps metrics.record_event, so its literals
#: are schema-checked like direct calls (W6).
GRAFTWIRE = {
    "idempotent": ("ping", "put_artifact", "prewarm", "capacity",
                   "ensure", "route", "drop", "infer",
                   "update_weights", "stats"),
    "event_emitters": ("_emit",),
}

HOST_HEALTHY = "healthy"
HOST_SUSPECT = "suspect"
HOST_DEAD = "dead"

FLEET_HEALTHY = "healthy"
FLEET_DEGRADED = "degraded"
FLEET_PARTITIONED = "partitioned"


class HostDead(RuntimeError):
    """The request's host lane was verdicted dead (missed-beat ladder
    exhausted). In-flight work fails over to surviving lanes; this
    exception only surfaces when NO lane can ever serve the work."""


# -- worker side ----------------------------------------------------------


class HostWorker:
    """The worker-side method table behind ``Transport.call`` —
    ``handle(method, payload)`` is the single entry
    (:func:`~raft_tpu.serving.transport.serve_connection` dispatches
    into it; :class:`~raft_tpu.serving.transport.LoopbackTransport`
    holds one directly).

    ``engine_factory`` builds the serving engine at *prewarm* time —
    AFTER artifacts land — so a real worker's
    ``RAFTEngine(aot_cache=aot_root, precompile=True)`` warms entirely
    from verified pushed artifacts (zero XLA compiles, pinned by the
    ``prewarm`` reply's counters). Until ``prewarm`` succeeds, every
    routing/infer method raises — the transport relays it as an error
    reply and the host takes no traffic.
    """

    def __init__(self, engine=None, *,
                 engine_factory: Optional[Callable[[], Any]] = None,
                 aot_root: Optional[str] = None):
        if engine is None and engine_factory is None:
            raise ValueError("HostWorker needs an engine or an "
                             "engine_factory")
        self._engine = engine
        self._factory = engine_factory
        self.aot_root = aot_root
        self._ready = engine is not None
        self._seq = 0

    # -- protocol ---------------------------------------------------------

    def handle(self, method: str, payload: Any):
        fn = getattr(self, f"_m_{method}", None)
        if fn is None:
            raise ValueError(f"unknown worker method {method!r}")
        return fn(payload or {})

    def _eng(self):
        if not self._ready or self._engine is None:
            raise RuntimeError(
                "host not prewarmed — push artifacts and call prewarm "
                "before routing traffic (pre-warm-before-traffic)")
        return self._engine

    def _m_ping(self, payload) -> Dict:
        self._seq += 1
        return {"seq": self._seq, "ready": self._ready}

    def _m_put_artifact(self, payload) -> Dict:
        """Receive one serialized-executable cache entry. Verified
        BEFORE any byte lands under the store (sha256 of the blob
        against both the message and the manifest), then written
        atomically — blob first, manifest LAST, tmp-dir rename — so a
        crash mid-push can never leave a loadable-looking torn entry.
        Idempotent: re-pushing a digest that already verifies is a
        no-op reply (the retry-after-corruption path)."""
        if self.aot_root is None:
            raise RuntimeError("worker has no aot_root to receive "
                               "artifacts into")
        digest = payload["digest"]
        blob = payload["blob"]
        manifest_bytes = payload["manifest"]
        want = payload["sha256"]
        got = hashlib.sha256(blob).hexdigest()
        if got != want:
            raise ValueError(
                f"artifact {digest}: blob sha256 mismatch (corrupted "
                f"in transit): got {got[:12]} want {want[:12]}")
        manifest = json.loads(manifest_bytes.decode("utf-8"))
        if manifest.get("sha256") != want:
            raise ValueError(
                f"artifact {digest}: manifest/message sha256 disagree")
        objects = os.path.join(self.aot_root, "objects")
        edir = os.path.join(objects, digest)
        if not os.path.exists(os.path.join(edir, "manifest.json")):
            tmp = os.path.join(objects, f".push-{digest}-{os.getpid()}")
            os.makedirs(tmp, exist_ok=True)
            try:
                with open(os.path.join(tmp, "executable.bin"),
                          "wb") as fh:
                    fh.write(blob)
                    fh.flush()
                    os.fsync(fh.fileno())
                with open(os.path.join(tmp, "manifest.json"),
                          "wb") as fh:
                    fh.write(manifest_bytes)
                    fh.flush()
                    os.fsync(fh.fileno())
                try:
                    os.rename(tmp, edir)
                except OSError:
                    pass   # racer installed it first: theirs verified too
            finally:
                shutil.rmtree(tmp, ignore_errors=True)
        return {"sha256": want, "bytes": len(blob)}

    def _m_prewarm(self, payload) -> Dict:
        """Build/warm the engine (artifacts must already be in place —
        the factory's AOT-armed engine loads instead of compiling) and
        reply the counters the zero-compile contract pins."""
        if self._engine is None:
            self._engine = self._factory()
        self._ready = True
        eng = self._engine
        stats = (eng.aot_stats() if hasattr(eng, "aot_stats")
                 else {"enabled": 0})
        return {
            "compiles": int(stats.get("compiles",
                                      getattr(eng, "compile_count", 0))),
            "aot_hits": int(stats.get("aot_hits", 0)),
            "aot_misses": int(stats.get("aot_misses", 0)),
            "executables": int(eng.executable_count()
                               if hasattr(eng, "executable_count")
                               else len(getattr(eng, "_compiled", ()))),
        }

    def _m_capacity(self, payload):
        return self._eng().bucket_capacity(payload["h"], payload["w"],
                                           **payload.get("kw", {}))

    def _m_ensure(self, payload):
        return tuple(self._eng().ensure_bucket(
            payload["n"], payload["h"], payload["w"],
            **payload.get("kw", {})))

    def _m_route(self, payload):
        return tuple(self._eng().route_bucket(
            payload["n"], payload["h"], payload["w"]))

    def _m_drop(self, payload):
        self._eng().drop_bucket(tuple(payload["bucket"]),
                                **payload.get("kw", {}))
        return True

    def _m_infer(self, payload):
        import numpy as np

        fault_point("host.infer")
        eng = self._eng()
        i1 = payload["image1"]
        i2 = payload["image2"]
        if payload.get("return_low"):
            flow, low = eng.infer_batch(
                i1, i2, flow_init=payload.get("flow_init"),
                return_low=True)
            return (np.asarray(flow), np.asarray(low))
        return np.asarray(eng.infer_batch(i1, i2))

    def _m_update_weights(self, payload):
        self._eng().update_weights(payload["variables"])
        return True

    def _m_stats(self, payload) -> Dict:
        eng = self._engine
        if eng is None:
            return {"ready": False, "executables": 0}
        return {
            "ready": self._ready,
            "executables": int(eng.executable_count()
                               if hasattr(eng, "executable_count")
                               else len(getattr(eng, "_compiled", ()))),
            "aot": (eng.aot_stats() if hasattr(eng, "aot_stats")
                    else {"enabled": 0}),
        }


# -- scheduler side -------------------------------------------------------


class RemoteEngine:
    """Engine-shaped proxy over a transport — what a host lane's
    ``_ReplicaLane.engine`` actually is. Deliberately the *sync*
    engine surface only (no ``infer_batch_async``): the scheduler's
    fleet path then runs the blocking RPC on the lane's supervised
    executor thread, where the fleet watchdog and the dead-host
    verdict both know how to reach it. ``warm_start`` is False — v1
    remote lanes serve the cold-start path; warm-start/feature-cache
    state is device-resident and single-host by design."""

    wire = "f32"
    warm_start = False
    feature_cache = False
    ragged = False

    def __init__(self, transport, name: str, *,
                 call_timeout_s: Optional[float] = None):
        self._transport = transport
        self.name = name
        self._timeout = call_timeout_s

    def _call(self, method: str, payload=None):
        return self._transport.call(method, payload,
                                    timeout_s=self._timeout)

    def rebind(self, transport) -> None:
        """Point the proxy at a restarted worker's transport (the
        explicit-rejoin path)."""
        self._transport = transport

    def poison(self) -> None:
        """Close the transport out from under any in-flight RPC — the
        dead-host verdict's way of unsticking a lane blocked on a
        zombie's socket (the blocked recv raises, the lane's except
        path sees ``job.abandoned`` and settles nothing)."""
        self._transport.close()

    def bucket_capacity(self, h: int, w: int, **kw):
        return self._call("capacity", {"h": h, "w": w, "kw": kw})

    def ensure_bucket(self, n: int, h: int, w: int, **kw) -> Tuple:
        return tuple(self._call("ensure",
                                {"n": n, "h": h, "w": w, "kw": kw}))

    def route_bucket(self, n: int, h: int, w: int) -> Tuple:
        return tuple(self._call("route", {"n": n, "h": h, "w": w}))

    def drop_bucket(self, bucket, **kw) -> None:
        # best-effort: this runs from verdict paths where the host is
        # typically already unreachable — the worker's own table is
        # rebuilt on rejoin anyway (prewarm from artifacts)
        try:
            self._call("drop", {"bucket": tuple(bucket), "kw": kw})
        except TransportError:
            pass

    def infer_batch(self, image1, image2, **kw):
        return self._call("infer", dict(image1=image1, image2=image2,
                                        **kw))

    def update_weights(self, variables) -> None:
        self._call("update_weights", {"variables": variables})

    def executable_count(self) -> int:
        try:
            return int(self._call("stats").get("executables") or 0)
        except TransportError:
            return 0

    def aot_stats(self) -> Dict:
        try:
            return dict(self._call("stats").get("aot")
                        or {"enabled": 0})
        except TransportError:
            return {"enabled": 0}


class _Host:
    __slots__ = ("name", "transport", "engine", "breaker", "state",
                 "missed", "beats", "last_beat", "ready", "failovers",
                 "push_entries", "push_bytes", "push_retries",
                 "prewarm", "rejoins")

    def __init__(self, name: str, transport, breaker: CircuitBreaker,
                 call_timeout_s: Optional[float]):
        self.name = name
        self.transport = transport
        self.engine = RemoteEngine(transport, name,
                                   call_timeout_s=call_timeout_s)
        self.breaker = breaker
        self.state = HOST_HEALTHY
        self.missed = 0
        self.beats = 0
        self.last_beat: Optional[float] = None
        #: takes zero traffic until artifacts verified + prewarmed
        self.ready = False
        self.failovers = 0
        self.push_entries = 0
        self.push_bytes = 0
        self.push_retries = 0
        self.prewarm: Dict = {}
        self.rejoins = 0


class HostFleet:
    """Liveness + membership for the remote lanes.

    ``transports``: ``{name: Transport}`` (insertion-ordered — lane
    order) or a plain list (named ``h0``, ``h1``, ...).

    Missed-beat ladder (per host, consecutive misses):
    ``suspect_after`` ⇒ ``suspect``, ``dead_after`` ⇒ ``dead`` + a
    queued verdict notice. ``clock`` is injectable — tests walk the
    ladder with ``beat_all()`` and a fake clock, no sleeping. The
    per-host breaker's jittered backoff paces reconnect probes after a
    dead verdict; a probe that answers triggers the full rejoin
    protocol (artifact re-push, sha-verified → prewarm → ready), never
    a bare "it pinged once" revival.

    The fleet NEVER settles futures or touches scheduler state — it
    queues ``("dead"|"rejoined", name)`` notices that the scheduler's
    dispatcher drains (``_host_notices``), keeping every consequence
    on the one thread that owns the lanes."""

    def __init__(self, transports, *, aot_cache=None,
                 heartbeat_s: float = 0.5,
                 heartbeat_timeout_s: float = 2.0,
                 suspect_after: int = 2, dead_after: int = 4,
                 reconnect_backoff_s: float = 0.5,
                 reconnect_backoff_max_s: float = 30.0,
                 rng=None, clock: Callable[[], float] = time.monotonic,
                 metrics=None, call_timeout_s: Optional[float] = 60.0,
                 push_attempts: int = 4):
        if not isinstance(transports, dict):
            transports = {f"h{k}": t for k, t in enumerate(transports)}
        if suspect_after < 1 or dead_after <= suspect_after:
            raise ValueError(
                f"need 1 <= suspect_after ({suspect_after}) < "
                f"dead_after ({dead_after})")
        self.aot_cache = aot_cache
        self.heartbeat_s = float(heartbeat_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.suspect_after = int(suspect_after)
        self.dead_after = int(dead_after)
        self.push_attempts = int(push_attempts)
        self._rng = rng
        self._clock = clock
        self.metrics = metrics
        self._lock = threading.Lock()
        self._notices: List[Tuple[str, str]] = []
        self.hosts: Dict[str, _Host] = {}
        for name, t in transports.items():
            br = CircuitBreaker(
                failures=self.dead_after, base_s=reconnect_backoff_s,
                max_s=reconnect_backoff_max_s, rng=rng, clock=clock,
                label=f"host/{name}")
            self.hosts[name] = _Host(name, t, br, call_timeout_s)
        self._monitor: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- membership / artifact push ---------------------------------------

    def admit(self, name: str) -> Dict:
        """Bring one host to ready: push every AOT artifact
        (sha256-verified end to end, retry/backoff inside
        ``AOTCache.push``) then ``prewarm`` — only a host whose
        artifacts verified takes traffic. Raises ``TransportError``
        if the host can't be brought up (it stays not-ready)."""
        host = self.hosts[name]
        push = {"entries": 0, "bytes": 0, "retries": 0}
        if self.aot_cache is not None:
            push = self.aot_cache.push(
                host.transport, attempts=self.push_attempts,
                rng=self._rng)
        host.push_entries += push["entries"]
        host.push_bytes += push["bytes"]
        host.push_retries += push["retries"]
        host.prewarm = host.transport.call(
            "prewarm", timeout_s=max(self.heartbeat_timeout_s, 120.0))
        host.ready = True
        host.state = HOST_HEALTHY
        host.missed = 0
        host.last_beat = self._clock()
        if self.metrics is not None:
            self.metrics.record_host_push(
                name, entries=push["entries"], bytes=push["bytes"],
                retries=push["retries"])
            self.metrics.record_host_state(name, host.state,
                                           missed=0, ready=True)
        return host.prewarm

    def admit_all(self) -> Dict[str, Dict]:
        return {name: self.admit(name) for name in self.hosts}

    def rejoin(self, name: str, transport=None) -> Dict:
        """Re-admit a dead host — through a NEW transport when its
        worker restarted elsewhere (SIGKILL drill), or the existing
        one after a partition healed. Full protocol: artifact re-push
        + prewarm; only then does the lane reactivate (the scheduler
        drains the ``rejoined`` notice)."""
        host = self.hosts[name]
        if transport is not None:
            host.transport = transport
            host.engine.rebind(transport)
        stats = self.admit(name)
        host.rejoins += 1
        host.breaker.record_success()
        if self.metrics is not None:
            self.metrics.record_host_rejoin(name)
        self._emit("host_rejoined", host=name,
                   push_entries=host.push_entries,
                   push_bytes=host.push_bytes,
                   push_retries=host.push_retries,
                   compiles=int(stats.get("compiles", 0)))
        with self._lock:
            self._notices.append(("rejoined", name))
        return stats

    def poison(self, name: str) -> None:
        """Close the host's transport (a dead-host verdict
        consequence: unsticks any lane blocked on the zombie's
        socket)."""
        self.hosts[name].engine.poison()

    # -- heartbeats --------------------------------------------------------

    def beat(self, name: str) -> bool:
        """One heartbeat probe. Walks the missed-beat ladder on
        failure; emits ``host_suspect`` / ``host_dead`` events and
        queues the dead verdict notice exactly once per death."""
        host = self.hosts[name]
        host.last_beat = self._clock()
        ok = True
        try:
            fault_point("host.heartbeat")
            host.transport.call("ping",
                                timeout_s=self.heartbeat_timeout_s)
        except (TransportError, Exception) as exc:  # noqa: BLE001
            if not isinstance(exc, (TransportError, RuntimeError)):
                raise
            ok = False
        if ok:
            host.beats += 1
            host.missed = 0
            host.breaker.record_success()
            if host.state == HOST_SUSPECT:
                host.state = HOST_HEALTHY
                self._record_state(host)
            return True
        host.missed += 1
        host.breaker.record_failure()
        if host.state != HOST_DEAD and host.missed >= self.dead_after:
            host.state = HOST_DEAD
            host.ready = False
            self._record_state(host)
            self._emit("host_dead", host=name, missed=host.missed)
            with self._lock:
                self._notices.append(("dead", name))
        elif (host.state == HOST_HEALTHY
                and host.missed >= self.suspect_after):
            host.state = HOST_SUSPECT
            self._record_state(host)
            self._emit("host_suspect", host=name, missed=host.missed)
        return False

    def beat_all(self) -> List[str]:
        """Probe every non-dead host once (tests drive the ladder with
        this + an injectable clock); returns the hosts that missed."""
        return [name for name, h in self.hosts.items()
                if h.state != HOST_DEAD and not self.beat(name)]

    def tick(self) -> None:
        """One monitor pass: beat every live host that is due, pace a
        reconnect probe for every dead one (gated on its breaker's
        jittered backoff having expired — the half-open promotion)."""
        now = self._clock()
        for name, host in self.hosts.items():
            if host.state == HOST_DEAD:
                self._try_reconnect(host)
            elif (host.last_beat is None
                    or now - host.last_beat >= self.heartbeat_s):
                self.beat(name)

    def _try_reconnect(self, host: _Host) -> None:
        if host.breaker.state() != BREAKER_HALF_OPEN:
            return   # backoff not expired: no probe yet
        transport = host.transport
        if getattr(transport, "closed", False):
            reopen = getattr(transport, "reopen", None)
            if reopen is None:
                host.breaker.record_failure()
                return
            try:
                transport = reopen()
            except TransportError:
                host.breaker.record_failure()
                return
        try:
            transport.call("ping", timeout_s=self.heartbeat_timeout_s)
            self.rejoin(host.name,
                        transport if transport is not host.transport
                        else None)
        except TransportError:
            host.breaker.record_failure()

    # -- verdict seam ------------------------------------------------------

    def pop_notices(self) -> List[Tuple[str, str]]:
        """Drain queued ``("dead"|"rejoined", name)`` notices — called
        from the scheduler's dispatcher tick, which owns every
        consequence."""
        with self._lock:
            out, self._notices = self._notices, []
        return out

    def record_failover(self, name: str, requeued: int) -> None:
        """Scheduler callback: one failover (requeued in-flight
        requests) was applied against this host's verdict."""
        host = self.hosts.get(name)
        if host is not None:
            host.failovers += 1
        if self.metrics is not None:
            self.metrics.record_host_failover(name, requeued=requeued)

    # -- observability -----------------------------------------------------

    def degradation(self) -> str:
        states = [h.state for h in self.hosts.values()]
        if not states:
            return FLEET_HEALTHY
        if all(s == HOST_DEAD for s in states):
            return FLEET_PARTITIONED
        if any(s != HOST_HEALTHY for s in states) \
                or any(not h.ready for h in self.hosts.values()):
            return FLEET_DEGRADED
        return FLEET_HEALTHY

    def health(self) -> Dict:
        return {
            "state": self.degradation(),
            "heartbeat_s": self.heartbeat_s,
            "suspect_after": self.suspect_after,
            "dead_after": self.dead_after,
            "hosts": {
                name: {
                    "state": h.state,
                    "ready": h.ready,
                    "missed_beats": h.missed,
                    "beats": h.beats,
                    "failovers": h.failovers,
                    "rejoins": h.rejoins,
                    "push_entries": h.push_entries,
                    "push_bytes": h.push_bytes,
                    "push_retries": h.push_retries,
                    "breaker": h.breaker.snapshot(),
                } for name, h in self.hosts.items()},
        }

    def _record_state(self, host: _Host) -> None:
        if self.metrics is not None:
            self.metrics.record_host_state(host.name, host.state,
                                           missed=host.missed,
                                           ready=host.ready)

    def _emit(self, kind: str, **fields) -> None:
        if self.metrics is not None:
            self.metrics.record_event(kind, **fields)

    # -- monitor thread ----------------------------------------------------

    def start(self) -> None:
        """Run ``tick()`` on a daemon monitor thread (real
        deployments/drills; tier-1 tests drive ``tick`` directly)."""
        if self._monitor is not None:
            return
        self._stop.clear()

        def _loop():
            while not self._stop.wait(self.heartbeat_s / 4):
                self.tick()

        self._monitor = threading.Thread(
            target=_loop, name="HostFleet-monitor", daemon=True)
        self._monitor.start()

    def close(self) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=10.0)
            self._monitor = None
        for host in self.hosts.values():
            try:
                host.transport.close()
            except Exception:  # noqa: BLE001 — best-effort shutdown
                pass
