"""Multi-model serving registry: versioned engines, canary rollout.

The serving stack below this module is deliberately single-model: one
``RAFTEngine`` (one arch, one weight tree), one ``MicroBatchScheduler``
(one queue, one breaker board, one metrics block). The paper family
itself ships two architectures (RAFT basic + RAFT small — "Rethinking
RAFT" makes the case for serving a cheap variant as a fast tier next to
the accurate one), and a front-end for heavy multi-tenant traffic must
route, roll out, and roll back *models* the way TPU-native serving
systems are multi-tenant by construction (Ragged Paged Attention,
PAPERS.md) — without a restart and without one model's failure touching
another's traffic.

:class:`ModelRegistry` is that layer. Each named model family owns
**variants** — an arch config + weight version backed by its OWN
``RAFTEngine`` (its own buckets) and its OWN ``MicroBatchScheduler``
(its own queue, breakers keyed ``model/HxW``, metrics namespaced by
model into one shared metrics.jsonl). Variant lifecycle::

    loading -> canary -> live -> draining -> retired

- ``add_model(name, weights, config)`` builds and goes straight live.
- ``deploy(name, weights, canary_fraction=f)`` builds a canary variant
  next to the live one; a **deterministic request-hash fraction** of
  that model's traffic (sha256 of the route token — stable across
  processes and replicas, no RNG) serves from the canary while the
  rest stays on the untouched live engine. A deploy that fails to
  build (bad weights, uncompilable arch, the ``registry.load`` fault
  site) auto-rolls-back: the partial variant is discarded, live
  traffic never saw it, and the error surfaces as
  :class:`DeployError`.
- ``promote(name)`` makes the canary the live version atomically:
  same-arch canaries land as a ``RAFTEngine.update_weights`` swap into
  the live engine (every compiled bucket reused — no compile storm);
  a new arch swaps the whole variant (engine + scheduler) under the
  registry lock, then drains the old one. Either way the drained
  scheduler settles every accepted future — zero stranded.
- ``rollback(name)`` stops canary routing first, then drains the
  canary with the same zero-stranded guarantee.

``submit(..., model=..., priority=...)`` routes one request: pick the
model family, hash the route token against the canary fraction, and
hand the frame pair to that variant's scheduler — where the priority
classes (``interactive`` / ``batch``: shed-batch-first backpressure,
weighted dequeue) apply per model. A request racing a
rollback/promote into a just-drained canary scheduler re-routes to
live instead of failing — the rollout machinery is invisible to
callers.

Engine-direct and single-scheduler deployments never pay for any of
this: the registry is a composition layer, not a rewrite — with no
registry constructed, every code path below is bitwise the PR-8 stack.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Dict, List, Optional

from raft_tpu.config import ITERS_EXPORT, RAFTConfig
from raft_tpu.serving.engine import RAFTEngine
from raft_tpu.serving.guardian import AdmissionBudget
from raft_tpu.serving.metrics import ServingMetrics
from raft_tpu.serving.scheduler import (BackpressureError,
                                        MicroBatchScheduler,
                                        SchedulerClosed)
from raft_tpu.serving.trace import TraceLedger
from raft_tpu.testing.faults import fault_point

#: graftthread T3: the registry lock is the OUTERMOST serving lock —
#: where it is held into a variant's scheduler at all, the direction
#: is registry -> scheduler, never the reverse (drains, closes and
#: health walks all release the registry lock first; a scheduler
#: thread must never call back into a locked registry).
LOCK_ORDER = (
    ("registry.ModelRegistry._lock",
     "scheduler.MicroBatchScheduler._cv"),
)

#: variant lifecycle states (strings on purpose: they go straight into
#: health() JSON and metrics.jsonl events)
MODEL_LOADING = "loading"
MODEL_CANARY = "canary"
MODEL_LIVE = "live"
MODEL_DRAINING = "draining"
MODEL_RETIRED = "retired"


class UnknownModel(KeyError):
    """``submit``/``deploy``/... named a model the registry doesn't
    hold (or omitted ``model=`` with more than one registered)."""


class DeployError(RuntimeError):
    """A canary deploy failed to build (bad weights, uncompilable
    arch, an injected ``registry.load`` fault). The partial variant
    was discarded — live traffic never routed to it — and no canary
    is left behind; fix the artifact and deploy again."""


class RolloutInProgress(RuntimeError):
    """``deploy`` while the model already has a canary: one rollout at
    a time per model — promote or roll back the current one first."""


def canary_hash_fraction(model: str, token) -> float:
    """Deterministic routing hash in [0, 1): a request routes to the
    model's canary iff this is < the deploy's ``canary_fraction``.
    sha256 over ``model:token`` — stable across processes, replicas
    and restarts (no RNG, no state), so the SAME request key always
    lands on the same side of the split and a sticky token (a session
    id) pins a whole stream to one variant."""
    digest = hashlib.sha256(f"{model}:{token}".encode()).digest()
    return int.from_bytes(digest[:8], "big") / 2.0 ** 64


class _Variant:
    """One deployed weight version: engine + scheduler + lifecycle."""

    __slots__ = ("engine", "scheduler", "version", "state", "config",
                 "same_arch", "final_snapshot")

    def __init__(self, engine: RAFTEngine, scheduler: MicroBatchScheduler,
                 version: str, config: RAFTConfig, state: str,
                 same_arch: bool = False):
        self.engine = engine
        self.scheduler = scheduler
        self.version = version
        self.config = config
        self.state = state
        #: canary only: True when the live engine can absorb these
        #: weights via update_weights (promote reuses its executables)
        self.same_arch = same_arch
        #: metrics snapshot frozen at retire time, so per-model
        #: accounting stays auditable after the scheduler is gone
        self.final_snapshot: Optional[Dict] = None


class _Model:
    """One named model family: the live variant, at most one canary,
    and the retired history."""

    __slots__ = ("name", "live", "canary", "canary_fraction", "counter",
                 "deploys", "retired")

    def __init__(self, name: str, live: _Variant):
        self.name = name
        self.live = live
        self.canary: Optional[_Variant] = None
        self.canary_fraction = 0.0
        self.counter = 0      # default route-token source
        self.deploys = 1      # version auto-numbering
        self.retired: List[_Variant] = []


class ModelRegistry:
    """Named model variants over the scheduler/engine stack.

    ``metrics_path``: one shared metrics.jsonl — every variant's
    snapshots and events land there stamped with its model namespace,
    plus the registry's own rollout events (``model_deploy`` /
    ``model_promote`` / ``model_rollback`` / ``model_state``).

    ``scheduler_defaults``: kwargs applied to every variant's
    ``MicroBatchScheduler`` (per-model overrides via ``add_model``).

    ``admission_budget``: registry-wide overload control — a shared
    :class:`~raft_tpu.serving.guardian.AdmissionBudget` of this many
    tokens gates ``submit()`` across ALL models before the per-variant
    queues (one token per admitted request, released when its future
    settles). Exhaustion fails fast with ``BackpressureError``,
    counted per model as ``admission_rejected``; the last
    ``admission_interactive_reserve`` tokens (default capacity/4) are
    interactive-only, so one model's batch flood can no longer
    monopolize the aggregate queue capacity another model's
    interactive traffic needs. None (default) = no gate, bitwise the
    historical submit path.
    """

    #: duck-type marker (VideoSession and other layers route on it
    #: without importing this module)
    is_registry = True

    def __init__(self, *, metrics_path: Optional[str] = None,
                 admission_budget: Optional[int] = None,
                 admission_interactive_reserve: Optional[int] = None,
                 trace_path: Optional[str] = None,
                 trace_sample: float = 1.0,
                 **scheduler_defaults):
        """``trace_path`` arms request-scoped tracing registry-wide:
        ONE shared :class:`~raft_tpu.serving.trace.TraceLedger` writes
        every variant's spans to one ``spans.jsonl`` (ids unique
        across models), every variant scheduler gets it as its
        ``tracer``, and ``submit``/``submit_cached`` stamp each span
        with the routing decision (model, variant version, canary
        assignment) the scheduler below can't see. ``trace_sample``
        is the ledger's keep fraction (tail exemplars and failures
        are always kept). Default None: no ledger, bitwise the
        untraced registry."""
        self._lock = threading.RLock()
        self._models: Dict[str, _Model] = {}
        self._metrics_path = metrics_path
        self._sched_defaults = scheduler_defaults
        self._events = ServingMetrics(metrics_path, namespace="registry")
        self._budget = (AdmissionBudget(admission_budget,
                                        admission_interactive_reserve)
                        if admission_budget else None)
        #: shared request-tracing ledger (None = tracing off); public
        #: so sessions chain parents through the registry duck-typed,
        #: like they do off a plain scheduler
        self.tracer = (TraceLedger(trace_path,
                                   sample_rate=trace_sample)
                       if trace_path is not None else None)
        self._closed = False

    @property
    def metrics_path(self) -> Optional[str]:
        """The shared metrics.jsonl destination (None = not writing) —
        the surface attendant layers (the SLO guardian) append their
        own events to."""
        return self._metrics_path

    def admission_snapshot(self) -> Optional[Dict]:
        """The shared admission budget's state (None when no budget is
        configured): capacity, reserve, in-use tokens, per-class
        admitted/rejected counts."""
        return (self._budget.snapshot() if self._budget is not None
                else None)

    # -- internals ---------------------------------------------------------

    def _model(self, name: Optional[str]) -> _Model:
        with self._lock:
            if name is None:
                if len(self._models) != 1:
                    raise UnknownModel(
                        "model= is required with "
                        f"{len(self._models)} models registered")
                return next(iter(self._models.values()))
            m = self._models.get(name)
            if m is None:
                raise UnknownModel(
                    f"unknown model {name!r} (registered: "
                    f"{sorted(self._models)})")
            return m

    def _set_state(self, name: str, variant: _Variant, new: str) -> None:
        old, variant.state = variant.state, new
        self._events.record_event("model_state", model=name,
                                  version=variant.version,
                                  state=new, previous=old)

    def _build_variant(self, name: str, variables, config: RAFTConfig,
                       version: str, *, iters: int, envelope,
                       engine_kw: Dict, sched_kw: Dict,
                       engine: Optional[RAFTEngine],
                       same_arch: bool = False) -> _Variant:
        """Build one variant's engine + scheduler (state ``loading``).
        The ``registry.load`` fault site fires before the build — the
        chaos harness's stand-in for a bad checkpoint read, an
        uncompilable arch, an OOM'd weight upload."""
        fault_point("registry.load")
        if engine is None:
            engine = RAFTEngine(variables, config, iters=iters,
                                envelope=envelope, precompile=True,
                                **engine_kw)
        ns = f"{name}@{version}"
        metrics = ServingMetrics(self._metrics_path, namespace=ns)
        merged = {**self._sched_defaults, **sched_kw}
        if self.tracer is not None:
            # every variant shares the registry's ledger: one
            # spans.jsonl, registry-unique trace ids, rollout-proof
            # session chains
            merged.setdefault("tracer", self.tracer)
        if getattr(engine, "feature_cache", False):
            # a feature-cache engine gets a feature-cache scheduler:
            # the per-variant pool is what the rollout brooms flush
            merged.setdefault("feature_cache", True)
        sched = MicroBatchScheduler(
            engine, metrics=metrics, namespace=ns, **merged)
        return _Variant(engine, sched, version, config, MODEL_LOADING,
                        same_arch=same_arch)

    def _drain(self, name: str, variant: _Variant) -> None:
        """draining -> retired: settle every accepted future (zero
        stranded — ``close(drain=True)`` is the guarantee), freeze the
        final metrics snapshot for the per-model accounting audit."""
        self._set_state(name, variant, MODEL_DRAINING)
        variant.scheduler.close(drain=True)
        variant.final_snapshot = variant.scheduler.metrics.snapshot(
            executables=len(variant.engine._compiled))
        self._set_state(name, variant, MODEL_RETIRED)
        self._retire_artifacts(name, variant)

    def _retire_artifacts(self, name: str, variant: _Variant) -> None:
        """AOT-store hygiene for a retired variant: evict its
        serialized executables from the artifact cache UNLESS a
        surviving variant (any model's live or canary) still serves
        the same weights fingerprint — a rolled-back canary's blobs go,
        the live engine's stay, and a shared-fingerprint re-deploy
        keeps its warm path. Best-effort: a GC failure never fails the
        rollout that triggered it."""
        aot = getattr(variant.engine, "_aot", None)
        fp = getattr(variant.engine, "_weights_fp", None)
        if aot is None or fp is None or not hasattr(aot, "evict"):
            return
        with self._lock:
            survivors = {
                getattr(v.engine, "_weights_fp", None)
                for m in self._models.values()
                for v in (m.live, m.canary) if v is not None
                and v is not variant}
        if fp in survivors:
            return
        try:
            gone = aot.evict(weights=fp)
        except Exception:  # noqa: BLE001 — GC must not fail a rollout
            return
        if gone.get("removed"):
            self._events.record_event(
                "aot_evicted", model=name, version=variant.version,
                removed=gone["removed"],
                removed_bytes=gone["removed_bytes"])

    # -- lifecycle ---------------------------------------------------------

    def add_model(self, name: str, variables,
                  config: Optional[RAFTConfig] = None, *,
                  iters: int = ITERS_EXPORT, envelope=(),
                  version: str = "v1",
                  engine: Optional[RAFTEngine] = None,
                  warm_start: bool = False, wire: str = "f32",
                  exact_shapes: bool = False,
                  feature_cache: bool = False,
                  artifact_dir: Optional[str] = None,
                  **sched_kw) -> None:
        """Register a model family; the first version goes straight
        live (``loading -> live``). ``engine=`` injects a prebuilt
        engine (drills share compiles across rounds); otherwise one is
        built from ``variables``/``config`` and precompiled over
        ``envelope``. ``artifact_dir=`` points the engine at a
        serialized-executable cache (serving/aot.py): a replica
        starting against a warm dir LOADS its envelope instead of
        compiling it — the fleet-rollout compile storm becomes one
        compile, N loads. Extra kwargs reach the variant's
        scheduler."""
        with self._lock:
            if self._closed:
                raise SchedulerClosed("registry is closed")
            if name in self._models:
                raise ValueError(
                    f"model {name!r} already registered — new weights "
                    "roll out via deploy(), not a second add_model()")
        variant = self._build_variant(
            name, variables, config or RAFTConfig(), version,
            iters=iters, envelope=envelope,
            engine_kw=dict(warm_start=warm_start, wire=wire,
                           exact_shapes=exact_shapes,
                           feature_cache=feature_cache,
                           aot_cache=artifact_dir),
            sched_kw=sched_kw, engine=engine)
        with self._lock:
            # re-checked at publish: the build ran outside the lock
            # (compiles take seconds), and a racing duplicate
            # add_model or close() must not orphan a running
            # scheduler or overwrite a published variant
            conflict = ("registry is closed" if self._closed
                        else f"model {name!r} already registered"
                        if name in self._models else None)
            if conflict is None:
                self._models[name] = _Model(name, variant)
        if conflict is not None:
            variant.scheduler.close(drain=False)
            if self._closed:
                raise SchedulerClosed(conflict)
            raise ValueError(conflict + " — new weights roll out via "
                                        "deploy(), not a second "
                                        "add_model()")
        self._set_state(name, variant, MODEL_LIVE)

    def deploy(self, name: str, variables,
               config: Optional[RAFTConfig] = None, *,
               canary_fraction: float = 0.25,
               version: Optional[str] = None,
               iters: Optional[int] = None, envelope=None,
               engine: Optional[RAFTEngine] = None,
               artifact_dir: Optional[str] = None,
               **sched_kw) -> str:
        """Roll out new weights (same arch) or a new arch for
        ``name`` as a canary serving ``canary_fraction`` of the
        model's traffic. Returns the canary's version string.

        The canary gets its OWN engine (even same-arch: its buckets
        compile at deploy time, so a broken artifact fails HERE — with
        auto-rollback — never under live traffic) defaulting to the
        live engine's bucket envelope and wire/warm-start recipe.
        ``promote()`` then reuses the live executables for a same-arch
        canary via ``update_weights``. ``artifact_dir=`` threads a
        serialized-executable cache (serving/aot.py) into the canary
        engine: a restarting supervisor re-deploying known weights
        loads the canary envelope instead of recompiling it (keys are
        weights-content addressed, so a genuinely NEW checkpoint still
        compiles — and serializes for the replicas that follow)."""
        if not 0.0 < canary_fraction <= 1.0:
            raise ValueError(
                f"canary_fraction={canary_fraction}: must be in (0, 1]")
        m = self._model(name)
        with self._lock:
            if self._closed:
                raise SchedulerClosed("registry is closed")
            if m.canary is not None:
                raise RolloutInProgress(
                    f"model {name!r} already has canary "
                    f"{m.canary.version!r} at "
                    f"{m.canary_fraction:.0%} — promote() or "
                    "rollback() first")
            live = m.live
            m.deploys += 1
            version = version or f"v{m.deploys}"
        cfg = config if config is not None else live.config
        # same-arch probe (getattr: drills run duck-typed engines that
        # can't judge weight trees — those promote as engine swaps)
        compat = getattr(live.engine, "compatible_weights", None)
        same_arch = (cfg == live.config and compat is not None
                     and compat(variables))
        shapes = getattr(live.engine, "bucket_shapes",
                         lambda: sorted(live.engine._compiled))
        # fleet-proportional canary: when the live variant runs N
        # replica lanes, the canary defaults to its traffic share of
        # the fleet (fraction * N, floor 1) — a 25% canary over a
        # 4-lane live gets 1 lane, not 4 idle engines' worth of
        # compiles. Explicit replicas= in sched_kw wins.
        live_fleet = len(getattr(live.scheduler, "_lanes", ()) or ())
        if live_fleet > 1:
            sched_kw.setdefault(
                "replicas",
                max(1, round(canary_fraction * live_fleet)))
        try:
            variant = self._build_variant(
                name, variables, cfg, version,
                iters=(iters if iters is not None
                       else getattr(live.engine, "iters", ITERS_EXPORT)),
                envelope=(envelope if envelope is not None else shapes()),
                engine_kw=dict(
                    warm_start=getattr(live.engine, "warm_start", False),
                    wire=getattr(live.engine, "wire", "f32"),
                    exact_shapes=getattr(live.engine, "exact_shapes",
                                         False),
                    feature_cache=getattr(live.engine, "feature_cache",
                                          False),
                    aot_cache=artifact_dir),
                sched_kw=sched_kw, engine=engine, same_arch=same_arch)
        except Exception as exc:
            # auto-rollback: nothing was routed, nothing is left. The
            # failed build never touched the live variant — its
            # engine, scheduler and traffic are exactly as before.
            self._events.record_event(
                "model_deploy_failed", model=name, version=version,
                error=f"{type(exc).__name__}: {exc}")
            raise DeployError(
                f"canary deploy {name!r} {version!r} failed to build "
                "(auto-rolled-back; live traffic untouched): "
                f"{exc}") from exc
        with self._lock:
            # publish atomically (fraction + variant appear together),
            # re-checking the one-rollout/open invariants: the build
            # ran outside the lock, and a racing deploy or close()
            # must not let this variant overwrite a published canary
            # (orphaning its dispatcher thread) or land after drain
            conflict = ("registry is closed" if self._closed
                        else f"model {name!r} already has canary "
                             f"{m.canary.version!r}"
                        if m.canary is not None else None)
            if conflict is None:
                m.canary = variant
                m.canary_fraction = float(canary_fraction)
        if conflict is not None:
            variant.scheduler.close(drain=False)
            if self._closed:
                raise SchedulerClosed(conflict)
            raise RolloutInProgress(
                conflict + " — promote() or rollback() first")
        self._set_state(name, variant, MODEL_CANARY)
        self._events.record_event(
            "model_deploy", model=name, version=version,
            canary_fraction=float(canary_fraction),
            same_arch=same_arch)
        return version

    def promote(self, name: Optional[str] = None) -> Dict:
        """Make the canary the live version. Same-arch: the live
        engine absorbs the canary's weights via ``update_weights`` —
        every compiled bucket is reused (no compile storm) and the
        canary's duplicate engine retires. New arch: the canary
        variant (engine + scheduler) BECOMES live under the registry
        lock and the old live drains. Both paths stop canary routing
        before any drain, so zero futures strand and no request ever
        routes into a closing scheduler (a racer that does is
        re-routed to live by ``submit``)."""
        m = self._model(name)
        with self._lock:
            canary = m.canary
            if canary is None:
                raise RolloutInProgress(
                    f"model {m.name!r} has no canary to promote")
            # routing off FIRST: from here every submit sees live only
            m.canary = None
            m.canary_fraction = 0.0
            live = m.live
        if canary.same_arch:
            # weight swap through the live SCHEDULER: atomic wrt
            # in-flight dispatches (the engine snapshots its tree per
            # dispatch), executables reused — and when the live variant
            # runs a replica fleet, swap_weights applies the new tree
            # to every lane under one quiesced epoch (all-or-nothing:
            # a lane that fails mid-swap rolls the already-swapped
            # lanes back, so the fleet is never half-rolled)
            swap = getattr(live.scheduler, "swap_weights", None)
            if swap is not None:
                swap(canary.engine.variables)
            else:
                live.engine.update_weights(canary.engine.variables)
            # feature-cache broom: every slot in the live pool was
            # computed by the OLD weights — stale canary-era features
            # must never feed the promoted model (streams re-prime;
            # the engine's weights-version stamp backstops the racing
            # in-flight window)
            flush = getattr(live.scheduler, "flush_feature_cache", None)
            if flush is not None:
                flush("promote", model=m.name, version=canary.version)
            live.version = canary.version
            self._drain(m.name, canary)
            m.retired.append(canary)
            mode = "weights_swap"
        else:
            with self._lock:
                m.live = canary
            self._set_state(m.name, canary, MODEL_LIVE)
            self._drain(m.name, live)
            m.retired.append(live)
            mode = "engine_swap"
        self._events.record_event("model_promote", model=m.name,
                                  version=canary.version, mode=mode)
        return {"model": m.name, "version": canary.version, "mode": mode}

    def rollback(self, name: Optional[str] = None) -> Dict:
        """Abort the rollout: stop canary routing (live takes 100%
        again), then drain the canary — every future it accepted
        settles (zero stranded), racing submits re-route to live."""
        m = self._model(name)
        with self._lock:
            canary = m.canary
            if canary is None:
                raise RolloutInProgress(
                    f"model {m.name!r} has no canary to roll back")
            m.canary = None
            m.canary_fraction = 0.0
        # the canary's pool dies with it, but flush explicitly (and
        # stamped) BEFORE the drain: its slots hold canary-weight
        # features no surviving variant may ever correlate against,
        # and the cache_flush event is the rollback drill's evidence
        flush = getattr(canary.scheduler, "flush_feature_cache", None)
        if flush is not None:
            flush("rollback", model=m.name, version=canary.version)
        self._drain(m.name, canary)
        m.retired.append(canary)
        self._events.record_event("model_rollback", model=m.name,
                                  version=canary.version)
        return {"model": m.name, "version": canary.version}

    # -- traffic -----------------------------------------------------------

    def routes_to_canary(self, name: str, token) -> bool:
        """Would a request carrying ``token`` serve from ``name``'s
        canary right now? (The test/ops predicate for the
        deterministic split — pure function of token + fraction.)"""
        m = self._model(name)
        with self._lock:
            if m.canary is None:
                return False
            frac = m.canary_fraction
        return canary_hash_fraction(m.name, token) < frac

    def _routed_variant(self, m: _Model, route_key) -> _Variant:
        """The variant a ``route_key`` request routes to right now —
        the single read-only form of the canary-hash decision
        (``variant_version`` and ``invalidate_stream`` share it; the
        submit path's ``_route_and_admit`` fuses the same expression
        with its counter-bump atom)."""
        with self._lock:
            canary = m.canary
            if (canary is not None and route_key is not None
                    and canary_hash_fraction(m.name, route_key)
                    < m.canary_fraction):
                return canary
            return m.live

    def variant_version(self, name: Optional[str] = None,
                        route_key=None) -> str:
        """Version string of the variant a ``route_key`` request would
        serve from right now. Recurrence holders (``VideoSession``)
        poll this before each warm submit and cold-restart when it
        changes: a rollout event (deploy/promote/rollback) must never
        let warm-start state produced by one variant feed another
        model's refinement."""
        return self._routed_variant(self._model(name),
                                    route_key).version

    def submit(self, image1, image2, *, model: Optional[str] = None,
               priority: Optional[str] = None, route_key=None, **kw):
        """Route one frame pair to ``model``'s live or canary variant
        and enqueue it there; returns the scheduler Future.

        ``route_key`` is the canary-routing token — pass a session or
        user id for sticky assignment (one stream, one variant);
        default is a per-model submit counter (each request hashes
        independently, converging on the deploy's fraction).
        ``priority`` is the scheduler's class knob, applied per model.
        Remaining kwargs are the scheduler's (deadline_s, flow_init,
        want_low, low_device)."""
        return self._route_and_admit(
            model, priority, route_key,
            lambda sched: sched.submit(image1, image2,
                                       priority=priority, **kw))

    def submit_cached(self, frame, *, model: Optional[str] = None,
                      priority: Optional[str] = None, route_key=None,
                      **kw):
        """Feature-cache form of :meth:`submit`: route ONE frame of a
        video stream to ``model``'s live or canary variant and enqueue
        it on that variant's ``MicroBatchScheduler.submit_cached``
        (``stream``/``seq``/``prime`` ride in ``kw``). Same
        deterministic canary hash, same admission budget, same
        re-route-on-drain contract — note a re-routed stream's next
        pair misses on the new variant's pool and cleanly re-primes
        (the session's cold-restart path)."""
        return self._route_and_admit(
            model, priority, route_key,
            lambda sched: sched.submit_cached(frame,
                                              priority=priority,
                                              **kw))

    def invalidate_stream(self, stream, *, model: Optional[str] = None,
                          route_key=None) -> bool:
        """End-of-stream hygiene for feature-cache sessions: drop the
        stream's slot from the variant its ``route_key`` currently
        routes to (if a rollout moved the stream since it last served,
        the old variant's pool was flushed or retired with it)."""
        target = self._routed_variant(self._model(model), route_key)
        inv = getattr(target.scheduler, "invalidate_stream", None)
        return inv(stream) if inv is not None else False

    def _route_and_admit(self, model: Optional[str],
                         priority: Optional[str], route_key, call):
        """The shared intake skeleton behind ``submit`` and
        ``submit_cached``: pick the variant (deterministic canary
        hash over the route token), pass the registry-wide admission
        gate, run ``call`` against the chosen scheduler with the
        re-route-on-drain guard, and tie the admission token to the
        future's settlement."""
        m = self._model(model)
        with self._lock:
            if self._closed:
                raise SchedulerClosed("registry is closed")
            canary = m.canary
            if route_key is None:
                route_key = m.counter
                m.counter += 1
            to_canary = (canary is not None
                         and canary_hash_fraction(m.name, route_key)
                         < m.canary_fraction)
            target = canary if to_canary else m.live
        if self._budget is not None \
                and not self._budget.try_acquire(priority):
            # registry-wide admission gate, BEFORE the per-variant
            # queue: the whole registry is over budget — shed here so
            # one model's flood can't convert another model's queue
            # headroom into its own backlog
            target.scheduler.metrics.record_admission_rejected(priority)
            raise BackpressureError(
                f"registry admission budget exhausted "
                f"({self._budget.capacity} requests in flight across "
                "models) — shedding new work; retry with backoff")
        try:
            fut = self._submit_variant(m, target, call)
        except BaseException:
            if self._budget is not None:
                self._budget.release()   # nothing was admitted
            raise
        if self._budget is not None:
            fut.add_done_callback(lambda _f: self._budget.release())
        return fut

    def _trace_stamp(self, m: _Model, target: _Variant) -> None:
        """Stamp the routing decision onto the span the next submit on
        THIS thread mints (trace.py's thread-local intake context) —
        the model/variant/canary assignment only the registry knows."""
        if self.tracer is not None:
            self.tracer.stamp_intake(
                model=m.name, variant=target.version,
                canary=target.state == MODEL_CANARY)

    def _submit_variant(self, m: _Model, target: _Variant, call):
        try:
            self._trace_stamp(m, target)
            try:
                return call(target.scheduler)
            except SchedulerClosed:
                # raced a promote/rollback into a draining variant (the
                # canary, or the old live of a new-arch promote): the
                # rollout machinery must be invisible — re-route to the
                # CURRENT live. If the registry itself is closing, the
                # live scheduler is closed too and the error propagates.
                with self._lock:
                    live = m.live
                if live is target:
                    raise
                self._trace_stamp(m, live)
                return call(live.scheduler)
        finally:
            if self.tracer is not None:
                # a rejected submit must not leak its stamp into an
                # unrelated later span on this thread
                self.tracer.clear_intake()

    def update_weights(self, variables, model: Optional[str] = None
                       ) -> None:
        """Direct live weight swap (the single-model API, per model) —
        for rollouts WITH a bake period use deploy()/promote(). Routed
        through the variant's scheduler so an armed feature cache
        flushes with the swap."""
        m = self._model(model)
        live = m.live
        live.scheduler.update_weights(variables)

    # -- observability -----------------------------------------------------

    def models(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def health(self) -> Dict:
        """Per-model operator surface: live + canary variant health
        (each is the scheduler's full health block), rollout state."""
        with self._lock:
            items = [(m.name, m.live, m.canary, m.canary_fraction)
                     for m in self._models.values()]
        out = {}
        for name, live, canary, frac in sorted(items):
            out[name] = {
                "live": {"version": live.version, "state": live.state,
                         "health": live.scheduler.health()},
                "canary": None if canary is None else {
                    "version": canary.version, "state": canary.state,
                    "fraction": frac,
                    "health": canary.scheduler.health()},
            }
        return out

    def snapshot(self) -> Dict:
        """Per-model metrics: every variant's full serving snapshot
        (live + canary + retired finals) plus the per-model accounting
        identity ``submitted == completed + failed + deadline_missed +
        cancelled`` summed across the model's variants — one rollout
        must never lose a request."""
        with self._lock:
            items = [(m.name, m.live, m.canary, list(m.retired))
                     for m in self._models.values()]
        out = {}
        for name, live, canary, retired in sorted(items):
            snaps = [live.scheduler.metrics.snapshot(
                executables=len(live.engine._compiled))]
            if canary is not None:
                snaps.append(canary.scheduler.metrics.snapshot(
                    executables=len(canary.engine._compiled)))
            snaps += [v.final_snapshot for v in retired
                      if v.final_snapshot is not None]
            totals = {k: sum(s.get(k, 0) for s in snaps)
                      for k in ("submitted", "completed", "failed",
                                "shed", "evicted", "admission_rejected",
                                "deadline_missed", "cancelled")}
            out[name] = {
                "live": snaps[0],
                "canary": (snaps[1] if canary is not None else None),
                "retired": [v.final_snapshot for v in retired
                            if v.final_snapshot is not None],
                "totals": totals,
                "accounting_ok": totals["submitted"] == (
                    totals["completed"] + totals["failed"]
                    + totals["deadline_missed"] + totals["cancelled"]),
            }
        return out

    def write_metrics(self) -> Dict:
        """Append every active variant's snapshot line to the shared
        metrics.jsonl (model-stamped); returns the registry snapshot."""
        with self._lock:
            variants = [v for m in self._models.values()
                        for v in (m.live, m.canary) if v is not None]
        if self._metrics_path:
            for v in variants:
                v.scheduler.write_metrics()
        return self.snapshot()

    # -- shutdown ----------------------------------------------------------

    def close(self, drain: bool = True, timeout: float = 120.0) -> None:
        """Close every variant's scheduler (canaries first — their
        racers re-route to a live scheduler that is still open).
        Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            models = list(self._models.values())
        for m in models:
            with self._lock:
                canary, m.canary = m.canary, None
                m.canary_fraction = 0.0
            if canary is not None:
                canary.scheduler.close(drain=drain, timeout=timeout)
                canary.final_snapshot = canary.scheduler.metrics.snapshot(
                    executables=len(canary.engine._compiled))
                self._set_state(m.name, canary, MODEL_RETIRED)
                m.retired.append(canary)
        for m in models:
            m.live.scheduler.close(drain=drain, timeout=timeout)
        self._events.record_event("registry_closed",
                                  models=[m.name for m in models])

    def __enter__(self) -> "ModelRegistry":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close(drain=exc_type is None)
