"""The one blessed Future-settle idiom for the serving stack.

Every settle in a serving stack races something: the caller's
``cancel()``, a wedge verdict failing the batch from the supervision
loop, a deadline sweep, a no-drain close. ``Future.set_result`` /
``set_exception`` raise ``InvalidStateError`` when the other side of
the race got there first — and an unguarded settle then kills whatever
thread ran it (the ``_expire``-vs-cancel race PR 7 caught by hand
would have taken down the dispatcher from the supervision-loop
sweep). Before this module the guard was a copy-pasted
``try/except InvalidStateError`` at every site; now it is ONE helper,
and the graftthread T2 rule fails any raw settle outside it.

Returning whether the settle WON the race is the load-bearing part:
per-future accounting (``submitted == completed + failed +
deadline_missed + cancelled``) stays exact because every site counts
its outcome from the return value instead of double-counting a future
some other path already settled.
"""

from __future__ import annotations

from concurrent.futures import Future, InvalidStateError
from typing import Callable, Optional, Union

# graftthread: this module DEFINES the blessed raw-settle site (T2)
GRAFTTHREAD = {"settle_helper": True}


def settle_future(fut: Future,
                  result_or_exc: Union[BaseException, object],
                  raced: Optional[Callable[[], None]] = None) -> bool:
    """Settle ``fut`` with a result, or — when ``result_or_exc`` is an
    exception INSTANCE — fail it. Returns True when this call actually
    settled the future; False when a concurrent settle/cancel won the
    race (``raced``, if given, is invoked exactly then — the hook for
    per-future accounting, e.g. ``metrics.record_cancelled``).

    Never raises ``InvalidStateError``: losing a settle race is a
    counted outcome here, not a thread-killing surprise.
    """
    try:
        if isinstance(result_or_exc, BaseException):
            fut.set_exception(result_or_exc)
        else:
            fut.set_result(result_or_exc)
    except InvalidStateError:
        if raced is not None:
            raced()
        return False
    return True
