"""Request-scoped tracing: a span ledger across the serving stack.

The serving metrics (serving/metrics.py) are *aggregates* — latency
histograms, counters, breaker/guardian events — so when p99 spikes
there is no way to say WHICH request was slow or WHY (queue wait?
cross-shape coalescing? cache-miss re-prime? pipeline slot wait? wedge
collateral?). This module is the per-request causality layer: every
ACCEPTED request gets a trace id minted at intake, its **span**
records phase timestamps (enqueue → micro-batch assembly → dispatch →
device fetch → settle) plus structured annotations from every layer
it crosses — coalesce fan-in (one *dispatch span* linked to the N
request spans it carried, with bucket/capacity-class key and
padding-waste share), feature-cache hit/miss/prime, breaker state at
admit, wedge/deadline/shed/eviction outcome, and session chaining
(frame N's span links frame N−1's, so a warm-start recurrence is a
walkable chain) — the per-request attribution Ragged Paged Attention
(arXiv 2604.15464) applies to padded-vs-real work, lifted to the
whole request lifecycle.

Spans append to ``spans.jsonl`` (one JSON object per line, beside
metrics.jsonl) under a **sampling knob with always-keep-tail exemplar
capture**: ``sample_rate`` drops the bulk deterministically (sha256
of the trace id — no RNG, reproducible), but a request landing in a
top latency-histogram bucket (``tail=True`` — ServingMetrics flags it
at completion) is retained regardless, and so is every non-completed
outcome (failures ARE the forensic targets). ``raft_tpu.cli.
serve_trace`` reconstructs a trace's timeline and answers "where did
the p99 go" with a phase-attribution table over the exemplars.

Exactly-once closure is the contract the chaos drill pins: every span
opened for an accepted request closes exactly once, with an outcome
tag whose accounting **class** (``completed`` | ``failed`` |
``deadline_missed`` | ``cancelled``) matches the counter the request
landed in — spans and the accounting identity reconcile
bucket-for-bucket. Closure races (a wedge verdict vs the completion
stage) are settled by whoever won the FUTURE (serving/futures.py);
``close`` is additionally idempotent so a linked dispatch span may be
closed from every path that could orphan it.

I/O discipline: ``close`` never writes — records buffer under the
ledger's leaf lock (pure list append) and :meth:`flush` does the file
I/O with NO lock held (the T1 rule), called from the scheduler's
dispatcher loop, the completion stage, and ``close()`` — spans.jsonl
is eventually consistent while serving and complete after a drain.

Deliberately jax-free. Tracing defaults OFF everywhere (no ledger
constructed ⇒ every serving path is bitwise the PR-13 stack — the
standing knob convention).
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional

#: graftthread T3: the ledger lock is a LEAF — span opens/closes arrive
#: from under the scheduler's queue lock (``_cv``, the deadline sweep)
#: and the metrics lock's callers, so taking any other serving lock in
#: here would invert the declared order. Span writes never settle
#: futures or fire listeners under it; file I/O happens in ``flush``
#: with NO lock held (T1).
LOCK_ORDER = (("trace.TraceLedger._lock",),)

#: graftthread declarations: one leaf lock, no callbacks, no threads,
#: no futures — every method is dict/list bookkeeping under ``_lock``
#: except ``flush``'s lock-free file append.
GRAFTTHREAD = {"locks": ("_lock",)}

#: accounting-identity classes a request span may close under — the
#: four counters of submitted == completed + failed + deadline_missed
#: + cancelled (serving/metrics.py)
SPAN_CLASSES = ("completed", "failed", "deadline_missed", "cancelled")

#: phase marks a request span may carry (ms offsets from enqueue):
#: ``taken`` — popped into a micro-batch (assembly begins), ``shipped``
#: — the async device call was issued, ``fetch_start`` — the blocking
#: D2H fetch began (the pipelined completion stage's clock)
SPAN_MARKS = ("taken", "shipped", "fetch_start")


def sample_fraction(trace_id: str) -> float:
    """Deterministic sampling hash in [0, 1): a span is sampled in iff
    this is < the ledger's ``sample_rate``. sha256 over the trace id —
    stable across processes and re-runs (no RNG, no state), the same
    discipline as the registry's canary hash."""
    digest = hashlib.sha256(trace_id.encode()).digest()
    return int.from_bytes(digest[:8], "big") / 2.0 ** 64


class _Span:
    """One open span: identity + start clock + marks + fields.

    ``marks`` are monotonic ms offsets from ``t0``; ``fields`` are the
    static annotations (bucket label, model/variant/canary stamp,
    stream/seq, breaker state at admit, ...). ``linked`` is the
    request span's dispatch span (closed together on failure paths so
    a wedged batch can never orphan its dispatch record);
    ``child_kept`` on a dispatch span records that at least one linked
    request span was written — a dispatch span with no written
    children is dropped (its refs would dangle)."""

    __slots__ = ("trace_id", "span", "t0", "wall0", "marks", "fields",
                 "closed", "linked", "child_kept")

    def __init__(self, trace_id: str, span: str, t0: float,
                 wall0: float, fields: Dict):
        self.trace_id = trace_id
        self.span = span            # "request" | "dispatch"
        self.t0 = t0
        self.wall0 = wall0
        self.marks: Dict[str, float] = {}
        self.fields = fields
        self.closed = False
        self.linked: Optional["_Span"] = None
        self.child_kept = False


def _phases(marks: Dict[str, float], total_ms: float) -> Dict[str, float]:
    """Phase durations from a span's marks: queue (enqueue→taken),
    assembly (taken→shipped), device (shipped→fetch_start — the async
    in-flight window; ~0 on the unpipelined path where fetch follows
    the ship immediately), fetch (fetch_start→settle). Absent marks
    collapse into the preceding phase — a span failed while queued is
    100% queue."""
    taken = marks.get("taken")
    shipped = marks.get("shipped")
    fstart = marks.get("fetch_start")
    ph = {"queue_ms": taken if taken is not None else total_ms}
    if taken is not None:
        ph["assembly_ms"] = (shipped if shipped is not None
                             else total_ms) - taken
    if shipped is not None:
        ph["device_ms"] = (fstart if fstart is not None
                           else total_ms) - shipped
    if fstart is not None:
        ph["fetch_ms"] = total_ms - fstart
    return {k: round(max(0.0, v), 3) for k, v in ph.items()}


class TraceLedger:
    """Thread-safe span ledger writing ``spans.jsonl``.

    ``path``: the jsonl destination (None: spans are tracked and
    counted but never written — the unit-test mode). ``sample_rate``
    in [0, 1]: deterministic keep fraction for completed request
    spans; tail exemplars and non-completed outcomes are ALWAYS kept.

    Intake context rides a thread-local, not an API change: the
    registry's ``_route_and_admit`` calls :meth:`stamp_intake` with
    the model/variant/canary assignment just before handing the
    request to the variant's scheduler (same thread), and a
    ``VideoSession`` calls :meth:`set_parent` with the previous
    frame's trace id — :meth:`begin` consumes both, so the scheduler's
    submit signature stays untouched.
    """

    def __init__(self, path: Optional[str] = None,
                 sample_rate: float = 1.0):
        if not 0.0 <= sample_rate <= 1.0:
            raise ValueError(
                f"sample_rate={sample_rate}: must be in [0, 1]")
        self.path = path
        self.sample_rate = float(sample_rate)
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._seq = 0
        self._open: Dict[str, _Span] = {}
        self._buffer: List[str] = []
        # counters (the ledger's own observability block)
        self.opened = 0
        self.closed = 0
        self.written = 0
        self.sampled_out = 0
        self.tail_kept = 0
        self.discarded = 0
        self.write_errors = 0

    # -- intake context (thread-local, consumed by begin) ------------------

    def stamp_intake(self, **fields) -> None:
        """Stamp routing context (model/variant/canary, ...) onto the
        NEXT span this thread opens — the registry's hook."""
        self._tls.intake = fields

    def clear_intake(self) -> None:
        """Drop any unconsumed intake stamp (the registry's finally —
        a submit rejected before the mint must not leak its stamp into
        an unrelated later span on the same thread)."""
        self._tls.intake = None
        self._tls.parent = None

    def set_parent(self, trace_id: Optional[str]) -> None:
        """Link the NEXT span this thread opens to ``trace_id`` — the
        session-chaining hook (frame N's span points at frame N−1's)."""
        self._tls.parent = trace_id

    # -- span lifecycle ----------------------------------------------------

    def begin(self, span: str = "request", **fields) -> _Span:
        """Open a span: mints the trace id (``r-``/``d-`` + counter),
        consumes this thread's intake stamp and parent link, registers
        it open (the orphan-detection set)."""
        intake = getattr(self._tls, "intake", None)
        parent = getattr(self._tls, "parent", None)
        self._tls.intake = None
        self._tls.parent = None
        if intake:
            fields = {**fields, **intake}
        if parent is not None and "parent" not in fields:
            fields["parent"] = parent
        with self._lock:
            self._seq += 1
            trace_id = f"{'d' if span == 'dispatch' else 'r'}-{self._seq}"
            s = _Span(trace_id, span, time.monotonic(), time.time(),
                      fields)
            self._open[trace_id] = s
            self.opened += 1
        return s

    def annotate(self, s: _Span, **fields) -> None:
        """Merge annotations into an open span (later layers: cache
        hit/miss, dispatch link, fan-in, ...)."""
        with self._lock:
            s.fields.update(fields)

    def mark(self, s: _Span, phase: str,
             at: Optional[float] = None) -> None:
        """Stamp a phase mark (monotonic ``at``, default now) as a ms
        offset from the span's open."""
        t = at if at is not None else time.monotonic()
        with self._lock:
            s.marks[phase] = (t - s.t0) * 1e3

    def discard(self, s: _Span) -> None:
        """Un-open a span that never became an accepted request (the
        enqueue raised backpressure/closed after the mint) — counted,
        never written; the zero-orphan invariant covers accepted
        requests only. A consumed parent link is RESTORED to the
        thread-local (discard runs on the minting thread): a
        rollout-raced registry submit that re-routes to live, or a
        session retry after backpressure, must not drop its frame out
        of the stream's trace chain."""
        with self._lock:
            if s.closed:
                return
            s.closed = True
            self._open.pop(s.trace_id, None)
            self.discarded += 1
        parent = s.fields.get("parent")
        if parent is not None:
            self._tls.parent = parent

    def close(self, s: _Span, outcome: str, cls: Optional[str] = None,
              tail: bool = False, **fields) -> bool:
        """Close a span exactly once (idempotent — a second close is a
        counted no-op returning False): compute phases, decide
        retention (class != completed, tail exemplar, or the
        deterministic sample), buffer the record. Returns whether the
        record was KEPT. Never does file I/O (see :meth:`flush`)."""
        t_close = time.monotonic()
        with self._lock:
            if s.closed:
                return False
            s.closed = True
            self._open.pop(s.trace_id, None)
            self.closed += 1
            if fields:
                s.fields.update(fields)
            total_ms = round((t_close - s.t0) * 1e3, 3)
            if s.span == "dispatch":
                keep = s.child_kept
            else:
                keep = (tail or (cls is not None and cls != "completed")
                        or sample_fraction(s.trace_id) < self.sample_rate)
            if tail:
                self.tail_kept += 1
            if not keep:
                # an unkept child never marks its dispatch span kept
                self.sampled_out += 1
                return False
            if s.linked is not None:
                s.linked.child_kept = True
            rec = {"kind": "span", "span": s.span,
                   "trace_id": s.trace_id, "time": s.wall0,
                   "outcome": outcome, "total_ms": total_ms,
                   "tail": bool(tail), **s.fields}
            if cls is not None:
                rec["class"] = cls
            if s.span == "request":
                rec["phases"] = _phases(s.marks, total_ms)
            self.written += 1
            if self.path is None:
                return True
            self._buffer.append(json.dumps(rec))
        return True

    # -- I/O + observability -----------------------------------------------

    def flush(self) -> int:
        """Append every buffered span record to ``path``; returns how
        many lines were written. File I/O runs with NO lock held (a
        slow disk must never stall a settle under the queue lock); a
        failed append is logged and swallowed — observability must
        never take down serving."""
        with self._lock:
            if not self._buffer or self.path is None:
                return 0
            lines, self._buffer = self._buffer, []
        try:
            parent = os.path.dirname(self.path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            with open(self.path, "a", encoding="utf-8") as fh:
                fh.write("\n".join(lines) + "\n")
        except OSError as exc:
            with self._lock:
                self.write_errors += 1
            print(f"[serve-trace] span append failed ({exc}) — "
                  "continuing", file=sys.stderr, flush=True)
            return 0
        return len(lines)

    def open_count(self) -> int:
        """How many spans are open right now — 0 after a drain, or
        there is an orphan (the chaos drill's invariant)."""
        with self._lock:
            return len(self._open)

    def open_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._open)

    def snapshot(self) -> Dict:
        """The ledger's counter block (rides the serve_bench summary
        when tracing is armed)."""
        with self._lock:
            return {"sample_rate": self.sample_rate,
                    "opened": self.opened, "closed": self.closed,
                    "open": len(self._open), "written": self.written,
                    "sampled_out": self.sampled_out,
                    "tail_kept": self.tail_kept,
                    "discarded": self.discarded,
                    "write_errors": self.write_errors,
                    "buffered": len(self._buffer)}
