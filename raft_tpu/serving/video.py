"""Batch pad/unpad + multi-flow video visualization.

The ``raft_trt_utils.py`` analog: functional stride-8 padding for engine
inputs (raft_trt_utils.py:8-21 — provided by ``raft_tpu.ops.padding``) and
the multi-flow AVI writer (raft_trt_utils.py:24-51). Keeps the fork's fixed
normalization radius so colors stay consistent across frames
(core/utils/flow_viz.py:128-130).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from raft_tpu.utils.flow_viz import flow_to_image


def optical_flow_visualize(flows: Sequence[np.ndarray],
                           output: str = "flow.avi",
                           fps: float = 30.0,
                           images: Optional[Sequence[np.ndarray]] = None
                           ) -> str:
    """Render flows (each (H, W, 2)) to an AVI; optionally stack each frame
    above its flow like the reference's side-by-side viz."""
    import cv2

    assert len(flows) > 0
    frames = []
    for i, flow in enumerate(flows):
        flo = flow_to_image(np.asarray(flow))
        if images is not None:
            img = np.asarray(images[i]).astype(np.uint8)
            flo = np.concatenate([img, flo], axis=0)
        frames.append(cv2.cvtColor(flo, cv2.COLOR_RGB2BGR))

    h, w = frames[0].shape[:2]
    writer = cv2.VideoWriter(output, cv2.VideoWriter_fourcc(*"MJPG"), fps,
                             (w, h))
    try:
        for f in frames:
            writer.write(f)
    finally:
        writer.release()
    return output
