"""Serving resilience primitives: dispatch watchdog + circuit breakers.

PR 3 made *training* survive wedges; this module is the serving
counterpart. The scheduler's dispatch path runs arbitrary device work
(XLA compiles, bucket executions) that can hang forever on a half-up
backend — the failure mode ``testing/faults`` models at the
``serve.request`` site. Python cannot kill a thread, so the recovery
discipline mirrors the PR-3 watchdog's exit-class discipline one level
down:

- :class:`DispatchExecutor` runs each dispatch on a supervised worker
  thread. The scheduler (the supervisor) waits on the job with a
  wall-clock deadline; on a wedge verdict it fails the batch's futures
  with :class:`DispatchWedged`, quarantines the stuck thread (daemon —
  it parks until its hang ends, then exits without touching the
  mailbox), spawns a replacement, and *accounts the leak* in metrics
  instead of pretending the thread died.
- :class:`CircuitBreaker` isolates failure per bucket (the natural
  unit of ragged multi-shape TPU serving: one poisoned shape must not
  take down the fleet of healthy shapes): closed -> open after K
  consecutive failures/wedges -> half-open probe after a jittered
  exponential backoff (``utils/retry.backoff_delays``, the shared
  transient-failure policy) -> closed again on a probe success.

Deliberately jax-free and engine-agnostic; the scheduler composes
these with the engine-recovery path (drop the suspect bucket's
executable, lazily recompile on the half-open probe).
"""

from __future__ import annotations

import itertools
import queue
import random
import threading
import time
from typing import Callable, List, Optional, Tuple

from raft_tpu.testing.faults import fault_point
from raft_tpu.utils.retry import backoff_delays

#: graftthread T3: both locks here are LEAVES — nothing is ever
#: acquired under them (the scheduler's chains name them as terminal
#: nodes; the breaker listener contract below is what keeps it so).
LOCK_ORDER = (
    ("resilience.CircuitBreaker._lock",),
    ("resilience.DispatchExecutor._lock",),
)

#: graftthread T4: transition listeners are caller-supplied code that
#: reads OTHER locked state (the scheduler's health recompute walks
#: the whole breaker board) — they fire via the _set/_notify split,
#: never inside the breaker lock.
GRAFTTHREAD = {"callbacks": ("_on_transition", "on_transition")}


class DispatchWedged(RuntimeError):
    """A dispatch exceeded ``dispatch_timeout_s``: the watchdog failed
    its futures, quarantined the stuck worker thread, and replaced it.
    The bucket is suspect — its compiled executable is dropped and the
    breaker (if armed) opens."""


class CircuitOpen(RuntimeError):
    """The request's bucket breaker is open: the bucket failed/wedged
    K consecutive times and is failing fast until the half-open probe
    succeeds. Healthy buckets keep serving; retry after backoff."""


#: breaker states — strings on purpose: they go straight into
#: ``health()`` JSON and metrics.jsonl events
BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"


class CircuitBreaker:
    """Per-bucket failure isolation: closed -> open -> half-open.

    ``failures``: consecutive failures/wedges that trip the breaker.
    Backoff between open and the half-open probe follows
    ``backoff_delays(base_s, max_s, jitter=jitter, rng=rng)`` — each
    failed probe re-opens with the next (longer) delay; a recovery
    (probe success -> closed) resets the series. ``clock`` is
    injectable for deterministic tests.

    ``on_transition(old, new)`` fires on every state change, *outside*
    the breaker lock (listeners append metrics events and recompute
    scheduler health — they must be free to read other breakers).

    ``label`` names the breaker on the health surface. The scheduler
    keys it ``model/HxW`` when it serves under a registry namespace
    and plain ``HxW`` single-model — per model+bucket, so one model's
    poisoned shape reads unambiguously on a board N models share.

    Probe discipline: this class does not ration probes itself — the
    scheduler's single dispatcher thread serializes dispatch, so at
    most one half-open probe is in flight by construction.
    """

    def __init__(self, failures: int = 3, base_s: float = 0.25,
                 max_s: float = 30.0, jitter: float = 0.5,
                 rng: Optional[random.Random] = None,
                 clock: Callable[[], float] = time.monotonic,
                 on_transition: Optional[Callable[[str, str], None]]
                 = None, label: Optional[str] = None):
        if failures < 1:
            raise ValueError(f"failures={failures}: must be >= 1")
        self.failures = int(failures)
        self.label = label
        self._clock = clock
        self._on_transition = on_transition
        self._mk_delays = lambda: backoff_delays(base_s, max_s,
                                                 jitter=jitter, rng=rng)
        self._delays = self._mk_delays()
        self._lock = threading.Lock()
        self._state = BREAKER_CLOSED
        self._retry_at: Optional[float] = None
        self.consecutive = 0   # consecutive failures since last success
        self.opens = 0         # cumulative closed/half-open -> open trips
        self.wedges = 0        # how many of the failures were wedges

    def _set(self, new: str) -> Optional[Tuple[str, str]]:
        """State write under the lock; returns the transition for the
        caller to notify AFTER releasing (listeners read other
        breakers — firing under the lock would deadlock a health
        recompute)."""
        old = self._state
        if old == new:
            return None
        self._state = new
        return (old, new)

    def _notify(self, fired: Optional[Tuple[str, str]]) -> None:
        if fired is not None and self._on_transition is not None:
            self._on_transition(*fired)

    def state(self) -> str:
        """Current state, promoting an expired ``open`` to
        ``half_open`` (fires the transition listener)."""
        with self._lock:
            fired = None
            if (self._state == BREAKER_OPEN
                    and self._clock() >= self._retry_at):
                fired = self._set(BREAKER_HALF_OPEN)
            st = self._state
        self._notify(fired)
        return st

    def peek(self) -> str:
        """State without side effects (health snapshots): an expired
        ``open`` reads as ``half_open`` but no transition fires."""
        with self._lock:
            if (self._state == BREAKER_OPEN
                    and self._clock() >= self._retry_at):
                return BREAKER_HALF_OPEN
            return self._state

    def record_failure(self, wedged: bool = False) -> None:
        with self._lock:
            self.consecutive += 1
            if wedged:
                self.wedges += 1
            fired = None
            if self._state == BREAKER_HALF_OPEN:
                # failed probe: back to open with the next, longer delay
                self.opens += 1
                self._retry_at = self._clock() + next(self._delays)
                fired = self._set(BREAKER_OPEN)
            elif (self._state == BREAKER_CLOSED
                    and self.consecutive >= self.failures):
                self.opens += 1
                self._delays = self._mk_delays()  # fresh series per trip
                self._retry_at = self._clock() + next(self._delays)
                fired = self._set(BREAKER_OPEN)
        self._notify(fired)

    def record_success(self) -> None:
        with self._lock:
            self.consecutive = 0
            fired = None
            if self._state != BREAKER_CLOSED:
                self._retry_at = None
                fired = self._set(BREAKER_CLOSED)
        self._notify(fired)

    def snapshot(self) -> dict:
        """Health-surface view of this breaker."""
        with self._lock:
            retry_in = None
            state = self._state
            if state == BREAKER_OPEN:
                retry_in = max(0.0, self._retry_at - self._clock())
                if retry_in == 0.0:
                    state = BREAKER_HALF_OPEN  # peek semantics
            snap = {"state": state,
                    "consecutive_failures": self.consecutive,
                    "opens": self.opens,
                    "wedges": self.wedges,
                    "retry_in_s": (round(retry_in, 3)
                                   if retry_in is not None else None)}
            if self.label is not None:
                snap["label"] = self.label
            return snap


class _DispatchJob:
    """One supervised dispatch (or, at ``pipeline_depth`` > 1, one
    pipelined completion). The executing thread fills ``bucket`` (the
    routed executable shape — the wedge verdict's drop target) and
    ``batch`` (the taken requests — the wedge verdict's futures to
    fail) as it goes; the supervisor sets ``abandoned`` at the verdict
    so a late-waking thread aborts instead of dispatching into a
    dropped bucket (which would compile a leaked duplicate).
    Completion jobs additionally carry ``key`` (the request shape, for
    the breaker board) and ``t_start`` (handoff time — the completion
    watchdog's clock)."""

    __slots__ = ("fn", "done", "error", "outcome", "bucket", "batch",
                 "abandoned", "key", "t_start", "cached", "ragged")

    def __init__(self, fn: Optional[Callable[["_DispatchJob"], None]]):
        self.fn = fn
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        self.outcome: Optional[str] = None   # "ok" | "failed" | None
        self.bucket: Optional[Tuple[int, int, int]] = None
        self.batch = None
        self.abandoned = False
        self.key: Optional[Tuple[int, int]] = None
        self.t_start: Optional[float] = None
        #: feature-cache dispatch: a wedge verdict must drop the
        #: CACHED executable for ``bucket``, not its plain sibling
        self.cached = False
        #: ragged capacity-class dispatch: the verdict's drop target
        #: is the RAGGED table's executable for ``bucket``
        self.ragged = False


class DispatchExecutor:
    """One supervised worker thread running dispatch jobs in order.

    Single-supervisor contract: ``submit``, ``quarantine_and_replace``
    and ``close`` are called from the scheduler's dispatcher thread
    only — one job is in flight at a time, so each worker owns a
    private mailbox and a quarantined worker (its mailbox replaced
    under the lock) exits after its stuck job instead of stealing work
    from the replacement.
    """

    _ids = itertools.count(1)

    def __init__(self, name: str = "MicroBatchScheduler-exec"):
        self._name = name
        self._lock = threading.Lock()
        self._closed = False
        self.quarantined: List[threading.Thread] = []
        self._mailbox: Optional[queue.SimpleQueue] = None
        self._thread: Optional[threading.Thread] = None
        self._spawn()

    def _spawn(self) -> None:
        mailbox: queue.SimpleQueue = queue.SimpleQueue()
        t = threading.Thread(
            target=self._loop, args=(mailbox,),
            name=f"{self._name}-{next(self._ids)}", daemon=True)
        self._mailbox, self._thread = mailbox, t
        t.start()

    def _loop(self, mailbox: queue.SimpleQueue) -> None:
        while True:
            job = mailbox.get()
            if job is None:
                return
            try:
                # chaos site: a hang here wedges the executor worker
                # itself (not the engine) — the quarantine path must
                # not care WHERE in the dispatch the thread stuck
                fault_point("serve.dispatch_exec")
                job.fn(job)
            except BaseException as exc:  # noqa: BLE001 — outcome goes
                job.error = exc           # to the supervisor, the
            finally:                      # worker must survive anything
                job.done.set()
            with self._lock:
                if mailbox is not self._mailbox:
                    # quarantined while running: a replacement owns the
                    # executor now — park no longer, exit quietly
                    return

    def submit(self, fn: Callable[[_DispatchJob], None]) -> _DispatchJob:
        job = _DispatchJob(fn)
        self._mailbox.put(job)
        return job

    def enqueue(self, job: _DispatchJob) -> None:
        """Queue an already-built job on the CURRENT worker. Two users:
        the pipelined completion stage hands off prebuilt jobs here,
        and a completion-wedge verdict re-queues the jobs that were
        parked BEHIND the stuck one — their entries live in the
        abandoned mailbox (a quarantined worker exits without draining
        it), so the supervisor must re-queue them on the replacement or
        their futures strand."""
        self._mailbox.put(job)

    def quarantine_and_replace(self) -> int:
        """Wedge verdict: abandon the stuck worker (Python can't kill
        it; it exits on its own when the hang ends) and spawn a fresh
        one. Returns how many quarantined threads are still alive —
        the leak the metrics record."""
        with self._lock:
            self.quarantined.append(self._thread)
            self._spawn()
        return sum(t.is_alive() for t in self.quarantined)

    def quarantined_alive(self) -> int:
        with self._lock:
            return sum(t.is_alive() for t in self.quarantined)

    def worker_alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def close(self, timeout: float = 10.0) -> bool:
        """Stop and join the current worker (idempotent). Quarantined
        threads are daemon and not joinable — they are accounted, not
        waited for. Returns True when the current worker exited."""
        with self._lock:
            self._closed = True
            mailbox, thread = self._mailbox, self._thread
        mailbox.put(None)
        thread.join(timeout)
        return not thread.is_alive()
