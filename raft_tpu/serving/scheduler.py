"""Async micro-batching scheduler: the serving front-end over the engine.

``RAFTEngine`` is a synchronous bucket router — one caller drives it at
a time, and a lone request pads a bucket's whole batch dimension with
zeros. Production TPU serving wins by decoupling request ARRIVAL from
device DISPATCH and coalescing ragged traffic into a small set of
pre-compiled shapes (the lesson Ragged Paged Attention draws for LLM
inference kernels on TPU, arXiv 2604.15464). This module is that
front-end: requests from any number of callers land in one bounded
queue, a single dispatcher thread groups same-shape requests into a
micro-batch, and the bucket's batch dimension fills with *different
callers' work* instead of padding.

Robustness contract (first-class, not best-effort):

- **Backpressure**: the queue is bounded; a full queue rejects NEW work
  with :class:`BackpressureError` (counted as shed) — load shedding
  never touches accepted or in-flight requests.
- **Deadlines** are enforced while QUEUED only: a request that expires
  before dispatch fails fast with :class:`DeadlineExceeded`; a
  dispatched request always runs to completion (the executable is
  non-preemptible anyway) — zero deadline-abandoned in-flight work, by
  construction (``Future.set_running_or_notify_cancel`` pins it).
  Expiry is scanned at submit and, under a dispatch watchdog, from the
  supervision loop — an in-flight dispatch (even a slow compile) no
  longer starves already-expired queued requests.
- **Drain on shutdown**: ``close(drain=True)`` stops intake, finishes
  everything queued, and joins the worker — no leaked threads (the
  PR-3 loader-semaphore lesson, one layer up).
- **Live weight swap**: ``update_weights`` is safe under concurrent
  dispatch — the engine snapshots its weight tree once per dispatch
  under its lock, so a swap lands between dispatches, never inside one.

Resilience layer (serving/resilience.py; every knob defaults OFF, so
the base semantics above are unchanged until armed):

- **Dispatch watchdog** (``dispatch_timeout_s``): dispatch execution
  moves off the queue-owning dispatcher thread onto a supervised
  executor. A dispatch (capacity probe + compile + gather + device
  call) exceeding the wall-clock deadline gets a *wedge verdict*: its
  futures fail with :class:`DispatchWedged`, the stuck thread is
  quarantined and accounted (Python can't kill it — a replacement is
  spawned and the leak lands in metrics), the suspect bucket's
  executable is dropped from the engine, and queued-deadline scanning
  never stopped while the dispatch was in flight.
- **Per-bucket circuit breakers** (``breaker_failures`` > 0): K
  consecutive failures/wedges open a request-shape's breaker — its
  traffic fails fast with :class:`CircuitOpen` (submit-time and
  queued) instead of burning the queue, while other shapes keep
  serving. After a jittered backoff the breaker half-opens; the next
  request is the probe, and a probe against a dropped bucket lazily
  recompiles it (``ensure_bucket``). Success closes the breaker.
- **Health surface**: :meth:`health` reports
  ``healthy | degraded | wedged`` plus per-bucket breaker states,
  worker liveness, last-dispatch age, and quarantined threads; state
  and breaker transitions append as events to the same metrics.jsonl
  the snapshots use (the supervisor-alerting pattern — dashboards tail
  one file).

Fault drills: every micro-batch passes through the ``serve.request``
fault site (testing/faults) — ``raise`` fails just that batch's
futures (the worker survives), ``hang`` models a half-up device. The
supervised executor adds ``serve.dispatch_exec``, the engine
``engine.compile``, and the pipelined completion stage ``serve.fetch``
— the chaos sites ``serve_bench --chaos`` drives.

Hot path (ISSUE 8; knobs default OFF = bitwise the above):
``pipeline_depth`` > 1 splits dispatch into stages over JAX async
dispatch (assembly of batch N+1 overlaps device compute of batch N;
the blocking fetch moves to a supervised completion worker), and a
``wire="u8"`` engine keeps frames uint8 from ``submit`` intake through
the host pads to the device (4× fewer H2D bytes, on-device
normalize). The ``hot_path`` metrics block (dispatch-gap histogram,
assembly overlap ratio, H2D bytes) proves it.

Priority classes (ISSUE 9; default OFF = bitwise the above):
``submit(..., priority=...)`` tags a request ``interactive``
(sessions, one-shot demos) or ``batch`` (bulk offline traffic).
Priority changes exactly two decisions and only when both classes are
actually queued: **shed-batch-first backpressure** — an interactive
arrival at a full queue evicts the newest queued batch-class request
(its future fails ``BackpressureError``, counted shed AND failed so
the accounting identity holds) instead of being rejected itself — and
**weighted dequeue** — the dispatcher picks the interactive head
``interactive_weight`` times for every batch head, so a batch flood
cannot starve interactive p99 while batch still drains at a bounded
fraction (no starvation either way). Priority-less traffic is one
class: FIFO head, reject-new backpressure — the historical semantics,
bit for bit. ``namespace`` prefixes breaker labels (``model/HxW``)
and stamps metrics records when the scheduler serves one model of a
:class:`~raft_tpu.serving.registry.ModelRegistry`.

Overload control one layer up (ISSUE 10): under a registry with an
``admission_budget``, submits are gated by a registry-WIDE token
bucket before they ever reach this scheduler's queue — a budget
rejection is the same ``BackpressureError`` contract as a full queue,
counted in this scheduler's metrics as ``admission_rejected`` (a shed
subset), and the per-queue semantics here are unchanged. The SLO
guardian (serving/guardian.py) likewise reads this scheduler's
metrics/health surfaces to judge canary bakes; it adds no hooks into
the dispatch path.

Observability rides along in :class:`~raft_tpu.serving.metrics.
ServingMetrics`: per-bucket latency histograms for each stage
(enqueue->dispatch->complete), batch occupancy, queue depth, shed and
deadline-miss counters, wedge/quarantine/breaker counters, snapshotted
to ``metrics.jsonl`` on close and dumpable on demand
(``write_metrics``).

Request-scoped tracing (ISSUE 14; ``tracer=`` default None = bitwise
the above): a :class:`~raft_tpu.serving.trace.TraceLedger` mints a
span per ACCEPTED request at intake and closes it exactly once on the
path that settled its future, with the outcome tag matching the
accounting-identity class it was counted under; dispatches add fan-in
spans (bucket key, padding-waste share) linked to their request
spans, and phase marks (taken/shipped/fetch_start) give
``serve_trace`` the queue-vs-assembly-vs-device-vs-fetch attribution
behind a p99 spike.
"""

from __future__ import annotations

import collections
import random
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Deque, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from raft_tpu.ops.padding import pad_amounts
from raft_tpu.parallel.placement import Placement
from raft_tpu.serving.feature_cache import (FeatureCacheMiss,
                                            FeatureCachePool)
from raft_tpu.serving.futures import settle_future
from raft_tpu.serving.hosts import HostDead
from raft_tpu.serving.metrics import ServingMetrics
from raft_tpu.serving.resilience import (BREAKER_CLOSED, BREAKER_OPEN,
                                         CircuitBreaker, CircuitOpen,
                                         DispatchExecutor, DispatchWedged,
                                         _DispatchJob)
from raft_tpu.serving.trace import TraceLedger
from raft_tpu.serving.transport import TransportError
from raft_tpu.testing.faults import fault_point


#: priority classes: ``interactive`` holds its p99 under load (evicts
#: queued batch work at a full queue, wins the weighted dequeue);
#: ``batch`` is the bulk tier that sheds first. None = the single
#: historical class.
PRIORITY_INTERACTIVE = "interactive"
PRIORITY_BATCH = "batch"
_PRIORITIES = (None, PRIORITY_INTERACTIVE, PRIORITY_BATCH)

#: graftthread T3 declaration (tools/graftthread): the machine-checked
#: form of the comment discipline at ``_refresh_state`` — the health
#: recompute holds ``_state_lock`` while reading the queue (``_cv``),
#: the pipeline FIFO (``_pipe_lock``) and the breaker board
#: (``peek``); ``_cv`` is held while metrics record; a completion
#: wedge holds ``_pipe_lock`` across the executor swap. Nothing may
#: ever acquire these in the reverse direction (the breaker fires its
#: listeners OUTSIDE its lock precisely so ``_on_breaker`` can take
#: ``_state_lock``).
LOCK_ORDER = (
    ("scheduler.MicroBatchScheduler._state_lock",
     "scheduler.MicroBatchScheduler._cv",
     "metrics.ServingMetrics._lock"),
    ("scheduler.MicroBatchScheduler._state_lock",
     "scheduler.MicroBatchScheduler._pipe_lock",
     "resilience.DispatchExecutor._lock"),
    ("scheduler.MicroBatchScheduler._state_lock",
     "resilience.CircuitBreaker._lock"),
    # span closes run from the deadline sweep (under _cv) into the
    # trace ledger's leaf lock — never the reverse (the ledger calls
    # back into nothing, and its file I/O happens lock-free in flush)
    ("scheduler.MicroBatchScheduler._cv",
     "trace.TraceLedger._lock"),
)

#: graftthread T6: wedge verdicts must land every consequence (drop
#: the suspect executable, record the breaker failure, quarantine the
#: stuck thread) BEFORE any future settles — a woken caller observes
#: consistent state, never a half-applied verdict.
GRAFTTHREAD = {
    "verdicts": ("_wedge_verdict", "_wedge_completion",
                 "_wedge_replica", "_wedge_host"),
    "consequences": ("drop_bucket", "record_failure",
                     "quarantine_and_replace"),
    "settles": ("_fail_requests",),
}

#: graftwire W4: the dead-HOST verdict must land every cross-seam
#: consequence (breaker, executor quarantine, placement mark,
#: transport poison — the one that unsticks a thread blocked in the
#: zombie's recv) before the in-flight batch is failed over or failed;
#: ``_failover_requeue`` counts as a settle because requeued requests
#: become visible to surviving lanes the moment they hit the queue.
GRAFTWIRE = {
    "verdicts": ("_wedge_host",),
    "consequences": ("record_failure", "quarantine_and_replace",
                     "mark_host", "poison"),
    "settles": ("_fail_requests", "_failover_requeue"),
}


class BackpressureError(RuntimeError):
    """Queue at max_queue: shed — the submitter should back off/retry.
    Also fails a QUEUED batch-class future whose slot was taken by an
    interactive arrival (shed-batch-first)."""


class DeadlineExceeded(RuntimeError):
    """The request's deadline expired while it was still queued."""


class SchedulerClosed(RuntimeError):
    """submit() after close(), or queued work dropped by a no-drain
    close."""


class ConfigError(ValueError):
    """A constructor-time knob combination that could only misbehave
    at runtime (e.g. ``feature_cache=True`` with ``replicas>1`` would
    silently correlate a stream's frames across replica-local device
    pools) — rejected loudly up front instead."""


class _ReplicaLane:
    """One replica's serving lane in the fleet: its engine, its own
    supervised dispatch executor (a single worker — the engine's
    single-caller contract holds PER REPLICA), its own breaker board
    (labels ``model/HxW/r<k>`` — a wedge on one replica's executable
    must not open a sibling's breaker), the in-flight job, and the
    fan-out gauges the least-loaded pick reads. All mutable state is
    owned by the ONE fleet dispatcher thread (the DispatchExecutor
    single-supervisor contract, N times over); other threads only read
    it for health snapshots."""

    __slots__ = ("index", "engine", "exec", "breakers", "job",
                 "t_launch", "active", "quarantined", "dispatches",
                 "prev_pending", "idle_since", "host")

    def __init__(self, index: int, engine, host: Optional[str] = None):
        self.index = index
        self.engine = engine
        #: host name when this lane lives on a REMOTE host
        #: (serving/hosts.py) — None for every local lane
        self.host = host
        self.exec = DispatchExecutor(f"MicroBatchScheduler-r{index}")
        self.breakers: Dict[Tuple, CircuitBreaker] = {}
        self.job: Optional[_DispatchJob] = None
        self.t_launch = 0.0
        #: takes new dispatches (False: retired by the idle policy or
        #: quarantined by a wedge verdict — the fleet serves without it)
        self.active = True
        self.quarantined = False
        self.dispatches = 0
        self.prev_pending = None
        self.idle_since: Optional[float] = time.monotonic()


class ServeResult(NamedTuple):
    flow: np.ndarray               #: (H, W, 2), cropped to the request
    flow_low: Optional[np.ndarray]  #: (hp/8, wp/8, 2) in ÷8-padded frame
    #: space when requested (``want_low``) — the next frame's warm-start
    #: substrate — else None


class _Request:
    __slots__ = ("image1", "image2", "key", "flow_init", "want_low",
                 "low_device", "future", "t_submit", "deadline",
                 "priority", "stream", "seq", "prime", "span")

    def __init__(self, image1, image2, key, flow_init, want_low,
                 low_device, deadline, priority=None, stream=None,
                 seq=0, prime=False):
        self.image1 = image1
        self.image2 = image2
        self.key = key                  # (H, W) — the coalescing group;
        #                                 (H, W, "cache") for cached rows
        self.flow_init = flow_init
        self.want_low = want_low
        self.low_device = low_device    # flow_low stays a device array
        self.future: Future = Future()
        self.t_submit = time.monotonic()
        self.deadline = deadline        # absolute monotonic, or None
        self.priority = priority        # interactive | batch | None
        #: feature-cache stream identity + the session's frame counter
        #: (slot validity is seq-exact); ``prime`` rows carry no pair —
        #: their flow result is discarded, their cache output isn't
        self.stream = stream
        self.seq = seq
        self.prime = prime
        #: request-tracing span (serving/trace.py) — None whenever the
        #: scheduler runs without a ledger (tracing off, the default)
        self.span = None


class MicroBatchScheduler:
    """Bounded-queue micro-batching front-end over a ``RAFTEngine``.

    ``max_queue``: pending-request bound (backpressure past it).
    ``max_batch``: coalescing ceiling per dispatch; for a spatial shape
    with no precompiled bucket, ONE bucket is pre-warmed at this batch
    so later micro-batches batch-fill instead of compiling per fill
    count. ``gather_window_s``: how long dispatch holds an underfull
    micro-batch open for concurrent submitters — the latency/occupancy
    knob (bounded; an already-full batch never waits).

    Resilience knobs (all default OFF — identical semantics until set):
    ``dispatch_timeout_s`` arms the dispatch watchdog (must exceed
    ``gather_window_s`` plus a worst-case compile — the deadline covers
    the whole supervised dispatch). ``breaker_failures`` > 0 arms
    per-bucket circuit breakers opening after that many consecutive
    failures/wedges, with jittered exponential backoff
    (``breaker_backoff_s`` base, ``breaker_backoff_max_s`` cap,
    ``breaker_rng`` injectable for deterministic drills) before the
    half-open probe.

    ``pipeline_depth`` (default 1 — bitwise the historical path): at
    depth N > 1 the dispatch path splits into stages riding JAX's
    async dispatch. The dispatcher assembles and SHIPS batch K+1 while
    the device still computes batch K (``engine.infer_batch_async``),
    and the blocking D2H fetch + future settling move to a completion
    stage (its own supervised worker) — up to N batches are in flight,
    and the dispatch gap between consecutive device calls drops to ~0
    under load. The deadline/backpressure/accounting contract is
    unchanged: a handed-off batch is in-flight work (never shed, never
    deadline-expired), completions settle in dispatch order, and with
    the watchdog armed a completion exceeding ``dispatch_timeout_s``
    (a hang in device compute or D2H — the ``serve.fetch`` chaos
    site) gets the same wedge verdict as a stuck dispatch:
    consequences first (bucket dropped, breaker opened, completion
    worker quarantined + replaced, trailing completions re-queued on
    the replacement), THEN the batch's futures fail ``DispatchWedged``.

    ``interactive_weight`` (only observable when BOTH priority classes
    are queued): interactive dequeue picks per batch pick.
    ``namespace``: the model name this scheduler serves under a
    :class:`~raft_tpu.serving.registry.ModelRegistry` — prefixes
    breaker labels and stamps metrics records; None (default) keeps
    single-model labels/records byte-identical.
    """

    def __init__(self, engine, *, max_queue: int = 64, max_batch: int = 8,
                 gather_window_s: float = 0.002,
                 dispatch_timeout_s: Optional[float] = None,
                 breaker_failures: int = 0,
                 breaker_backoff_s: float = 0.25,
                 breaker_backoff_max_s: float = 30.0,
                 breaker_rng: Optional[random.Random] = None,
                 pipeline_depth: int = 1,
                 interactive_weight: int = 4,
                 namespace: Optional[str] = None,
                 metrics: Optional[ServingMetrics] = None,
                 metrics_path: Optional[str] = None,
                 feature_cache: bool = False,
                 feature_cache_capacity: int = 256,
                 ragged: bool = False,
                 tracer: Optional[TraceLedger] = None,
                 replicas: int = 1,
                 replica_ceiling: Optional[int] = None,
                 replica_idle_retire_s: float = 30.0,
                 placement: Optional[Placement] = None,
                 host_fleet=None):
        """(Trailing knobs) ``feature_cache=True`` (needs a
        ``RAFTEngine(feature_cache=True)``) arms the cross-frame
        device feature-cache pool: ``submit_cached`` becomes
        available, per-stream encoder state lives on device in a
        ``feature_cache_capacity``-slot LRU pool
        (serving/feature_cache), and warm video pairs dispatch
        through the cached bucket signature — one encoder pass and
        ONE frame of H2D per pair. Default OFF: no pool exists,
        ``submit_cached`` raises, everything else is bitwise
        unchanged.

        ``ragged=True`` (needs a ``RAFTEngine(ragged=True)``): the
        coalescing key becomes the engine's CAPACITY CLASS instead of
        the request's ``(h, w)`` — requests of ANY shape mapping to
        the same class box fill one micro-batch and dispatch through
        ONE ragged executable (``infer_ragged_async``), with per-row
        crops on the way out. Today's same-shape-only coalescing can
        only fill a batch from one shape's queue; the ragged key fills
        it from the whole mixed-shape queue. Breakers, deadlines,
        priorities, pipelining and the accounting identity are
        unchanged — a class is just a coarser bucket key (labelled
        ``BxHxW/ragged``). Default OFF: keys, labels and dispatch are
        byte-identical to the bucketed path.

        ``tracer`` (a :class:`~raft_tpu.serving.trace.TraceLedger`)
        arms request-scoped tracing: every ACCEPTED request gets a
        span minted at intake and closed exactly once with the
        accounting class it was counted under; dispatches get fan-in
        spans linked to their request spans; spans.jsonl appends under
        the ledger's sampling knob with always-keep-tail exemplars.
        Default None: no span objects exist, every path above is
        bitwise the untraced stack.

        ``replicas`` > 1 (or a ``replica_ceiling`` above it, or an
        explicit ``placement``) arms the REPLICA FLEET: N sibling
        engines (``RAFTEngine.spawn_replica`` — replicas 2..N warm by
        LOADING the primary's AOT artifacts, zero extra XLA compiles)
        each serve whole coalesced micro-batches on their own
        supervised lane, picked least-loaded per dispatch. Each lane
        carries its OWN breaker board (``model/HxW/r<k>``) and a wedge
        verdict quarantines ONE replica while the rest keep serving;
        queue pressure activates lanes up to ``replica_ceiling`` and
        ``replica_idle_retire_s`` of idleness retires them back to the
        floor. 4K-class buckets pin to the primary lane (the mesh/pjit
        path) — the placement layer
        (:class:`~raft_tpu.parallel.placement.Placement`) owns both
        decisions. ``replicas=1`` (the default) is bitwise the
        single-engine scheduler. ``feature_cache`` and
        ``pipeline_depth>1`` raise :class:`ConfigError` with a fleet —
        see the messages for why.

        ``host_fleet`` (a :class:`~raft_tpu.serving.hosts.HostFleet`,
        already admitted — every host's artifacts pushed + prewarmed)
        extends the replica fleet across HOSTS: each remote worker
        becomes one more lane, served through its
        :class:`~raft_tpu.serving.hosts.RemoteEngine` proxy exactly
        like a local replica. The fleet's heartbeat monitor is started
        here; its dead-host verdicts drain on the dispatcher tick into
        :meth:`_wedge_host` — quarantine + transport poison FIRST,
        then the in-flight batch FAILS OVER by requeue to surviving
        lanes (never stranded, never double-settled). With remote
        lanes, set ``breaker_failures>=1`` so a dying-but-unverdicted
        host is paced by its lane breakers instead of re-picked every
        tick. ``host_fleet=None`` (the default) builds none of this —
        bitwise the PR-17 scheduler."""
        self.engine = engine
        self.max_queue = int(max_queue)
        self.max_batch = int(max_batch)
        self.gather_window_s = float(gather_window_s)
        self.dispatch_timeout_s = (float(dispatch_timeout_s)
                                   if dispatch_timeout_s else None)
        #: interactive heads dequeued per batch head when BOTH classes
        #: are queued (priority-less or single-class queues stay FIFO);
        #: >= 1 so batch is rationed, never starved
        self.interactive_weight = max(1, int(interactive_weight))
        self._rr = 0           # weighted-round-robin dispatch counter
        #: lifetime class flags (set under _cv at submit): until BOTH
        #: have been seen, class mixing is impossible and the
        #: dispatcher's head choice stays the O(1) FIFO peek — the
        #: priority-less hot path never pays a queue scan
        self._seen_batch = False
        self._seen_interactive = False
        self.namespace = namespace
        self.metrics = metrics or ServingMetrics(metrics_path,
                                                 namespace=namespace)
        if feature_cache and not getattr(engine, "feature_cache", False):
            raise ValueError(
                "feature_cache=True needs an engine compiled with "
                "feature_cache=True (the cached bucket signature)")
        if ragged and not getattr(engine, "ragged", False):
            raise ValueError(
                "ragged=True needs an engine compiled with ragged=True "
                "(the capacity-class executables)")
        if ragged and feature_cache:
            raise ValueError(
                "ragged=True with feature_cache=True is not supported "
                "yet — the cached signature keeps per-shape buckets")
        self._ragged = bool(ragged)
        #: replica fleet (ISSUE 17): the placement layer owns replica
        #: construction/assignment and the per-bucket replicate-vs-
        #: shard decision; the scheduler owns the lanes. Fleet mode is
        #: any ceiling above one engine; replicas=1 with no ceiling
        #: builds NO placement and stays bitwise the single path.
        want = (placement.ceiling if placement is not None
                else max(1, int(replicas), int(replica_ceiling or 0)))
        if host_fleet is not None and ragged:
            raise ConfigError(
                "ragged=True with host_fleet: remote lanes speak the "
                "bucketed engine surface only — capacity-class "
                "executables are not proxied yet")
        if want > 1 or host_fleet is not None:
            if feature_cache:
                raise ConfigError(
                    "feature_cache=True with replicas>1: a stream's "
                    "device slot lives in ONE replica's pool, and "
                    "fleet coalescing would silently correlate its "
                    "frames across replica-local pools — run one "
                    "feature-cache scheduler per replica (pinning "
                    "streams yourself) or set replicas=1")
            if int(pipeline_depth) > 1:
                raise ConfigError(
                    "pipeline_depth>1 with replicas>1: fleet lanes run "
                    "dispatch+fetch+settle inline per replica — cross-"
                    "batch overlap comes from replica concurrency, not "
                    "a shared completion stage")
        self.placement = (placement if placement is not None
                          else (Placement(engine, replicas=replicas,
                                          ceiling=replica_ceiling)
                                if want > 1 or host_fleet is not None
                                else None))
        #: fleet lanes, primary first; EMPTY list = single-engine mode
        #: (every `if self._lanes` fleet branch below is dead)
        self._lanes: List[_ReplicaLane] = (
            [_ReplicaLane(k, eng)
             for k, eng in enumerate(self.placement.engines)]
            if self.placement is not None else [])
        #: multi-host fleet (ISSUE 18): each admitted remote worker is
        #: one more lane, appended AFTER the local lanes (local indices
        #: never move); its heartbeat verdicts drain in _run_fleet
        self.host_fleet = host_fleet
        if host_fleet is not None:
            if host_fleet.metrics is None:
                host_fleet.metrics = self.metrics
            for name, host in host_fleet.hosts.items():
                idx = self.placement.attach_host(name, host.engine)
                lane = _ReplicaLane(idx, host.engine, host=name)
                # pre-warm-before-traffic: a host that was never
                # admitted (artifacts unverified) starts INACTIVE and
                # only a rejoin notice can activate it
                lane.active = host.ready
                self._lanes.append(lane)
            host_fleet.start()
        self.replica_idle_retire_s = float(replica_idle_retire_s)
        #: swap barrier: a fleet-atomic weight swap quiesces the lanes
        #: (no new launches) while the dispatcher keeps reaping
        self._swapping = False
        #: high-water mark of simultaneously busy lanes (the fan-out
        #: acceptance gauge: > 1 under mixed-shape load)
        self._concurrency_max = 0
        #: request-tracing ledger (serving/trace.py); public so
        #: sessions (parent chaining) and the registry (intake stamps)
        #: can reach it duck-typed. None = tracing off, zero overhead.
        self.tracer = tracer
        self._fcache = (FeatureCachePool(feature_cache_capacity)
                        if feature_cache else None)
        if self._fcache is not None:
            # snapshots grow a per-bucket feature_cache block; the
            # provider is read with NO metrics lock held (pool lock
            # stays a leaf)
            self.metrics.feature_cache_provider = self._fcache.snapshot
        self._cv = threading.Condition()
        self._q: Deque[_Request] = collections.deque()
        self._capacity: Dict[Tuple[int, int], int] = {}
        self._closed = False
        self._breaker_failures = int(breaker_failures)
        self._breaker_backoff_s = float(breaker_backoff_s)
        self._breaker_backoff_max_s = float(breaker_backoff_max_s)
        self._breaker_rng = breaker_rng
        self._breakers: Dict[Tuple[int, int], CircuitBreaker] = {}
        # fleet mode: each lane has its own executor and the fleet
        # watchdog verdicts per lane — the single supervised executor
        # stays un-built
        self._exec = (DispatchExecutor()
                      if self.dispatch_timeout_s is not None
                      and not self._lanes else None)
        self.pipeline_depth = max(1, int(pipeline_depth))
        #: pipelined completion stage: a second supervised worker owns
        #: the blocking fetch + settle; ``_pending_jobs`` is the FIFO
        #: of handed-off-but-unsettled batches (head == the one the
        #: completion worker is on — the watchdog's verdict target)
        self._completion = (DispatchExecutor("MicroBatchScheduler-compl")
                            if self.pipeline_depth > 1 else None)
        self._pipe_lock = threading.Lock()
        self._pending_jobs: Deque[_DispatchJob] = collections.deque()
        #: previous dispatch's PendingBatch — the dispatch-gap clock
        #: (its ``t_ready`` is None while the batch is still in flight,
        #: which IS the perfect-overlap reading: gap 0)
        self._prev_pending = None
        #: the engine's wire dtype: keep frames in it end-to-end so a
        #: u8 wire never widens on the host (submit → stack → pad →
        #: H2D all ride uint8)
        self._wire_np = (np.uint8
                         if getattr(engine, "wire", "f32") == "u8"
                         else np.float32)
        # guards the _health_state compare-and-set + event emit:
        # refreshes race in from the dispatcher, submitters (breaker
        # transitions), and health() callers, and an unsynchronized
        # RMW would emit duplicate/stale-previous serving_state events
        self._state_lock = threading.Lock()
        self._health_state = "healthy"
        self._inflight_since: Optional[float] = None
        self._last_dispatch_done: Optional[float] = None
        self._worker = threading.Thread(
            target=self._run_fleet if self._lanes else self._run,
            name="MicroBatchScheduler-dispatch", daemon=True)
        self._worker.start()

    # -- intake ------------------------------------------------------------

    def submit(self, image1, image2, *, deadline_s: Optional[float] = None,
               flow_init: Optional[np.ndarray] = None,
               want_low: bool = False, low_device: bool = False,
               priority: Optional[str] = None) -> Future:
        """Enqueue ONE ``(H, W, 3)`` frame pair; returns a Future
        resolving to :class:`ServeResult`. Raises
        :class:`BackpressureError` when the queue is full,
        :class:`CircuitOpen` when the shape's breaker is open, and
        :class:`SchedulerClosed` after ``close()``.

        ``priority``: ``"interactive"`` | ``"batch"`` | None (the
        single historical class). At a full queue an interactive
        arrival takes the newest queued batch request's slot (that
        future fails ``BackpressureError``); a batch or priority-less
        arrival is rejected as before.

        ``flow_init`` may be a host array (validated here, embedded on
        the host) or a device array the engine itself produced
        (``low_device=True`` results) — the device path never round-
        trips through host memory. ``low_device=True`` makes the
        result's ``flow_low`` a device array too."""
        if priority not in _PRIORITIES:
            raise ValueError(
                f"priority={priority!r}: choose "
                f"{PRIORITY_INTERACTIVE!r}, {PRIORITY_BATCH!r} or None")
        image1 = np.asarray(image1)
        image2 = np.asarray(image2)
        # frames ride the engine's wire dtype from intake on: with a
        # u8-wire engine every downstream copy (stack, pad) moves 1
        # byte/px instead of 4, and the host never widens
        if image1.dtype != self._wire_np:
            image1 = image1.astype(self._wire_np)
        if image2.dtype != self._wire_np:
            image2 = image2.astype(self._wire_np)
        if image1.ndim != 3 or image1.shape[-1] != 3:
            raise ValueError(
                f"submit takes one (H, W, 3) frame pair, got "
                f"{image1.shape} — batching is the scheduler's job")
        if image1.shape != image2.shape:
            raise ValueError(f"frame shapes differ: {image1.shape} vs "
                             f"{image2.shape}")
        if ((flow_init is not None or want_low)
                and not getattr(self.engine, "warm_start", False)):
            raise ValueError(
                "flow_init/want_low need a warm_start=True engine")
        if flow_init is not None:
            h, w = image1.shape[:2]
            left, right, top, bottom = pad_amounts(h, w)
            want = ((h + top + bottom) // 8, (w + left + right) // 8, 2)
            if isinstance(flow_init, np.ndarray) \
                    or not hasattr(flow_init, "shape"):
                flow_init = np.asarray(flow_init, np.float32)
                if flow_init.shape != want:
                    # validated HERE so a malformed warm start fails ITS
                    # caller alone — at dispatch time the row assignment
                    # would throw inside the shared try and fail (or, if
                    # broadcastable, silently corrupt) the whole
                    # coalesced micro-batch, other callers included
                    raise ValueError(
                        f"flow_init shape {flow_init.shape} != {want} "
                        "(1/8 of the ÷8-padded frame)")
                if not np.isfinite(flow_init).all():
                    # a NaN warm start would only poison this caller's
                    # own row, but fail it here with a cause instead of
                    # returning NaN flow from the device
                    raise ValueError(
                        "flow_init contains non-finite values")
            else:
                # device-resident warm start: shape-check without a
                # D2H sync. No finiteness read — the device
                # forward-splat (ops/interp.forward_interpolate_device)
                # drops non-finite points by construction, so a
                # poisoned flow degrades to a cold start, not NaN flow
                if tuple(flow_init.shape) != want:
                    raise ValueError(
                        f"flow_init shape {tuple(flow_init.shape)} != "
                        f"{want} (1/8 of the ÷8-padded frame)")
        if self._ragged:
            # CROSS-SHAPE coalescing: the key is the capacity-class
            # box this shape maps to, so mixed-shape requests share a
            # queue group (and a breaker) — compiles nothing here
            h, w = image1.shape[:2]
            key = self.engine.ragged_class_for(h, w) + ("ragged",)
        else:
            key = tuple(image1.shape[:2])
        self._intake_guard(key)
        deadline = (time.monotonic() + deadline_s
                    if deadline_s is not None else None)
        req = _Request(image1, image2, key, flow_init, want_low,
                       low_device, deadline, priority)
        if self.tracer is not None:
            req.span = self._trace_begin(
                key, priority, deadline_s, warm=flow_init is not None)
        self._enqueue_traced(req, priority)
        return req.future

    def submit_cached(self, frame, *, stream, seq: int,
                      prime: bool = False,
                      deadline_s: Optional[float] = None,
                      priority: Optional[str] = None) -> Future:
        """Enqueue ONE frame of a feature-cached video stream; returns
        a Future resolving to :class:`ServeResult` (``flow_low`` is
        always None — the recurrence state lives in the device pool).

        ``stream`` is the pool slot identity; ``seq`` is the stream's
        frame counter. ``prime=True`` submits the stream's (re)start
        frame: the dispatch's flow output is discarded (the future
        resolves to ``ServeResult(None, None)``) and its cache output
        installs the slot — pair ``seq`` then correlates THIS frame
        against a slot at ``seq - 1``. A pair submit with no valid
        slot (never primed, LRU-evicted, flushed by a weight swap, or
        a seq hole left by a failed/expired pair) fails fast with
        :class:`~raft_tpu.serving.feature_cache.FeatureCacheMiss` —
        the caller cold-restarts by re-priming
        (``VideoSession(feature_cache=True)`` does this itself).

        Raises the same intake errors as :meth:`submit`
        (``BackpressureError``/``CircuitOpen``/``SchedulerClosed``;
        cached rows get their own breaker per shape, labelled
        ``HxW/cache``)."""
        if self._fcache is None:
            raise ValueError(
                "submit_cached needs a feature_cache=True scheduler")
        frame = np.asarray(frame)
        if frame.dtype != self._wire_np:
            frame = frame.astype(self._wire_np)
        if frame.ndim != 3 or frame.shape[-1] != 3:
            raise ValueError(
                f"submit_cached takes one (H, W, 3) frame, got "
                f"{frame.shape}")
        if priority not in _PRIORITIES:
            raise ValueError(
                f"priority={priority!r}: choose "
                f"{PRIORITY_INTERACTIVE!r}, {PRIORITY_BATCH!r} or None")
        h, w = frame.shape[:2]
        key = (h, w, "cache")
        # closed/breaker checks BEFORE the pool probe: a closed (or
        # draining) scheduler must say SchedulerClosed — the registry
        # re-route catches that, while a spurious FeatureCacheMiss
        # would send the session into a futile re-prime round trip
        # against a dead variant (and mutate a flushed pool's counters)
        self._intake_guard(key)
        if not prime and not self._fcache.valid(stream, (h, w),
                                                seq - 1):
            # fail fast BEFORE the queue: a pair with no valid slot
            # could only dispatch garbage — the miss is the caller's
            # cold-restart signal, not a request failure
            self._fcache.record_miss()
            raise FeatureCacheMiss(
                f"stream {stream!r} has no valid cache slot for "
                f"{h}x{w} seq {seq - 1} (unprimed, evicted, flushed, "
                "or a missed store) — re-prime the previous frame")
        deadline = (time.monotonic() + deadline_s
                    if deadline_s is not None else None)
        req = _Request(None, frame, key, None, False, False, deadline,
                       priority, stream=stream, seq=int(seq),
                       prime=prime)
        if self.tracer is not None:
            req.span = self._trace_begin(
                key, priority, deadline_s, stream=stream, seq=int(seq),
                prime=prime)
        self._enqueue_traced(req, priority)
        return req.future

    def _intake_guard(self, key) -> None:
        """Shared submit-time fail-fast checks (closed, open breaker)."""
        with self._cv:
            if self._closed:
                # checked before the breaker: a closed scheduler must
                # say so — CircuitOpen's "retry after backoff" would
                # send the caller into a futile retry loop
                raise SchedulerClosed("scheduler is closed")
        if self._lanes:
            # fleet: a shape fails fast only when it is open on EVERY
            # active replica — one replica's bad executable must not
            # reject traffic its siblings serve fine (state() promotes
            # an expired open to half_open, so the probe gets through)
            states = []
            for lane in self._lanes:
                if not lane.active:
                    continue
                br = lane.breakers.get(key)
                states.append(br.state() if br is not None
                              else BREAKER_CLOSED)
            if states and all(s == BREAKER_OPEN for s in states):
                self.metrics.record_circuit_rejected()
                raise CircuitOpen(
                    f"bucket {key} circuit open on every active "
                    "replica — failing fast; retry after backoff")
            return
        br = self._breakers.get(key)
        if br is not None and br.state() == BREAKER_OPEN:
            # fail fast at intake: an open bucket must not burn queue
            # slots healthy shapes could use (state() promotes an
            # expired open to half_open, so the first submit past the
            # backoff gets through as the probe)
            self.metrics.record_circuit_rejected()
            raise CircuitOpen(
                f"bucket {key} circuit open ({br.consecutive} "
                "consecutive failures) — failing fast; retry after "
                "backoff")

    def _enqueue(self, req: _Request, priority: Optional[str]) -> None:
        """Shared queue-insertion tail: expiry sweep, backpressure
        (shed-batch-first for interactive arrivals), append + notify."""
        with self._cv:
            if self._closed:
                raise SchedulerClosed("scheduler is closed")
            # sweep expired entries first: they must not hold
            # backpressure slots, and submit is an expiry edge — a
            # deadline fires within one submit/supervision tick even
            # while a dispatch is in flight
            self._sweep_locked(time.monotonic())
            if len(self._q) >= self.max_queue:
                victim = None
                if priority == PRIORITY_INTERACTIVE:
                    # shed-batch-first: the NEWEST queued batch-class
                    # entry gives up its slot (it has waited least —
                    # the oldest is closest to dispatch and evicting it
                    # would waste the most queue time). Interactive and
                    # priority-less entries are never evicted.
                    for r in reversed(self._q):
                        if (r.priority == PRIORITY_BATCH
                                and not r.future.done()):
                            victim = r
                            break
                if victim is None:
                    self.metrics.record_shed(priority)
                    raise BackpressureError(
                        f"queue full ({self.max_queue} pending) — "
                        "shedding new work; retry with backoff")
                self._q.remove(victim)
                if settle_future(
                        victim.future, BackpressureError(
                            "shed by an interactive arrival under "
                            "full-queue backpressure (batch class "
                            "sheds first); retry with backoff"),
                        # raced: the victim's caller cancelled in the
                        # race window
                        raced=self.metrics.record_cancelled):
                    self.metrics.record_evicted(victim.priority)
                    if self.tracer is not None \
                            and victim.span is not None:
                        # evicted futures fail (counted shed AND
                        # failed) — the span's class follows the
                        # counter, the outcome names the real story
                        self.tracer.close(victim.span, "evicted",
                                          "failed")
                else:
                    self._trace_cancel(victim)
            self._q.append(req)
            if priority == PRIORITY_BATCH:
                self._seen_batch = True
            else:
                self._seen_interactive = True
            self.metrics.record_submit(depth=len(self._q),
                                       priority=priority)
            self._cv.notify()

    # -- request tracing (serving/trace.py; every helper is a no-op
    # when self.tracer is None — the tracing-off hot path pays one
    # attribute read) -------------------------------------------------------

    def _enqueue_traced(self, req: _Request, priority) -> None:
        """``_enqueue`` with span hygiene: a request REJECTED at the
        queue (backpressure, closed) was never accepted — its
        just-minted span is discarded, never an orphan; an accepted
        one stamps its trace id onto the returned future (the
        session-chaining handle)."""
        if req.span is None:
            self._enqueue(req, priority)
            return
        try:
            self._enqueue(req, priority)
        except BaseException:
            self.tracer.discard(req.span)
            raise
        req.future.trace_id = req.span.trace_id

    def _trace_begin(self, key, priority, deadline_s, *, warm=False,
                     stream=None, seq=None, prime=False):
        """Mint one accepted request's span at intake: bucket label,
        priority/deadline, breaker state at admit (``peek`` — the
        read must not promote a half-open probe), cache identity for
        cached rows. The registry's thread-local intake stamp
        (model/variant/canary) and a session's parent link merge in
        at ``begin``."""
        fields = {"bucket": self._key_label(key)}
        if self.namespace is not None:
            fields["model"] = self.namespace
        if priority is not None:
            fields["priority"] = priority
        if deadline_s is not None:
            fields["deadline_s"] = deadline_s
        if warm:
            fields["warm"] = True
        if stream is not None:
            fields["stream"] = str(stream)
            fields["seq"] = seq
            if prime:
                fields["prime"] = True
        br = self._breakers.get(key)
        fields["breaker_at_admit"] = (br.peek() if br is not None
                                      else BREAKER_CLOSED)
        return self.tracer.begin("request", **fields)

    def _trace_cancel(self, req: _Request) -> None:
        """Close a span whose caller cancelled the future (reaped at
        sweep/take/dispatch, or raced into a settle)."""
        if self.tracer is not None and req.span is not None:
            self.tracer.close(req.span, "cancelled", "cancelled")

    def _trace_dispatch(self, live: List[_Request], label: str,
                        bucket, t_disp: float, real_px: int,
                        padded_px: int, **extra) -> None:
        """Mint the coalesce fan-in DISPATCH span and link/mark the
        batch's request spans: one dispatch span carries N request
        trace ids (bucket/capacity-class key + padding-waste share),
        each request span gets the back-link, its ``taken`` mark, and
        its own padding share of the executable's box."""
        tr = self.tracer
        if tr is None:
            return
        spans = [r.span for r in live if r.span is not None]
        if not spans:
            return
        waste = (round(1.0 - real_px / padded_px, 4) if padded_px
                 else 0.0)
        d = tr.begin("dispatch", bucket=label, fan_in=len(live),
                     capacity=int(bucket[0]), padding_waste=waste,
                     requests=[s.trace_id for s in spans],
                     **({"model": self.namespace}
                        if self.namespace is not None else {}),
                     **extra)
        for r in live:
            if r.span is None:
                continue
            px = (r.image2.shape[0] * r.image2.shape[1]
                  if r.image2 is not None else 0)
            tr.mark(r.span, "taken", at=t_disp)
            r.span.linked = d
            tr.annotate(r.span, dispatch=d.trace_id, fan_in=len(live),
                        padding_share=(round(px / padded_px, 4)
                                       if padded_px else 0.0))

    def _trace_mark(self, live: List[_Request], phase: str,
                    at: Optional[float] = None) -> None:
        tr = self.tracer
        if tr is None:
            return
        t = at if at is not None else time.monotonic()
        for r in live:
            if r.span is not None:
                tr.mark(r.span, phase, at=t)

    def _trace_span_ctx(self, pending, live: List[_Request]) -> None:
        """Hand the batch's span context to the engine's PendingBatch
        so the pipelined completion stage can stamp ``fetch_start``
        from the pending it actually fetches (duck-typed pendings
        without the slot are tolerated — the marks just stay on the
        dispatch path's ``live`` closure)."""
        if self.tracer is None:
            return
        try:
            pending.span_ctx = [r.span for r in live]
        except AttributeError:
            pass

    def _trace_close_dispatch(self, live: List[_Request],
                              outcome: str) -> None:
        """Close the batch's linked dispatch span (idempotent — the
        failure paths close it per-request too, first close wins)."""
        tr = self.tracer
        if tr is None:
            return
        for r in live:
            if r.span is not None and r.span.linked is not None:
                tr.close(r.span.linked, outcome)
                return

    def update_weights(self, variables) -> None:
        """Live checkpoint swap; atomic wrt in-flight micro-batches
        (the engine snapshots its tree once per dispatch). With a
        feature cache armed, the pool flushes — features computed by
        the old tree must never feed the new one (the engine's
        weights-version stamp is the backstop for the race window).
        With a replica fleet, the swap is FLEET-ATOMIC
        (:meth:`swap_weights`): all replicas move under one epoch or
        none do."""
        self.swap_weights(variables)
        if self._fcache is not None:
            self.flush_feature_cache("weights_swap")

    def swap_weights(self, variables, timeout_s: float = 30.0) -> None:
        """Swap the serving weight tree across EVERY replica as one
        epoch: raise the swap barrier (the dispatcher reaps in-flight
        lanes but launches nothing new), wait for the lanes to
        quiesce, then swap engine by engine — any failure (the
        ``scheduler.swap`` chaos site) rolls the already-swapped
        engines BACK before re-raising, so the fleet is never
        observable half-rolled: every dispatch before this returns ran
        the old tree everywhere, every dispatch after runs the new
        tree everywhere. Single-engine mode is the plain engine swap
        it always was."""
        if not self._lanes:
            self.engine.update_weights(variables)
            return
        with self._cv:
            if self._swapping:
                raise RuntimeError("a fleet weight swap is already in "
                                   "progress")
            self._swapping = True
        try:
            deadline = time.monotonic() + timeout_s
            while any(lane.job is not None for lane in self._lanes):
                # the dispatcher keeps reaping (and wedging) under the
                # barrier — a wedged lane cannot stall the epoch past
                # its own verdict
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"fleet did not quiesce within {timeout_s}s "
                        "for the weight swap")
                time.sleep(0.001)
            swapped = []
            try:
                for lane in self._lanes:
                    old = lane.engine.variables
                    fault_point("scheduler.swap")
                    lane.engine.update_weights(variables)
                    swapped.append((lane, old))
            except BaseException:
                # epoch abort: restore the engines already swapped (in
                # reverse) — a failed rollout leaves the WHOLE fleet
                # on the old tree, never a mixed one
                for lane, old in reversed(swapped):
                    lane.engine.update_weights(old)
                raise
            self.metrics.record_event(
                "fleet_weights_swap", replicas=len(self._lanes))
        finally:
            with self._cv:
                self._swapping = False
                self._cv.notify_all()

    def invalidate_stream(self, stream) -> bool:
        """Drop one stream's feature-cache slot (end-of-stream
        hygiene — ``VideoSession.drain`` calls this so a finished
        stream's device arrays stop occupying pool capacity). True if
        a slot was dropped; no-op without a pool."""
        if self._fcache is None:
            return False
        return self._fcache.invalidate(stream)

    def flush_feature_cache(self, reason: str, **stamp) -> int:
        """Drop every feature-cache slot and record a ``cache_flush``
        event (stamped with ``reason`` + any caller fields — the
        registry adds model/version). Returns how many slots dropped;
        no-op (0) when no pool is armed."""
        if self._fcache is None:
            return 0
        n = self._fcache.flush()
        self.metrics.record_event("cache_flush", reason=reason,
                                  slots=n, **stamp)
        return n

    # -- breakers / health -------------------------------------------------

    #: label suffix marking feature-cache groups/buckets (a different
    #: executable, a different failure domain than the plain program
    #: at the same shape) — the one definition ``_key_label`` and the
    #: cached dispatch's bucket label both use
    CACHE_LABEL_SUFFIX = "/cache"
    #: ragged capacity-class groups/buckets: the key dims are the
    #: CLASS box, not a request shape, and the executable lives in the
    #: engine's ragged table — its own failure domain too
    RAGGED_LABEL_SUFFIX = "/ragged"

    @classmethod
    def _key_label(cls, key) -> str:
        """Namespace-less label for a coalescing-group key: ``HxW``,
        plus :attr:`CACHE_LABEL_SUFFIX` / :attr:`RAGGED_LABEL_SUFFIX`
        for feature-cache / capacity-class groups — shared by
        ``_label`` and ``health()``."""
        base = f"{key[0]}x{key[1]}"
        if len(key) > 2:
            return base + (cls.RAGGED_LABEL_SUFFIX
                           if key[2] == "ragged"
                           else cls.CACHE_LABEL_SUFFIX)
        return base

    def _label(self, key, lane: Optional[_ReplicaLane] = None) -> str:
        """Breaker/event label for a request shape: ``model/HxW``
        under a registry namespace, plain ``HxW`` single-model — the
        per-model+bucket key the shared metrics.jsonl needs. A fleet
        lane appends its replica suffix (``model/HxW/r<k>``): one
        replica's failure domain, one label."""
        base = self._key_label(key)
        if lane is not None:
            base = f"{base}/r{lane.index}"
        return f"{self.namespace}/{base}" if self.namespace else base

    def _breaker(self, key: Tuple[int, int],
                 lane: Optional[_ReplicaLane] = None
                 ) -> Optional[CircuitBreaker]:
        """The shape's breaker — on ``lane``'s own board in fleet mode
        (a wedge on replica k's executable opens replica k's breaker,
        nobody else's) — created on first dispatch (so health lists
        every active bucket). None when breakers are disarmed."""
        if not self._breaker_failures:
            return None
        board = lane.breakers if lane is not None else self._breakers
        with self._cv:
            br = board.get(key)
            if br is not None:
                return br
        label = self._label(key, lane)
        br = CircuitBreaker(
            failures=self._breaker_failures,
            base_s=self._breaker_backoff_s,
            max_s=self._breaker_backoff_max_s,
            rng=self._breaker_rng,
            label=label,
            on_transition=lambda old, new, label=label:
                self._on_breaker(label, old, new))
        with self._cv:
            return board.setdefault(key, br)

    def _on_breaker(self, label: str, old: str, new: str) -> None:
        self.metrics.record_breaker_transition(label, old, new)
        self._refresh_state(f"breaker {label} {old}->{new}")

    def _compute_state(self) -> str:
        if not self._closed and not self._worker.is_alive():
            return "wedged"      # dispatcher died: nothing drains
        t0 = self._inflight_since
        if (self.dispatch_timeout_s is not None and t0 is not None
                and time.monotonic() - t0 > self.dispatch_timeout_s):
            return "wedged"      # verdict due/being handled right now
        if self._lanes and self.dispatch_timeout_s is not None:
            now = time.monotonic()
            for lane in self._lanes:
                job = lane.job
                if (job is not None and not job.done.is_set()
                        and now - lane.t_launch
                        > self.dispatch_timeout_s):
                    return "wedged"   # lane verdict due right now
        if self._completion is not None \
                and self.dispatch_timeout_s is not None:
            with self._pipe_lock:
                head = (self._pending_jobs[0] if self._pending_jobs
                        else None)
                age = (time.monotonic() - head.t_start
                       if head is not None else 0.0)
            if age > self.dispatch_timeout_s:
                return "wedged"  # completion-stage verdict due
        with self._cv:
            breakers = list(self._breakers.values())
            for lane in self._lanes:
                breakers.extend(lane.breakers.values())
        if any(br.peek() != BREAKER_CLOSED for br in breakers):
            return "degraded"
        if any(lane.quarantined for lane in self._lanes):
            return "degraded"    # serving on a reduced fleet
        if self.host_fleet is not None \
                and self.host_fleet.degradation() != "healthy":
            return "degraded"    # a host suspect/dead/partitioned
        return "healthy"

    def _refresh_state(self, reason: str) -> None:
        # lock order: _state_lock -> _cv -> breaker lock (compute
        # walks the breaker board); nothing takes _state_lock while
        # holding either of the others
        with self._state_lock:
            new = self._compute_state()
            old = self._health_state
            if new != old:
                self._health_state = new
                self.metrics.record_state_change(old, new, reason)

    def health(self) -> Dict:
        """Operator surface: overall state (``healthy`` — everything
        closed and live; ``degraded`` — at least one bucket breaker
        open/half-open; ``wedged`` — a dispatch is past its deadline or
        the dispatcher thread is dead), per-bucket breaker states,
        worker liveness, ages, and the quarantined-thread leak
        count."""
        self._refresh_state("health probe")
        now = time.monotonic()
        with self._cv:
            breakers = dict(self._breakers)
            depth = len(self._q)
        with self._pipe_lock:
            pending = len(self._pending_jobs)
        t0 = self._inflight_since
        done = self._last_dispatch_done
        out = {
            "state": self._health_state,
            "buckets": {self._key_label(k): br.snapshot()
                        for k, br in sorted(breakers.items())},
            "worker_alive": self._worker.is_alive(),
            "dispatch_worker_alive": (self._exec.worker_alive()
                                      if self._exec else None),
            "queue_depth": depth,
            "inflight_age_s": (round(now - t0, 3)
                               if t0 is not None else None),
            "last_dispatch_age_s": (round(now - done, 3)
                                    if done is not None else None),
            "quarantined_threads": self.metrics.quarantined_threads,
            "quarantined_alive": (self._exec.quarantined_alive()
                                  if self._exec else 0)
            + (self._completion.quarantined_alive()
               if self._completion else 0)
            + sum(lane.exec.quarantined_alive()
                  for lane in self._lanes),
            "pending_completions": pending,
            "completion_worker_alive": (self._completion.worker_alive()
                                        if self._completion else None),
        }
        if self._lanes:
            out["fleet"] = {
                "replicas": len(self._lanes),
                "active": sum(1 for ln in self._lanes if ln.active),
                "ceiling": self.placement.ceiling,
                "concurrency_max": self._concurrency_max,
                "placement": self.placement.snapshot(),
                "lanes": {
                    f"r{ln.index}": {
                        "active": ln.active,
                        "quarantined": ln.quarantined,
                        "busy": ln.job is not None,
                        "dispatches": ln.dispatches,
                        "worker_alive": ln.exec.worker_alive(),
                        **({"host": ln.host}
                           if ln.host is not None else {}),
                        "breakers": {
                            self._key_label(k): br.snapshot()
                            for k, br in sorted(
                                dict(ln.breakers).items())},
                    } for ln in self._lanes},
            }
        if self.host_fleet is not None:
            # degradation states healthy|degraded|partitioned + the
            # per-host heartbeat/failover/push evidence
            out["hosts"] = self.host_fleet.health()
        return out

    # -- dispatch loop -----------------------------------------------------

    def _shape_capacity(self, key,
                        lane: Optional[_ReplicaLane] = None) -> int:
        """Per-key dispatch capacity, probed/warmed through the
        placement layer's :meth:`~raft_tpu.parallel.placement.
        Placement.bucket_fit` (the capacity-or-ensure logic that used
        to live here — one copy, engine-parametric, so every fleet
        lane warms ITS engine's table exactly the way the single
        engine always did). Cached per key (per key+replica in fleet
        mode: capacity is a property of one replica's table — a wedge
        drops one replica's bucket, not the fleet's number)."""
        ck = key if lane is None else (key, lane.index)
        cap = self._capacity.get(ck)
        if cap is None:
            eng = lane.engine if lane is not None else self.engine
            fit = Placement.bucket_fit(eng, key, self.max_batch)
            cap = max(1, min(fit, self.max_batch))
            self._capacity[ck] = cap
        return cap

    def _expire(self, req: _Request, now: float) -> bool:
        if req.deadline is not None and now > req.deadline:
            if settle_future(
                    req.future, DeadlineExceeded(
                        f"deadline expired after "
                        f"{now - req.t_submit:.3f}s in queue (never "
                        "dispatched)"),
                    # raced: the caller cancelled between the
                    # cancelled() check and here — count it as the
                    # cancel it was, and don't let the race kill a
                    # submitter or the dispatcher
                    raced=self.metrics.record_cancelled):
                self.metrics.record_deadline_miss(priority=req.priority)
                if self.tracer is not None and req.span is not None:
                    self.tracer.close(req.span, "deadline_expired",
                                      "deadline_missed")
            else:
                self._trace_cancel(req)
            return True
        return False

    def _sweep_locked(self, now: float) -> None:
        """Drop expired/caller-cancelled entries from the queue
        (caller holds ``_cv``). The single queue representation — every
        path that rewrites the queue goes through here or ``_take``,
        both keeping it a deque (a plain-list rebind would crash
        ``close``'s ``popleft``; pinned by regression test)."""
        if not any(r.deadline is not None or r.future.cancelled()
                   for r in self._q):
            return
        keep: Deque[_Request] = collections.deque()
        for r in self._q:
            if r.future.cancelled():
                self.metrics.record_cancelled()
                self._trace_cancel(r)
            elif self._expire(r, now):
                pass
            else:
                keep.append(r)
        self._q = keep

    def _expiry_scan(self) -> None:
        """Expiry edge usable from the supervision loop while a
        dispatch is in flight — queued deadlines fire within one poll
        tick instead of waiting out a slow compile or hung device."""
        with self._cv:
            self._sweep_locked(time.monotonic())

    def _gather(self, key: Tuple[int, int], capacity: int) -> None:
        """Hold dispatch open briefly so concurrent submitters can fill
        the micro-batch — bounded by ``gather_window_s``; a full batch
        (or a closing scheduler) never waits."""
        t_end = time.monotonic() + self.gather_window_s
        while True:
            with self._cv:
                if (self._closed
                        or sum(1 for r in self._q if r.key == key)
                        >= capacity):
                    return
            if time.monotonic() >= t_end:
                return
            time.sleep(min(0.0005, self.gather_window_s))

    def _take(self, key: Tuple[int, int], capacity: int,
              prefer: Optional[str] = None) -> List[_Request]:
        """Pop up to ``capacity`` same-shape requests FIFO, expiring
        stale deadlines (and reaping caller-cancelled futures) across
        the whole queue on the way. ``prefer`` (a priority class)
        takes that class's entries first, then fills FIFO — without
        it, a same-shape batch flood queued AHEAD of the interactive
        head would defeat the weighted dequeue pick (``_take`` is
        shape-keyed, and FIFO would hand the flood the whole
        micro-batch). ``prefer=None`` is byte-identical to the
        historical FIFO."""
        now = time.monotonic()
        with self._cv:
            live: List[_Request] = []
            for r in self._q:
                if r.future.cancelled():
                    self.metrics.record_cancelled()
                    self._trace_cancel(r)
                elif self._expire(r, now):
                    pass
                else:
                    live.append(r)
            same = [r for r in live if r.key == key]
            if prefer is not None:
                want_batch = prefer == PRIORITY_BATCH
                same = ([r for r in same
                         if (r.priority == PRIORITY_BATCH) == want_batch]
                        + [r for r in same
                           if (r.priority == PRIORITY_BATCH)
                           != want_batch])
            taken = same[:capacity]
            ids = set(map(id, taken))
            self._q = collections.deque(r for r in live
                                        if id(r) not in ids)
        return taken

    def _fail_requests(self, requests: List[_Request], exc: Exception
                      ) -> int:
        """Settle ``requests`` with ``exc``; returns how many actually
        settled (already-done futures — raced by a wedge verdict or a
        late-waking quarantined thread — are skipped, keeping
        submitted == completed + failed + deadline_missed + cancelled
        exact). Tracing armed: each settled request's span closes
        under the ``failed`` class (outcome = the exception type),
        and its linked dispatch span closes with it so a wedged batch
        never orphans its fan-in record; a raced CANCEL closes the
        span cancelled, any other racer owns the close itself."""
        n = 0
        tr = self.tracer
        for r in requests:
            if r.future.done():
                # an already-done future here was settled by a racer
                # who closed its span — EXCEPT a caller cancel, which
                # owns nothing: close it (idempotent) or the span
                # orphans
                if tr is not None and r.future.cancelled():
                    self._trace_cancel(r)
                continue
            if settle_future(r.future, exc):
                n += 1
                if tr is not None and r.span is not None:
                    tr.close(r.span, type(exc).__name__, "failed",
                             reason=str(exc)[:160])
            elif tr is not None and r.span is not None \
                    and r.future.cancelled():
                self._trace_cancel(r)
        if tr is not None:
            # close the batch's linked dispatch span once, whatever
            # mix of settles/races the loop saw — an all-cancelled
            # batch must not orphan its fan-in record (idempotent; a
            # completion racer's "ok" close wins if it got there
            # first)
            self._trace_close_dispatch(requests, "failed")
        return n

    def _await_pipeline_slot(self) -> None:
        """Block the dispatcher until the pipeline has room for another
        in-flight batch (bounded depth — backpressure against a slow
        completion stage), scanning queued deadlines and the completion
        watchdog while waiting so neither stalls behind the wait."""
        if self._completion is None:
            return
        while True:
            with self._pipe_lock:
                n = len(self._pending_jobs)
            if n < self.pipeline_depth:
                return
            self._expiry_scan()
            self._check_completions()
            time.sleep(0.001)

    def _select_locked(self) -> Tuple[Tuple[int, int], Optional[str]]:
        """Dispatch-head choice (caller holds ``_cv``, queue
        nonempty): ``(key, preferred class)``. One queued class —
        including the priority-less default — dispatches pure FIFO
        with no preference (bitwise the historical path). With BOTH
        interactive and batch work queued, weighted round-robin: the
        interactive head wins ``interactive_weight`` picks per batch
        pick, so a batch flood cannot starve interactive p99 while
        batch still drains at a bounded fraction (never starved
        either). Priority-less requests ride the interactive class —
        default traffic must not queue behind a bulk flood. The
        winning class is also the ``_take`` preference: its requests
        fill the micro-batch first, the other class's same-shape work
        may ride along in spare rows."""
        if not (self._seen_batch and self._seen_interactive):
            # only one class has EVER been submitted: mixing is
            # impossible, skip the scan — the priority-less hot path
            # stays the O(1) peek it always was
            return self._q[0].key, None
        first_int = first_bat = None
        for r in self._q:
            if r.priority == PRIORITY_BATCH:
                if first_bat is None:
                    first_bat = r
            elif first_int is None:
                first_int = r
            if first_int is not None and first_bat is not None:
                break
        if first_int is None or first_bat is None:
            return self._q[0].key, None
        self._rr += 1
        if self._rr % (self.interactive_weight + 1) == 0:
            return first_bat.key, PRIORITY_BATCH
        return first_int.key, PRIORITY_INTERACTIVE

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._closed:
                    self._cv.wait(timeout=0.05)
                    if self._completion is not None:
                        break   # idle tick: run the completion watchdog
                key, prefer = (self._select_locked() if self._q
                               else (None, None))
                closed = self._closed
            if self._completion is not None:
                self._check_completions()
            if self.tracer is not None:
                # span records buffer under the leaf lock; the
                # dispatcher's tick is the serving-time flush point
                # (close() flushes the rest)
                self.tracer.flush()
            if key is None:
                if closed:
                    return
                continue
            self._await_pipeline_slot()
            br = self._breaker(key)
            if br is not None and br.state() == BREAKER_OPEN:
                # queued work behind an open breaker fails fast —
                # neither starving until deadline nor burning dispatch
                # slots other shapes could use
                doomed = self._take(key, self.max_queue or 1)
                n = self._fail_requests(doomed, CircuitOpen(
                    f"bucket {key} circuit open — failing fast"))
                self.metrics.record_failure(n)
                continue
            if self._exec is None:
                job = _DispatchJob(None)
                self._serve_key(key, job, prefer)
                self._after_dispatch(key, job)
            else:
                self._supervise(key, prefer)

    # -- fleet dispatch loop (replicas > 1) --------------------------------

    def _run_fleet(self) -> None:
        """The fleet dispatcher: ONE thread owns every lane's executor
        (submit/quarantine/close — the DispatchExecutor single-
        supervisor contract, N times over), fanning coalesced
        micro-batches across the least-loaded free replica while
        reaping finished lanes, wedging overdue ones, and scaling the
        active set against queue depth. Concurrency comes from the
        lanes: while replica k's executor runs a dispatch, this loop
        is already picking a lane for the next key."""
        while True:
            with self._cv:
                if not self._q and not self._closed \
                        and not self._busy_lanes():
                    self._cv.wait(timeout=0.05)
                key, prefer = (self._select_locked() if self._q
                               else (None, None))
                closed = self._closed
                swapping = self._swapping
            self._reap_lanes()
            if self.host_fleet is not None:
                self._host_notices()
            self._expiry_scan()
            if self.tracer is not None:
                self.tracer.flush()
            if key is None:
                if closed and not self._busy_lanes():
                    return
                if self._busy_lanes():
                    time.sleep(0.0005)
                self._retire_idle()
                continue
            if swapping:
                # fleet-atomic weight swap in progress: reap (above),
                # launch nothing — the epoch needs quiesced lanes
                time.sleep(0.0005)
                continue
            self._scale_fleet()
            lane = self._pick_lane(key)
            if lane is None:
                if self._fleet_all_open(key):
                    # the shape is open on every active replica:
                    # queued work fails fast, exactly the single-board
                    # open-breaker discipline
                    doomed = self._take(key, self.max_queue or 1)
                    n = self._fail_requests(doomed, CircuitOpen(
                        f"bucket {key} circuit open on every active "
                        "replica — failing fast"))
                    self.metrics.record_failure(n)
                elif not any(ln.active for ln in self._lanes) \
                        and len(self.placement.engines) \
                        >= self.placement.ceiling:
                    # every replica quarantined and no headroom to
                    # grow: nothing can ever serve this — fail rather
                    # than strand
                    doomed = self._take(key, self.max_queue or 1)
                    n = self._fail_requests(doomed,
                                            self._wedge_error(key))
                    self.metrics.record_failure(n)
                else:
                    # lanes busy (or probing backoff): wait a beat,
                    # reap on the next tick
                    time.sleep(0.0005)
                continue
            self._launch(lane, key, prefer)

    def _busy_lanes(self) -> int:
        return sum(1 for lane in self._lanes if lane.job is not None)

    def _reap_lanes(self) -> None:
        """Collect finished lane jobs: outcome bookkeeping (breaker
        success/failure on the LANE's board) and lane release."""
        for lane in self._lanes:
            job = lane.job
            if job is not None and job.done.is_set():
                lane.job = None
                lane.idle_since = time.monotonic()
                self._after_dispatch(job.key, job, lane)
        self._fleet_watchdog()

    def _fleet_watchdog(self) -> None:
        """Wedge verdict for any lane past ``dispatch_timeout_s`` —
        the per-lane analogue of ``_supervise``'s inline deadline."""
        if self.dispatch_timeout_s is None:
            return
        now = time.monotonic()
        for lane in self._lanes:
            job = lane.job
            if (job is not None and not job.done.is_set()
                    and now - lane.t_launch > self.dispatch_timeout_s):
                self._wedge_replica(lane, job)

    def _pick_lane(self, key) -> Optional[_ReplicaLane]:
        """Least-loaded FREE lane for ``key`` — or the primary alone
        when placement says the bucket pjit-shards (a sharded program
        only exists on the mesh-armed engine). Skips lanes whose
        breaker for the shape is open (their backoff expiry promotes
        to half_open, which re-admits the lane as the probe). None:
        nothing can take the key right now."""
        lanes = (self._lanes[:1]
                 if self.placement.decide(key) == "shard"
                 else self._lanes)
        best = None
        for lane in lanes:
            if not lane.active or lane.job is not None:
                continue
            br = lane.breakers.get(key)
            if br is not None and br.state() == BREAKER_OPEN:
                continue
            if best is None or lane.dispatches < best.dispatches:
                best = lane
        return best

    def _fleet_all_open(self, key) -> bool:
        if not self._breaker_failures:
            return False
        lanes = [lane for lane in self._lanes if lane.active]
        if not lanes:
            return False
        for lane in lanes:
            br = lane.breakers.get(key)
            if br is None or br.state() != BREAKER_OPEN:
                return False
        return True

    def _launch(self, lane: _ReplicaLane, key,
                prefer: Optional[str]) -> None:
        """Hand one micro-batch dispatch for ``key`` to ``lane``'s
        executor; the loop reaps it later (the lane stays busy until
        then)."""
        lane.dispatches += 1
        lane.idle_since = None
        lane.t_launch = time.monotonic()
        job = lane.exec.submit(
            lambda j, key=key, prefer=prefer, lane=lane:
            self._serve_key(key, j, prefer, lane=lane))
        job.key = key
        lane.job = job
        busy = self._busy_lanes()
        if busy > self._concurrency_max:
            self._concurrency_max = busy

    def _wedge_replica(self, lane: _ReplicaLane,
                       job: _DispatchJob) -> None:
        """Wedge verdict scoped to ONE replica: consequences first —
        abandon the job, drop the suspect executable from the LANE's
        engine (its siblings' tables are untouched — zero
        cross-replica leakage), open the lane's breaker, quarantine
        the lane's worker and RETIRE the lane — then fail the taken
        batch's futures. The queue survives: work the wedged lane
        never took keeps serving on the remaining replicas."""
        key = job.key
        job.abandoned = True   # a late-waking thread must abort, not
        #                        dispatch into a dropped bucket
        lane.job = None
        label = self._label(key, lane)
        if job.bucket is not None:
            # best-effort on a remote lane: the drop travels the wire,
            # and a wedged host is exactly the kind whose transport
            # may raise — the dispatcher thread must survive (the
            # lane is retired below either way; a stale remote bucket
            # dies with its worker)
            try:
                if job.ragged:
                    lane.engine.drop_bucket(job.bucket, ragged=True)
                else:
                    lane.engine.drop_bucket(job.bucket)
            except Exception:
                if lane.host is None:
                    raise
        self._capacity.pop((key, lane.index), None)
        br = self._breaker(key, lane)
        if br is not None:
            br.record_failure(wedged=True)
        alive = lane.exec.quarantine_and_replace()
        lane.prev_pending = None
        lane.active = False
        lane.quarantined = True
        self.metrics.record_quarantined(label, alive=alive)
        self.metrics.record_event(
            "replica_quarantined", replica=lane.index,
            bucket=self._key_label(key))
        exc = self._wedge_error(key)
        # fail ONLY what the wedged lane actually took — a pre-take
        # wedge (hung capacity probe) leaves the shape's queued work
        # for the surviving replicas
        n = self._fail_requests(list(job.batch or ()), exc)
        self.metrics.record_wedge(label, failed=n,
                                  timeout_s=self.dispatch_timeout_s)
        self._refresh_state(f"replica wedge on {label}")

    def _host_notices(self) -> None:
        """Drain the host fleet's liveness verdicts on the dispatcher
        tick — the ONE thread that owns the lanes applies every
        consequence (the heartbeat monitor only queues)."""
        for kind, name in self.host_fleet.pop_notices():
            lane = next((ln for ln in self._lanes if ln.host == name),
                        None)
            if lane is None:
                continue
            if kind == "dead":
                self._wedge_host(lane)
            elif kind == "rejoined":
                # full re-admission already happened (artifacts
                # re-pushed + verified, prewarm counters read): the
                # lane may serve again. Fresh breaker board + capacity
                # table — the restarted worker shares nothing with its
                # dead predecessor.
                lane.breakers = {}
                for ck in [ck for ck in self._capacity
                           if ck[1] == lane.index]:
                    self._capacity.pop(ck, None)
                lane.quarantined = False
                lane.active = True
                lane.idle_since = time.monotonic()
                self.placement.mark_host(name, "healthy")
                self._refresh_state(f"host {name} rejoined")

    def _wedge_host(self, lane: _ReplicaLane) -> None:
        """Dead-host verdict: the remote analogue of
        :meth:`_wedge_replica`, with FAILOVER instead of failure.
        Consequences first — abandon the in-flight job, clear the
        lane's capacity entries, open its breaker, quarantine its
        executor, mark the placement layer, poison the transport (this
        unsticks a lane thread blocked in the zombie's recv) — THEN
        the in-flight batch fails over: its not-yet-settled requests
        requeue for the surviving lanes (idempotent by request —
        futures stay pending/RUNNING and settle exactly once wherever
        they land; a late answer from the zombie is dropped by the
        ``job.abandoned`` check + ``settle_future``'s raced hook).
        Only when NO lane can ever serve again does the batch fail,
        with :class:`~raft_tpu.serving.hosts.HostDead`."""
        name = lane.host
        job = lane.job
        requeue: List[_Request] = []
        if job is not None:
            job.abandoned = True   # a late-waking lane thread must
            #                        drop its answer, never settle
            lane.job = None
            requeue = [r for r in (job.batch or ())
                       if not r.future.done()]
            job.batch = []   # reaped-nowhere: nothing may re-fail these
            key = job.key
            br = self._breaker(key, lane)
            if br is not None:
                br.record_failure(wedged=True)
        for ck in [ck for ck in self._capacity if ck[1] == lane.index]:
            self._capacity.pop(ck, None)
        alive = lane.exec.quarantine_and_replace()
        lane.prev_pending = None
        lane.active = False
        lane.quarantined = True
        self.placement.mark_host(name, "dead")
        self.host_fleet.poison(name)
        self.metrics.record_quarantined(f"host:{name}", alive=alive)
        self.metrics.record_event(
            "replica_quarantined", replica=lane.index,
            bucket=f"host:{name}")
        survivors = any(ln.active for ln in self._lanes)
        if requeue and not survivors \
                and len(self.placement.engines) >= self.placement.ceiling:
            # nothing left to fail over TO and no headroom to grow:
            # fail rather than strand (consequences above all landed)
            n = self._fail_requests(requeue, HostDead(
                f"host {name} verdicted dead with no surviving lane — "
                "in-flight work cannot fail over"))
            self.metrics.record_failure(n)
            requeue = []
        n = self._failover_requeue(lane, requeue)
        self.metrics.record_event("failover", host=name,
                                  replica=lane.index, requeued=n)
        self.host_fleet.record_failover(name, requeued=n)
        self._refresh_state(f"host {name} dead")

    def _failover_requeue(self, lane: _ReplicaLane,
                          requests: List[_Request]) -> int:
        """Put a dead host lane's in-flight requests back at the head
        of the shared queue for the surviving lanes. Idempotent: a
        request already settled, or already requeued by the other side
        of the verdict race, is skipped — each settles exactly once.
        No accounting changes here: the requests never left
        ``submitted`` and will be counted by whatever finally settles
        them."""
        n = 0
        with self._cv:
            for r in reversed(requests):
                if r.future.done() or r in self._q:
                    continue
                self._q.appendleft(r)
                n += 1
            if n:
                self._cv.notify_all()
        return n

    def _scale_fleet(self) -> None:
        """Queue-pressure scale-up within the ceiling: reactivate a
        retired (non-quarantined) lane first, else grow a fresh
        replica through the placement layer (AOT-warmed — the spawn
        loads, it does not compile)."""
        with self._cv:
            depth = len(self._q)
        if not depth:
            return
        active = sum(1 for lane in self._lanes if lane.active)
        if active and not self.placement.want_scale_up(
                depth, active, self.max_batch):
            return
        if active >= self.placement.ceiling:
            return
        for lane in self._lanes:
            # host lanes only (re)activate through the fleet's rejoin
            # protocol (artifacts verified + prewarmed), never by
            # queue-pressure policy
            if not lane.active and not lane.quarantined \
                    and lane.host is None:
                lane.active = True
                lane.idle_since = time.monotonic()
                self.metrics.record_event(
                    "replica_activated", replica=lane.index,
                    queue_depth=depth)
                return
        if len(self.placement.engines) >= self.placement.ceiling:
            return   # only quarantined lanes left below the ceiling
        try:
            eng = self.placement.grow()
        except Exception as exc:  # noqa: BLE001 — scale-up is advisory
            self.metrics.record_event("replica_grow_failed",
                                      error=str(exc)[:160])
            return
        lane = _ReplicaLane(len(self._lanes), eng)
        self._lanes.append(lane)
        self.metrics.record_event("replica_activated",
                                  replica=lane.index, queue_depth=depth,
                                  grown=True)

    def _retire_idle(self) -> None:
        """Idle-time scale-down back toward the configured floor
        (never the primary — shard-pinned buckets only run there)."""
        now = time.monotonic()
        active = sum(1 for lane in self._lanes if lane.active)
        for lane in reversed(self._lanes):
            if (lane.index > 0 and lane.host is None and lane.active
                    and lane.job is None
                    and lane.idle_since is not None
                    and self.placement.want_retire(
                        now - lane.idle_since, active,
                        self.replica_idle_retire_s)):
                lane.active = False
                active -= 1
                self.metrics.record_event(
                    "replica_retired", replica=lane.index,
                    idle_s=round(now - lane.idle_since, 3))

    def _supervise(self, key: Tuple[int, int],
                   prefer: Optional[str] = None) -> None:
        """Run one supervised dispatch for ``key`` on the executor,
        scanning queued deadlines while it is in flight; wedge verdict
        past ``dispatch_timeout_s``."""
        timeout = self.dispatch_timeout_s
        job = self._exec.submit(
            lambda j, key=key, prefer=prefer:
            self._serve_key(key, j, prefer))
        self._inflight_since = time.monotonic()
        try:
            poll = min(0.02, timeout / 4)
            while not job.done.wait(poll):
                self._expiry_scan()
                if self._completion is not None:
                    self._check_completions()
                if time.monotonic() - self._inflight_since > timeout:
                    self._wedge_verdict(key, job)
                    return
            self._after_dispatch(key, job)
        finally:
            self._inflight_since = None
            self._refresh_state("dispatch settled")

    def _wedge_error(self, key: Tuple[int, int]) -> DispatchWedged:
        return DispatchWedged(
            f"dispatch for bucket {key[0]}x{key[1]} exceeded "
            f"dispatch_timeout_s={self.dispatch_timeout_s}: futures "
            "failed, thread quarantined, executable dropped — "
            "half-open probe will recompile")

    def _wedge_verdict(self, key: Tuple[int, int], job: _DispatchJob
                       ) -> None:
        """The watchdog's exit-class discipline, serving-side: fail the
        wedged batch, quarantine + replace the stuck thread (accounted,
        not hidden), drop the suspect executable, open the breaker."""
        timeout = self.dispatch_timeout_s
        job.abandoned = True  # a late-waking thread must abort, not
        #                       dispatch into (and recompile) a
        #                       dropped bucket
        self._inflight_since = None  # supervision is over: health is
        #                              degraded now, not wedged
        label = self._label(key)
        # verdict consequences land BEFORE the futures fail, so a
        # caller woken by its DispatchWedged observes consistent state
        # (executable dropped, breaker open, health degraded)
        if job.bucket is not None:
            # engine recovery: the executable that hung is suspect —
            # drop it (and the cached capacity routed through it) so
            # the half-open probe recompiles from clean state
            if job.ragged:
                self.engine.drop_bucket(job.bucket, ragged=True)
            elif job.cached:
                self.engine.drop_bucket(job.bucket, cached=True)
            else:
                self.engine.drop_bucket(job.bucket)
        self._capacity.pop(key, None)
        br = self._breaker(key)
        if br is not None:
            br.record_failure(wedged=True)
        alive = self._exec.quarantine_and_replace()
        self.metrics.record_quarantined(label, alive=alive)
        exc = self._wedge_error(key)
        # fail the taken batch; a wedge before _take (a hung compile in
        # the capacity probe) instead fails the shape's queued requests
        # — nothing may stay stranded behind a stuck thread
        batch = job.batch
        if batch is None:
            batch = self._take(key, self.max_queue or 1)
        n = self._fail_requests(batch, exc)
        self.metrics.record_wedge(label, failed=n, timeout_s=timeout)
        self._refresh_state(f"wedge verdict on {label}")

    def _check_completions(self) -> None:
        """Completion-stage watchdog (pipeline_depth > 1, watchdog
        armed): verdict the OLDEST pending completion past the
        deadline. Only the head — it is the job the completion worker
        is actually on (FIFO, single worker); trailing jobs age behind
        it and get their own verdicts on later ticks if the cascade is
        real."""
        if self.dispatch_timeout_s is None:
            return
        with self._pipe_lock:
            job = self._pending_jobs[0] if self._pending_jobs else None
        if job is not None and job.t_start is not None \
                and time.monotonic() - job.t_start \
                > self.dispatch_timeout_s:
            self._wedge_completion(job)

    def _wedge_completion(self, job: _DispatchJob) -> None:
        """Wedge verdict on a pipelined completion (device compute or
        D2H that never finishes): same consequences-before-futures-fail
        ordering as the dispatch-stage verdict, now spanning in-flight
        batches — drop the suspect executable, open the breaker,
        quarantine + replace the completion worker (re-queuing the
        completions parked BEHIND the stuck one so they can't strand),
        THEN fail the wedged batch."""
        key = job.key
        job.abandoned = True   # a late-waking fetch must not settle
        #                        results or record a breaker success
        label = self._label(key)
        if job.bucket is not None:
            if job.ragged:
                # a ragged completion hung: indict the capacity-class
                # executable in the ragged table
                self.engine.drop_bucket(job.bucket, ragged=True)
            elif job.cached:
                # the executable that hung is the CACHED program —
                # indict it, not its plain sibling at the same shape
                self.engine.drop_bucket(job.bucket, cached=True)
            else:
                self.engine.drop_bucket(job.bucket)
        self._capacity.pop(key, None)
        br = self._breaker(key)
        if br is not None:
            br.record_failure(wedged=True)
        # snapshot + worker swap + re-queue are one atom under
        # _pipe_lock, mirroring the handoff atom in _dispatch: no
        # completion can slip into the dying mailbox between the
        # trailing snapshot and the replacement spawn
        with self._pipe_lock:
            try:
                self._pending_jobs.remove(job)
            except ValueError:
                pass   # completion raced the verdict and finished
            trailing = list(self._pending_jobs)
            alive = self._completion.quarantine_and_replace()
            for t in trailing:
                # their mailbox entries died with the quarantined
                # worker's mailbox — re-queue on the replacement, in
                # order, with a fresh watchdog stamp (their queue-wait
                # behind the wedged head must not pre-spend their own
                # deadline)
                t.t_start = time.monotonic()
                self._completion.enqueue(t)
        self._prev_pending = None   # the wedged fetch never completes:
        #                             don't pin its buffers (or feed
        #                             its t_ready to the gap clock)
        self.metrics.record_quarantined(label, alive=alive)
        exc = self._wedge_error(key)
        n = self._fail_requests(list(job.batch or ()), exc)
        self.metrics.record_wedge(label, failed=n,
                                  timeout_s=self.dispatch_timeout_s)
        self._refresh_state(f"completion wedge on {label}")

    def _after_dispatch(self, key: Tuple[int, int], job: _DispatchJob,
                        lane: Optional[_ReplicaLane] = None) -> None:
        """Outcome bookkeeping for a dispatch that settled in time
        (``lane``: the fleet lane that ran it — its board takes the
        breaker outcome)."""
        if job.error is not None and job.batch:
            # a failure that escaped _serve_key's routing (e.g. the
            # serve.dispatch_exec fault firing mid-job) with requests
            # already taken: settle them here — never strand
            n = self._fail_requests(list(job.batch), job.error)
            self.metrics.record_failure(n)
        br = self._breaker(key, lane)
        if job.error is not None or job.outcome == "failed":
            if br is not None:
                br.record_failure()
        elif job.outcome == "ok":
            self._last_dispatch_done = time.monotonic()
            if br is not None:
                br.record_success()
        # "dispatched": handed off to the completion stage — it owns
        # the breaker outcome (success must mean RESULTS, not enqueue)
        self._refresh_state("dispatch outcome")

    def _serve_key(self, key: Tuple[int, int], job: _DispatchJob,
                   prefer: Optional[str] = None,
                   lane: Optional[_ReplicaLane] = None) -> None:
        """One micro-batch for ``key``: capacity (may compile) ->
        gather -> take (``prefer``'s class first) -> dispatch. Runs
        inline on the dispatcher thread (no watchdog), on the
        supervised executor, or — fleet mode — on ``lane``'s executor
        against ``lane``'s engine."""
        try:
            # capacity may compile a bucket — never under the queue
            # lock (submitters would shed through the whole compile)
            capacity = self._shape_capacity(key, lane)
        except Exception as exc:
            if (lane is not None and lane.host is not None
                    and isinstance(exc, TransportError)):
                # the probe died with the HOST, not the shape: take
                # nothing — the queued work stays for the surviving
                # lanes, the lane breaker records the failure (via
                # _after_dispatch) and the heartbeat verdict owns
                # quarantine/failover
                job.error = exc
                job.outcome = "failed"
                return
            # an unservable shape (mesh-invalid extent, a compile
            # failure) fails ITS requests — it must not kill the
            # dispatcher and strand every queued future unsettled
            # behind a dead thread
            doomed = self._take(key, self.max_batch)
            job.batch = doomed
            self.metrics.record_failure(self._fail_requests(doomed, exc))
            job.outcome = "failed"
            return
        if job.abandoned:
            # the capacity probe (a compile) outlived the watchdog: a
            # quarantined thread must not take fresh work — but its
            # compile was NOT wasted (ensure_bucket's first-insert-wins
            # means the replacement's probe finds the bucket ready)
            return
        self._gather(key, capacity)
        batch = self._take(key, capacity, prefer)
        job.batch = batch
        if job.abandoned:
            # verdict landed between the check above and the take: the
            # verdict saw batch=None, so disposing of these is OUR job
            # — a quarantined thread may never strand what it took. On
            # a host lane the verdict is a DEAD-HOST failover: the
            # requests go back to the queue for the survivors; on a
            # local lane the wedge verdict failed the batch, so these
            # stragglers fail the same way.
            if lane is not None and lane.host is not None:
                self._failover_requeue(lane, batch)
            else:
                self.metrics.record_failure(self._fail_requests(
                    batch, self._wedge_error(key)))
            return
        if batch:
            if len(key) > 2 and key[2] == "ragged":
                self._dispatch_ragged(key, batch, job, lane)
            elif len(key) > 2:
                self._dispatch_cached(key, batch, job)
            else:
                self._dispatch(key, batch, job, lane)

    def _assemble_flow_init(self, live: List[_Request], key):
        """The micro-batch's coalesced warm start, or None when every
        row is cold. Host rows build an np batch (zero rows ARE cold
        starts); if any row is device-resident the batch assembles ON
        DEVICE (scatter into device zeros) so session state never
        round-trips through host memory."""
        if not any(r.flow_init is not None for r in live):
            return None
        h, w = key
        n = len(live)
        left, right, top, bottom = pad_amounts(h, w)
        lh = (h + top + bottom) // 8
        lw = (w + left + right) // 8
        if any(r.flow_init is not None
               and not isinstance(r.flow_init, np.ndarray)
               for r in live):
            import jax.numpy as jnp
            finit = jnp.zeros((n, lh, lw, 2), jnp.float32)
            for i, r in enumerate(live):
                if r.flow_init is not None:
                    finit = finit.at[i].set(r.flow_init)
            return finit
        finit = np.zeros((n, lh, lw, 2), np.float32)
        for i, r in enumerate(live):
            if r.flow_init is not None:
                finit[i] = r.flow_init
        return finit

    def _settle(self, live: List[_Request], outs, label: str,
                t_disp: float, warm: bool,
                replica: Optional[int] = None,
                host: Optional[str] = None) -> None:
        """Resolve a finished micro-batch's futures + per-request
        latency records (inline at depth 1, on the completion worker
        at depth > 1; ``replica`` stamps fleet completions into the
        per-replica metrics block; ``host`` set means a lost settle
        race is a ZOMBIE answer — the request already failed over and
        settled elsewhere, counted as a drop, never double-settled)."""
        if warm:
            flows, lows = outs
        else:
            flows, lows = outs, None
        raced = (None if host is None
                 else lambda: self.metrics.record_host_zombie_drop(host))
        t_done = time.monotonic()
        for i, r in enumerate(live):
            low = None
            if lows is not None and r.want_low:
                low = lows[i]
                if not r.low_device and not isinstance(low, np.ndarray):
                    low = np.asarray(low)
            if not settle_future(r.future, ServeResult(flows[i], low),
                                 raced):
                # wedge verdict settled it first (and owns the span
                # close); a raced caller cancel owns nothing — close
                # the span cancelled (idempotent either way)
                if r.future.cancelled():
                    self._trace_cancel(r)
                continue
            queue_ms = (t_disp - r.t_submit) * 1e3
            device_ms = (t_done - t_disp) * 1e3
            tail = self.metrics.record_complete(
                label, queue_ms=queue_ms, device_ms=device_ms,
                priority=r.priority,
                trace_id=(r.span.trace_id if r.span is not None
                          else None),
                replica=replica)
            if self.tracer is not None and r.span is not None:
                # observed_ms: the exact value the latency histogram
                # binned — serve_trace's top-bucket selection must
                # reproduce the histogram's membership, not re-derive
                # it from the span's own (ms-skewed) close clock
                self.tracer.close(
                    r.span, "completed", "completed", tail=tail,
                    observed_ms=round(queue_ms + device_ms, 3))
        self._trace_close_dispatch(live, "ok")

    def _run_completion(self, key, live: List[_Request], pending,
                        job: _DispatchJob, settle) -> None:
        """Completion-stage skeleton (pipeline_depth > 1), shared by
        the plain and cached paths: the blocking fetch + settle off
        the dispatch path, on the completion executor's worker.
        ``settle(outs)`` is the ONLY mode-specific step — the
        abandoned/breaker/accounting protocol must never diverge
        between the two. A verdicted (abandoned) job settles nothing
        and records no breaker outcome."""
        # the watchdog clock restarts when the worker actually BEGINS
        # this job: queue-wait behind a slow-but-legal predecessor must
        # not count against dispatch_timeout_s, or steady traffic at
        # fetch_time > timeout/depth wedges healthy batches. The stuck
        # cases still age correctly: a hang in the executor loop's own
        # fault site (before fn) leaves the handoff stamp running, and
        # a hang in fetch ages from here.
        job.t_start = time.monotonic()
        if self.tracer is not None:
            # the pending carries its batch's span context (set at
            # dispatch): stamp the device-fetch phase edge from the
            # completion worker that actually blocks on it
            ctx = getattr(pending, "span_ctx", None)
            if ctx:
                t = time.monotonic()
                for s in ctx:
                    if s is not None:
                        self.tracer.mark(s, "fetch_start", at=t)
        try:
            try:
                outs = pending.fetch()
            except Exception as exc:
                if job.abandoned:
                    return
                self.metrics.record_failure(
                    self._fail_requests(live, exc))
                job.outcome = "failed"
                br = self._breaker(key)
                if br is not None:
                    br.record_failure()
                self._refresh_state("completion failed")
                return
            if job.abandoned:
                # verdict landed between the fetch returning and here:
                # the verdict already failed these futures — the
                # safety-net settle below covers the race where it saw
                # an empty batch (guards keep accounting exact)
                n = self._fail_requests(live, self._wedge_error(key))
                if n:
                    self.metrics.record_failure(n)
                return
            settle(outs)
            job.outcome = "ok"
            self._last_dispatch_done = time.monotonic()
            br = self._breaker(key)
            if br is not None:
                br.record_success()
            self._refresh_state("completion outcome")
        finally:
            with self._pipe_lock:
                try:
                    self._pending_jobs.remove(job)
                except ValueError:
                    pass   # a wedge verdict removed it already
            if self.tracer is not None:
                self.tracer.flush()   # after the lock: I/O stays
                #                       lock-free (T1)

    def _complete_batch(self, key: Tuple[int, int], label: str,
                        live: List[_Request], pending, t_disp: float,
                        warm: bool, job: _DispatchJob) -> None:
        self._run_completion(
            key, live, pending, job,
            lambda outs: self._settle(live, outs, label, t_disp, warm))

    def _dispatch(self, key: Tuple[int, int], batch: List[_Request],
                  job: _DispatchJob,
                  lane: Optional[_ReplicaLane] = None) -> None:
        eng = lane.engine if lane is not None else self.engine
        replica = lane.index if lane is not None else None
        live: List[_Request] = []
        for r in batch:
            # once this returns True the future can no longer be
            # cancelled: a dispatched request is never abandoned — the
            # acceptance invariant behind metrics.abandoned_inflight==0
            try:
                running = r.future.set_running_or_notify_cancel()
            except (InvalidStateError, RuntimeError):
                # stdlib futures raise bare RuntimeError here for any
                # non-PENDING state
                if r.future.done():
                    continue  # wedge verdict settled it between take
                    #           and here
                # already RUNNING: a failed-over request whose first
                # dispatch died with its host — re-dispatch is
                # idempotent (the future settles exactly once, and it
                # can no longer be cancelled, same as first dispatch)
                running = True
            if running:
                live.append(r)
            else:
                self.metrics.record_cancelled()
                self._trace_cancel(r)
        if not live:
            return
        job.batch = live
        h, w = key
        n = len(live)
        t_disp = time.monotonic()
        try:  # EVERYTHING here routes failures to the batch's futures —
            # nothing may escape and kill the dispatcher thread
            bucket = eng.route_bucket(n, h, w)
            job.bucket = bucket
            label = "x".join(map(str, bucket))
            if lane is not None:
                label = f"{label}/r{lane.index}"
            with self._cv:
                depth = len(self._q)
            # padding-waste gauge: requested pixels vs the padded
            # pixels the executable actually runs (batch fill + align
            # pad + bucket fill) — comparable across the bucketed and
            # ragged paths, shared with the dispatch span
            real_px = n * h * w
            padded_px = bucket[0] * bucket[1] * bucket[2]
            self.metrics.record_dispatch(
                label, filled=n, capacity=bucket[0], depth=depth,
                real_px=real_px, padded_px=padded_px, replica=replica)
            self._trace_dispatch(live, label, bucket, t_disp,
                                 real_px=real_px, padded_px=padded_px,
                                 **({"replica": replica}
                                    if replica is not None else {}))
            fault_point("serve.request")
            if job.abandoned:
                # wedge verdict landed while we were stuck above:
                # routing into the engine now would compile a leaked
                # duplicate. Dispose of anything the verdict's batch
                # read raced past (it may have seen batch=None) — a
                # quarantined thread never strands what it took. Host
                # lanes fail over; local lanes fail.
                if lane is not None and lane.host is not None:
                    self._failover_requeue(lane, live)
                else:
                    self.metrics.record_failure(self._fail_requests(
                        live, self._wedge_error(key)))
                return
            warm = getattr(eng, "warm_start", False)
            prev = (lane.prev_pending if lane is not None
                    else self._prev_pending)
            overlapped = prev is not None and prev.t_ready is None
            t_asm0 = time.monotonic()
            i1 = np.stack([r.image1 for r in live])
            i2 = np.stack([r.image2 for r in live])
            finit = self._assemble_flow_init(live, key) if warm else None
            call_async = getattr(eng, "infer_batch_async", None)
            if call_async is None:
                # duck-typed engine without the async API: synchronous
                # call, settled inline (no pipelining, no gap stats)
                if lane is not None:
                    lane.prev_pending = None
                else:
                    self._prev_pending = None
                if warm:
                    outs = eng.infer_batch(
                        i1, i2, flow_init=finit, return_low=True)
                else:
                    outs = eng.infer_batch(i1, i2)
                if job.abandoned:
                    # a ZOMBIE answer: the dead-host verdict landed
                    # while the RPC was out and already failed over
                    # (or failed) this batch — drop the late result
                    # wholesale, never double-settle
                    if lane is not None and lane.host is not None:
                        for _ in live:
                            self.metrics.record_host_zombie_drop(
                                lane.host)
                    return
                self._settle(live, outs, label, t_disp, warm,
                             replica=replica,
                             host=(lane.host if lane is not None
                                   else None))
                job.outcome = "ok"
                return
            if warm:
                low_dev = any(r.want_low and r.low_device for r in live)
                pending = call_async(i1, i2, flow_init=finit,
                                     return_low=True,
                                     low_device=low_dev)
            else:
                pending = call_async(i1, i2)
            # hot-path sample: gap = host-observed device idle before
            # this dispatch (0 when we shipped before the previous
            # batch's results were even ready — perfect overlap)
            t_call_end = time.monotonic()
            gap_ms = None
            if prev is not None:
                gap_ms = (0.0 if prev.t_ready is None
                          else max(0.0, (t_call_end - prev.t_ready)
                                   * 1e3))
            self.metrics.record_hot_path(
                gap_ms=gap_ms, assembly_ms=(t_call_end - t_asm0) * 1e3,
                overlapped=overlapped, h2d_bytes=pending.h2d_bytes,
                requests=n)
            self._trace_mark(live, "shipped", at=t_call_end)
            self._trace_span_ctx(pending, live)
            if lane is not None:
                lane.prev_pending = pending
            else:
                self._prev_pending = pending
            if job.abandoned:
                # a wedge verdict landed while the engine call was out
                # (hung compile that eventually returned): the verdict
                # already failed these futures, dropped the bucket and
                # opened the breaker — handing off now would record a
                # completion SUCCESS that closes the breaker the
                # verdict just opened. Settle any stragglers and stop.
                n = self._fail_requests(live, self._wedge_error(key))
                if n:
                    self.metrics.record_failure(n)
                return
            if self._completion is None:
                self._trace_mark(live, "fetch_start")
                self._settle(live, pending.fetch(), label, t_disp, warm,
                             replica=replica)
                job.outcome = "ok"
                return
            # pipelined handoff: the blocking fetch + settle move to
            # the completion worker; the dispatcher is free to assemble
            # the next micro-batch while the device computes this one
            cjob = _DispatchJob(
                lambda j, key=key, label=label, live=live,
                pending=pending, t_disp=t_disp, warm=warm:
                self._complete_batch(key, label, live, pending,
                                     t_disp, warm, j))
            cjob.key = key
            cjob.bucket = bucket
            cjob.batch = live
            cjob.t_start = time.monotonic()
            # append + mailbox enqueue are one atom under _pipe_lock:
            # a concurrent completion-wedge verdict swaps the mailbox
            # under the same lock, so a handoff lands either fully
            # before the swap (re-queued with the trailing jobs) or
            # fully after (queued on the replacement) — never into the
            # dead mailbox
            with self._pipe_lock:
                self._pending_jobs.append(cjob)
                self._completion.enqueue(cjob)
            job.outcome = "dispatched"   # the breaker verdict belongs
            #                              to the completion stage now
        except Exception as exc:  # route to the callers; worker survives
            if job.abandoned:
                # the raise IS the dead-host verdict unsticking us
                # (poisoned transport) — the verdict already owned the
                # batch (requeued or failed); settling here would
                # double-dispose the very futures it failed over
                job.outcome = "failed"
                return
            if (lane is not None and lane.host is not None
                    and isinstance(exc, TransportError)):
                # the transport died mid-dispatch BEFORE any heartbeat
                # verdict (e.g. socket reset the instant the worker
                # was killed): fail over NOW — requeue the live batch
                # for the surviving lanes, keep job.error so the lane
                # breaker paces re-picks; the missed-beat ladder will
                # deliver the quarantine verdict shortly
                n = self._failover_requeue(lane, live)
                self.metrics.record_event(
                    "failover", host=lane.host, replica=lane.index,
                    requeued=n)
                if self.host_fleet is not None:
                    self.host_fleet.record_failover(lane.host,
                                                    requeued=n)
                job.batch = []
                job.error = exc
                job.outcome = "failed"
                return
            self.metrics.record_failure(self._fail_requests(live, exc))
            job.outcome = "failed"

    # -- ragged (capacity-class) dispatch ----------------------------------

    def _dispatch_ragged(self, key, batch: List[_Request],
                         job: _DispatchJob,
                         lane: Optional[_ReplicaLane] = None) -> None:
        """One MIXED-SHAPE micro-batch through a capacity-class
        executable: every request in ``batch`` mapped to the same
        class box (the submit-time key), whatever its own ``(h, w)``.
        Assembly, warm starts and crops are per-row inside
        ``engine.infer_ragged_async``; everything else — deadlines,
        watchdog, breaker outcomes, pipelined completion, the
        accounting identity — is the plain dispatch protocol with a
        coarser bucket key."""
        eng = lane.engine if lane is not None else self.engine
        replica = lane.index if lane is not None else None
        live: List[_Request] = []
        for r in batch:
            try:
                running = r.future.set_running_or_notify_cancel()
            except (InvalidStateError, RuntimeError):
                continue  # wedge verdict settled it between take and here
            if running:
                live.append(r)
            else:
                self.metrics.record_cancelled()
                self._trace_cancel(r)
        if not live:
            return
        job.batch = live
        job.ragged = True
        ch, cw = key[0], key[1]
        n = len(live)
        t_disp = time.monotonic()
        try:  # EVERYTHING here routes failures to the batch's futures
            bucket = eng.route_ragged(n, ch, cw)
            job.bucket = bucket
            label = ("x".join(map(str, bucket))
                     + self.RAGGED_LABEL_SUFFIX)
            if lane is not None:
                label = f"{label}/r{lane.index}"
            with self._cv:
                depth = len(self._q)
            shapes = {tuple(r.image1.shape[:2]) for r in live}
            real_px = sum(r.image1.shape[0] * r.image1.shape[1]
                          for r in live)
            padded_px = bucket[0] * bucket[1] * bucket[2]
            self.metrics.record_dispatch(
                label, filled=n, capacity=bucket[0], depth=depth,
                real_px=real_px, padded_px=padded_px,
                ragged=True, cross_shape=len(shapes) > 1,
                replica=replica)
            self._trace_dispatch(
                live, label, bucket, t_disp,
                real_px=real_px, padded_px=padded_px,
                ragged=True, cross_shape=len(shapes) > 1,
                **({"replica": replica}
                   if replica is not None else {}))
            fault_point("serve.request")
            if job.abandoned:
                self.metrics.record_failure(self._fail_requests(
                    live, self._wedge_error(key)))
                return
            warm = getattr(eng, "warm_start", False)
            prev = (lane.prev_pending if lane is not None
                    else self._prev_pending)
            overlapped = prev is not None and prev.t_ready is None
            t_asm0 = time.monotonic()
            # box=(ch, cw): the engine routes on the SAME extents
            # route_bucket above used, so the executable dispatched is
            # exactly the one job.bucket/label name — a wedge verdict
            # must drop the program that actually hung, never a
            # same-key sibling class the batch's own maxima would
            # route to
            pairs = [(r.image1, r.image2) for r in live]
            if warm:
                low_dev = any(r.want_low and r.low_device for r in live)
                pending = eng.infer_ragged_async(
                    pairs,
                    flow_inits=[r.flow_init for r in live],
                    return_low=True, low_device=low_dev,
                    box=(ch, cw))
            else:
                pending = eng.infer_ragged_async(
                    pairs, box=(ch, cw))
            t_call_end = time.monotonic()
            gap_ms = None
            if prev is not None:
                gap_ms = (0.0 if prev.t_ready is None
                          else max(0.0, (t_call_end - prev.t_ready)
                                   * 1e3))
            self.metrics.record_hot_path(
                gap_ms=gap_ms, assembly_ms=(t_call_end - t_asm0) * 1e3,
                overlapped=overlapped, h2d_bytes=pending.h2d_bytes,
                requests=n)
            self._trace_mark(live, "shipped", at=t_call_end)
            self._trace_span_ctx(pending, live)
            if lane is not None:
                lane.prev_pending = pending
            else:
                self._prev_pending = pending
            if job.abandoned:
                n_failed = self._fail_requests(live,
                                               self._wedge_error(key))
                if n_failed:
                    self.metrics.record_failure(n_failed)
                return
            if self._completion is None:
                # per-row fetch output matches _settle's (flows, lows)
                # protocol — the settle/accounting path is shared, not
                # forked
                self._trace_mark(live, "fetch_start")
                self._settle(live, pending.fetch(), label, t_disp, warm,
                             replica=replica)
                job.outcome = "ok"
                return
            cjob = _DispatchJob(
                lambda j, key=key, label=label, live=live,
                pending=pending, t_disp=t_disp, warm=warm:
                self._complete_batch(key, label, live, pending,
                                     t_disp, warm, j))
            cjob.key = key
            cjob.bucket = bucket
            cjob.ragged = True
            cjob.batch = live
            cjob.t_start = time.monotonic()
            with self._pipe_lock:
                self._pending_jobs.append(cjob)
                self._completion.enqueue(cjob)
            job.outcome = "dispatched"
        except Exception as exc:  # route to the callers; worker survives
            self.metrics.record_failure(self._fail_requests(live, exc))
            job.outcome = "failed"

    # -- feature-cache dispatch --------------------------------------------

    def _settle_cached(self, key, live: List[_Request], outs,
                       label: str, t_disp: float, lh: int, lw: int,
                       ver: int) -> None:
        """Resolve a finished CACHED micro-batch: install every row's
        pool slot (fmap + speculative context + flow_low, sliced from
        the full-bucket device outputs), THEN settle its future — a
        session harvesting the future must find the slot present (the
        sequential-harvest contract that makes the next pair warm).
        Prime rows store a flow-less slot and resolve to
        ``ServeResult(None, None)`` — their flow is refinement against
        zero features, never surfaced."""
        flow, low_full, fmap2, ctx2 = outs
        hw = (key[0], key[1])
        t_done = time.monotonic()
        for i, r in enumerate(live):
            # per-row slices are fresh device buffers computed from
            # the call's OWNING outputs — the pool never holds a view
            # of a donation target (the PR-10 discipline)
            fl = None if r.prime else low_full[i, :lh, :lw]
            self._fcache.store(r.stream, hw, r.seq, ver,
                               fmap2[i, :lh, :lw], ctx2[i, :lh, :lw],
                               fl)
            res = ServeResult(None if r.prime else flow[i], None)
            if not settle_future(r.future, res):
                # wedge verdict settled it first (owns the span
                # close); a raced cancel owns nothing — close here
                if r.future.cancelled():
                    self._trace_cancel(r)
                continue
            queue_ms = (t_disp - r.t_submit) * 1e3
            device_ms = (t_done - t_disp) * 1e3
            tail = self.metrics.record_complete(
                label, queue_ms=queue_ms, device_ms=device_ms,
                priority=r.priority,
                trace_id=(r.span.trace_id if r.span is not None
                          else None))
            if self.tracer is not None and r.span is not None:
                self.tracer.close(
                    r.span, "completed", "completed", tail=tail,
                    observed_ms=round(queue_ms + device_ms, 3))
        self._trace_close_dispatch(live, "ok")

    def _complete_cached(self, key, label: str, live: List[_Request],
                         pending, t_disp: float, lh: int, lw: int,
                         ver: int, job: _DispatchJob) -> None:
        self._run_completion(
            key, live, pending, job,
            lambda outs: self._settle_cached(key, live, outs, label,
                                             t_disp, lh, lw, ver))

    def _dispatch_cached(self, key, batch: List[_Request],
                         job: _DispatchJob) -> None:
        """One feature-cached micro-batch: acquire every returning
        row's pool slot (seq/geometry/weights-version exact — invalid
        rows fail fast with ``FeatureCacheMiss``, they must not poison
        the batch), warp each slot's ``flow_low`` into the row's
        ``flow_init`` on device, and dispatch through the CACHED
        bucket signature — one encoder pass, one frame of H2D per
        row. Prime rows ride the same executable with zeroed cache
        inputs."""
        live: List[_Request] = []
        for r in batch:
            try:
                running = r.future.set_running_or_notify_cancel()
            except (InvalidStateError, RuntimeError):
                continue  # wedge verdict settled it between take and here
            if running:
                live.append(r)
            else:
                self.metrics.record_cancelled()
                self._trace_cancel(r)
        if not live:
            return
        job.batch = live
        job.cached = True
        h, w = key[0], key[1]
        left, right, top, bottom = pad_amounts(h, w)
        lh = (h + top + bottom) // 8
        lw = (w + left + right) // 8
        t_disp = time.monotonic()
        try:  # EVERYTHING here routes failures to the batch's futures
            bucket = self.engine.route_bucket(len(live), h, w,
                                              cached=True)
            job.bucket = bucket
            label = ("x".join(map(str, bucket))
                     + self.CACHE_LABEL_SUFFIX)
            fault_point("serve.request")
            if job.abandoned:
                self.metrics.record_failure(self._fail_requests(
                    live, self._wedge_error(key)))
                return
            # slot acquisition at assembly time: the submit-time probe
            # already failed obvious misses fast, but eviction/flush/
            # swap can land while queued — those rows fail HERE with
            # the cold-restart signal, and the rest of the batch
            # serves. ``ver`` is the stamp the engine re-checks under
            # its snapshot lock (StaleFeatureError on a raced swap).
            ver = getattr(self.engine, "weights_version", 0)
            # hoisted out of the per-row loop (ops.interp defers its
            # own jax import; the scheduler stays lazy at module scope)
            from raft_tpu.ops.interp import forward_interpolate_device
            slots = []
            kept: List[_Request] = []
            missed: List[_Request] = []
            for r in live:
                if r.prime:
                    kept.append(r)
                    slots.append(None)
                    continue
                slot = self._fcache.acquire(r.stream, (h, w),
                                            r.seq - 1, ver)
                if slot is None:
                    missed.append(r)
                    continue
                fi = None
                if slot.flow_low is not None:
                    # device-resident recurrence warm start: warp the
                    # slot's flow_low on device (holes stay zero =
                    # locally cold; a non-finite flow scatters nothing
                    # — the poisoned-pair guard without a host sync)
                    fi = forward_interpolate_device(slot.flow_low)
                kept.append(r)
                slots.append((slot.fmap, slot.ctx, fi))
            if self.tracer is not None:
                # feature-cache attribution: whether each row's slot
                # actually held at assembly (the p99 question "was the
                # stream warm or re-priming")
                for r in kept:
                    if r.span is not None:
                        self.tracer.annotate(
                            r.span,
                            cache="prime" if r.prime else "hit",
                            warm=not r.prime)
                for r in missed:
                    if r.span is not None:
                        self.tracer.annotate(r.span, cache="miss")
            if missed:
                n = self._fail_requests(missed, FeatureCacheMiss(
                    "cache slot invalidated while queued (evicted, "
                    "flushed, or weights swapped) — re-prime the "
                    "stream"))
                self.metrics.record_failure(n)
            if not kept:
                # nothing reached the engine: a miss is pool churn,
                # not an executable fault — no breaker outcome (a
                # "failed" here would let cache churn open a healthy
                # bucket's breaker), and no dispatch/occupancy record
                # (nothing dispatched)
                return
            live = kept
            job.batch = live
            # recorded AFTER acquisition so occupancy counts the rows
            # that actually reach the engine — queued-invalidation
            # misses must not inflate the warm-video A/B numbers
            with self._cv:
                depth = len(self._q)
            real_px = len(live) * h * w
            padded_px = bucket[0] * bucket[1] * bucket[2]
            self.metrics.record_dispatch(
                label, filled=len(live), capacity=bucket[0],
                depth=depth, real_px=real_px, padded_px=padded_px)
            self._trace_dispatch(live, label, bucket, t_disp,
                                 real_px=real_px, padded_px=padded_px,
                                 cached=True)
            prev = self._prev_pending
            overlapped = prev is not None and prev.t_ready is None
            t_asm0 = time.monotonic()
            i2 = np.stack([r.image2 for r in live])
            pending = self.engine.infer_cached_async(
                i2, slots, expect_version=ver)
            t_call_end = time.monotonic()
            gap_ms = None
            if prev is not None:
                gap_ms = (0.0 if prev.t_ready is None
                          else max(0.0, (t_call_end - prev.t_ready)
                                   * 1e3))
            self.metrics.record_hot_path(
                gap_ms=gap_ms, assembly_ms=(t_call_end - t_asm0) * 1e3,
                overlapped=overlapped, h2d_bytes=pending.h2d_bytes,
                requests=len(live))
            self._trace_mark(live, "shipped", at=t_call_end)
            self._trace_span_ctx(pending, live)
            self._prev_pending = pending
            if job.abandoned:
                n = self._fail_requests(live, self._wedge_error(key))
                if n:
                    self.metrics.record_failure(n)
                return
            if self._completion is None:
                self._trace_mark(live, "fetch_start")
                self._settle_cached(key, live, pending.fetch(), label,
                                    t_disp, lh, lw, ver)
                job.outcome = "ok"
                return
            cjob = _DispatchJob(
                lambda j, key=key, label=label, live=live,
                pending=pending, t_disp=t_disp, lh=lh, lw=lw, ver=ver:
                self._complete_cached(key, label, live, pending,
                                      t_disp, lh, lw, ver, j))
            cjob.key = key
            cjob.bucket = bucket
            cjob.cached = True
            cjob.batch = live
            cjob.t_start = time.monotonic()
            with self._pipe_lock:
                self._pending_jobs.append(cjob)
                self._completion.enqueue(cjob)
            job.outcome = "dispatched"
        except Exception as exc:  # route to the callers; worker survives
            self.metrics.record_failure(self._fail_requests(live, exc))
            job.outcome = "failed"

    # -- lifecycle ---------------------------------------------------------

    def executable_count(self) -> int:
        if self._lanes:
            # fleet: the whole fleet's table entries (replica tables
            # mirror the primary's keys, so N replicas ≈ N× the
            # single-engine count — the graftaudit canary pins it)
            return sum(self._engine_executables(lane.engine)
                       for lane in self._lanes)
        return self._engine_executables(self.engine)

    @staticmethod
    def _engine_executables(engine) -> int:
        count = getattr(engine, "executable_count", None)
        if count is not None:
            # RAFTEngine: plain + cached signature tables
            return count()
        return len(engine._compiled)

    def write_metrics(self, path: Optional[str] = None) -> Dict:
        """Dump a metrics snapshot on demand (appends a jsonl line).
        With tracing armed the span buffer flushes first, so the
        snapshot's ``tail_exemplars`` refs resolve in spans.jsonl."""
        if self.tracer is not None:
            self.tracer.flush()
        return self.metrics.write_snapshot(
            executables=self.executable_count(), path=path)

    def close(self, drain: bool = True, timeout: float = 120.0) -> None:
        """Stop intake; ``drain=True`` finishes everything queued
        first, ``drain=False`` fails pending work with
        :class:`SchedulerClosed`. Joins the worker and the supervised
        executor (leaked dispatch threads are a bug, not a shutdown
        mode; quarantined wedge threads are the accounted exception —
        daemon, reported in ``health()``) and writes a final metrics
        snapshot when a metrics path is configured. Idempotent."""
        with self._cv:
            first = not self._closed
            self._closed = True
            if not drain:
                n = 0
                exc = SchedulerClosed("dropped by no-drain close")
                while self._q:
                    r = self._q.popleft()
                    if r.future.done() or not settle_future(r.future,
                                                            exc):
                        # a queued future can only be done here by a
                        # caller cancel no sweep got to: count (and
                        # close the span as) the cancel it was — the
                        # identity must survive shutdown too
                        if r.future.cancelled():
                            self.metrics.record_cancelled()
                            self._trace_cancel(r)
                        continue
                    n += 1
                    if self.tracer is not None \
                            and r.span is not None:
                        self.tracer.close(r.span, "SchedulerClosed",
                                          "failed")
                self.metrics.record_failure(n)
            self._cv.notify_all()
        self._worker.join(timeout)
        if self._worker.is_alive():
            raise RuntimeError(
                f"scheduler worker failed to drain within {timeout}s")
        if self._exec is not None and not self._exec.close(timeout):
            raise RuntimeError(
                "supervised dispatch executor failed to stop within "
                f"{timeout}s")
        if self.host_fleet is not None:
            # stop the heartbeat monitor BEFORE closing lanes: a
            # verdict with no dispatcher left to drain it would just
            # sit in the notices queue
            self.host_fleet.close()
        for lane in self._lanes:
            # the fleet loop drained every lane before returning
            # (quarantined wedge threads stay the accounted daemon
            # exception, same as the single executor)
            if not lane.exec.close(timeout):
                raise RuntimeError(
                    f"replica r{lane.index} dispatch executor failed "
                    f"to stop within {timeout}s")
        if self._completion is not None:
            # handed-off batches are in-flight work: wait them out
            # (wedging any overdue one when the watchdog is armed —
            # the dispatcher that normally runs the scan is gone)
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                with self._pipe_lock:
                    n = len(self._pending_jobs)
                if not n:
                    break
                self._check_completions()
                time.sleep(0.005)
            if not self._completion.close(
                    max(0.1, deadline - time.monotonic())):
                raise RuntimeError(
                    "completion stage failed to drain within "
                    f"{timeout}s")
        if first and self._fcache is not None:
            # retired variants keep their scheduler objects (frozen
            # snapshots) — the pool must not pin per-stream device
            # arrays past close
            self.flush_feature_cache("close")
        if self.tracer is not None:
            # every accepted span settled above (drain or fail):
            # spans.jsonl is complete once close returns
            self.tracer.flush()
        if first and self.metrics.path:
            self.metrics.write_snapshot(
                executables=self.executable_count())

    def __enter__(self) -> "MicroBatchScheduler":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close(drain=exc_type is None)
