"""Async micro-batching scheduler: the serving front-end over the engine.

``RAFTEngine`` is a synchronous bucket router — one caller drives it at
a time, and a lone request pads a bucket's whole batch dimension with
zeros. Production TPU serving wins by decoupling request ARRIVAL from
device DISPATCH and coalescing ragged traffic into a small set of
pre-compiled shapes (the lesson Ragged Paged Attention draws for LLM
inference kernels on TPU, arXiv 2604.15464). This module is that
front-end: requests from any number of callers land in one bounded
queue, a single dispatcher thread groups same-shape requests into a
micro-batch, and the bucket's batch dimension fills with *different
callers' work* instead of padding.

Robustness contract (first-class, not best-effort):

- **Backpressure**: the queue is bounded; a full queue rejects NEW work
  with :class:`BackpressureError` (counted as shed) — load shedding
  never touches accepted or in-flight requests.
- **Deadlines** are enforced while QUEUED only: a request that expires
  before dispatch fails fast with :class:`DeadlineExceeded`; a
  dispatched request always runs to completion (the executable is
  non-preemptible anyway) — zero deadline-abandoned in-flight work, by
  construction (``Future.set_running_or_notify_cancel`` pins it).
- **Drain on shutdown**: ``close(drain=True)`` stops intake, finishes
  everything queued, and joins the worker — no leaked threads (the
  PR-3 loader-semaphore lesson, one layer up).
- **Live weight swap**: ``update_weights`` is safe under concurrent
  dispatch — the engine snapshots its weight tree once per dispatch
  under its lock, so a swap lands between dispatches, never inside one.

Fault drills: every micro-batch passes through the ``serve.request``
fault site (testing/faults) — ``raise`` fails just that batch's
futures (the worker survives), ``hang`` models a half-up device
stalling dispatch until the queue sheds.

Observability rides along in :class:`~raft_tpu.serving.metrics.
ServingMetrics`: per-bucket latency histograms for each stage
(enqueue->dispatch->complete), batch occupancy, queue depth, shed and
deadline-miss counters, snapshotted to ``metrics.jsonl`` on close and
dumpable on demand (``write_metrics``).
"""

from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future
from typing import Deque, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from raft_tpu.ops.padding import pad_amounts
from raft_tpu.serving.metrics import ServingMetrics
from raft_tpu.testing.faults import fault_point


class BackpressureError(RuntimeError):
    """Queue at max_queue: shed — the submitter should back off/retry."""


class DeadlineExceeded(RuntimeError):
    """The request's deadline expired while it was still queued."""


class SchedulerClosed(RuntimeError):
    """submit() after close(), or queued work dropped by a no-drain
    close."""


class ServeResult(NamedTuple):
    flow: np.ndarray               #: (H, W, 2), cropped to the request
    flow_low: Optional[np.ndarray]  #: (hp/8, wp/8, 2) in ÷8-padded frame
    #: space when requested (``want_low``) — the next frame's warm-start
    #: substrate — else None


class _Request:
    __slots__ = ("image1", "image2", "key", "flow_init", "want_low",
                 "future", "t_submit", "deadline")

    def __init__(self, image1, image2, key, flow_init, want_low,
                 deadline):
        self.image1 = image1
        self.image2 = image2
        self.key = key                  # (H, W) — the coalescing group
        self.flow_init = flow_init
        self.want_low = want_low
        self.future: Future = Future()
        self.t_submit = time.monotonic()
        self.deadline = deadline        # absolute monotonic, or None


class MicroBatchScheduler:
    """Bounded-queue micro-batching front-end over a ``RAFTEngine``.

    ``max_queue``: pending-request bound (backpressure past it).
    ``max_batch``: coalescing ceiling per dispatch; for a spatial shape
    with no precompiled bucket, ONE bucket is pre-warmed at this batch
    so later micro-batches batch-fill instead of compiling per fill
    count. ``gather_window_s``: how long dispatch holds an underfull
    micro-batch open for concurrent submitters — the latency/occupancy
    knob (bounded; an already-full batch never waits).
    """

    def __init__(self, engine, *, max_queue: int = 64, max_batch: int = 8,
                 gather_window_s: float = 0.002,
                 metrics: Optional[ServingMetrics] = None,
                 metrics_path: Optional[str] = None):
        self.engine = engine
        self.max_queue = int(max_queue)
        self.max_batch = int(max_batch)
        self.gather_window_s = float(gather_window_s)
        self.metrics = metrics or ServingMetrics(metrics_path)
        self._cv = threading.Condition()
        self._q: Deque[_Request] = collections.deque()
        self._capacity: Dict[Tuple[int, int], int] = {}
        self._closed = False
        self._worker = threading.Thread(
            target=self._run, name="MicroBatchScheduler-dispatch",
            daemon=True)
        self._worker.start()

    # -- intake ------------------------------------------------------------

    def submit(self, image1, image2, *, deadline_s: Optional[float] = None,
               flow_init: Optional[np.ndarray] = None,
               want_low: bool = False) -> Future:
        """Enqueue ONE ``(H, W, 3)`` frame pair; returns a Future
        resolving to :class:`ServeResult`. Raises
        :class:`BackpressureError` when the queue is full and
        :class:`SchedulerClosed` after ``close()``."""
        image1 = np.asarray(image1, np.float32)
        image2 = np.asarray(image2, np.float32)
        if image1.ndim != 3 or image1.shape[-1] != 3:
            raise ValueError(
                f"submit takes one (H, W, 3) frame pair, got "
                f"{image1.shape} — batching is the scheduler's job")
        if image1.shape != image2.shape:
            raise ValueError(f"frame shapes differ: {image1.shape} vs "
                             f"{image2.shape}")
        if ((flow_init is not None or want_low)
                and not getattr(self.engine, "warm_start", False)):
            raise ValueError(
                "flow_init/want_low need a warm_start=True engine")
        if flow_init is not None:
            flow_init = np.asarray(flow_init, np.float32)
            h, w = image1.shape[:2]
            left, right, top, bottom = pad_amounts(h, w)
            want = ((h + top + bottom) // 8, (w + left + right) // 8, 2)
            if flow_init.shape != want:
                # validated HERE so a malformed warm start fails ITS
                # caller alone — at dispatch time the row assignment
                # would throw inside the shared try and fail (or, if
                # broadcastable, silently corrupt) the whole coalesced
                # micro-batch, other callers included
                raise ValueError(
                    f"flow_init shape {flow_init.shape} != {want} (1/8 "
                    "of the ÷8-padded frame)")
            if not np.isfinite(flow_init).all():
                # a NaN warm start would only poison this caller's own
                # row, but fail it here with a cause instead of
                # returning NaN flow from the device
                raise ValueError("flow_init contains non-finite values")
        deadline = (time.monotonic() + deadline_s
                    if deadline_s is not None else None)
        req = _Request(image1, image2, tuple(image1.shape[:2]),
                       flow_init, want_low, deadline)
        with self._cv:
            if self._closed:
                raise SchedulerClosed("scheduler is closed")
            if len(self._q) >= self.max_queue:
                self.metrics.record_shed()
                raise BackpressureError(
                    f"queue full ({self.max_queue} pending) — shedding "
                    "new work; retry with backoff")
            self._q.append(req)
            self.metrics.record_submit(depth=len(self._q))
            self._cv.notify()
        return req.future

    def update_weights(self, variables) -> None:
        """Live checkpoint swap; atomic wrt in-flight micro-batches
        (the engine snapshots its tree once per dispatch)."""
        self.engine.update_weights(variables)

    # -- dispatch loop -----------------------------------------------------

    def _shape_capacity(self, key: Tuple[int, int]) -> int:
        cap = self._capacity.get(key)
        if cap is None:
            h, w = key
            fit = self.engine.bucket_capacity(h, w)
            if fit is None:
                # no compiled bucket fits this spatial shape: pre-warm
                # exactly one at max_batch so every later fill count
                # batch-fills into it (executable count stays one per
                # shape, the H3 discipline)
                fit = self.engine.ensure_bucket(self.max_batch, h, w)[0]
            cap = max(1, min(fit, self.max_batch))
            self._capacity[key] = cap
        return cap

    def _expire(self, req: _Request, now: float) -> bool:
        if req.deadline is not None and now > req.deadline:
            self.metrics.record_deadline_miss()
            req.future.set_exception(DeadlineExceeded(
                f"deadline expired after {now - req.t_submit:.3f}s in "
                "queue (never dispatched)"))
            return True
        return False

    def _gather(self, key: Tuple[int, int], capacity: int) -> None:
        """Hold dispatch open briefly so concurrent submitters can fill
        the micro-batch — bounded by ``gather_window_s``; a full batch
        (or a closing scheduler) never waits."""
        t_end = time.monotonic() + self.gather_window_s
        while True:
            with self._cv:
                if (self._closed
                        or sum(1 for r in self._q if r.key == key)
                        >= capacity):
                    return
            if time.monotonic() >= t_end:
                return
            time.sleep(min(0.0005, self.gather_window_s))

    def _take(self, key: Tuple[int, int], capacity: int
              ) -> List[_Request]:
        """Pop up to ``capacity`` same-shape requests FIFO, expiring
        stale deadlines (and reaping caller-cancelled futures) across
        the whole queue on the way."""
        now = time.monotonic()
        taken: List[_Request] = []
        keep: Deque[_Request] = collections.deque()
        with self._cv:
            for r in self._q:
                if r.future.cancelled():
                    self.metrics.record_cancelled()
                elif self._expire(r, now):
                    pass
                elif r.key == key and len(taken) < capacity:
                    taken.append(r)
                else:
                    keep.append(r)
            self._q = keep
        return taken

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._closed:
                    self._cv.wait(timeout=0.05)
                if not self._q:
                    if self._closed:
                        return
                    continue
                key = self._q[0].key
            try:
                # capacity may compile a bucket — never under the queue
                # lock (submitters would shed through the whole
                # compile)
                capacity = self._shape_capacity(key)
            except Exception as exc:
                # an unservable shape (mesh-invalid extent, a compile
                # failure) fails ITS requests — it must not kill the
                # dispatcher and strand every queued future unsettled
                # behind a dead thread
                doomed = self._take(key, self.max_batch)
                self.metrics.record_failure(len(doomed))
                for r in doomed:
                    if not r.future.done():
                        r.future.set_exception(exc)
                continue
            self._gather(key, capacity)
            batch = self._take(key, capacity)
            if batch:
                self._dispatch(key, batch)

    def _dispatch(self, key: Tuple[int, int], batch: List[_Request]
                  ) -> None:
        live: List[_Request] = []
        for r in batch:
            # once this returns True the future can no longer be
            # cancelled: a dispatched request is never abandoned — the
            # acceptance invariant behind metrics.abandoned_inflight==0
            if r.future.set_running_or_notify_cancel():
                live.append(r)
            else:
                self.metrics.record_cancelled()
        if not live:
            return
        h, w = key
        n = len(live)
        t_disp = time.monotonic()
        try:  # EVERYTHING here routes failures to the batch's futures —
            # nothing may escape and kill the dispatcher thread
            bucket = self.engine.route_bucket(n, h, w)
            label = "x".join(map(str, bucket))
            with self._cv:
                depth = len(self._q)
            self.metrics.record_dispatch(label, filled=n,
                                         capacity=bucket[0], depth=depth)
            fault_point("serve.request")
            i1 = np.stack([r.image1 for r in live])
            i2 = np.stack([r.image2 for r in live])
            if getattr(self.engine, "warm_start", False):
                finit = None
                if any(r.flow_init is not None for r in live):
                    left, right, top, bottom = pad_amounts(h, w)
                    lh = (h + top + bottom) // 8
                    lw = (w + left + right) // 8
                    # zero rows are cold starts: warm sessions and
                    # one-shot requests share the dispatch
                    finit = np.zeros((n, lh, lw, 2), np.float32)
                    for i, r in enumerate(live):
                        if r.flow_init is not None:
                            finit[i] = r.flow_init
                flows, lows = self.engine.infer_batch(
                    i1, i2, flow_init=finit, return_low=True)
            else:
                flows = self.engine.infer_batch(i1, i2)
                lows = None
            t_done = time.monotonic()
            for i, r in enumerate(live):
                low = lows[i] if (lows is not None and r.want_low) \
                    else None
                r.future.set_result(ServeResult(flows[i], low))
                self.metrics.record_complete(
                    label, queue_ms=(t_disp - r.t_submit) * 1e3,
                    device_ms=(t_done - t_disp) * 1e3)
        except Exception as exc:  # route to the callers; worker survives
            failed = [r for r in live if not r.future.done()]
            self.metrics.record_failure(len(failed))
            for r in failed:
                r.future.set_exception(exc)

    # -- lifecycle ---------------------------------------------------------

    def executable_count(self) -> int:
        return len(self.engine._compiled)

    def write_metrics(self, path: Optional[str] = None) -> Dict:
        """Dump a metrics snapshot on demand (appends a jsonl line)."""
        return self.metrics.write_snapshot(
            executables=self.executable_count(), path=path)

    def close(self, drain: bool = True, timeout: float = 120.0) -> None:
        """Stop intake; ``drain=True`` finishes everything queued
        first, ``drain=False`` fails pending work with
        :class:`SchedulerClosed`. Joins the worker (leaked dispatch
        threads are a bug, not a shutdown mode) and writes a final
        metrics snapshot when a metrics path is configured.
        Idempotent."""
        with self._cv:
            first = not self._closed
            self._closed = True
            if not drain:
                while self._q:
                    r = self._q.popleft()
                    if not r.future.done():
                        r.future.set_exception(SchedulerClosed(
                            "dropped by no-drain close"))
            self._cv.notify_all()
        self._worker.join(timeout)
        if self._worker.is_alive():
            raise RuntimeError(
                f"scheduler worker failed to drain within {timeout}s")
        if first and self.metrics.path:
            self.metrics.write_snapshot(
                executables=self.executable_count())

    def __enter__(self) -> "MicroBatchScheduler":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close(drain=exc_type is None)
