"""Serving observability: latency histograms, occupancy, shed counters.

Built into the serving front-end, not bolted on: the scheduler records
every request's life (enqueue -> dispatch -> complete) here, and a
snapshot answers the operator questions a serving stack lives by — how
long are callers waiting and where (queue vs device), how full are the
compiled buckets actually running (batch occupancy vs the
one-request-per-dispatch baseline), how deep is the queue, and how much
work was shed or missed its deadline.

Snapshots append to ``metrics.jsonl`` in the trainer Logger's format
(one JSON object per line carrying a ``step`` key,
training/logger.py:96-103) so the same ``tail -f`` / ``jq`` tooling
reads training and serving records side by side. Deliberately jax-free.
"""

from __future__ import annotations

import bisect
import collections
import json
import math
import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

#: graftthread T3: the metrics lock is a LEAF — record_* calls arrive
#: from under the scheduler's queue lock (``_cv``), so taking any
#: other serving lock in here would invert the declared order. The
#: event appenders (record_event) deliberately do their file I/O with
#: NO lock held (T1: no blocking I/O under a lock).
LOCK_ORDER = (("metrics.ServingMetrics._lock",),)

#: 1-2-5 log ladder, 0.1 ms .. 60 s — everything from a warm CPU
#: dispatch to a cold-compile stall lands inside it
_BOUNDS_MS: List[float] = [
    m * decade
    for decade in (0.1, 1.0, 10.0, 100.0, 1000.0, 10000.0)
    for m in (1, 2, 5)
] + [60000.0]


class LatencyHistogram:
    """Fixed log-ladder histogram. Percentile estimates report the
    matched bucket's upper bound — pessimistic but stable, and two
    histograms with the same ladder merge by adding counts."""

    __slots__ = ("bounds", "counts", "count", "total", "max")

    def __init__(self, bounds: Optional[List[float]] = None):
        self.bounds = list(_BOUNDS_MS if bounds is None else bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.total = 0.0
        self.max = 0.0

    def bucket_idx(self, ms: float) -> int:
        """The ladder bucket :meth:`observe` bins ``ms`` into — the
        ONE binning definition (the tail-exemplar refs and
        serve_trace's top-bucket membership both reuse it, so they
        can never drift from the histogram they must reproduce)."""
        return bisect.bisect_left(self.bounds, ms)

    def observe(self, ms: float) -> None:
        self.counts[self.bucket_idx(ms)] += 1
        self.count += 1
        self.total += ms
        if ms > self.max:
            self.max = ms

    @classmethod
    def from_snapshot(cls, snap: Dict,
                      bounds: Optional[List[float]] = None
                      ) -> "LatencyHistogram":
        """Rebuild a histogram from a :meth:`snapshot` dict (the jsonl
        form) so post-hoc consumers can :meth:`merge` blocks without
        poking the internals. ``total`` is re-derived from the rounded
        ``mean_ms`` — percentiles are exact (counts are), the merged
        mean carries the snapshot's 3-decimal rounding."""
        h = cls(bounds)
        h.counts = list(snap["counts"])
        h.count = snap["count"]
        h.total = snap["mean_ms"] * snap["count"]
        h.max = snap["max_ms"]
        return h

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """Fold ``other`` into this histogram (same ladder required) —
        how per-variant latency blocks aggregate into the per-priority
        summaries a multi-model drill reports."""
        if other.bounds != self.bounds:
            raise ValueError("cannot merge histograms with different "
                             "bucket ladders")
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.total += other.total
        if other.max > self.max:
            self.max = other.max
        return self

    def quantile(self, q: float) -> float:
        if not self.count:
            return 0.0
        target = max(1, math.ceil(q * self.count))
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                return self.bounds[i] if i < len(self.bounds) else self.max
        return self.max

    def snapshot(self) -> Dict:
        mean = self.total / self.count if self.count else 0.0
        return {"count": self.count, "mean_ms": round(mean, 3),
                "max_ms": round(self.max, 3),
                "p50_ms": self.quantile(0.5),
                "p99_ms": self.quantile(0.99),
                "counts": list(self.counts)}


#: per-request latency stages: enqueue->dispatch, dispatch->complete,
#: and their sum
_STAGES = ("queue", "device", "total")


class ServingMetrics:
    """Thread-safe counters + per-bucket histograms for the scheduler.

    ``path``: optional ``metrics.jsonl`` destination for
    :meth:`write_snapshot` (appended, Logger-style). Counter semantics:
    ``shed`` is work REJECTED at submit (queue full — backpressure),
    ``evicted`` is the subset of shed that was already QUEUED and gave
    its slot to a higher-priority arrival (shed-batch-first; those
    futures fail, so they also count ``failed`` — the accounting
    identity stays exact), ``admission_rejected`` is the subset of
    shed turned away by the registry-wide admission budget before this
    model's queue ever saw it, ``deadline_missed`` is work that expired
    while still queued, ``abandoned_inflight`` counts dispatched
    requests the scheduler gave up on — by design never incremented;
    the acceptance drill pins it at zero.

    ``namespace``: the model name this metrics block belongs to in a
    multi-model registry — stamped as ``"model"`` on every snapshot
    and event record so one metrics.jsonl serves N models and a
    dashboard can group by it. None (default) keeps the single-model
    record schema byte-identical.

    Per-priority blocks appear lazily: the first ``priority=`` seen
    creates that class's counters + latency histogram; priority-less
    traffic records nothing there (zero overhead, unchanged schema).
    """

    def __init__(self, path: Optional[str] = None,
                 namespace: Optional[str] = None):
        self.path = path
        self.namespace = namespace
        self._lock = threading.Lock()
        self._buckets: Dict[str, Dict] = {}
        self._latency = LatencyHistogram()       # all-bucket total
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.shed = 0
        self.evicted = 0
        self.admission_rejected = 0
        self.deadline_missed = 0
        self.cancelled = 0
        self._priority: Dict[str, Dict] = {}
        self.abandoned_inflight = 0
        self.dispatches = 0
        self.depth_last = 0
        self.depth_max = 0
        self._depth_sum = 0
        self._depth_samples = 0
        self._snapshots = 0
        # resilience surface (serving/resilience.py): wedge verdicts,
        # quarantined (leaked) dispatch threads, breaker activity
        self.wedged = 0
        self.quarantined_threads = 0
        self.circuit_rejected = 0
        self.breaker_transitions = {"open": 0, "half_open": 0,
                                    "closed": 0}
        # hot-path surface (zero-copy serving): dispatch-gap histogram
        # (host-observed device-idle bound between consecutive
        # dispatches — 0 when the next batch shipped before the
        # previous one's results were even ready), host-assembly vs
        # device-compute overlap, and the H2D wire-bytes counter the
        # u8 wire exists to shrink
        self._gap = LatencyHistogram()
        self.h2d_bytes = 0
        self.h2d_requests = 0
        self._assembly_ms = 0.0
        self._assembly_overlapped_ms = 0.0
        # padding-waste gauge (device pixels padded vs requested) on
        # EVERY dispatch path — bucketed, cached, ragged — so a ragged
        # A/B and the bucketed baseline report comparable waste
        self.real_px = 0
        self.padded_px = 0
        # ragged capacity-class surface: how full the boxes ran
        # (px-based — honest about capacity padding, not just row
        # counts) and how often a dispatch actually coalesced ACROSS
        # request shapes (the thing per-shape bucketing can never do)
        self.ragged_dispatches = 0
        self.ragged_cross_shape = 0
        self.ragged_real_px = 0
        self.ragged_padded_px = 0
        #: cross-frame feature cache (serving/feature_cache): when the
        #: scheduler arms a pool it points this at the pool's
        #: ``snapshot`` — every metrics snapshot then carries a
        #: ``feature_cache`` block (hits/misses/evictions/flushes/
        #: occupancy). Called with NO metrics lock held (the pool lock
        #: stays a leaf; see the T3 declarations). None = no block,
        #: the historical schema byte for byte.
        self.feature_cache_provider: Optional[Callable[[], Dict]] = None
        #: tail exemplars (request tracing, serving/trace.py): when
        #: ``record_complete`` carries a trace id, completions landing
        #: in the latency histogram's top occupied bucket are kept as
        #: exemplar REFS here (bounded), the snapshot grows a
        #: ``tail_exemplars`` block, and the guardian's evidence
        #: windows carry the refs. With tracing off no trace id ever
        #: arrives — the deque stays empty and the snapshot schema is
        #: byte-identical to the untraced stack.
        self._exemplars = collections.deque(maxlen=64)
        self._tail_max_idx = -1
        #: replica fleet (scheduler replicas>1): per-replica dispatch/
        #: occupancy/latency blocks, created lazily by the first
        #: ``replica=`` record. Single-engine serving never passes a
        #: replica, the dict stays empty, and the snapshot schema is
        #: byte-identical to the fleet-less stack.
        self._replicas: Dict[int, Dict] = {}
        #: multi-host fleet (scheduler host_fleet): per-host liveness/
        #: failover/artifact-push blocks, created lazily by the first
        #: ``record_host_*`` call. ``hosts=0`` records nothing — the
        #: dict stays empty and the snapshot schema is byte-identical
        #: to the single-host stack.
        self._hosts: Dict[str, Dict] = {}

    # -- recording --------------------------------------------------------

    def _bucket(self, key: str) -> Dict:
        b = self._buckets.get(key)
        if b is None:
            b = {"dispatches": 0, "filled": 0, "capacity": 0,
                 "real_px": 0, "padded_px": 0}
            for stage in _STAGES:
                b[stage] = LatencyHistogram()
            self._buckets[key] = b
        return b

    def _depth(self, depth: int) -> None:
        self.depth_last = depth
        self.depth_max = max(self.depth_max, depth)
        self._depth_sum += depth
        self._depth_samples += 1

    def _replica(self, replica: Optional[int]) -> Optional[Dict]:
        """The replica's fleet block, created on first use (caller
        holds the lock). None replica records nothing per-replica."""
        if replica is None:
            return None
        r = self._replicas.get(replica)
        if r is None:
            r = {"dispatches": 0, "filled": 0, "capacity": 0,
                 "completed": 0, "queue_depth_last": 0,
                 "latency": LatencyHistogram()}
            self._replicas[replica] = r
        return r

    def _host(self, name: str) -> Dict:
        """The host's fleet block, created on first use (caller holds
        the lock)."""
        h = self._hosts.get(name)
        if h is None:
            h = {"state": "healthy", "ready": False, "missed_beats": 0,
                 "failovers": 0, "requeued": 0, "zombie_drops": 0,
                 "push_entries": 0, "push_bytes": 0, "push_retries": 0,
                 "rejoins": 0}
            self._hosts[name] = h
        return h

    def record_host_state(self, name: str, state: str, *,
                          missed: int = 0, ready: bool = False) -> None:
        with self._lock:
            h = self._host(name)
            h["state"] = state
            h["missed_beats"] = int(missed)
            h["ready"] = bool(ready)

    def record_host_failover(self, name: str, *,
                             requeued: int = 0) -> None:
        with self._lock:
            h = self._host(name)
            h["failovers"] += 1
            h["requeued"] += int(requeued)

    def record_host_zombie_drop(self, name: str) -> None:
        """A late answer from a verdicted-dead host was dropped
        instead of settling an already-failed-over future."""
        with self._lock:
            self._host(name)["zombie_drops"] += 1

    def record_host_push(self, name: str, *, entries: int = 0,
                         bytes: int = 0, retries: int = 0) -> None:
        with self._lock:
            h = self._host(name)
            h["push_entries"] += int(entries)
            h["push_bytes"] += int(bytes)
            h["push_retries"] += int(retries)

    def record_host_rejoin(self, name: str) -> None:
        with self._lock:
            self._host(name)["rejoins"] += 1

    def _prio(self, priority: Optional[str]) -> Optional[Dict]:
        """The class's counter block, created on first use (caller
        holds the lock). None priority records nothing per-class."""
        if priority is None:
            return None
        p = self._priority.get(priority)
        if p is None:
            p = {"submitted": 0, "completed": 0, "shed": 0,
                 "deadline_missed": 0, "latency": LatencyHistogram()}
            self._priority[priority] = p
        return p

    def record_submit(self, depth: int,
                      priority: Optional[str] = None) -> None:
        with self._lock:
            self.submitted += 1
            self._depth(depth)
            p = self._prio(priority)
            if p is not None:
                p["submitted"] += 1

    def record_shed(self, priority: Optional[str] = None) -> None:
        with self._lock:
            self.shed += 1
            p = self._prio(priority)
            if p is not None:
                p["shed"] += 1

    def record_evicted(self, priority: Optional[str] = None) -> None:
        """A queued request gave its slot to a higher-priority arrival
        (shed-batch-first backpressure). Its future fails, so it counts
        both shed AND failed — submitted == completed + failed +
        deadline_missed + cancelled stays an identity."""
        with self._lock:
            self.shed += 1
            self.evicted += 1
            self.failed += 1
            p = self._prio(priority)
            if p is not None:
                p["shed"] += 1

    def record_admission_rejected(self, priority: Optional[str] = None
                                  ) -> None:
        """Rejected by the registry-wide admission budget BEFORE this
        model's queue (no future was created, so — like ``shed`` — it
        never enters the accounting identity). Counted as shed too:
        admission control is backpressure, one layer up."""
        with self._lock:
            self.admission_rejected += 1
            self.shed += 1
            p = self._prio(priority)
            if p is not None:
                p["shed"] += 1

    def record_deadline_miss(self, n: int = 1,
                             priority: Optional[str] = None) -> None:
        with self._lock:
            self.deadline_missed += n
            p = self._prio(priority)
            if p is not None:
                p["deadline_missed"] += n

    def record_cancelled(self, n: int = 1) -> None:
        with self._lock:
            self.cancelled += n

    def record_abandoned_inflight(self, n: int = 1) -> None:
        with self._lock:
            self.abandoned_inflight += n

    def record_dispatch(self, bucket: str, filled: int, capacity: int,
                        depth: int, real_px: int = 0,
                        padded_px: int = 0, ragged: bool = False,
                        cross_shape: bool = False,
                        replica: Optional[int] = None) -> None:
        """``real_px``/``padded_px``: requested pixels vs the
        executable's padded pixels for this dispatch (the padding-waste
        gauge; 0/0 from duck-typed callers keeps the historical
        records). ``ragged``: a capacity-class dispatch;
        ``cross_shape``: it coalesced more than one distinct request
        shape. ``replica``: the fleet lane that ran it — feeds the
        per-replica blocks (None = single-engine, no block)."""
        with self._lock:
            self.dispatches += 1
            b = self._bucket(bucket)
            b["dispatches"] += 1
            b["filled"] += filled
            b["capacity"] += capacity
            b["real_px"] += real_px
            b["padded_px"] += padded_px
            self.real_px += real_px
            self.padded_px += padded_px
            if ragged:
                self.ragged_dispatches += 1
                self.ragged_real_px += real_px
                self.ragged_padded_px += padded_px
                if cross_shape:
                    self.ragged_cross_shape += 1
            r = self._replica(replica)
            if r is not None:
                r["dispatches"] += 1
                r["filled"] += filled
                r["capacity"] += capacity
                r["queue_depth_last"] = depth
            self._depth(depth)

    def record_complete(self, bucket: str, queue_ms: float,
                        device_ms: float,
                        priority: Optional[str] = None,
                        trace_id: Optional[str] = None,
                        replica: Optional[int] = None) -> bool:
        """Record one completion. ``trace_id`` (request tracing
        armed): the completion is judged against the latency
        histogram's top occupied bucket — returns True when it IS a
        tail exemplar (the request landed in the top bucket, so its
        span must be retained whatever the sample rate says), and its
        ref lands in the snapshot's ``tail_exemplars`` block. Without
        a trace id (tracing off) the return is always False and
        nothing new is recorded — the historical behavior."""
        total = queue_ms + device_ms
        with self._lock:
            self.completed += 1
            b = self._bucket(bucket)
            b["queue"].observe(queue_ms)
            b["device"].observe(device_ms)
            b["total"].observe(total)
            self._latency.observe(total)
            p = self._prio(priority)
            if p is not None:
                p["completed"] += 1
                p["latency"].observe(total)
            r = self._replica(replica)
            if r is not None:
                r["completed"] += 1
                r["latency"].observe(total)
            if trace_id is None:
                return False
            idx = self._latency.bucket_idx(total)
            tail = idx >= self._tail_max_idx
            if idx > self._tail_max_idx:
                self._tail_max_idx = idx
            if tail:
                self._exemplars.append(
                    {"trace_id": trace_id, "bucket": bucket,
                     "total_ms": round(total, 3), "bucket_idx": idx})
            return tail

    def record_failure(self, n: int = 1) -> None:
        with self._lock:
            self.failed += n

    def record_hot_path(self, gap_ms: Optional[float] = None,
                        assembly_ms: float = 0.0,
                        overlapped: bool = False,
                        h2d_bytes: int = 0, requests: int = 0) -> None:
        """One dispatch's hot-path sample: ``gap_ms`` — host-observed
        idle between this dispatch and the previous one's results being
        ready (None for the first dispatch); ``assembly_ms`` — host
        stack/pad/ship time, ``overlapped=True`` when it ran while a
        previous batch was still in flight on the device;
        ``h2d_bytes``/``requests`` — wire bytes shipped for this
        micro-batch and how many requests rode them."""
        with self._lock:
            if gap_ms is not None:
                self._gap.observe(gap_ms)
            self._assembly_ms += assembly_ms
            if overlapped:
                self._assembly_overlapped_ms += assembly_ms
            self.h2d_bytes += h2d_bytes
            self.h2d_requests += requests

    # -- resilience events ------------------------------------------------

    def record_event(self, event: str, **fields) -> None:
        """Append one event record to metrics.jsonl — the supervisor's
        restart-event format (training/supervisor.py), so the dashboard
        tailing one file sees serving health transitions next to
        trainer restarts. No-op without a path; a failed append is
        logged and swallowed (observability must never take down
        serving)."""
        if self.path is None:
            return
        rec = {"event": event, "time": time.time(),
               "kind": "serving_event", **fields}
        if self.namespace is not None and "model" not in rec:
            rec["model"] = self.namespace
        try:
            parent = os.path.dirname(self.path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            with open(self.path, "a", encoding="utf-8") as fh:
                fh.write(json.dumps(rec) + "\n")
        except OSError as exc:
            print(f"[serving-metrics] event append failed ({exc}) — "
                  "continuing", file=sys.stderr, flush=True)

    def record_wedge(self, bucket: str, failed: int,
                     timeout_s: float) -> None:
        """A dispatch wedge verdict: ``failed`` futures were failed
        with DispatchWedged after ``timeout_s``."""
        with self._lock:
            self.wedged += 1
            self.failed += failed
        self.record_event("dispatch_wedged", bucket=bucket,
                          failed=failed, timeout_s=timeout_s)

    def record_quarantined(self, bucket: str, alive: int) -> None:
        """A stuck dispatch thread was quarantined and replaced;
        ``alive`` is how many quarantined threads still live — the
        leak, recorded rather than hidden."""
        with self._lock:
            self.quarantined_threads += 1
        self.record_event("thread_quarantined", bucket=bucket,
                          alive=alive)

    def record_breaker_transition(self, bucket: str, old: str,
                                  new: str) -> None:
        with self._lock:
            if new in self.breaker_transitions:
                self.breaker_transitions[new] += 1
        self.record_event("breaker_" + new, bucket=bucket,
                          previous=old)

    def record_state_change(self, old: str, new: str,
                            reason: str) -> None:
        """Scheduler health-state transition (healthy|degraded|wedged)."""
        self.record_event("serving_state", state=new, previous=old,
                          reason=reason)

    def record_circuit_rejected(self, n: int = 1) -> None:
        """Submit-time fail-fast: the bucket's breaker was open."""
        with self._lock:
            self.circuit_rejected += n

    # -- reporting --------------------------------------------------------

    def snapshot(self, executables: Optional[int] = None) -> Dict:
        """One self-contained record: counters, queue-depth gauges,
        occupancy vs the one-request-per-dispatch baseline, and the
        per-bucket stage histograms."""
        # read the feature-cache block BEFORE taking the metrics lock:
        # the pool lock is a leaf and must never nest under this one
        prov = self.feature_cache_provider
        fcache = prov() if prov is not None else None
        with self._lock:
            self._snapshots += 1
            filled = sum(b["filled"] for b in self._buckets.values())
            capacity = sum(b["capacity"] for b in self._buckets.values())
            depth_mean = (self._depth_sum / self._depth_samples
                          if self._depth_samples else 0.0)
            rec = {
                # the Logger contract: every jsonl record carries "step"
                "step": self._snapshots,
                "kind": "serving",
                "submitted": self.submitted,
                "completed": self.completed,
                "failed": self.failed,
                "shed": self.shed,
                "evicted": self.evicted,
                "admission_rejected": self.admission_rejected,
                "deadline_missed": self.deadline_missed,
                "cancelled": self.cancelled,
                "abandoned_inflight": self.abandoned_inflight,
                "dispatches": self.dispatches,
                "executables": executables,
                "resilience": {
                    "wedged": self.wedged,
                    "quarantined_threads": self.quarantined_threads,
                    "circuit_rejected": self.circuit_rejected,
                    "breaker_transitions":
                        dict(self.breaker_transitions),
                },
                "queue_depth": {"last": self.depth_last,
                                "max": self.depth_max,
                                "mean": round(depth_mean, 3)},
                "occupancy": {
                    "filled": filled,
                    "capacity": capacity,
                    "mean": round(filled / capacity, 4) if capacity
                    else 0.0,
                    # what the same dispatch count would score carrying
                    # ONE request each — the no-coalescing strawman the
                    # drill must strictly beat
                    "one_per_dispatch_baseline":
                        round(self.dispatches / capacity, 4) if capacity
                        else 0.0,
                },
                # padded device pixels vs requested pixels, every
                # dispatch path — the waste the ragged A/B compares
                "padding_waste": {
                    "real_px": self.real_px,
                    "padded_px": self.padded_px,
                    "waste_ratio": round(
                        1.0 - self.real_px / self.padded_px, 4)
                    if self.padded_px else 0.0,
                },
                "ragged": {
                    "dispatches": self.ragged_dispatches,
                    "cross_shape_dispatches": self.ragged_cross_shape,
                    "cross_shape_coalesce_rate": round(
                        self.ragged_cross_shape
                        / self.ragged_dispatches, 4)
                    if self.ragged_dispatches else 0.0,
                    "capacity_fill": round(
                        self.ragged_real_px / self.ragged_padded_px, 4)
                    if self.ragged_padded_px else 0.0,
                },
                "hot_path": {
                    "dispatch_gap": self._gap.snapshot(),
                    "h2d_bytes": self.h2d_bytes,
                    "h2d_bytes_per_req":
                        round(self.h2d_bytes / self.h2d_requests, 1)
                        if self.h2d_requests else 0.0,
                    "assembly": {
                        "total_ms": round(self._assembly_ms, 3),
                        "overlapped_ms":
                            round(self._assembly_overlapped_ms, 3),
                        "overlap_ratio": round(
                            self._assembly_overlapped_ms
                            / self._assembly_ms, 4)
                        if self._assembly_ms else 0.0,
                    },
                },
                "latency": self._latency.snapshot(),
                "priority": {
                    cls: {"submitted": p["submitted"],
                          "completed": p["completed"],
                          "shed": p["shed"],
                          "deadline_missed": p["deadline_missed"],
                          "latency": p["latency"].snapshot()}
                    for cls, p in sorted(self._priority.items())
                },
                "hist_bounds_ms": list(_BOUNDS_MS),
                "buckets": {
                    key: {
                        "dispatches": b["dispatches"],
                        "filled": b["filled"],
                        "capacity": b["capacity"],
                        "occupancy": round(b["filled"] / b["capacity"], 4)
                        if b["capacity"] else 0.0,
                        "real_px": b["real_px"],
                        "padded_px": b["padded_px"],
                        "padding_waste": round(
                            1.0 - b["real_px"] / b["padded_px"], 4)
                        if b["padded_px"] else 0.0,
                        **{stage: b[stage].snapshot()
                           for stage in _STAGES},
                    }
                    for key, b in sorted(self._buckets.items())
                },
            }
            if self._replicas:
                # replica fleet armed: per-lane fan-out blocks (the
                # balance/occupancy evidence the 2×-spread acceptance
                # reads). Absent in single-engine mode: additive
                # schema, byte-identical without a fleet.
                rec["replicas"] = {
                    str(k): {
                        "dispatches": r["dispatches"],
                        "filled": r["filled"],
                        "capacity": r["capacity"],
                        "occupancy": round(r["filled"] / r["capacity"],
                                           4)
                        if r["capacity"] else 0.0,
                        "completed": r["completed"],
                        "queue_depth_last": r["queue_depth_last"],
                        "latency": r["latency"].snapshot(),
                    }
                    for k, r in sorted(self._replicas.items())
                }
            if self._hosts:
                # multi-host fleet armed: per-host liveness/failover/
                # artifact-push evidence (the kill-drill acceptance
                # reads host_dead counts + failovers + push bytes
                # here). Absent with hosts=0: additive schema,
                # byte-identical without remote lanes.
                rec["hosts"] = {
                    name: dict(h)
                    for name, h in sorted(self._hosts.items())
                }
            if fcache is not None:
                rec["feature_cache"] = fcache
            if self._exemplars:
                # request tracing armed: refs of completions in the
                # CURRENT top occupied latency bucket — the span ids
                # serve_trace's phase attribution runs over (early
                # exemplars overtaken by a later, slower top bucket
                # are filtered here; their spans stay retained).
                # Absent whenever tracing is off: additive schema.
                top = self._tail_max_idx
                refs = [dict(e) for e in self._exemplars
                        if e["bucket_idx"] == top]
                rec["tail_exemplars"] = {
                    "top_bucket_idx": top,
                    "top_bucket_gt_ms": (self._latency.bounds[top - 1]
                                         if top > 0 else 0.0),
                    "refs": refs,
                }
            if self.namespace is not None:
                rec["model"] = self.namespace
        return rec

    def write_snapshot(self, executables: Optional[int] = None,
                       path: Optional[str] = None) -> Dict:
        """Append one snapshot line to ``path`` (default: the ctor's);
        returns the record."""
        rec = self.snapshot(executables=executables)
        dest = path or self.path
        if dest is None:
            raise ValueError("no metrics path configured")
        parent = os.path.dirname(dest)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(dest, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(rec) + "\n")
        return rec
