"""The metrics.jsonl / spans.jsonl record schemas, in ONE place.

Every serving layer appends to the same observability files — metrics
snapshots (``kind: "serving"``), supervisor-style events
(``kind: "serving_event"``: breaker transitions, wedge verdicts,
rollout moves, guardian decisions, cache flushes) and, with tracing
armed, span records (``kind: "span"``). Before this module each test
re-declared its slice of the schema inline (the breaker-event keys in
test_scheduler, the rollout events in test_registry, the guardian
evidence in test_guardian, ...) — a field rename could pass every
local test and still break the dashboards tailing the file. This
registry is the single source of truth the schema-assert test
(tests/test_serving_schema.py) checks every emitted record against,
and the reference a dashboard author reads.

Jax-free, import-cheap (the CLI readers use it too). The contract is
**additive**: a field may be added to a record (new keys are never a
validation error), but the required fields here may only grow.
"""

from __future__ import annotations

from typing import Dict, List

#: accounting classes a request span may close under — trace.py owns
#: the tuple (jax-free, import-cheap); re-exported so schema
#: consumers need only this module and the two can never drift
from raft_tpu.serving.trace import SPAN_CLASSES  # noqa: F401

#: every jsonl record carries its kind (snapshots use the trainer
#: Logger contract: a "step" key; events/spans a "time" stamp)
RECORD_KINDS = ("serving", "serving_event", "span")

#: required top-level keys of a metrics SNAPSHOT record
#: (ServingMetrics.snapshot) — "model" and the tracing/feature-cache
#: blocks are conditional (namespace set / pool armed / tracing armed)
SNAPSHOT_KEYS = frozenset({
    "step", "kind", "submitted", "completed", "failed", "shed",
    "evicted", "admission_rejected", "deadline_missed", "cancelled",
    "abandoned_inflight", "dispatches", "executables", "resilience",
    "queue_depth", "occupancy", "padding_waste", "ragged", "hot_path",
    "latency", "priority", "hist_bounds_ms", "buckets",
})

#: serving_event kinds → REQUIRED extra fields (beyond the base
#: {"event", "time", "kind"}; "model" is stamped whenever the emitting
#: metrics block carries a namespace). One entry per record_event call
#: site in the serving stack — a new event kind lands HERE first.
EVENT_FIELDS: Dict[str, frozenset] = {
    # scheduler / resilience (serving/metrics.py emitters)
    "serving_state": frozenset({"state", "previous", "reason"}),
    "dispatch_wedged": frozenset({"bucket", "failed", "timeout_s"}),
    "thread_quarantined": frozenset({"bucket", "alive"}),
    "breaker_open": frozenset({"bucket", "previous"}),
    "breaker_half_open": frozenset({"bucket", "previous"}),
    "breaker_closed": frozenset({"bucket", "previous"}),
    # feature cache (scheduler.flush_feature_cache; the registry's
    # rollout brooms stamp model/version on top)
    "cache_flush": frozenset({"reason", "slots"}),
    # registry rollout lifecycle (serving/registry.py)
    "model_state": frozenset({"model", "version", "state", "previous"}),
    "model_deploy": frozenset({"model", "version", "canary_fraction",
                               "same_arch"}),
    "model_deploy_failed": frozenset({"model", "version", "error"}),
    "model_promote": frozenset({"model", "version", "mode"}),
    "model_rollback": frozenset({"model", "version"}),
    "registry_closed": frozenset({"models"}),
    # AOT-store GC on variant retirement (registry._retire_artifacts;
    # undeclared until the graftwire W6 first scan caught the drift —
    # the dynamic drill had never driven the eviction path)
    "aot_evicted": frozenset({"model", "version", "removed",
                              "removed_bytes"}),
    # replica fleet (scheduler fleet mode — replicas>1 or host lanes)
    "replica_quarantined": frozenset({"replica", "bucket"}),
    "replica_activated": frozenset({"replica", "queue_depth"}),
    "replica_retired": frozenset({"replica", "idle_s"}),
    "replica_grow_failed": frozenset({"error"}),
    "fleet_weights_swap": frozenset({"replicas"}),
    # multi-host fleet (serving/hosts.py + scheduler._wedge_host)
    "host_suspect": frozenset({"host", "missed"}),
    "host_dead": frozenset({"host", "missed"}),
    "host_rejoined": frozenset({"host", "push_entries", "push_bytes",
                                "push_retries", "compiles"}),
    "failover": frozenset({"host", "replica", "requeued"}),
    # SLO guardian (serving/guardian.py)
    "guardian_bake_start": frozenset({"model", "version",
                                      "bake_window_s"}),
    "guardian_promote": frozenset({"model", "version", "reason",
                                   "evidence"}),
    "guardian_rollback": frozenset({"model", "version", "reason",
                                    "evidence"}),
    "guardian_decision_failed": frozenset({"model", "version",
                                           "intended", "error"}),
    "guardian_error": frozenset({"error"}),
}

#: the wire-protocol method registry: every method a transport client
#: may ``call()`` and a :class:`~raft_tpu.serving.hosts.HostWorker`
#: must table (``_m_<method>``), mapped to the payload keys the worker
#: REQUIRES (the additive contract again: extra payload keys are never
#: an error; a method lands HERE first). The graftwire W6 tier checks
#: every client call string and handler entry against these keys
#: statically; tests/test_serving_schema.py pins the table against the
#: real HostWorker surface.
WIRE_METHODS: Dict[str, frozenset] = {
    "ping": frozenset(),
    "put_artifact": frozenset({"digest", "blob", "manifest", "sha256"}),
    "prewarm": frozenset(),
    "capacity": frozenset({"h", "w"}),
    "ensure": frozenset({"n", "h", "w"}),
    "route": frozenset({"n", "h", "w"}),
    "drop": frozenset({"bucket"}),
    "infer": frozenset({"image1", "image2"}),
    "update_weights": frozenset({"variables"}),
    "stats": frozenset(),
}

#: span record types (serving/trace.py) → required fields. Request
#: spans additionally carry "class" (the accounting-identity class
#: they reconcile against) and "phases"; dispatch spans the fan-in
#: link surface.
SPAN_KINDS = ("request", "dispatch")
SPAN_FIELDS: Dict[str, frozenset] = {
    "request": frozenset({"trace_id", "time", "outcome", "class",
                          "total_ms", "tail", "bucket", "phases"}),
    "dispatch": frozenset({"trace_id", "time", "outcome", "total_ms",
                           "bucket", "fan_in", "capacity",
                           "padding_waste", "requests"}),
}

def validate_record(rec: Dict) -> List[str]:
    """Validate ONE parsed jsonl record against the registry; returns
    the list of problems (empty = conforming). Unknown kinds and
    unknown event names are errors — every emitter must be declared;
    extra fields are not (the additive contract)."""
    problems: List[str] = []
    kind = rec.get("kind")
    if kind == "serving":
        missing = SNAPSHOT_KEYS - rec.keys()
        if missing:
            problems.append(f"snapshot missing {sorted(missing)}")
        if not isinstance(rec.get("step"), int):
            problems.append("snapshot step must be an int")
    elif kind == "serving_event":
        event = rec.get("event")
        if "time" not in rec:
            problems.append("event missing time")
        required = EVENT_FIELDS.get(event)
        if required is None:
            problems.append(f"undeclared event kind {event!r} — add "
                            "it to serving/schema.py EVENT_FIELDS")
        else:
            missing = required - rec.keys()
            if missing:
                problems.append(
                    f"event {event!r} missing {sorted(missing)}")
    elif kind == "span":
        span = rec.get("span")
        required = SPAN_FIELDS.get(span)
        if required is None:
            problems.append(f"unknown span type {span!r}")
        else:
            missing = required - rec.keys()
            if missing:
                problems.append(
                    f"span {span!r} missing {sorted(missing)}")
            if span == "request" \
                    and rec.get("class") not in SPAN_CLASSES:
                problems.append(
                    f"span class {rec.get('class')!r} not in "
                    f"{SPAN_CLASSES}")
    else:
        problems.append(f"unknown record kind {kind!r}")
    return problems


def validate_lines(lines) -> List[str]:
    """Validate an iterable of parsed records; problems are prefixed
    with their line index."""
    problems = []
    for i, rec in enumerate(lines):
        problems += [f"line {i}: {p}" for p in validate_record(rec)]
    return problems
