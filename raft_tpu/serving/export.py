"""Portable model export via StableHLO (the ONNX-export analog).

``test_trt.py:102-161`` exports a single-output graph (``flowup``) with the
20-iteration loop baked in and dynamic batch/H/W axes. The TPU-native
equivalent is ``jax.export``: serialize the jitted serving function to
StableHLO bytes that any XLA runtime (TPU/CPU/GPU) can reload and run,
with symbolic batch/spatial dims for the dynamic axes.

This is the PORTABILITY artifact — reloading it still pays a full XLA
compile on the consumer. The zero-compile sibling is
``raft_tpu/serving/aot.py``: the engine's serialized-EXECUTABLE cache
(``jax.experimental.serialize_executable``), same-platform/same-version
only, keyed on full provenance and audited by ``tools/graftexport``.
Export ships programs across runtimes; the AOT cache ships compiled
bytes across replicas.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import export as jax_export

from raft_tpu.config import ITERS_EXPORT, RAFTConfig
from raft_tpu.models import RAFT


def make_serving_fn(variables: Dict, config: RAFTConfig = RAFTConfig(),
                    iters: int = ITERS_EXPORT):
    """Closure (image1, image2) -> flow_up with weights baked in."""
    model = RAFT(config)

    def serve(image1, image2):
        _, flow_up = model.apply(variables, image1, image2, iters=iters,
                                 test_mode=True)
        return flow_up

    return serve


def export_stablehlo(variables: Dict, config: RAFTConfig = RAFTConfig(),
                     iters: int = ITERS_EXPORT,
                     image_hw: Tuple[int, int] = (440, 1024),
                     dynamic_batch: bool = True) -> bytes:
    """Serialize the serving function to portable StableHLO bytes.

    Spatial dims stay static (XLA recompiles per shape; the engine's shape
    buckets handle the envelope) while batch may be symbolic — mirroring the
    ONNX dynamic axes declaration (test_trt.py:150-160) as far as the
    platform allows.
    """
    serve = jax.jit(make_serving_fn(variables, config, iters))
    h, w = image_hw
    if dynamic_batch:
        (b,) = jax_export.symbolic_shape("b")
        spec = jax.ShapeDtypeStruct((b, h, w, 3), jnp.float32)
    else:
        spec = jax.ShapeDtypeStruct((1, h, w, 3), jnp.float32)
    exported = jax_export.export(serve)(spec, spec)
    return bytes(exported.serialize())  # serialize() may hand back bytearray


def load_stablehlo(blob: bytes):
    """Deserialize and return a callable (image1, image2) -> flow_up."""
    exported = jax_export.deserialize(blob)
    return lambda i1, i2: exported.call(i1, i2)


def main(argv=None):
    import argparse

    from raft_tpu.utils.platform import setup_cli

    setup_cli()

    p = argparse.ArgumentParser(
        description="Export RAFT to portable StableHLO")
    p.add_argument("--model", required=True, help=".pth or .msgpack weights")
    p.add_argument("--out", required=True, help="output .stablehlo path")
    p.add_argument("--small", action="store_true")
    p.add_argument("--iters", type=int, default=ITERS_EXPORT)
    p.add_argument("--height", type=int, default=440)
    p.add_argument("--width", type=int, default=1024)
    p.add_argument("--static_batch", action="store_true")
    args = p.parse_args(argv)

    from raft_tpu.training.trainer import load_weights

    cfg = RAFTConfig(small=args.small)
    variables = load_weights(args.model, cfg)
    blob = export_stablehlo(variables, cfg, args.iters,
                            (args.height, args.width),
                            dynamic_batch=not args.static_batch)
    with open(args.out, "wb") as f:
        f.write(blob)
    print(f"exported {len(blob)} bytes -> {args.out}")


if __name__ == "__main__":
    main()
