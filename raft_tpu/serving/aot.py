"""AOT executable cache: serialized bucket/class programs behind a
content-addressed on-disk store.

The reference deployment serializes inference programs once and ships
artifacts (ONNX -> TensorRT, ``cvt2trt.*``); this module is the
JAX-native analog for the serving engine's bucket executables. A
100-replica rollout that recompiles every bucket 100x pays cold-start
in compile time; with a warm artifact dir the engine LOADS the bytes
XLA already produced (``jax.experimental.serialize_executable``) and
performs zero compiles for precompiled signatures.

Trust model — a serialized executable is a new boundary:

- The CACHE KEY is the full provenance of the program: a content
  fingerprint of the weights (not a per-process counter — a restarting
  supervisor must re-derive the same key), bucket geometry + program
  kind, wire dtype, the donation signature, the partition-spec hash,
  config/iters, and the jax/jaxlib versions + platform. Canonical-JSON
  sha256 of that dict names the entry directory.
- Every entry carries a MANIFEST sidecar: the full key (checked
  verbatim on load — a blob sitting at the wrong digest never loads),
  the blob's sha256 (checked before a single byte is unpickled), and
  the calling-convention signature (flat in/out avals + donated flat
  params) so the ``tools/graftexport`` tier can audit drift against
  the engine's live signature table.
- ANY verification failure — unreadable or torn manifest, key
  mismatch, version skew, hash mismatch, deserialization error — is a
  clean MISS: :meth:`AOTCache.load` returns ``None`` and the caller
  recompiles. No failure mode loads a wrong executable, and no failure
  mode raises into the serving path.
- Writes are atomic (publish a fully-written temp dir via ``rename``)
  and first-insert-wins; an existing entry that fails verification is
  replaced, so one corrupted blob cannot wedge a digest forever.

Fault site: ``aot.load`` (see ``raft_tpu/testing/faults.py``) —
``fault_file`` corrupts the entry on disk before the read and
``fault_point`` raises inside the verification scope, so the chaos
drill can assert both read as miss-and-recompile.

The store is an accelerator, never a correctness gate: ``store``
swallows serialization/IO errors (some programs — e.g. ones carrying
host callbacks — cannot serialize; the engine simply keeps its
in-process executable).
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import pickle
import shutil
import tempfile
import threading
from typing import Dict, List, Optional, Tuple

from raft_tpu.testing.faults import fault_file, fault_point

#: bump when the entry layout or pickle payload shape changes — old
#: entries then read as miss, never as a misparse
AOT_FORMAT = "jax_serialize_executable_v1"

#: every component a complete cache key must carry.
#: ``tools/graftexport`` rule E1 audits written manifests against a
#: literal mirror of this set (pinned equal by tests/test_graftexport)
#: — a key missing any of these is a stale-load hazard: two programs
#: differing only in the missing component would collide on one digest.
REQUIRED_KEY_FIELDS = frozenset({
    "format",     # AOT_FORMAT — layout/payload version
    "program",    # serve | serve_warm | serve_cached | serve_ragged...
    "weights",    # content fingerprint of the weight tree
    "geometry",   # bucket/class (batch, H, W)
    "wire",       # f32 | u8 boundary dtype
    "iters",      # refinement iterations baked into the trace
    "config",     # model config fingerprint
    "donations",  # donate_argnums of the jitted program
    "partition",  # mesh/spec hash, or "single"
    "jax",        # jax version that compiled the blob
    "jaxlib",     # jaxlib version
    "platform",   # backend platform the executable targets
})

_MANIFEST = "manifest.json"
_BLOB = "executable.bin"


_PC_LOCK = threading.Lock()
_PC_DEPTH = 0
_PC_PRIOR = True


@contextlib.contextmanager
def fresh_compile():
    """Disable jax's own persistent compile cache for the scope of a
    compile that will be SERIALIZED into this store.

    A persistent-cache hit hands back an executable that was itself
    DESERIALIZED; re-serializing it is a second-generation payload,
    and those fail ``deserialize_and_load`` with ``Symbols not
    found`` in any process without a live fresh-compiled twin to
    borrow object code from (jax 0.4.37 CPU thunk runtime) — a
    stillborn artifact that every fresh replica reads as a miss, so
    the zero-compile warm start silently never happens. Compiling
    fresh makes every stored payload a first-generation
    serialization of a backend compile, which loads deterministically
    anywhere. The AOT store replaces that cache for engine programs
    anyway (content-addressed one level up, with provenance).

    Flipping ``jax_enable_compilation_cache`` alone is NOT enough:
    ``compilation_cache.is_cache_used`` memoizes enabled-ness on the
    first compile of the process, so the flag flip must be paired
    with ``reset_cache()`` (entry AND exit — exit re-arms the cache
    for ordinary compiles). Depth-counted so concurrent engine
    compiles (which deliberately run outside the engine lock) nest
    without restoring the flag early."""
    import jax

    def _reset_cache_probe():
        # drop the per-process "is the cache used" memo so the flag
        # value is re-read at the next compile
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:  # noqa: BLE001 — jax-internal API, best effort
            pass

    global _PC_DEPTH, _PC_PRIOR
    with _PC_LOCK:
        if _PC_DEPTH == 0:
            _PC_PRIOR = bool(jax.config.jax_enable_compilation_cache)
            if _PC_PRIOR:
                jax.config.update("jax_enable_compilation_cache", False)
                _reset_cache_probe()
        _PC_DEPTH += 1
    try:
        yield
    finally:
        with _PC_LOCK:
            _PC_DEPTH -= 1
            if _PC_DEPTH == 0 and _PC_PRIOR:
                jax.config.update("jax_enable_compilation_cache", True)
                _reset_cache_probe()


# -- fingerprints ---------------------------------------------------------

def weights_fingerprint(variables) -> str:
    """Content hash over the weight pytree: treedef + per-leaf path,
    shape, dtype, and bytes. Derivable in any process holding the same
    checkpoint — the property that makes cross-process warm starts key
    to the same entries — and guaranteed to change under
    ``update_weights``/promote, so a swapped model can never load the
    old model's artifact."""
    import jax
    import numpy as np

    flat, treedef = jax.tree_util.tree_flatten_with_path(variables)
    h = hashlib.sha256(str(treedef).encode())
    for path, leaf in flat:
        arr = np.asarray(leaf)
        h.update(jax.tree_util.keystr(path).encode())
        h.update(str(arr.shape).encode())
        h.update(str(arr.dtype).encode())
        h.update(arr.tobytes())
    return h.hexdigest()[:16]


def config_fingerprint(config, iters: int) -> str:
    """Model-architecture component of the key: the dataclass repr is
    stable and covers every knob that changes the traced program."""
    h = hashlib.sha256(repr(config).encode())
    h.update(str(int(iters)).encode())
    return h.hexdigest()[:16]


def partition_fingerprint(mesh, declared_specs=()) -> str:
    """Mesh axes/sizes + the declared spec table, or ``"single"`` for
    the single-device engine. Includes device COUNT: an executable
    partitioned for 4 devices must never load into an 8-device
    assembly."""
    if mesh is None:
        return "single"
    h = hashlib.sha256()
    h.update(repr(tuple(mesh.axis_names)).encode())
    h.update(repr(tuple(mesh.devices.shape)).encode())
    h.update(repr(tuple(declared_specs)).encode())
    return h.hexdigest()[:16]


def declared_donations(lowered_text: str) -> List[int]:
    """Flat entry-param indices the lowered module marks donatable
    (``tf.aliasing_output`` / ``jax.buffer_donor``) — the signature's
    donation half. Split on ``%arg``, not an attribute-dict regex:
    attrs may nest braces (same parser discipline as
    ``tools/graftshard/artifacts.py``, kept dependency-free here
    because serving code must not import the lint tooling)."""
    try:
        sig = lowered_text[lowered_text.index("@main("):]
        sig = sig[:sig.index(") -> ")]
    except ValueError:
        return []
    out = []
    for chunk in sig.split("%arg")[1:]:
        ix = chunk.split(":", 1)[0]
        if ix.isdigit() and ("tf.aliasing_output" in chunk
                             or "jax.buffer_donor" in chunk):
            out.append(int(ix))
    return sorted(out)


def _fmt_aval(x) -> str:
    import jax.numpy as jnp

    shape = ",".join(str(int(d)) for d in jnp.shape(x))
    return f"{jnp.result_type(x)}[{shape}]"


def build_signature(args, lowered) -> Dict:
    """Calling-convention record for the manifest: flat input
    shapes/dtypes, flat output shapes/dtypes, and the donated flat
    params — what graftexport E5 diffs against the engine's live
    recipe."""
    import jax

    sig: Dict = {
        "in": [_fmt_aval(leaf)
               for leaf in jax.tree_util.tree_leaves(list(args))],
        "out": [],
        "donations": [],
    }
    try:
        sig["out"] = [_fmt_aval(o) for o in
                      jax.tree_util.tree_leaves(lowered.out_info)]
    except Exception:
        pass
    try:
        sig["donations"] = declared_donations(lowered.as_text())
    except Exception:
        pass
    return sig


# -- the cache ------------------------------------------------------------

def key_digest(components: Dict) -> str:
    """Canonical-JSON sha256 over the component dict — the entry name.
    Raises on non-JSON components: a key that cannot round-trip through
    the manifest cannot be verified on load."""
    blob = json.dumps(components, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


class AOTCache:
    """Content-addressed store of serialized XLA executables.

    Layout: ``root/objects/<digest>/{manifest.json, executable.bin}``
    where ``digest = sha256(canonical key json)``. The blob is a pickle
    of ``(serialized_bytes, in_tree, out_tree)`` exactly as
    ``jax.experimental.serialize_executable.serialize`` returns them.

    Thread-safety: stateless but for monotonic counters; the engine
    serializes its own compiles per bucket, and concurrent processes
    racing one digest resolve by atomic rename (first insert wins,
    both blobs are byte-equivalent by construction of the key).
    """

    def __init__(self, root: str):
        self.root = os.path.abspath(str(root))
        self.objects = os.path.join(self.root, "objects")
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.last_miss = ""   # why the last load missed (tests/debug)

    def entry_dir(self, components: Dict) -> str:
        return os.path.join(self.objects, key_digest(components))

    # -- load (never raises, never loads wrong) ---------------------------

    def load(self, components: Dict):
        """The verified load path: returns a ready-to-call executable,
        or ``None`` on ANY verification failure. The checks run in
        trust order — manifest parse, format tag, verbatim key match,
        blob hash — before the first unpickled byte."""
        edir = self.entry_dir(components)
        if not os.path.isdir(edir):
            return self._miss("absent")
        # chaos surface: corrupt the artifact before the read...
        fault_file("aot.load", edir)
        try:
            # ...and raise inside the verification scope — both must
            # read as a clean miss
            fault_point("aot.load")
            with open(os.path.join(edir, _MANIFEST),
                      encoding="utf-8") as f:
                manifest = json.load(f)
            if manifest.get("format") != AOT_FORMAT:
                return self._miss("format skew")
            if manifest.get("key") != components:
                return self._miss("key mismatch")
            with open(os.path.join(edir, _BLOB), "rb") as f:
                blob = f.read()
            if hashlib.sha256(blob).hexdigest() != manifest.get("sha256"):
                return self._miss("blob hash mismatch")
            from jax.experimental import serialize_executable as se

            payload, in_tree, out_tree = pickle.loads(blob)
            exe = se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception as exc:  # noqa: BLE001 — any failure is a miss
            return self._miss(f"{type(exc).__name__}: {exc}")
        self.hits += 1
        return exe

    def _miss(self, why: str):
        self.misses += 1
        self.last_miss = why
        return None

    # -- store (atomic, best-effort) --------------------------------------

    def store(self, components: Dict, compiled, lowered=None,
              args: Tuple = ()) -> Optional[str]:
        """Serialize ``compiled`` under ``components``; returns the
        entry dir, or ``None`` when the program cannot serialize (host
        callbacks etc.) or the write fails — the cache accelerates, it
        never gates."""
        missing = REQUIRED_KEY_FIELDS - set(components)
        if missing:
            raise ValueError(
                f"aot cache key missing component(s) {sorted(missing)} "
                "— an incomplete key is a stale-load hazard "
                "(graftexport E1)")
        try:
            from jax.experimental import serialize_executable as se

            payload, in_tree, out_tree = se.serialize(compiled)
            blob = pickle.dumps((payload, in_tree, out_tree),
                                protocol=pickle.HIGHEST_PROTOCOL)
            manifest = {
                "format": AOT_FORMAT,
                "key": components,
                "sha256": hashlib.sha256(blob).hexdigest(),
                "blob_bytes": len(blob),
                "signature": (build_signature(args, lowered)
                              if lowered is not None else {}),
            }
            final = self.entry_dir(components)
            if os.path.isdir(final):
                if self._entry_valid(final, components):
                    return final           # first insert already won
                shutil.rmtree(final, ignore_errors=True)
            os.makedirs(self.objects, exist_ok=True)
            tmp = tempfile.mkdtemp(prefix=".tmp-", dir=self.objects)
            with open(os.path.join(tmp, _BLOB), "wb") as f:
                f.write(blob)
            # manifest LAST: a torn write can only ever lose the
            # manifest, and an entry without one reads as miss
            with open(os.path.join(tmp, _MANIFEST), "w",
                      encoding="utf-8") as f:
                json.dump(manifest, f, indent=1, sort_keys=True)
            try:
                os.rename(tmp, final)
            except OSError:
                shutil.rmtree(tmp, ignore_errors=True)   # racer won
            self.stores += 1
            return final
        except Exception:  # noqa: BLE001
            return None

    def _entry_valid(self, edir: str, components: Dict) -> bool:
        """Cheap integrity read (no unpickle): manifest parses, key
        matches, blob hash matches."""
        try:
            with open(os.path.join(edir, _MANIFEST),
                      encoding="utf-8") as f:
                manifest = json.load(f)
            if (manifest.get("format") != AOT_FORMAT
                    or manifest.get("key") != components):
                return False
            with open(os.path.join(edir, _BLOB), "rb") as f:
                blob = f.read()
            return hashlib.sha256(blob).hexdigest() == \
                manifest.get("sha256")
        except Exception:  # noqa: BLE001
            return False

    # -- GC (the store must not only grow) ---------------------------------

    def evict(self, max_bytes: Optional[int] = None,
              max_age_s: Optional[float] = None,
              weights: Optional[str] = None) -> Dict[str, int]:
        """Garbage-collect entries; returns
        ``{removed, removed_bytes, remaining, remaining_bytes}``.

        Three independent policies, applied in this order:

        - ``weights``: drop every entry whose key's weights fingerprint
          matches — the registry's retirement hook (a retired variant's
          artifacts are dead weight the moment no live/canary engine
          shares its fingerprint).
        - ``max_age_s``: drop entries whose manifest is older than this
          many seconds.
        - ``max_bytes``: after the above, drop OLDEST-first until the
          store's blob bytes fit the budget (the entries most recently
          stored — the ones a warm restart will want — survive).

        Unparseable/torn entries (no manifest, bad JSON) already read
        as a load miss; any size/age policy treats them as removable
        garbage. Like :meth:`store`, eviction is best-effort: an
        unremovable entry is skipped, never raised into serving."""
        import time as _time

        out = {"removed": 0, "removed_bytes": 0,
               "remaining": 0, "remaining_bytes": 0}
        if not os.path.isdir(self.objects):
            return out
        entries = []
        for name in sorted(os.listdir(self.objects)):
            edir = os.path.join(self.objects, name)
            if not os.path.isdir(edir) or name.startswith(".tmp"):
                continue
            manifest = None
            mpath = os.path.join(edir, _MANIFEST)
            try:
                with open(mpath, encoding="utf-8") as f:
                    manifest = json.load(f)
                size = int(manifest.get("blob_bytes", 0))
                mtime = os.path.getmtime(mpath)
            except Exception:  # noqa: BLE001 — torn entry: garbage
                size = sum(
                    os.path.getsize(os.path.join(edir, p))
                    for p in os.listdir(edir)
                    if os.path.isfile(os.path.join(edir, p)))
                mtime = 0.0      # oldest possible: first to go
            entries.append((edir, manifest, size, mtime))

        def _drop(entry) -> None:
            edir, _, size, _ = entry
            shutil.rmtree(edir, ignore_errors=True)
            if not os.path.isdir(edir):
                out["removed"] += 1
                out["removed_bytes"] += size

        keep = []
        for e in entries:
            _, manifest, _, _ = e
            key = (manifest or {}).get("key") or {}
            if weights is not None and key.get("weights") == weights:
                _drop(e)
            else:
                keep.append(e)
        if max_age_s is not None:
            cutoff = _time.time() - float(max_age_s)
            fresh = []
            for e in keep:
                if e[3] < cutoff:
                    _drop(e)
                else:
                    fresh.append(e)
            keep = fresh
        if max_bytes is not None:
            total = sum(e[2] for e in keep)
            for e in sorted(keep, key=lambda e: e[3]):   # oldest first
                if total <= max_bytes:
                    break
                before = out["removed"]
                _drop(e)
                if out["removed"] > before:
                    total -= e[2]
                    keep.remove(e)
        out["remaining"] = len(keep)
        out["remaining_bytes"] = sum(e[2] for e in keep)
        return out

    # -- fleet distribution ------------------------------------------------

    def push(self, transport, *, attempts: int = 4,
             base_s: float = 0.25, max_s: float = 8.0,
             rng=None, sleep=None) -> Dict[str, int]:
        """Ship every valid entry to a joining host over the transport
        seam (closes the ROADMAP "deploy PUSHES artifact dirs to
        remote replicas" item).

        Per entry: one ``put_artifact`` call carrying the manifest
        bytes, the blob, and the blob's sha256. Verification is end to
        end — the worker recomputes the hash before any byte lands in
        its store, and the reply echoes the digest this side checks
        again. Corruption in transit (the ``transport.send`` chaos
        site) therefore reads as a clean ``TransportError`` and the
        entry is re-pushed under ``utils/retry``'s jittered backoff —
        at most ``attempts`` tries per entry before the push (and the
        host's admission) fails. Torn/invalid local entries are
        skipped, exactly as :meth:`load` would skip them.

        Returns ``{"entries", "bytes", "retries"}``."""
        from ..utils.retry import retry as _retry

        out = {"entries": 0, "bytes": 0, "retries": 0}
        if not os.path.isdir(self.objects):
            return out
        for name in sorted(os.listdir(self.objects)):
            edir = os.path.join(self.objects, name)
            mpath = os.path.join(edir, _MANIFEST)
            bpath = os.path.join(edir, _BLOB)
            if not (os.path.isdir(edir) and os.path.isfile(mpath)
                    and os.path.isfile(bpath)):
                continue
            try:
                with open(mpath, "rb") as f:
                    manifest_bytes = f.read()
                manifest = json.loads(manifest_bytes.decode("utf-8"))
                with open(bpath, "rb") as f:
                    blob = f.read()
            except Exception:  # noqa: BLE001 — torn entry: skip
                continue
            sha = hashlib.sha256(blob).hexdigest()
            if sha != manifest.get("sha256"):
                continue   # locally corrupt: a load-miss, not pushable

            def _send():
                reply = transport.call("put_artifact", {
                    "digest": name, "manifest": manifest_bytes,
                    "blob": blob, "sha256": sha})
                if reply.get("sha256") != sha:
                    raise RuntimeError(
                        f"artifact {name}: push ack digest mismatch")
                return reply

            kw = {"attempts": attempts, "base_s": base_s,
                  "max_s": max_s, "rng": rng,
                  "on_retry": lambda *a, **k: out.__setitem__(
                      "retries", out["retries"] + 1)}
            if sleep is not None:
                kw["sleep"] = sleep
            _retry(_send, **kw)
            out["entries"] += 1
            out["bytes"] += len(blob)
        return out

    def stats(self) -> Dict[str, int]:
        return {"hits": self.hits, "misses": self.misses,
                "stores": self.stores}
