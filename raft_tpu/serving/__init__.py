"""Serving/export: the TPU-native analog of the reference's TensorRT path.

The reference serves via ONNX -> trtexec -> a ``RAFTInferTRT`` engine
wrapper (test_trt.py:102-161, cvt2trt.sh, raft_trt.py). Here the same roles
are: AOT compilation (``jax.jit(...).lower().compile()``) over a shape-bucket
envelope (``engine.py``), portable StableHLO serialization (``export.py``),
and the video/batch helpers (``video.py`` = raft_trt_utils.py analog).

Above the engine sits the serving front-end the reference never had:
an async micro-batching scheduler with deadlines, backpressure and
priority classes (``scheduler.py``), per-stream warm-start video
sessions (``session.py``), the serving metrics surface
(``metrics.py``), the resilience layer (``resilience.py``): dispatch
watchdog with quarantine-and-replace, per-bucket circuit breakers,
engine recovery, and the ``health()`` surface — and the multi-model
registry (``registry.py``): versioned engines per named model, canary
rollout with deterministic hash routing, promote/rollback with zero
stranded futures — supervised by the SLO guardian (``guardian.py``):
automated canary judgment over bake-window metrics with auto-promote/
auto-rollback, plus the registry-wide admission budget that keeps one
model's flood out of every other model's queue headroom.

Request-scoped tracing (``trace.py``) threads one span per accepted
request through all of it — phase timestamps, coalesce fan-in,
cache/breaker/rollout annotations, tail-latency exemplars — written
to ``spans.jsonl`` and read back by ``raft_tpu.cli.serve_trace``; the
metrics.jsonl record/event schemas every layer emits are consolidated
in ``schema.py``.
"""

from raft_tpu.serving.engine import (SHAPE_ENVELOPE_LINUX, RAFTEngine,
                                     StaleFeatureError)
from raft_tpu.serving.feature_cache import (FeatureCacheMiss,
                                            FeatureCachePool)
from raft_tpu.serving.futures import settle_future
from raft_tpu.serving.guardian import (AdmissionBudget, GuardianPolicy,
                                       SLOGuardian)
from raft_tpu.serving.hosts import (HostDead, HostFleet, HostWorker,
                                    RemoteEngine)
from raft_tpu.serving.metrics import LatencyHistogram, ServingMetrics
from raft_tpu.serving.registry import (DeployError, ModelRegistry,
                                       RolloutInProgress, UnknownModel,
                                       canary_hash_fraction)
from raft_tpu.serving.resilience import (CircuitBreaker, CircuitOpen,
                                         DispatchExecutor, DispatchWedged)
from raft_tpu.serving.scheduler import (PRIORITY_BATCH,
                                        PRIORITY_INTERACTIVE,
                                        BackpressureError, DeadlineExceeded,
                                        MicroBatchScheduler, SchedulerClosed,
                                        ServeResult)
from raft_tpu.serving.session import VideoSession
from raft_tpu.serving.trace import TraceLedger
from raft_tpu.serving.transport import (LoopbackTransport,
                                        SocketTransport, TransportError)

__all__ = ["RAFTEngine", "SHAPE_ENVELOPE_LINUX", "MicroBatchScheduler",
           "BackpressureError", "DeadlineExceeded", "SchedulerClosed",
           "ServeResult", "VideoSession", "ServingMetrics",
           "LatencyHistogram", "CircuitBreaker", "CircuitOpen",
           "DispatchExecutor", "DispatchWedged", "ModelRegistry",
           "DeployError", "RolloutInProgress", "UnknownModel",
           "canary_hash_fraction", "PRIORITY_INTERACTIVE",
           "PRIORITY_BATCH", "SLOGuardian", "GuardianPolicy",
           "AdmissionBudget", "settle_future", "FeatureCachePool",
           "FeatureCacheMiss", "StaleFeatureError", "TraceLedger",
           "HostFleet", "HostWorker", "HostDead", "RemoteEngine",
           "LoopbackTransport", "SocketTransport", "TransportError"]
