"""Serving/export: the TPU-native analog of the reference's TensorRT path.

The reference serves via ONNX -> trtexec -> a ``RAFTInferTRT`` engine
wrapper (test_trt.py:102-161, cvt2trt.sh, raft_trt.py). Here the same roles
are: AOT compilation (``jax.jit(...).lower().compile()``) over a shape-bucket
envelope (``engine.py``), portable StableHLO serialization (``export.py``),
and the video/batch helpers (``video.py`` = raft_trt_utils.py analog).
"""

from raft_tpu.serving.engine import SHAPE_ENVELOPE_LINUX, RAFTEngine

__all__ = ["RAFTEngine", "SHAPE_ENVELOPE_LINUX"]
