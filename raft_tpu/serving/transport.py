"""Transport seam for the multi-host replica fleet.

The fleet's remote lanes (serving/hosts.py) never touch a socket or a
pickle directly — every byte that crosses a host boundary goes through
ONE seam, :class:`Transport.call`, so liveness drills, corruption
drills and the tier-1 determinism story all land in one place:

- :class:`LoopbackTransport` runs the worker **in-process** but still
  round-trips every message through the wire encoding (pickle +
  length-discipline + the ``transport.send`` / ``transport.recv``
  fault sites). Tier-1 drills a byte-identical protocol to the real
  thing without a subprocess — corruption in transit, raises, hangs
  all fire exactly where they would on a socket.
- :class:`SocketTransport` speaks the same messages over a
  length-prefixed TCP connection to a real worker process
  (``tests/host_worker.py`` is the reference server; see
  :func:`serve_connection` for the loop it runs). A dead peer —
  SIGKILL, reset, refused — surfaces as :class:`TransportError` on the
  caller, never a hang past the socket timeout.

Wire protocol (both directions): ``8-byte big-endian length`` +
``pickle((method, payload))`` out, ``8-byte length`` +
``pickle((status, payload))`` back, ``status in ("ok", "error")``.
Payloads are plain picklables (numpy arrays included). One request in
flight per connection — :class:`SocketTransport` serializes callers
with a leaf lock.

Fault sites (testing/faults.py): ``transport.send`` fires before a
request leaves (``corrupt`` zero-fills the encoded request — the
receiver sees garbage and the caller gets a clean
:class:`TransportError` to retry), ``transport.recv`` fires as the
reply is decoded (``corrupt`` smashes the reply bytes). ``raise``,
``hang`` and ``crash`` kinds behave as at every other site.
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
from typing import Any, Optional, Tuple

from ..testing.faults import fault_data, fault_point

#: graftthread lock declarations: both transports own ONE leaf lock
#: serializing calls; nothing is ever acquired while holding it except
#: the blocking socket I/O itself (no callbacks, no scheduler locks —
#: HostFleet and the scheduler call transports with NO lock held).
LOCK_ORDER = (
    ("transport.SocketTransport._lock",),
    ("transport.LoopbackTransport._lock",),
)

GRAFTTHREAD = {
    "locks": ("_lock",),
}

#: graftwire declarations: holding ``_lock`` across the socket I/O IS
#: the transport contract (one request in flight per connection), so
#: it is a wire lock, not a W3 finding; ``_send_msg``/``_recv_exact``
#: are the ONLY functions allowed to touch raw socket send/recv — all
#: framing lives there (W6).
GRAFTWIRE = {
    "wire_locks": ("_lock",),
    "framed_helpers": ("_send_msg", "_recv_exact"),
}

_LEN = struct.Struct(">Q")
#: sanity bound on a single message (a corrupted length prefix must
#: read as a protocol error, not a 2**60-byte allocation)
MAX_MESSAGE_BYTES = 1 << 32


class TransportError(RuntimeError):
    """The transport could not complete a call: peer dead/reset,
    timeout, protocol garbage, or corrupted bytes. Always retryable —
    the call either never reached the worker or its effect is
    idempotent by design (see the worker method contracts in
    serving/hosts.py)."""


def encode(obj: Any) -> bytes:
    return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


def decode(data: bytes) -> Any:
    try:
        return pickle.loads(data)
    except Exception as exc:  # noqa: BLE001 — any garbage, one error
        raise TransportError(
            f"undecodable message ({len(data)} bytes): {exc}") from None


def _send_msg(sock: socket.socket, data: bytes) -> None:
    try:
        sock.sendall(_LEN.pack(len(data)) + data)
    except OSError as exc:
        raise TransportError(f"send failed: {exc}") from None


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError as exc:
            raise TransportError(f"recv failed: {exc}") from None
        if not chunk:
            raise TransportError("peer closed the connection mid-message")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock: socket.socket) -> bytes:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > MAX_MESSAGE_BYTES:
        raise TransportError(
            f"message length {n} exceeds {MAX_MESSAGE_BYTES} "
            "(corrupted length prefix?)")
    return _recv_exact(sock, n)


class LoopbackTransport:
    """In-process transport over a worker OBJECT (anything with
    ``handle(method, payload) -> payload``). Every call still pays the
    full wire encode/decode round trip and fires both fault sites, so
    a tier-1 drill exercises byte-identical protocol paths — a
    ``transport.send`` corruption here reads exactly as it would on a
    socket: the request decodes to garbage and the caller retries."""

    def __init__(self, worker, name: str = "loopback"):
        self._worker = worker
        self.name = name
        self._lock = threading.Lock()
        self._closed = False

    def call(self, method: str, payload: Any = None,
             timeout_s: Optional[float] = None) -> Any:
        with self._lock:
            if self._closed:
                raise TransportError(f"{self.name}: transport closed")
            fault_point("transport.send")
            data = fault_data("transport.send", encode((method, payload)))
            try:
                req_method, req_payload = decode(data)
            except (TransportError, TypeError, ValueError) as exc:
                raise TransportError(
                    f"{self.name}: request corrupted in transit: "
                    f"{exc}") from None
            try:
                reply = ("ok", self._worker.handle(req_method,
                                                   req_payload))
            except Exception as exc:  # noqa: BLE001 — worker-side error
                reply = ("error", f"{type(exc).__name__}: {exc}")
            fault_point("transport.recv")
            rdata = fault_data("transport.recv", encode(reply))
            try:
                status, result = decode(rdata)
            except (TransportError, TypeError, ValueError) as exc:
                raise TransportError(
                    f"{self.name}: reply corrupted in transit: "
                    f"{exc}") from None
            if status != "ok":
                raise TransportError(f"{self.name}: worker error on "
                                     f"{method}: {result}")
            return result

    def close(self) -> None:
        with self._lock:
            self._closed = True

    def reopen(self) -> "LoopbackTransport":
        """Fresh transport to the SAME worker object (the reconnect
        probe path after a dead verdict poisoned this one)."""
        return LoopbackTransport(self._worker, name=self.name)

    @property
    def closed(self) -> bool:
        return self._closed


class SocketTransport:
    """Length-prefixed pickle RPC over TCP to a worker process.
    Lazy-connecting (a closed/killed peer surfaces on the next call,
    and :meth:`close` from ANOTHER thread poisons an in-flight recv —
    the dead-host verdict's way of unsticking a lane blocked on a
    zombie's socket)."""

    def __init__(self, host: str, port: int, *,
                 connect_timeout_s: float = 5.0,
                 call_timeout_s: Optional[float] = 60.0,
                 name: Optional[str] = None):
        self.host = host
        self.port = int(port)
        self.connect_timeout_s = float(connect_timeout_s)
        self.call_timeout_s = call_timeout_s
        self.name = name or f"{host}:{port}"
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._closed = False

    def _connect(self) -> socket.socket:
        if self._sock is None:
            try:
                self._sock = socket.create_connection(
                    (self.host, self.port),
                    timeout=self.connect_timeout_s)
            except OSError as exc:
                raise TransportError(
                    f"{self.name}: connect failed: {exc}") from None
        return self._sock

    def call(self, method: str, payload: Any = None,
             timeout_s: Optional[float] = None) -> Any:
        with self._lock:
            if self._closed:
                raise TransportError(f"{self.name}: transport closed")
            fault_point("transport.send")
            data = fault_data("transport.send", encode((method, payload)))
            sock = self._connect()
            sock.settimeout(timeout_s if timeout_s is not None
                            else self.call_timeout_s)
            try:
                _send_msg(sock, data)
                rdata = _recv_msg(sock)
            except TransportError:
                # a failed exchange leaves the stream unframed: drop
                # the connection so the NEXT call starts clean instead
                # of reading a stale half-message
                self._drop()
                raise
            fault_point("transport.recv")
            rdata = fault_data("transport.recv", rdata)
            status, result = decode(rdata)
            if status != "ok":
                raise TransportError(f"{self.name}: worker error on "
                                     f"{method}: {result}")
            return result

    def _drop(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def close(self) -> None:
        # deliberately NOT under _lock: close() is how the dead-host
        # verdict unsticks a caller blocked inside call()'s recv — the
        # socket close makes that recv raise, the caller drops the
        # connection and surfaces TransportError
        self._closed = True
        sock = self._sock
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    def reopen(self) -> "SocketTransport":
        """Fresh transport to the same endpoint (reconnect probe after
        a dead verdict — the worker may have been restarted on the
        same port, or the partition healed)."""
        return SocketTransport(
            self.host, self.port,
            connect_timeout_s=self.connect_timeout_s,
            call_timeout_s=self.call_timeout_s, name=self.name)

    @property
    def closed(self) -> bool:
        return self._closed


def serve_connection(conn: socket.socket, worker) -> None:
    """One connection's server loop (the worker side of
    :class:`SocketTransport` — ``tests/host_worker.py`` runs this per
    accepted connection): decode request, dispatch to
    ``worker.handle``, encode reply; returns when the peer closes."""
    while True:
        try:
            data = _recv_msg(conn)
        except TransportError:
            return   # peer gone / stream garbage: this connection ends
        try:
            method, payload = decode(data)
            reply = ("ok", worker.handle(method, payload))
        except Exception as exc:  # noqa: BLE001 — reply, don't die
            reply = ("error", f"{type(exc).__name__}: {exc}")
        try:
            _send_msg(conn, encode(reply))
        except TransportError:
            return


def serve_forever(port: int, worker, *, host: str = "127.0.0.1",
                  ready_fh=None) -> None:
    """Blocking single-threaded worker server: accept one connection
    at a time, run :func:`serve_connection` on it. Prints the bound
    port to ``ready_fh`` (e.g. stdout, for the parent to read) —
    pass ``port=0`` to bind an ephemeral one."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, port))
    srv.listen(4)
    if ready_fh is not None:
        ready_fh.write(f"PORT {srv.getsockname()[1]}\n")
        ready_fh.flush()
    while True:
        conn, _ = srv.accept()
        try:
            serve_connection(conn, worker)
        finally:
            try:
                conn.close()
            except OSError:
                pass
