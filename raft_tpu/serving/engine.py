"""AOT-compiled inference engine — the ``RAFTInferTRT`` analog.

The reference builds a TensorRT engine over a dynamic-shape envelope
(min/opt/max, ``cvt2trt.sh``) and binds I/O by name at runtime
(raft_trt.py:12-39). XLA has no dynamic shapes: the envelope becomes a set
of discrete shape buckets, each AOT-compiled once
(``jax.jit(...).lower().compile()``), and ``infer_batch`` routes a request
to the smallest bucket that fits, padding up (batch and spatial). That is
the same trick TensorRT's optimization profiles play, made explicit.

Like the fork's single-output ONNX export (test_trt.py:131 names only
``flowup``), the engine's serving function returns only the upsampled flow;
iteration count is baked at 20 (test_trt.py:124, ITERS_EXPORT).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.config import ITERS_EXPORT, RAFTConfig
from raft_tpu.models import RAFT
from raft_tpu.ops.padding import pad_amounts
from raft_tpu.testing.faults import fault_point

#: graftthread T3: the engine lock is a LEAF, and T1 is the reason it
#: can stay one — compiles (``lower()/compile()``, minutes on real
#: hardware) run OUTSIDE it by hard-won discipline (the PR-6 bug:
#: compiling under this lock stalled weight swaps and every
#: already-compiled dispatch behind one cold bucket).
LOCK_ORDER = (("engine.RAFTEngine._lock",),)

# cvt2trt.sh:1 envelope (min 1x3x256x256 / opt 2x3x800x800 / max 8x3x1024x1024)
SHAPE_ENVELOPE_LINUX: List[Tuple[int, int, int]] = [
    (1, 256, 256), (2, 800, 800), (8, 1024, 1024)]
# cvt2trt.bat:1 envelope (max 1x3x512x1024)
SHAPE_ENVELOPE_WINDOWS: List[Tuple[int, int, int]] = [
    (1, 256, 256), (1, 512, 800), (1, 512, 1024)]


class StaleFeatureError(RuntimeError):
    """A cached dispatch's feature rows were stamped with a weights
    version the engine has since moved past (a live weight swap raced
    the dispatch between cache assembly and the engine call). The
    batch fails BEFORE the executable runs — features computed by one
    weight tree must never feed a refinement running another. Streams
    recover by re-priming (the session's cold-restart path); the
    registry's flush-on-swap makes this a microsecond race window, not
    a steady state."""


class PendingBatch:
    """One in-flight engine dispatch (``infer_batch_async``).

    JAX dispatch is asynchronous: the executable call returns device
    arrays immediately and the host only blocks when it READS them.
    ``fetch()`` is that read — the D2H transfer plus the crop back to
    the request geometry. Splitting it out lets a serving front-end
    assemble and ship batch N+1 while the device still computes batch N
    (``MicroBatchScheduler(pipeline_depth=2)``); ``infer_batch`` is
    exactly ``infer_batch_async(...).fetch()``, so the synchronous path
    stays bitwise what it always was.

    ``h2d_bytes``: host bytes shipped to the device for this dispatch
    (padded frames + any host-built flow_init) — the wire-format
    counter the serving metrics aggregate. ``t_ready``: monotonic time
    the outputs were known complete (set by ``fetch``); the scheduler's
    dispatch-gap histogram reads it.
    """

    __slots__ = ("bucket", "h2d_bytes", "t_ready", "span_ctx", "_flow",
                 "_flow_low", "_crop", "_return_low", "_low_device",
                 "_inputs", "_donated", "_cache")

    def __init__(self, flow, flow_low, crop, bucket, h2d_bytes,
                 return_low, low_device, inputs=None, donated=False,
                 cache=None):
        self._flow = flow
        self._flow_low = flow_low
        self._crop = crop           # (b, h, w, top, left, hp, wp)
        self.bucket = bucket
        self.h2d_bytes = h2d_bytes
        self._return_low = return_low
        self._low_device = low_device
        #: the call's device input arrays, pinned until fetch: dropping
        #: the last reference to a DONATED buffer while its computation
        #: is still in flight makes the deallocation BLOCK on the
        #: computation (measured ~the full compute time on the CPU
        #: backend) — exactly the synchronous stall the async split
        #: exists to remove. fetch() releases them once the results are
        #: ready, when deletion is free.
        self._inputs = inputs
        #: True when this dispatch DONATED an input (the u8 warm
        #: engine's flow_init -> flow_low alias): fetch() must then
        #: hand the caller a flow_low decoupled from the aliased
        #: buffer — see the pinning note there
        self._donated = donated
        #: feature-cache dispatch (``infer_cached_async``): the call's
        #: ``(fmap2, cnet2)`` cache outputs — device arrays whose
        #: buffers alias the DONATED assembled cache inputs. fetch()
        #: then returns the four-tuple cached form.
        self._cache = cache
        #: request-tracing span context (serving/trace.py): the
        #: scheduler parks its batch's spans here at dispatch so the
        #: pipelined completion stage can stamp the ``fetch_start``
        #: phase edge from the pending it actually blocks on. None
        #: (tracing off) costs nothing.
        self.span_ctx = None
        self.t_ready: Optional[float] = None

    def fetch(self):
        """Block on the device result; returns what ``infer_batch``
        would have: flow, or ``(flow, flow_low)`` with return_low.
        One-shot: the pending's buffer references are released on
        return (a long-lived PendingBatch — e.g. the scheduler's
        dispatch-gap clock — must not pin full bucket-padded outputs
        in device memory)."""
        if self._flow is None:
            raise RuntimeError("PendingBatch.fetch() already consumed")
        # chaos site: a hang here models a device whose compute (or
        # D2H) never completes — at pipeline_depth>1 this is the
        # completion stage the scheduler's watchdog must also cover
        fault_point("serve.fetch")
        if self._cache is not None:
            return self._fetch_cached()
        b, h, w, top, left, hp, wp = self._crop
        flow = np.asarray(
            self._flow[:b, top:top + h, left:left + w, :])
        out = flow
        if self._return_low:
            # cropped to the ÷8-padded request (NOT the raw frame): the
            # align padding is identical for the next same-shape frame,
            # so this feeds straight back as its flow_init
            low = self._flow_low[:b, :hp // 8, :wp // 8, :]
            if self._donated:
                # On a donating engine flow_low IS the donated
                # flow_init buffer (input_output_alias), and a
                # full-extent crop short-circuits to the SAME array —
                # without this pin the caller's flow_low (device
                # handle or the host np.asarray VIEW below) aliases a
                # donation-target buffer whose owning references this
                # method is about to drop. Under whole-suite
                # allocation pressure that read garbage (the PR-8
                # donated-buffer landmine family; order-dependent
                # test_serving failure). Decouple: copy ONLY when the
                # crop short-circuited (a partial crop already made a
                # fresh buffer), and force the result READY either way
                # — its read of the donated buffer must complete while
                # _flow_low/_inputs still pin it. Cheap: the
                # executable just finished (flow was read above), so
                # this blocks only on a 1/8-res slice/copy dispatch.
                if low is self._flow_low:
                    low = jnp.array(low, copy=True)
                low.block_until_ready()
            if not self._low_device:
                low = np.asarray(low)
            out = (flow, low)
        self._flow = self._flow_low = None
        self._inputs = None     # results ready: releasing the donated
        #                         input buffer no longer blocks
        self.t_ready = time.monotonic()
        return out

    def _fetch_cached(self):
        """Feature-cache form of ``fetch``: ``(flow, flow_low_full,
        fmap2, cnet2)``. ``flow`` is host, cropped to the request
        geometry (rows that were PRIME rows carry meaningless flow the
        scheduler discards); the other three stay FULL-bucket DEVICE
        arrays — the per-stream pool slices its rows from them.

        Donated-alias discipline (the PR-10 lesson, applied forward):
        every one of the three device outputs aliases a DONATED input
        buffer (the assembled fmap1/cnet1/flow_init batches). What the
        caller gets are the call's OWNING result arrays — never host
        views of a donation target — and the host flow read above
        blocks on the whole executable, so the aliased outputs are
        READY before ``_inputs`` drops the pins on their source
        buffers. Downstream per-row slices are fresh device buffers
        computed from owned outputs; nothing outlives its owner."""
        b, h, w, top, left, hp, wp = self._crop
        flow = np.asarray(
            self._flow[:b, top:top + h, left:left + w, :])
        low, (fmap2, ctx2) = self._flow_low, self._cache
        out = (flow, low, fmap2, ctx2)
        self._flow = self._flow_low = self._cache = None
        self._inputs = None
        self.t_ready = time.monotonic()
        return out


class RaggedPendingBatch:
    """One in-flight RAGGED dispatch (``infer_ragged_async``): a
    mixed-shape micro-batch in one capacity-class executable.

    Same contract as :class:`PendingBatch` — async call, one-shot
    ``fetch()``, input pins held until the results are ready (the
    donated-buffer discipline), ``t_ready``/``h2d_bytes`` for the
    scheduler's hot-path clocks — but per-ROW geometry: ``fetch()``
    returns a LIST of flows (and, with ``return_low``, a list of
    per-row ``flow_low`` crops), each cropped to its own request.
    ``real_px``/``padded_px`` carry the dispatch's capacity-padding
    accounting (request pixels vs box pixels) for the padding-waste
    gauge."""

    __slots__ = ("bucket", "h2d_bytes", "t_ready", "span_ctx",
                 "real_px", "padded_px", "_flow", "_flow_low", "_rows",
                 "_return_low", "_low_device", "_inputs", "_donated")

    def __init__(self, flow, flow_low, rows, bucket, h2d_bytes,
                 return_low, low_device, inputs=None, donated=False,
                 real_px=0, padded_px=0):
        self._flow = flow
        self._flow_low = flow_low
        #: per-row (h, w, top, left, hp, wp) request geometry
        self._rows = rows
        self.bucket = bucket
        self.h2d_bytes = h2d_bytes
        self._return_low = return_low
        self._low_device = low_device
        self._inputs = inputs       # pinned until fetch (PendingBatch
        #                             donated-dealloc discipline)
        self._donated = donated
        self.real_px = real_px
        self.padded_px = padded_px
        #: request-tracing span context — same contract as
        #: :attr:`PendingBatch.span_ctx`
        self.span_ctx = None
        self.t_ready: Optional[float] = None

    def fetch(self):
        """Block on the device result; returns ``[flow_i]`` or
        ``([flow_i], [flow_low_i])`` with return_low. One-shot."""
        if self._flow is None:
            raise RuntimeError("RaggedPendingBatch.fetch() already "
                               "consumed")
        fault_point("serve.fetch")
        # per-row crops run ON DEVICE before the host read (the plain
        # fetch's discipline): D2H ships each request's own pixels —
        # never the whole capacity box with its fill rows — and every
        # returned flow is an OWNING host array, not a view pinning
        # the full (B, Hcap, Wcap, 2) buffer. The first np.asarray
        # blocks on the executable; the rest are cheap slice reads.
        flows = [np.asarray(self._flow[i, top:top + h,
                                       left:left + w, :])
                 for i, (h, w, top, left, _, _)
                 in enumerate(self._rows)]
        out = flows
        if self._return_low:
            lows = []
            for i, (h, w, top, left, hp, wp) in enumerate(self._rows):
                # fresh device buffer computed from the call's OWNING
                # output — never a view of the donated flow_init alias
                low = self._flow_low[i, :hp // 8, :wp // 8, :]
                if self._donated:
                    # its read of the donated buffer must complete
                    # while _flow_low/_inputs still pin it (the PR-10
                    # lesson); cheap — the executable just finished
                    low.block_until_ready()
                if not self._low_device:
                    low = np.asarray(low)
                lows.append(low)
            out = (flows, lows)
        self._flow = self._flow_low = None
        self._inputs = None
        self.t_ready = time.monotonic()
        return out


class RAFTEngine:
    """Shape-bucketed AOT engine over converted weights."""

    def __init__(self, variables: Dict, config: RAFTConfig = RAFTConfig(),
                 iters: int = ITERS_EXPORT,
                 envelope: Sequence[Tuple[int, int, int]] = (),
                 precompile: bool = True, mesh=None,
                 exact_shapes: bool = False, warm_start: bool = False,
                 wire: str = "f32", feature_cache: bool = False,
                 ragged: bool = False,
                 capacity_classes: Sequence[Tuple[int, int, int]] = (),
                 ragged_grain: int = 64, aot_cache=None):
        """``mesh``: optional ``jax.sharding.Mesh`` (data × spatial axes,
        `parallel.mesh.make_mesh`) — buckets then compile as SPMD
        programs with batch sharded over 'data' and image height over
        'spatial' (weights replicated), the serving-side counterpart of
        the sharded train step for resolutions/batches beyond one chip
        (SURVEY.md §5 long-context). The TRT analog has nothing like
        this; DataParallel never served (train.py:138 is training-only).
        All sharding decisions delegate to ONE
        ``parallel.partitioner.Partitioner`` (``self.partitioner``) —
        the pjit seam the registry fan-out grows on, and the spec table
        ``tools/graftshard`` audits (S1–S6) before any multi-device
        config ships.

        ``exact_shapes``: never route to a SPATIALLY larger bucket —
        compile (and cache) one executable per exact ÷8-padded request
        spatial shape instead. Costs a compile per distinct shape but
        removes the bucket-fill accuracy artifact entirely (the spatial
        fill shifts instance-norm statistics; see infer_batch) — the
        TRT-dynamic-shapes parity setting for accuracy-sensitive
        serving. Batch is still allowed to fill up to an
        already-compiled same-spatial bucket: batch fill is per-sample
        neutral, and without it every ragged sliding-window tail
        (``infer``'s last chunk) would compile its own executable.

        ``warm_start``: buckets compile with a low-res ``flow_init``
        input and a ``(flow_low, flow_up)`` output so per-stream video
        sessions can carry the previous pair's flow into the next
        refinement start (the Sintel warm-start path,
        evaluation/evaluate.py, lifted into serving). A zero
        ``flow_init`` row IS a cold start (``coords1 + 0``), so warm
        sessions and one-shot requests batch into the SAME executable —
        still one per bucket. Off by default: the engine-direct
        single-output contract (the exported-``flowup`` analog) is
        unchanged.

        ``wire``: host→device wire format for the frames. ``"f32"``
        (default) ships fp32 — bitwise the historical path. ``"u8"``
        compiles bucket executables that take **uint8** frames and run
        the ``2*(x/255)-1`` normalize on device (models/raft.py already
        converts via ``astype(float32)``, so the convert lands inside
        the compiled program): host-side align/zero-fill padding then
        happens in uint8 (4× cheaper copies) and H2D traffic per
        request drops ~4×. uint8→fp32 conversion is exact, so at
        integer-valued [0, 255] inputs the output is bitwise identical
        to the fp32 wire (pinned in tests/test_serving.py). Float
        inputs are cast to uint8 on the way in — callers feeding
        non-integer frames should stay on ``"f32"``. With
        ``warm_start=True`` the u8 wire also donates the ``flow_init``
        buffer to its same-shaped ``flow_low`` output (graftaudit H4
        verifies XLA honors the alias), so a device-resident
        ``flow_init`` passed at full bucket shape is CONSUMED by the
        call.

        ``feature_cache`` (needs ``warm_start=True``): additionally
        compile a SECOND bucket signature per served spatial shape —
        the cross-frame cached program (models/raft.py
        ``forward_cached``): it takes the NEW frame plus
        device-resident cached ``(fmap1, cnet1, flow_init)`` rows for
        returning streams and EMITS the new frame's fmap + speculative
        context as cache outputs, so steady-state video pays one
        encoder pass per frame instead of two (and ships ONE frame of
        H2D instead of two). A zeroed-cache row is the PRIME form of a
        cold start, so cold and warm stream rows coalesce into the
        same executable — still one cached executable per bucket
        shape. All three cache inputs are DONATED to their same-shaped
        cache outputs (verified honored in ``input_output_alias`` by
        graftaudit H4). Off by default: no cached program exists and
        every non-cached path is bitwise unchanged.

        ``ragged``: additionally compile RAGGED executables — one per
        ``capacity_classes`` entry ``(B, Hcap, Wcap)`` instead of one
        per request HxW. A ragged program takes a per-row validity
        descriptor (``(B,) int32`` 1/8-res extents — TRACED arguments,
        so every shape mix runs the same executable) and applies
        masked-tail correlation semantics
        (``models.RAFT.forward_ragged`` /
        ``kernels/corr_ragged_pallas``): requests of ANY ``(h, w)``
        fitting the box dispatch together through ONE program —
        cold-start compiles drop from O(shapes) to O(1) per class, and
        unseen client resolutions stop costing a fresh compile (the
        compile-cache DoS fix). A compile-on-miss request outside
        every class rounds its box up to ``ragged_grain`` pixels
        (must be a multiple of 8), bounding the class table. A
        full-extent row is bitwise the bucketed path at the same box
        (the select mask is the identity); sub-capacity rows get the
        cleaner zeros-tail semantics, documented in README "Ragged
        serving". Off by default: no ragged table exists and every
        other path is bitwise unchanged.

        ``aot_cache``: optional :class:`raft_tpu.serving.aot.AOTCache`
        (or a directory path — one is built) — the serialized-executable
        store. With it armed, ``_get_executable`` probes the cache
        BEFORE compiling (keyed on weights content + bucket geometry +
        wire + donation signature + partition hash + config/iters +
        jax/jaxlib/platform) and a hit loads the ready executable with
        ZERO XLA compiles; a miss compiles as before and serializes the
        result for the next process. Any key mismatch or corrupt blob
        reads as a clean miss-and-recompile — never a wrong load (see
        aot.py's trust model; ``tools/graftexport`` audits the
        artifacts). Off (``None``, the default): bitwise the PR-15
        engine, no on-disk state at all.
        """
        if wire not in ("f32", "u8"):
            raise ValueError(f"wire={wire!r}: choose 'f32' or 'u8'")
        if ragged and feature_cache:
            # checked FIRST: this combination must fail on ITSELF, not
            # on whichever other knob (warm_start) happens to be
            # missing — the caller needs the real reason, once, at the
            # constructor, before any compile runs
            raise ValueError(
                "ragged=True with feature_cache=True is not supported "
                "yet: the cached signature keeps its per-shape bucket "
                "table. See ROADMAP 'Ragged serving, next bricks' (a) "
                "— the per-row descriptor subsuming the cached "
                "signature's bucket matrix is the next brick. Serve "
                "ragged one-shot traffic and cached video from two "
                "engines until it lands.")
        if feature_cache and not warm_start:
            raise ValueError("feature_cache=True needs warm_start=True "
                             "(the cached program carries the "
                             "flow_init/flow_low recurrence state)")
        if feature_cache and mesh is not None:
            raise ValueError("feature_cache is not supported under a "
                             "mesh yet — per-stream cache rows assume "
                             "single-device buckets")
        if ragged and mesh is not None:
            raise ValueError("ragged=True is not supported under a "
                             "mesh yet — capacity classes assume "
                             "single-device executables")
        if ragged and (ragged_grain <= 0 or ragged_grain % 8):
            raise ValueError(f"ragged_grain={ragged_grain}: must be a "
                             "positive multiple of 8 (capacity boxes "
                             "are ÷8-aligned)")
        if capacity_classes and not ragged:
            raise ValueError("capacity_classes given without "
                             "ragged=True — they would compile nothing")
        self.config = config
        self.iters = iters
        self.mesh = mesh
        self.exact_shapes = exact_shapes
        self.warm_start = warm_start
        self.wire = wire
        self.feature_cache = feature_cache
        self.ragged = ragged
        self.ragged_grain = int(ragged_grain)
        #: bumped on every update_weights (under the lock): cache
        #: slots are stamped with the version that produced their
        #: features, and a cached dispatch refuses rows from another
        #: tree (StaleFeatureError) — the weight-swap flush's backstop
        self.weights_version = 0
        self._wire_np = np.uint8 if wire == "u8" else np.float32
        #: guards ``_compiled`` and the weight-tree swap so a live
        #: ``update_weights`` under concurrent dispatch can't mix old
        #: and new weights within one dispatch (each ``infer_batch``
        #: snapshots the tree ONCE under this lock), and two dispatch
        #: threads can't race a compile-on-miss insert
        self._lock = threading.RLock()
        if mesh is not None:
            from raft_tpu.parallel.partitioner import (Partitioner,
                                                       mesh_model_config)

            #: the pjit seam: all sharding decisions (which value rides
            #: which mesh axis, bucket grains, extent fences) live in
            #: ONE Partitioner — the same table tools/graftshard audits
            self.partitioner = Partitioner(mesh)
            self.variables = jax.device_put(variables,
                                            self.partitioner.replicated)
            # mesh-safe encoder path: the batch-concat encode would
            # redistribute every row per dispatch (see
            # RAFTConfig.split_encode); weights are identical either way
            model = RAFT(mesh_model_config(config, mesh))
        else:
            self.partitioner = None
            self.variables = jax.device_put(variables)
            model = RAFT(config)

        if warm_start:
            def serve(variables, image1, image2, flow_init):
                # warm-start serving fn: ``flow_init`` rides at 1/8
                # resolution and a zero row is exactly a cold start, so
                # the scheduler can coalesce warm sessions and one-shot
                # requests into one bucket executable. Returns flow_low
                # too — the state a session feeds back.
                flow_low, flow_up = model.apply(
                    variables, image1, image2, iters=iters,
                    flow_init=flow_init, test_mode=True)
                return flow_low, flow_up
        else:
            def serve(variables, image1, image2):
                # single-output serving fn, the exported-``flowup``
                # analog. Weights ride as an ARGUMENT, not a baked
                # closure: the compiled bucket (and its persistent-cache
                # entry) is then keyed by shapes only — swapping a
                # checkpoint reuses every executable instead of
                # recompiling the envelope, and the lowered program
                # stays KB-sized rather than carrying ~21 MB of weight
                # constants per bucket upload. (The StableHLO EXPORT
                # still bakes weights — a single portable artifact is
                # the point there, as with the reference's ONNX file.)
                _, flow_up = model.apply(variables, image1, image2,
                                         iters=iters, test_mode=True)
                return flow_up

        if feature_cache:
            def serve_cached(variables, image2, fmap1, cnet1, flow_init):
                # cross-frame cached serving fn: ONE encoder pass (the
                # new frame) + the recurrence; cache inputs arrive
                # device-resident and are DONATED to the same-shaped
                # cache outputs (fmap1->fmap2, cnet1->cnet2,
                # flow_init->flow_low) — the per-stream state recycles
                # its own HBM instead of doubling it per call
                return model.apply(variables, image2, fmap1, cnet1,
                                   flow_init, iters=iters,
                                   method="forward_cached")

            self._fn_cached = jax.jit(serve_cached,
                                      donate_argnums=(2, 3, 4))
        else:
            self._fn_cached = None
        #: cached-signature executables, one per bucket shape — a
        #: SECOND table, never mixed into ``_compiled`` (the plain
        #: router must not route one-shot pairs into a cached program)
        self._compiled_cached: Dict[Tuple[int, int, int],
                                    jax.stages.Compiled] = {}

        if ragged:
            if warm_start:
                def serve_ragged(variables, image1, image2, valid_h8,
                                 valid_w8, flow_init):
                    # ragged serving fn: the per-row validity extents
                    # ride as TRACED (B,) i32 arguments — any shape mix
                    # is data, never a new program
                    return model.apply(variables, image1, image2,
                                       valid_h8, valid_w8, flow_init,
                                       iters=iters,
                                       method="forward_ragged")
            else:
                def serve_ragged(variables, image1, image2, valid_h8,
                                 valid_w8):
                    _, flow_up = model.apply(variables, image1, image2,
                                             valid_h8, valid_w8, None,
                                             iters=iters,
                                             method="forward_ragged")
                    return flow_up

            if warm_start and wire == "u8":
                # same zero-copy discipline as the plain u8 warm
                # engine: flow_init (arg 5 here — after the two
                # descriptor arrays) donates to its same-shaped
                # flow_low output
                self._fn_ragged = jax.jit(serve_ragged,
                                          donate_argnums=(5,))
            else:
                self._fn_ragged = jax.jit(serve_ragged)
        else:
            self._fn_ragged = None
        #: ragged capacity-class executables, one per (B, Hcap, Wcap)
        #: box — a THIRD table, never mixed into the shape-keyed ones
        #: (a ragged program has a different signature and different
        #: sub-capacity semantics than the plain bucket at the same
        #: dims)
        self._compiled_ragged: Dict[Tuple[int, int, int],
                                    jax.stages.Compiled] = {}

        if warm_start and wire == "u8":
            # the u8 wire's zero-copy discipline extends to the warm
            # start: flow_init (arg 3) is donated to the same-shaped
            # flow_low output, so the per-call H2D init buffer is
            # recycled instead of doubling the 1/8-res state in HBM.
            # Tied to the wire knob so wire="f32" stays bitwise the
            # PR-6/7 contract (a donated input is consumed — a
            # behavior change, however benign).
            self._fn = jax.jit(serve, donate_argnums=(3,))
        else:
            self._fn = jax.jit(serve)
        self._compiled: Dict[Tuple[int, int, int], jax.stages.Compiled] = {}

        # -- AOT executable cache (load-not-compile) ----------------------
        if aot_cache is not None and not hasattr(aot_cache, "load"):
            from raft_tpu.serving.aot import AOTCache
            aot_cache = AOTCache(aot_cache)
        self._aot = aot_cache
        #: real XLA compiles this engine performed (cache hits don't
        #: count) — the zero-compile cold-start pin reads this
        self.compile_count = 0
        self.aot_hits = 0
        self.aot_misses = 0
        if self._aot is not None:
            from raft_tpu.serving import aot as _aotmod
            # content fingerprint, NOT the weights_version counter: a
            # fresh process must re-derive the same key from the same
            # checkpoint, and a swapped checkpoint must derive a
            # DIFFERENT one (the old artifact can never load)
            self._weights_fp = _aotmod.weights_fingerprint(self.variables)
            self._config_fp = _aotmod.config_fingerprint(config, iters)
            self._partition_fp = _aotmod.partition_fingerprint(
                mesh, self.partitioner.declared_specs()
                if self.partitioner is not None else ())

        for shape in envelope:
            if precompile:
                self._get_executable(shape)
                if feature_cache:
                    # the cached signature is its own program: warm it
                    # with the envelope too, or the first video
                    # dispatch pays the compile mid-traffic
                    self._get_executable(shape, cached=True)
            else:
                self._compiled.setdefault(shape, None)
        for cls in capacity_classes:
            b, ch, cw = cls
            if ch % 8 or cw % 8:
                raise ValueError(f"capacity class {cls}: Hcap/Wcap "
                                 "must be multiples of 8")
            if precompile:
                self._get_executable((b, ch, cw), ragged=True)
            else:
                self._compiled_ragged.setdefault((b, ch, cw), None)

    def _check_weights(self, variables: Dict) -> None:
        """Raise ``ValueError`` unless ``variables`` matches the
        engine's weight tree in structure AND leaf shapes/dtypes."""
        old_def = jax.tree_util.tree_structure(self.variables)
        new_def = jax.tree_util.tree_structure(variables)
        if old_def != new_def:
            # container types matter: the executables were lowered against
            # the old treedef, and e.g. FrozenDict vs plain dict flattens
            # to identical key paths while still failing at call time
            raise ValueError(
                "checkpoint structure mismatch: pytree definition differs "
                f"(engine: {str(old_def)[:120]}... vs {str(new_def)[:120]}"
                "...)")

        def avals(tree):
            return {jax.tree_util.keystr(k): (jnp.shape(l),
                                              jnp.result_type(l))
                    for k, l in
                    jax.tree_util.tree_flatten_with_path(tree)[0]}

        old, new = avals(self.variables), avals(variables)
        if old != new:
            diff = [f"{k}: {new[k]} vs engine's {old[k]}"
                    for k in old.keys() & new.keys() if old[k] != new[k]]
            raise ValueError(
                "checkpoint structure mismatch: " + "; ".join(diff[:5]))

    def compatible_weights(self, variables: Dict) -> bool:
        """True iff ``variables`` could be swapped in live via
        :meth:`update_weights` (same pytree structure and leaf
        shapes/dtypes as this engine's weights). The registry's
        same-arch test: a compatible canary promotes as a weight swap
        that reuses every compiled bucket; an incompatible one (a
        different architecture) needs a fresh engine."""
        try:
            self._check_weights(variables)
        except ValueError:
            return False
        return True

    def bucket_shapes(self) -> List[Tuple[int, int, int]]:
        """Sorted bucket shapes this engine owns (compiled or
        ``precompile=False`` placeholders) — e.g. the envelope a
        canary engine pre-warms so it serves the same request
        geometries as the live engine it shadows."""
        with self._lock:
            return sorted(self._compiled)

    def update_weights(self, variables: Dict) -> None:
        """Swap checkpoints without invalidating compiled buckets.

        Structure AND leaf shapes/dtypes must match the engine's current
        weights — the executables were compiled against those avals, so a
        same-structure checkpoint with different shapes (e.g. a basic
        checkpoint into a small-config engine, or bf16-cast weights)
        would brick every precompiled bucket with an opaque call-time
        error if it slipped through here."""
        self._check_weights(variables)
        staged = (jax.device_put(variables, self.partitioner.replicated)
                  if self.mesh is not None
                  else jax.device_put(variables))
        if self._aot is not None:
            # outside the lock (hashes the whole tree); the new
            # fingerprint keys every POST-swap compile to the new
            # weights — the old checkpoint's artifacts are unreachable
            # from this engine the moment the swap publishes
            from raft_tpu.serving import aot as _aotmod
            new_fp = _aotmod.weights_fingerprint(variables)
        # the swap itself is a single reference assignment under the
        # dispatch lock: an in-flight infer_batch already holds its own
        # snapshot, the next one sees the new tree whole. The version
        # bump rides the same atom: a cached dispatch that snapshots
        # the new tree can never accept old-version feature rows.
        with self._lock:
            self.variables = staged
            self.weights_version += 1
            if self._aot is not None:
                self._weights_fp = new_fp

    # -- shape routing ------------------------------------------------------

    def bucket_program(self, shape: Tuple[int, int, int], variables=None,
                       cached: bool = False, ragged: bool = False):
        """``(jitted fn, example args)`` for one bucket/class — the
        EXACT recipe ``_get_executable`` compiles, exposed so the AOT
        store records the true calling convention and the
        ``tools/graftexport`` tier lowers the very program the engine
        serves (E5 audits manifest signatures against this)."""
        if cached and self._fn_cached is None:
            raise ValueError("cached executables need a "
                             "feature_cache=True engine")
        if ragged and self._fn_ragged is None:
            raise ValueError("ragged executables need a "
                             "ragged=True engine")
        if variables is None:
            with self._lock:
                variables = self.variables
        b, h, w = shape
        if self.mesh is not None:
            self.partitioner.validate_extent(h)
            # compile-on-miss buckets are pre-rounded in infer_batch,
            # but user-supplied envelope buckets reach here unrounded;
            # the partitioner rejects uneven ones at compile time with
            # a readable message instead of the later opaque
            # uneven-sharding device_put error
            self.partitioner.validate_bucket(shape)
            shard = self.partitioner.sharding("frames")
        else:
            shard = None
        # wire="u8" buckets take uint8 frames; the normalize's
        # astype(float32) then runs ON DEVICE (exact conversion)
        spec = jax.ShapeDtypeStruct((b, h, w, 3),
                                    jnp.dtype(self._wire_np),
                                    sharding=shard)
        if ragged:
            # the ragged signature: two frames at the capacity box +
            # the per-row validity descriptor (+ warm-start flow_init)
            vspec = jax.ShapeDtypeStruct((b,), jnp.int32)
            args = [variables, spec, spec, vspec, vspec]
            if self.warm_start:
                args.append(jax.ShapeDtypeStruct(
                    (b, h // 8, w // 8, 2), jnp.float32))
            fn = self._fn_ragged
        elif cached:
            # the cached signature: the NEW frame + device-resident
            # cache rows (fp32, 1/8 res) — no second frame at all
            lh, lw = h // 8, w // 8
            args = [variables, spec,
                    jax.ShapeDtypeStruct((b, lh, lw,
                                          self.config.fnet_dim),
                                         jnp.float32),
                    jax.ShapeDtypeStruct((b, lh, lw,
                                          self.config.cnet_dim),
                                         jnp.float32),
                    jax.ShapeDtypeStruct((b, lh, lw, 2), jnp.float32)]
            fn = self._fn_cached
        else:
            args = [variables, spec, spec]
            if self.warm_start:
                # flow_init rides at 1/8 res; h % (8*spatial) == 0
                # under a mesh makes h//8 divide the spatial axis, so
                # the same batch+spatial rule applies
                args.append(jax.ShapeDtypeStruct(
                    (b, h // 8, w // 8, 2), jnp.float32,
                    sharding=(self.partitioner.sharding("flow_init")
                              if self.mesh is not None else None)))
            fn = self._fn
        return fn, args

    def _aot_key(self, shape: Tuple[int, int, int], cached: bool = False,
                 ragged: bool = False) -> Dict:
        """The serialized-executable cache key for one bucket/class:
        full program provenance, every component derivable by a fresh
        process holding the same checkpoint (see aot.REQUIRED_KEY_FIELDS
        — graftexport E1 audits written manifests against it)."""
        from raft_tpu.serving import aot as _aotmod
        import jaxlib

        if ragged:
            program = ("serve_ragged_warm" if self.warm_start
                       else "serve_ragged")
            donations = ([5] if self.warm_start and self.wire == "u8"
                         else [])
        elif cached:
            program = "serve_cached"
            donations = [2, 3, 4]
        else:
            program = "serve_warm" if self.warm_start else "serve"
            donations = ([3] if self.warm_start and self.wire == "u8"
                         else [])
        return {
            "format": _aotmod.AOT_FORMAT,
            "program": program,
            "weights": self._weights_fp,
            "geometry": [int(x) for x in shape],
            "wire": self.wire,
            "iters": int(self.iters),
            "config": self._config_fp,
            "donations": donations,
            "partition": self._partition_fp,
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "platform": jax.default_backend(),
        }

    def _get_executable(self, shape: Tuple[int, int, int], variables=None,
                        cached: bool = False, ragged: bool = False):
        if ragged:
            table = self._compiled_ragged
        else:
            table = self._compiled_cached if cached else self._compiled
        with self._lock:
            if variables is None:
                variables = self.variables
            exe = table.get(shape)
        if exe is not None:
            return exe
        fn, args = self.bucket_program(shape, variables=variables,
                                       cached=cached, ragged=ragged)
        key = (self._aot_key(shape, cached=cached, ragged=ragged)
               if self._aot is not None else None)
        if key is not None:
            # load-not-compile: a verified artifact skips XLA entirely.
            # aot.load NEVER raises and NEVER returns a wrong program —
            # any mismatch/corruption below falls through to the
            # compile path (chaos site "aot.load" proves it mid-run)
            exe = self._aot.load(key)
            if exe is not None:
                with self._lock:
                    self.aot_hits += 1
                    cur = table.get(shape)
                    if cur is None:
                        table[shape] = exe
                        cur = exe
                return cur
            with self._lock:
                self.aot_misses += 1
        # compile OUTSIDE the lock: minutes on real hardware, and the
        # lock must stay cheap (weight swaps and already-compiled
        # dispatches would stall behind it). The executable is keyed by
        # avals only, so compiling against a stale snapshot is fine;
        # racing threads at worst duplicate one compile and the first
        # insert wins.
        # chaos site (real compiles only — cache hits return above):
        # "raise" models an uncompilable shape, "hang" a compile that
        # never returns — the wedge the scheduler's dispatch watchdog
        # must survive
        fault_point("engine.compile")
        with self._lock:
            self.compile_count += 1
        if key is not None:
            # a compile that feeds the store must come from the
            # BACKEND: a jax-persistent-cache-deserialized executable
            # serializes to a payload that can never load back
            # (aot.fresh_compile) — publishing it would poison the
            # warm start for every replica that follows
            from raft_tpu.serving.aot import fresh_compile

            with fresh_compile():
                lowered = fn.lower(*args)
                exe = lowered.compile()
            # best-effort serialize for the next process; store never
            # raises (an unserializable program just stays in-process)
            self._aot.store(key, exe, lowered=lowered, args=tuple(args))
        else:
            lowered = fn.lower(*args)
            exe = lowered.compile()
        with self._lock:
            # first compile wins a race; a precompile=False placeholder
            # (None) is filled, not treated as an existing executable
            cur = table.get(shape)
            if cur is None:
                table[shape] = exe
                cur = exe
        return cur

    def aot_stats(self) -> Dict[str, int]:
        """Serialized-cache counters for the bench summary line:
        ``compiles_avoided`` == loads served without an XLA compile."""
        with self._lock:
            return {
                "enabled": int(self._aot is not None),
                "aot_hits": self.aot_hits,
                "aot_misses": self.aot_misses,
                "compiles": self.compile_count,
                "compiles_avoided": self.aot_hits,
            }

    # -- replica fleet (parallel/placement.py) ------------------------------

    def spawn_replica(self) -> "RAFTEngine":
        """A data-parallel sibling for the replica fleet: same config/
        iters/wire/mode flags and the same weight tree, SHARING this
        engine's AOT artifact store — so the sibling warms every bucket
        by LOADING the serialized executable this engine already stored
        (``aot_hits`` counts it; ``compile_count`` stays 0 per added
        replica, the fleet's zero-compile pin). Without a store the
        sibling adopts this engine's compiled executables directly
        (:meth:`adopt_executables`) — still zero compiles for warm
        buckets.

        The sibling's signature tables mirror this engine's bucket/
        class KEYS as ``precompile=False`` placeholders, so routing
        (``route_bucket``/``route_ragged``) answers identically across
        the fleet while the tables stay replica-LOCAL dicts — a wedge
        verdict's ``drop_bucket`` on one replica never touches a
        sibling's executable."""
        with self._lock:
            variables = self.variables
            plain = list(self._compiled)
            cached = list(self._compiled_cached)
            ragged = list(self._compiled_ragged)
        rep = RAFTEngine(
            variables, self.config, iters=self.iters, envelope=(),
            precompile=False, mesh=self.mesh,
            exact_shapes=self.exact_shapes,
            warm_start=self.warm_start, wire=self.wire,
            feature_cache=self.feature_cache, ragged=self.ragged,
            ragged_grain=self.ragged_grain, aot_cache=self._aot)
        with rep._lock:
            for s in plain:
                rep._compiled.setdefault(s, None)
            for s in cached:
                rep._compiled_cached.setdefault(s, None)
            for s in ragged:
                rep._compiled_ragged.setdefault(s, None)
        if self._aot is None:
            rep.adopt_executables(self)
        return rep

    def adopt_executables(self, source: "RAFTEngine") -> int:
        """Fill this engine's signature tables from ``source``'s
        compiled executables (the no-artifact-store fallback for
        :meth:`spawn_replica`). The TABLES stay this engine's own
        dicts — ``drop_bucket`` here never affects ``source`` — while
        the executable objects are shared (immutable once compiled;
        XLA executables are safe to invoke from concurrent replicas).
        Returns how many executables were adopted."""
        with source._lock:
            tables = (dict(source._compiled),
                      dict(source._compiled_cached),
                      dict(source._compiled_ragged))
        n = 0
        with self._lock:
            for mine, theirs in zip((self._compiled,
                                     self._compiled_cached,
                                     self._compiled_ragged), tables):
                for shape, exe in theirs.items():
                    if exe is not None and mine.get(shape) is None:
                        mine[shape] = exe
                        n += 1
                    else:
                        mine.setdefault(shape, exe)
        return n

    def _select_bucket(self, b: int, h: int, w: int,
                       cached: bool = False
                       ) -> Optional[Tuple[int, int, int]]:
        table = self._compiled_cached if cached else self._compiled
        if self.exact_shapes:
            # exact-shapes mode is exact SPATIALLY — spatial fill is
            # what shifts the encoders' instance-norm statistics (the
            # accuracy artifact the mode exists to remove). Batch fill
            # is per-sample neutral (instance norm reduces over H, W
            # only; eval-mode BatchNorm uses running averages — the
            # fill changes values only at conv-vectorization fp32 noise
            # scale, measured ~3e-5 px), so a
            # ragged sliding-window tail routes to an already-compiled
            # same-spatial bucket with fill + crop instead of compiling
            # one executable per distinct tail batch (pinned in
            # tests/test_serving.py: len(_compiled) stays 1 across a
            # ragged sequence).
            fits = [s for s in table
                    if s[0] >= b and s[1] == h and s[2] == w]
            return min(fits, key=lambda s: s[0]) if fits else None
        fits = [s for s in table
                if s[0] >= b and s[1] >= h and s[2] >= w]
        if not fits:
            return None
        return min(fits, key=lambda s: s[0] * s[1] * s[2])

    def _route(self, b: int, hp: int, wp: int,
               cached: bool = False) -> Tuple[int, int, int]:
        """Bucket a ÷8-padded ``(b, hp, wp)`` request will use: the
        smallest compiled fit, else the (mesh-rounded) compile-on-miss
        bucket — the single source infer_batch and the scheduler's
        routing questions share. ``cached=True`` routes over the
        cached-signature table instead."""
        with self._lock:
            bucket = self._select_bucket(b, hp, wp, cached=cached)
        if bucket is None:
            bb, bh = b, hp
            if self.mesh is not None:
                # batch rides the 'data' axis, height the 'spatial' axis
                # — round the ad-hoc bucket up so every device gets
                # whole examples and whole feature rows (the bucket's
                # zero-fill + output crop absorbs the padding either
                # way)
                bb, bh = self.partitioner.round_bucket(b, hp)
            bucket = (bb, bh, wp)
        return bucket

    def _padded(self, h: int, w: int) -> Tuple[int, int]:
        left, right, top, bottom = pad_amounts(h, w)
        return h + top + bottom, w + left + right

    def route_bucket(self, b: int, h: int, w: int,
                     cached: bool = False) -> Tuple[int, int, int]:
        """The bucket ``infer_batch`` would use for a raw ``(b, h, w)``
        request — compiles nothing."""
        hp, wp = self._padded(h, w)
        return self._route(b, hp, wp, cached=cached)

    def bucket_capacity(self, h: int, w: int,
                        cached: bool = False) -> Optional[int]:
        """Largest batch an already-compiled bucket can carry for an
        ``(h, w)`` request, or None when no compiled bucket spatially
        fits — the scheduler's cross-caller coalescing ceiling."""
        hp, wp = self._padded(h, w)
        table = self._compiled_cached if cached else self._compiled
        with self._lock:
            if self.exact_shapes:
                fits = [s[0] for s in table
                        if s[1] == hp and s[2] == wp]
            else:
                fits = [s[0] for s in table
                        if s[1] >= hp and s[2] >= wp]
        return max(fits) if fits else None

    def drop_bucket(self, shape: Tuple[int, int, int],
                    cached: bool = False, ragged: bool = False) -> bool:
        """Forget one compiled bucket executable (serving resilience:
        a dispatch-wedge verdict indicts the executable that hung —
        the scheduler drops it here and the breaker's half-open probe
        lazily recompiles via ``ensure_bucket``/compile-on-miss).
        Returns True when the bucket was present. ``precompile=False``
        placeholders count as present — the key is removed either way
        so the recompile starts clean. ``cached=True`` drops the
        cached-signature executable instead (a wedge on a cached
        dispatch indicts the cached program, not its plain sibling);
        ``ragged=True`` likewise drops the capacity-class executable
        from the ragged table."""
        missing = object()
        if ragged:
            table = self._compiled_ragged
        else:
            table = self._compiled_cached if cached else self._compiled
        with self._lock:
            return table.pop(shape, missing) is not missing

    def ensure_bucket(self, batch: int, h: int, w: int,
                      cached: bool = False) -> Tuple[int, int, int]:
        """Compile (if missing) and return the bucket that serves a
        ``(batch, h, w)`` request. The scheduler pre-warms ONE bucket
        per distinct spatial shape at its max micro-batch so every
        later fill count batch-fills into it instead of compiling per
        distinct micro-batch size (the PR-2 ragged-tail lesson, one
        layer up)."""
        hp, wp = self._padded(h, w)
        bucket = self._route(batch, hp, wp, cached=cached)
        self._get_executable(bucket, cached=cached)
        return bucket

    def executable_count(self) -> int:
        """Compiled buckets across ALL signature tables (plain +
        cached + ragged capacity classes) — the per-engine count the
        metrics/H3 discipline pins."""
        with self._lock:
            return (len(self._compiled) + len(self._compiled_cached)
                    + len(self._compiled_ragged))

    # -- ragged routing -----------------------------------------------------

    def ragged_classes(self) -> List[Tuple[int, int, int]]:
        """Sorted capacity classes this engine owns (compiled or
        ``precompile=False`` placeholders)."""
        with self._lock:
            return sorted(self._compiled_ragged)

    def _select_class(self, b: int, hp: int,
                      wp: int) -> Optional[Tuple[int, int, int]]:
        """Smallest capacity class fitting ``(b, hp, wp)`` (caller
        holds the lock)."""
        fits = [s for s in self._compiled_ragged
                if s[0] >= b and s[1] >= hp and s[2] >= wp]
        if not fits:
            return None
        return min(fits, key=lambda s: s[0] * s[1] * s[2])

    def _route_ragged(self, b: int, hp: int,
                      wp: int) -> Tuple[int, int, int]:
        """Capacity class a ÷8-padded ``(b, hp, wp)`` dispatch will
        use: the smallest fitting class, else a declared class's
        spatial box with a grown batch, else a ``ragged_grain``-rounded
        compile-on-miss box — the single source ``infer_ragged_async``
        and the scheduler's routing questions share (the bound on the
        class table is what makes arbitrary client resolutions a
        non-event for the compile cache)."""
        with self._lock:
            cls = self._select_class(b, hp, wp)
            if cls is None:
                # batch outgrew every fitting class: keep the smallest
                # declared spatial box, grow batch only — never mint a
                # new geometry when one already serves these extents
                sp = [s for s in self._compiled_ragged
                      if s[1] >= hp and s[2] >= wp]
                if sp:
                    s = min(sp, key=lambda s: s[1] * s[2])
                    cls = (b, s[1], s[2])
        if cls is None:
            g = self.ragged_grain
            cls = (b, -(-hp // g) * g, -(-wp // g) * g)
        return cls

    def ragged_class_for(self, h: int, w: int) -> Tuple[int, int]:
        """The ``(Hcap, Wcap)`` box a raw ``(h, w)`` request coalesces
        under — the scheduler's CROSS-SHAPE coalescing key (every
        request mapping to the same box rides the same micro-batch,
        whatever its own shape). Compiles nothing."""
        hp, wp = self._padded(h, w)
        with self._lock:
            sp = [s for s in self._compiled_ragged
                  if s[1] >= hp and s[2] >= wp]
        if sp:
            s = min(sp, key=lambda s: (s[1] * s[2], s[0]))
            return s[1], s[2]
        g = self.ragged_grain
        return -(-hp // g) * g, -(-wp // g) * g

    def route_ragged(self, b: int, h: int, w: int) -> Tuple[int, int, int]:
        """The capacity class ``infer_ragged_async`` would use for ``b``
        rows whose padded extents fit ``(h, w)`` — compiles nothing."""
        hp, wp = self._padded(h, w)
        return self._route_ragged(b, hp, wp)

    def ragged_capacity(self, h: int, w: int) -> Optional[int]:
        """Largest batch an already-compiled (or placeholder) class at
        the ``(h, w)`` request's box can carry, or None when no class
        spatially fits — the scheduler's coalescing ceiling."""
        hp, wp = self._padded(h, w)
        with self._lock:
            fits = [s[0] for s in self._compiled_ragged
                    if s[1] >= hp and s[2] >= wp]
        return max(fits) if fits else None

    def ensure_ragged(self, batch: int, h: int, w: int
                      ) -> Tuple[int, int, int]:
        """Compile (if missing) and return the capacity class serving
        a ``(batch, h, w)`` box — the scheduler pre-warms ONE class
        per coalescing box at its max micro-batch, exactly the
        ``ensure_bucket`` discipline one table over. Unlike
        ``route_ragged`` there is NO grain fallback here: callers pass
        class boxes (``ragged_class_for`` output — declared classes or
        already-grain-rounded), so a miss compiles that exact
        geometry. In particular the breaker's half-open probe after a
        wedge drop restores the DROPPED class, never a rounded
        stranger."""
        hp, wp = self._padded(h, w)
        with self._lock:
            cls = self._select_class(batch, hp, wp)
        if cls is None:
            cls = (batch, hp, wp)
        self._get_executable(cls, ragged=True)
        return cls

    # -- inference ----------------------------------------------------------

    def infer_batch_async(self, image1, image2, flow_init=None,
                          return_low: bool = False,
                          low_device: bool = False) -> PendingBatch:
        """Non-blocking dispatch: route, pad (in the wire dtype), ship,
        and CALL the bucket executable — JAX queues the computation and
        returns device handles immediately. The returned
        :class:`PendingBatch`'s ``fetch()`` blocks on the result;
        ``infer_batch`` is ``infer_batch_async(...).fetch()``.

        ``flow_init`` may be a host array (shape ``(B, hp//8, wp//8,
        2)``, embedded into the bucket on the host as before) or a JAX
        device array (same shape — embedded into the bucket ON DEVICE,
        no D2H→H2D round trip; a full-bucket-shaped device array passes
        through untouched, and on a u8-wire warm engine it is then
        donated/consumed). ``low_device=True`` leaves the returned
        ``flow_low`` on device (a lazily-sliced jax array) instead of
        materializing it to numpy — the session-state round-trip
        killer."""
        if (flow_init is not None or return_low) and not self.warm_start:
            raise ValueError(
                "flow_init/return_low need a warm_start=True engine — "
                "this engine compiled the single-output serving fn")
        # wire dtype on the HOST side too: with wire="u8" the align/fill
        # pads below copy uint8 (4× cheaper) and H2D ships 1 byte/px
        image1 = np.asarray(image1)
        image2 = np.asarray(image2)
        if image1.dtype != self._wire_np:
            image1 = image1.astype(self._wire_np)
        if image2.dtype != self._wire_np:
            image2 = image2.astype(self._wire_np)
        b, h, w, _ = image1.shape
        left, right, top, bottom = pad_amounts(h, w)
        hp, wp = h + top + bottom, w + left + right

        bucket = self._route(b, hp, wp)  # compile-on-miss, cached
        bb, bh, bw = bucket
        # one snapshot of the weight tree serves this whole dispatch:
        # a concurrent update_weights swaps the reference, never the
        # tree a running dispatch compiled-against/called-with
        with self._lock:
            variables = self.variables
        exe = self._get_executable(bucket, variables)  # validates
        # extent under a mesh; compiles outside the lock
        # edge-pad to stride alignment (InputPadder semantics), zero-fill the
        # rest of the bucket
        align = ((0, 0), (top, bottom), (left, right), (0, 0))
        fill = ((0, bb - b), (0, bh - hp), (0, bw - wp), (0, 0))
        i1 = np.pad(np.pad(image1, align, mode="edge"), fill)
        i2 = np.pad(np.pad(image2, align, mode="edge"), fill)
        h2d = i1.nbytes + i2.nbytes
        args = [i1, i2]
        if self.warm_start:
            want = (b, hp // 8, wp // 8, 2)
            full = (bb, bh // 8, bw // 8, 2)
            if flow_init is not None and isinstance(flow_init, jax.Array):
                if flow_init.shape == full:
                    finit = flow_init       # zero-copy pass-through
                elif flow_init.shape == want:
                    # embed ON DEVICE: the session's device-resident
                    # flow_low never touches the host
                    finit = jnp.zeros(full, jnp.float32).at[
                        :b, :hp // 8, :wp // 8, :].set(flow_init)
                else:
                    raise ValueError(
                        f"flow_init shape {flow_init.shape} != {want} "
                        "(1/8 of the ÷8-padded request)")
            else:
                finit = np.zeros(full, np.float32)
                if flow_init is not None:
                    fi = np.asarray(flow_init, np.float32)
                    if fi.shape != want:
                        raise ValueError(
                            f"flow_init shape {fi.shape} != {want} "
                            "(1/8 of the ÷8-padded request)")
                    finit[:b, :hp // 8, :wp // 8, :] = fi
                h2d += finit.nbytes
            args.append(finit)
        if self.mesh is not None:
            part = self.partitioner
            kinds = ["frames", "frames"] + (["flow_init"]
                                            if self.warm_start else [])
            args = [jax.device_put(a, part.sharding(k))
                    for a, k in zip(args, kinds)]
        else:
            args = [jnp.asarray(a) for a in args]
        out = exe(variables, *args)
        if self.warm_start:
            flow_low, flow = out
        else:
            flow_low, flow = None, out
        return PendingBatch(flow, flow_low,
                            (b, h, w, top, left, hp, wp), bucket, h2d,
                            return_low, low_device, inputs=args,
                            donated=(self.warm_start
                                     and self.wire == "u8"))

    def infer_batch(self, image1, image2, flow_init=None,
                    return_low: bool = False):
        """(B,H,W,3) [0,255] -> (B,H,W,2) flow. Routes to a bucket,
        padding up (raft_trt_utils.pad_images analog); falls back to an
        exact-shape jit specialization outside the envelope.

        ``flow_init`` (warm_start engines only): per-sample 1/8-res warm
        start, shape ``(B, hp//8, wp//8, 2)`` in the ÷8-padded frame
        space — exactly the ``flow_low`` a previous same-shape call
        returned (forward-interpolated by the session layer).
        ``return_low=True`` additionally returns that ``flow_low``.

        Accuracy note: bucket fill beyond the ÷8 pad shifts the encoders'
        instance-norm statistics, which couple every output pixel to the
        fill content — measured a few px of pointwise movement with a
        metric-neutral (<1e-2 px EPE) aggregate at trained weights
        (tests/test_evaluation.py bucketing-delta test). TensorRT's
        dynamic shapes don't pay this; exact-shape compile (an envelope
        bucket per deployed shape) avoids it here."""
        return self.infer_batch_async(image1, image2,
                                      flow_init=flow_init,
                                      return_low=return_low).fetch()

    def infer_ragged_async(self, pairs, flow_inits=None,
                           return_low: bool = False,
                           low_device: bool = False,
                           box: Optional[Tuple[int, int]] = None
                           ) -> RaggedPendingBatch:
        """Non-blocking MIXED-SHAPE dispatch through one capacity-class
        executable.

        ``pairs``: sequence of per-request ``(image1, image2)`` frame
        pairs — each ``(h_i, w_i, 3)``, shapes may all differ. Every
        row is edge-padded to its own ÷8 alignment and zero-embedded in
        the class box; the per-row valid extents ride as the ragged
        descriptor (traced data, one program for any mix), and padded
        rows/tails contribute nothing (masked-tail semantics —
        ``forward_ragged``).

        ``flow_inits`` (warm_start engines): per-row warm starts, each
        ``(hp_i/8, wp_i/8, 2)`` (host or device array) or None for a
        cold row. On a u8-wire warm engine the assembled full-box
        flow_init is donated to ``flow_low``, as on the plain path.

        ``box``: optional ``(Hcap, Wcap)`` the caller already routed
        the batch under (the scheduler's coalescing-key box). With it,
        class routing runs on the BOX extents — the same inputs
        ``route_ragged`` answers routing questions with — so the
        executable actually dispatched is exactly the one the caller's
        bookkeeping (wedge-verdict drop target, metrics label) names;
        without it (engine-direct callers) routing falls back to the
        batch's own max extents.

        ``fetch()`` returns per-row flows (and lows with
        ``return_low``) cropped to each request's geometry."""
        if not self.ragged:
            raise ValueError("infer_ragged_async needs a ragged=True "
                             "engine")
        n = len(pairs)
        if n == 0:
            raise ValueError("empty ragged micro-batch")
        if (flow_inits is not None or return_low) and not self.warm_start:
            raise ValueError(
                "flow_inits/return_low need a warm_start=True engine")
        rows = []
        imgs = []
        for i1, i2 in pairs:
            i1 = np.asarray(i1)
            i2 = np.asarray(i2)
            if i1.dtype != self._wire_np:
                i1 = i1.astype(self._wire_np)
            if i2.dtype != self._wire_np:
                i2 = i2.astype(self._wire_np)
            if i1.ndim != 3 or i1.shape[-1] != 3:
                raise ValueError(f"ragged rows are (H, W, 3) frame "
                                 f"pairs, got {i1.shape}")
            if i1.shape != i2.shape:
                raise ValueError(f"frame shapes differ: {i1.shape} vs "
                                 f"{i2.shape}")
            h, w = i1.shape[:2]
            left, right, top, bottom = pad_amounts(h, w)
            rows.append((h, w, top, left, h + top + bottom,
                         w + left + right))
            imgs.append((i1, i2))
        hpmax = max(r[4] for r in rows)
        wpmax = max(r[5] for r in rows)
        if box is not None:
            if box[0] < hpmax or box[1] < wpmax:
                raise ValueError(
                    f"box {box} does not fit the batch's padded "
                    f"extents ({hpmax}, {wpmax})")
            bucket = self._route_ragged(n, box[0], box[1])
        else:
            bucket = self._route_ragged(n, hpmax, wpmax)
        bb, bh, bw = bucket
        with self._lock:
            variables = self.variables
        exe = self._get_executable(bucket, variables, ragged=True)
        i1b = np.zeros((bb, bh, bw, 3), self._wire_np)
        i2b = np.zeros_like(i1b)
        # descriptor extents: 0 for batch-fill rows — the mask zeroes
        # their features whole, so fill rows contribute nothing
        vh8 = np.zeros((bb,), np.int32)
        vw8 = np.zeros((bb,), np.int32)
        for i, ((h, w, top, left, hp, wp), (a, b2)) in enumerate(
                zip(rows, imgs)):
            align = ((top, hp - h - top), (left, wp - w - left), (0, 0))
            i1b[i, :hp, :wp] = np.pad(a, align, mode="edge")
            i2b[i, :hp, :wp] = np.pad(b2, align, mode="edge")
            vh8[i] = hp // 8
            vw8[i] = wp // 8
        h2d = i1b.nbytes + i2b.nbytes + vh8.nbytes + vw8.nbytes
        args = [i1b, i2b, vh8, vw8]
        if self.warm_start:
            full = (bb, bh // 8, bw // 8, 2)
            finits = list(flow_inits) if flow_inits is not None else []
            if len(finits) > n:
                raise ValueError(f"{len(finits)} flow_inits for "
                                 f"{n} rows")
            device_rows = any(fi is not None
                              and isinstance(fi, jax.Array)
                              for fi in finits)
            for i, fi in enumerate(finits):
                if fi is None:
                    continue
                h, w, top, left, hp, wp = rows[i]
                want = (hp // 8, wp // 8, 2)
                if tuple(fi.shape) != want:
                    raise ValueError(
                        f"row {i} flow_init shape {tuple(fi.shape)} "
                        f"!= {want} (1/8 of the ÷8-padded request)")
            if device_rows:
                # embed ON DEVICE: device-resident session state never
                # touches the host (the plain path's discipline); any
                # HOST rows mixed in still cross the wire, so they
                # still count toward h2d
                finit = jnp.zeros(full, jnp.float32)
                for i, fi in enumerate(finits):
                    if fi is not None:
                        _, _, _, _, hp, wp = rows[i]
                        if not isinstance(fi, jax.Array):
                            fi = np.asarray(fi, np.float32)
                            h2d += fi.nbytes
                        finit = finit.at[i, :hp // 8, :wp // 8, :].set(fi)
            else:
                finit = np.zeros(full, np.float32)
                for i, fi in enumerate(finits):
                    if fi is not None:
                        _, _, _, _, hp, wp = rows[i]
                        finit[i, :hp // 8, :wp // 8, :] = np.asarray(
                            fi, np.float32)
                h2d += finit.nbytes
            args.append(finit)
        args = [jnp.asarray(a) for a in args]
        out = exe(variables, *args)
        if self.warm_start:
            flow_low, flow = out
        else:
            flow_low, flow = None, out
        return RaggedPendingBatch(
            flow, flow_low, rows, bucket, h2d, return_low, low_device,
            inputs=args,
            donated=(self.warm_start and self.wire == "u8"),
            real_px=sum(h * w for (h, w, _, _, _, _) in rows),
            padded_px=bb * bh * bw)

    def infer_ragged(self, pairs, flow_inits=None,
                     return_low: bool = False):
        """Synchronous form: ``infer_ragged_async(...).fetch()``."""
        return self.infer_ragged_async(
            pairs, flow_inits=flow_inits, return_low=return_low).fetch()

    def infer_cached_async(self, image2, slots,
                           expect_version: Optional[int] = None
                           ) -> PendingBatch:
        """Cross-frame cached dispatch: ONE encoder pass (the new
        frames) + the recurrence; each pair's first-frame features
        arrive as device-resident cache rows instead of pixels.

        ``image2``: (B, h, w, 3) — each stream's NEW frame (the only
        frame that ships: H2D per warm pair is HALF the plain path's).
        ``slots``: length-B list; entry i is None for a COLD/PRIME row
        (zeroed cache inputs — its flow outputs are meaningless and
        the serving layer discards them; its cache outputs prime the
        stream) or a ``(fmap1, cnet1, flow_init)`` triple of device
        arrays at the request's 1/8-÷8-padded geometry (``flow_init``
        may be None: warm features, cold recurrence — the
        post-prime pair's form).

        ``expect_version``: the engine ``weights_version`` the rows
        were stamped with; if the live tree moved past it (a weight
        swap raced this dispatch) the call raises
        :class:`StaleFeatureError` BEFORE running the executable —
        the registry flush drill's backstop. The check and the weight
        snapshot are one atom under the engine lock, so a dispatch is
        always wholly-old or wholly-new, never features from one tree
        under weights from another.

        ``fetch()`` returns ``(flow, flow_low_full, fmap2, cnet2)``;
        the last three stay full-bucket device arrays (the pool
        slices per-stream rows). The three assembled cache inputs are
        DONATED (fmap1->fmap2, cnet1->cnet2, flow_init->flow_low), so
        per-call cache state recycles its own HBM."""
        if not self.feature_cache:
            raise ValueError("infer_cached_async needs a "
                             "feature_cache=True engine")
        image2 = np.asarray(image2)
        if image2.dtype != self._wire_np:
            image2 = image2.astype(self._wire_np)
        b, h, w, _ = image2.shape
        if len(slots) != b:
            raise ValueError(f"{len(slots)} cache slots for batch {b}")
        left, right, top, bottom = pad_amounts(h, w)
        hp, wp = h + top + bottom, w + left + right
        lh, lw = hp // 8, wp // 8
        bucket = self._route(b, hp, wp, cached=True)
        bb, bh, bw = bucket
        with self._lock:
            if (expect_version is not None
                    and self.weights_version != expect_version):
                raise StaleFeatureError(
                    f"cache rows stamped weights_version="
                    f"{expect_version} but the engine is at "
                    f"{self.weights_version} — a weight swap raced "
                    "this dispatch; streams re-prime")
            variables = self.variables
        exe = self._get_executable(bucket, variables, cached=True)
        align = ((0, 0), (top, bottom), (left, right), (0, 0))
        fill = ((0, bb - b), (0, bh - hp), (0, bw - wp), (0, 0))
        i2 = np.pad(np.pad(image2, align, mode="edge"), fill)
        h2d = i2.nbytes
        # assemble the cache rows ON DEVICE: same-shape rows stack,
        # then pad to the bucket — zero rows ARE the PRIME/cold form,
        # so one stack+pad serves every warmth mix. The assembled
        # batches are fresh buffers (the slot arrays are only READ —
        # never donated; the pool keeps owning them until the store
        # replaces them), and THEY are what the executable consumes.
        fdim, cdim = self.config.fnet_dim, self.config.cnet_dim
        zf = jnp.zeros((lh, lw, fdim), jnp.float32)
        zc = jnp.zeros((lh, lw, cdim), jnp.float32)
        zl = jnp.zeros((lh, lw, 2), jnp.float32)
        fm = jnp.stack([s[0] if s is not None else zf for s in slots])
        cn = jnp.stack([s[1] if s is not None else zc for s in slots])
        fi = jnp.stack([s[2] if s is not None and s[2] is not None
                        else zl for s in slots])
        cpad = ((0, bb - b), (0, bh // 8 - lh), (0, bw // 8 - lw),
                (0, 0))
        fm = jnp.pad(fm, cpad)
        cn = jnp.pad(cn, cpad)
        fi = jnp.pad(fi, cpad)
        args = [jnp.asarray(i2), fm, cn, fi]
        flow_low, flow, fmap2, cnet2 = exe(variables, *args)
        return PendingBatch(flow, flow_low,
                            (b, h, w, top, left, hp, wp), bucket, h2d,
                            False, True, inputs=args, donated=True,
                            cache=(fmap2, cnet2))

    def infer_cached(self, image2, slots,
                     expect_version: Optional[int] = None):
        """Synchronous form: ``infer_cached_async(...).fetch()``."""
        return self.infer_cached_async(
            image2, slots, expect_version=expect_version).fetch()

    def infer(self, images: Sequence[np.ndarray], batch_size: int = 4,
              time_it: bool = False) -> List[np.ndarray]:
        """Sliding-window flow over a frame sequence (raft_trt.py:41-67):
        consecutive pairs, chunked into batches.

        The last chunk is usually ragged (n % batch_size pairs); bucket
        routing batch-fills it into the executable the full chunks
        already compiled — one executable serves the whole sequence in
        both bucketed and exact-shapes engines (pinned in
        tests/test_serving.py)."""
        flows: List[np.ndarray] = []
        n = len(images) - 1
        t0 = time.perf_counter()
        for i in range(0, n, batch_size):
            i1 = np.stack(images[i:min(i + batch_size, n)])
            i2 = np.stack(images[i + 1:min(i + batch_size, n) + 1])
            flows.extend(self.infer_batch(i1, i2))
        if time_it:
            dt = time.perf_counter() - t0
            print(f"{n} pairs in {dt:.3f}s ({n / max(dt, 1e-9):.2f} pairs/s)")
        return flows
