"""AOT-compiled inference engine — the ``RAFTInferTRT`` analog.

The reference builds a TensorRT engine over a dynamic-shape envelope
(min/opt/max, ``cvt2trt.sh``) and binds I/O by name at runtime
(raft_trt.py:12-39). XLA has no dynamic shapes: the envelope becomes a set
of discrete shape buckets, each AOT-compiled once
(``jax.jit(...).lower().compile()``), and ``infer_batch`` routes a request
to the smallest bucket that fits, padding up (batch and spatial). That is
the same trick TensorRT's optimization profiles play, made explicit.

Like the fork's single-output ONNX export (test_trt.py:131 names only
``flowup``), the engine's serving function returns only the upsampled flow;
iteration count is baked at 20 (test_trt.py:124, ITERS_EXPORT).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.config import ITERS_EXPORT, RAFTConfig
from raft_tpu.models import RAFT
from raft_tpu.ops.padding import pad_amounts

# cvt2trt.sh:1 envelope (min 1x3x256x256 / opt 2x3x800x800 / max 8x3x1024x1024)
SHAPE_ENVELOPE_LINUX: List[Tuple[int, int, int]] = [
    (1, 256, 256), (2, 800, 800), (8, 1024, 1024)]
# cvt2trt.bat:1 envelope (max 1x3x512x1024)
SHAPE_ENVELOPE_WINDOWS: List[Tuple[int, int, int]] = [
    (1, 256, 256), (1, 512, 800), (1, 512, 1024)]


class RAFTEngine:
    """Shape-bucketed AOT engine over converted weights."""

    def __init__(self, variables: Dict, config: RAFTConfig = RAFTConfig(),
                 iters: int = ITERS_EXPORT,
                 envelope: Sequence[Tuple[int, int, int]] = (),
                 precompile: bool = True, mesh=None,
                 exact_shapes: bool = False):
        """``mesh``: optional ``jax.sharding.Mesh`` (data × spatial axes,
        `parallel.mesh.make_mesh`) — buckets then compile as SPMD
        programs with batch sharded over 'data' and image height over
        'spatial' (weights replicated), the serving-side counterpart of
        the sharded train step for resolutions/batches beyond one chip
        (SURVEY.md §5 long-context). The TRT analog has nothing like
        this; DataParallel never served (train.py:138 is training-only).

        ``exact_shapes``: never route to a SPATIALLY larger bucket —
        compile (and cache) one executable per exact ÷8-padded request
        spatial shape instead. Costs a compile per distinct shape but
        removes the bucket-fill accuracy artifact entirely (the spatial
        fill shifts instance-norm statistics; see infer_batch) — the
        TRT-dynamic-shapes parity setting for accuracy-sensitive
        serving. Batch is still allowed to fill up to an
        already-compiled same-spatial bucket: batch fill is per-sample
        neutral, and without it every ragged sliding-window tail
        (``infer``'s last chunk) would compile its own executable.
        """
        self.config = config
        self.iters = iters
        self.mesh = mesh
        self.exact_shapes = exact_shapes
        if mesh is not None:
            from raft_tpu.parallel.mesh import (batch_sharding, replicated,
                                                validate_spatial_extent)

            self._in_shard = batch_sharding(mesh)
            self._rep = replicated(mesh)
            self._validate_extent = validate_spatial_extent
            self.variables = jax.device_put(variables, self._rep)
        else:
            self.variables = jax.device_put(variables)
        model = RAFT(config)

        def serve(variables, image1, image2):
            # single-output serving fn, the exported-``flowup`` analog.
            # Weights ride as an ARGUMENT, not a baked closure: the
            # compiled bucket (and its persistent-cache entry) is then
            # keyed by shapes only — swapping a checkpoint reuses every
            # executable instead of recompiling the envelope, and the
            # lowered program stays KB-sized rather than carrying ~21 MB
            # of weight constants per bucket upload. (The StableHLO
            # EXPORT still bakes weights — a single portable artifact is
            # the point there, as with the reference's ONNX file.)
            _, flow_up = model.apply(variables, image1, image2,
                                     iters=iters, test_mode=True)
            return flow_up

        self._fn = jax.jit(serve)
        self._compiled: Dict[Tuple[int, int, int], jax.stages.Compiled] = {}
        for shape in envelope:
            if precompile:
                self._get_executable(shape)
            else:
                self._compiled.setdefault(shape, None)

    def update_weights(self, variables: Dict) -> None:
        """Swap checkpoints without invalidating compiled buckets.

        Structure AND leaf shapes/dtypes must match the engine's current
        weights — the executables were compiled against those avals, so a
        same-structure checkpoint with different shapes (e.g. a basic
        checkpoint into a small-config engine, or bf16-cast weights)
        would brick every precompiled bucket with an opaque call-time
        error if it slipped through here."""
        old_def = jax.tree_util.tree_structure(self.variables)
        new_def = jax.tree_util.tree_structure(variables)
        if old_def != new_def:
            # container types matter: the executables were lowered against
            # the old treedef, and e.g. FrozenDict vs plain dict flattens
            # to identical key paths while still failing at call time
            raise ValueError(
                "checkpoint structure mismatch: pytree definition differs "
                f"(engine: {str(old_def)[:120]}... vs {str(new_def)[:120]}"
                "...)")

        def avals(tree):
            return {jax.tree_util.keystr(k): (jnp.shape(l),
                                              jnp.result_type(l))
                    for k, l in
                    jax.tree_util.tree_flatten_with_path(tree)[0]}

        old, new = avals(self.variables), avals(variables)
        if old != new:
            diff = [f"{k}: {new[k]} vs engine's {old[k]}"
                    for k in old.keys() & new.keys() if old[k] != new[k]]
            raise ValueError(
                "checkpoint structure mismatch: " + "; ".join(diff[:5]))
        self.variables = (jax.device_put(variables, self._rep)
                          if self.mesh is not None
                          else jax.device_put(variables))

    # -- shape routing ------------------------------------------------------

    def _mesh_grain(self) -> Tuple[int, int]:
        """(batch grain, height grain) a bucket must divide under a mesh.
        Single source for both the compile-time check and the
        compile-on-miss rounding — the two must agree or the router's own
        ad-hoc buckets would fail the engine's validation."""
        data = self.mesh.shape.get("data", 1)
        spatial = self.mesh.shape.get("spatial", 1)
        return data, 8 * spatial

    def _get_executable(self, shape: Tuple[int, int, int]):
        exe = self._compiled.get(shape)
        if exe is None:
            b, h, w = shape
            if self.mesh is not None:
                self._validate_extent(h, self.mesh)
                # compile-on-miss buckets are pre-rounded in infer_batch,
                # but user-supplied envelope buckets reach here unrounded;
                # an uneven bucket compiles fine and only fails later at
                # device_put with an opaque uneven-sharding ValueError
                bg, hg = self._mesh_grain()
                if b % bg or h % hg:
                    raise ValueError(
                        f"bucket {shape} is not mesh-divisible: batch must "
                        f"be a multiple of data={bg} and height a "
                        f"multiple of 8*spatial={hg}")
                spec = jax.ShapeDtypeStruct((b, h, w, 3), jnp.float32,
                                            sharding=self._in_shard)
            else:
                spec = jax.ShapeDtypeStruct((b, h, w, 3), jnp.float32)
            exe = self._fn.lower(self.variables, spec, spec).compile()
            self._compiled[shape] = exe
        return exe

    def _select_bucket(self, b: int, h: int, w: int
                       ) -> Optional[Tuple[int, int, int]]:
        if self.exact_shapes:
            # exact-shapes mode is exact SPATIALLY — spatial fill is
            # what shifts the encoders' instance-norm statistics (the
            # accuracy artifact the mode exists to remove). Batch fill
            # is per-sample neutral (instance norm reduces over H, W
            # only; eval-mode BatchNorm uses running averages — the
            # fill changes values only at conv-vectorization fp32 noise
            # scale, measured ~3e-5 px), so a
            # ragged sliding-window tail routes to an already-compiled
            # same-spatial bucket with fill + crop instead of compiling
            # one executable per distinct tail batch (pinned in
            # tests/test_serving.py: len(_compiled) stays 1 across a
            # ragged sequence).
            fits = [s for s in self._compiled
                    if s[0] >= b and s[1] == h and s[2] == w]
            return min(fits, key=lambda s: s[0]) if fits else None
        fits = [s for s in self._compiled
                if s[0] >= b and s[1] >= h and s[2] >= w]
        if not fits:
            return None
        return min(fits, key=lambda s: s[0] * s[1] * s[2])

    # -- inference ----------------------------------------------------------

    def infer_batch(self, image1, image2) -> np.ndarray:
        """(B,H,W,3) float [0,255] -> (B,H,W,2) flow. Routes to a bucket,
        padding up (raft_trt_utils.pad_images analog); falls back to an
        exact-shape jit specialization outside the envelope.

        Accuracy note: bucket fill beyond the ÷8 pad shifts the encoders'
        instance-norm statistics, which couple every output pixel to the
        fill content — measured a few px of pointwise movement with a
        metric-neutral (<1e-2 px EPE) aggregate at trained weights
        (tests/test_evaluation.py bucketing-delta test). TensorRT's
        dynamic shapes don't pay this; exact-shape compile (an envelope
        bucket per deployed shape) avoids it here."""
        image1 = np.asarray(image1, np.float32)
        image2 = np.asarray(image2, np.float32)
        b, h, w, _ = image1.shape
        left, right, top, bottom = pad_amounts(h, w)
        hp, wp = h + top + bottom, w + left + right

        bucket = self._select_bucket(b, hp, wp)
        if bucket is None:
            bb, bh = b, hp
            if self.mesh is not None:
                # batch rides the 'data' axis, height the 'spatial' axis —
                # round the ad-hoc bucket up so every device gets whole
                # examples and whole feature rows (the bucket's zero-fill
                # + output crop absorbs the padding either way)
                bg, hg = self._mesh_grain()
                bb = -(-b // bg) * bg
                bh = -(-hp // hg) * hg
            bucket = (bb, bh, wp)  # compile-on-miss, cached thereafter
        bb, bh, bw = bucket
        # edge-pad to stride alignment (InputPadder semantics), zero-fill the
        # rest of the bucket
        align = ((0, 0), (top, bottom), (left, right), (0, 0))
        fill = ((0, bb - b), (0, bh - hp), (0, bw - wp), (0, 0))
        i1 = np.pad(np.pad(image1, align, mode="edge"), fill)
        i2 = np.pad(np.pad(image2, align, mode="edge"), fill)
        exe = self._get_executable(bucket)  # validates extent under a mesh
        if self.mesh is not None:
            i1 = jax.device_put(i1, self._in_shard)
            i2 = jax.device_put(i2, self._in_shard)
        else:
            i1, i2 = jnp.asarray(i1), jnp.asarray(i2)
        flow = exe(self.variables, i1, i2)
        return np.asarray(flow[:b, top:top + h, left:left + w, :])

    def infer(self, images: Sequence[np.ndarray], batch_size: int = 4,
              time_it: bool = False) -> List[np.ndarray]:
        """Sliding-window flow over a frame sequence (raft_trt.py:41-67):
        consecutive pairs, chunked into batches.

        The last chunk is usually ragged (n % batch_size pairs); bucket
        routing batch-fills it into the executable the full chunks
        already compiled — one executable serves the whole sequence in
        both bucketed and exact-shapes engines (pinned in
        tests/test_serving.py)."""
        flows: List[np.ndarray] = []
        n = len(images) - 1
        t0 = time.perf_counter()
        for i in range(0, n, batch_size):
            i1 = np.stack(images[i:min(i + batch_size, n)])
            i2 = np.stack(images[i + 1:min(i + batch_size, n) + 1])
            flows.extend(self.infer_batch(i1, i2))
        if time_it:
            dt = time.perf_counter() - t0
            print(f"{n} pairs in {dt:.3f}s ({n / max(dt, 1e-9):.2f} pairs/s)")
        return flows
