"""Cross-frame device feature cache: per-stream encoder state.

RAFT's encoders are roughly half the serve FLOPs, and consecutive
video pairs share a frame — frame t's ``fmap2`` (plus a speculative
context encoding of frame t) ARE pair (t, t+1)'s ``fmap1``/context
inputs (models/raft.py ``forward_cached``). This module is the state
side of that reuse (the compiler-first O(1) autoregressive-cache
discipline of arXiv 2603.09555): a capacity-managed pool of per-stream
**slots**, each holding the stream's last frame's feature map, its
speculative context encoding, and the recurrence's ``flow_low`` — all
as DEVICE arrays, so warm-stream state never crosses the host boundary
between frames.

Validity is structural, not hopeful. A slot is keyed by stream id and
stamped with:

- the request geometry (``key`` = (H, W)) — a mid-stream resolution
  change can never feed old-geometry features to a new-geometry pair;
- a **sequence number** (the session's frame counter) — a pair at seq
  t only matches a slot at seq t-1, so ANY missed store (failed pair,
  queued-deadline expiry, wedge) turns into a clean submit-time miss
  instead of silently correlating against the wrong frame's features;
- the engine's **weights version** — features computed by one weight
  tree must never feed a refinement running another (the registry's
  promote/rollback flush is the broom; this stamp is the backstop the
  flush drill pins).

Any mismatch drops the slot and reads as a miss: the stream
cold-restarts (re-primes) — the pool never serves stale state.

Eviction is LRU at ``capacity``: ``store`` always lands (stream
continuity first), then evicts least-recently-used slots down to the
bound — thousands of concurrent sessions degrade to cache churn
(visible in ``hit_rate``), never to unbounded device memory. Arrays
evicted while a dispatch still references them stay alive until that
dispatch completes (JAX refcounting); the pool holds plain owning
references and never donates its slots — the DONATED buffers are the
per-dispatch assembled batches the engine builds (serving/engine.py).

Deliberately jax-free: slots store opaque array handles.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Hashable, Optional, Tuple

#: graftthread T3: the pool lock is a LEAF. The scheduler takes it from
#: submit (validity probe, before its queue lock), dispatch assembly
#: and completion store (neither holds a scheduler lock), and the
#: metrics snapshot reads it with NO metrics lock held (the provider
#: runs before the snapshot's own lock — metrics.ServingMetrics).
#: Nothing may call back into scheduler/registry/metrics from under it.
LOCK_ORDER = (("feature_cache.FeatureCachePool._lock",),)

#: graftthread declarations: one lock, no callbacks, no threads, no
#: futures — every method is dict bookkeeping under ``_lock``.
GRAFTTHREAD = {"locks": ("_lock",)}


class FeatureCacheMiss(RuntimeError):
    """A cached submit found no valid slot for its stream (never
    primed, LRU-evicted, flushed by a weight swap, seq hole from a
    failed pair, or a geometry change): cold-restart the stream —
    re-prime its previous frame, then resubmit the pair. The
    ``VideoSession(feature_cache=True)`` state machine does exactly
    that; the error is the signal, not a failure of the request's
    frame data."""


class _Slot:
    """One stream's cached state. Arrays are device handles at the
    stream's 1/8-res ÷8-padded geometry; ``flow_low`` is None when the
    recurrence is cold (the slot came from a PRIME dispatch, whose
    flow output is meaningless)."""

    __slots__ = ("key", "seq", "version", "fmap", "ctx", "flow_low")

    def __init__(self, key: Tuple[int, int], seq: int, version: int,
                 fmap, ctx, flow_low):
        self.key = key
        self.seq = seq
        self.version = version
        self.fmap = fmap
        self.ctx = ctx
        self.flow_low = flow_low


class FeatureCachePool:
    """Capacity-bounded LRU pool of per-stream feature slots.

    Thread-safe; every operation is O(1) dict work under one lock (no
    device calls, no I/O — the T1 discipline). Counters cover the
    operator questions: ``hits``/``misses`` (and the derived
    ``hit_rate``) say whether streams are actually warm, ``stale``
    splits out validity kills (seq hole / geometry / weights version),
    ``evictions`` says the capacity is too small for the live stream
    population, ``flushes`` counts invalidation brooms (weight swaps,
    rollouts, close).
    """

    def __init__(self, capacity: int = 256):
        if capacity < 1:
            raise ValueError(f"capacity={capacity}: need >= 1")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._slots: "OrderedDict[Hashable, _Slot]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.stale = 0
        self.evictions = 0
        self.flushes = 0
        self.stores = 0

    # -- read side ---------------------------------------------------------

    def valid(self, stream: Hashable, key: Tuple[int, int],
              seq: int) -> bool:
        """Would ``acquire`` succeed right now (version aside)? The
        submit-time probe behind the fail-fast ``FeatureCacheMiss`` —
        counts nothing (the dispatch-time ``acquire`` owns the
        hit/miss accounting; ``record_miss`` covers the raise)."""
        with self._lock:
            slot = self._slots.get(stream)
            return (slot is not None and slot.key == tuple(key)
                    and slot.seq == seq)

    def record_miss(self, stale: bool = False) -> None:
        """Count a submit-time miss (the ``valid`` probe failed and
        the submit raised)."""
        with self._lock:
            self.misses += 1
            if stale:
                self.stale += 1

    def acquire(self, stream: Hashable, key: Tuple[int, int], seq: int,
                version: int) -> Optional[_Slot]:
        """The dispatch-time read: the stream's slot if it matches
        ``key``/``seq``/``version``, else None. A mismatched slot is
        DROPPED (it can never become valid again — seq only moves
        forward, geometry changes restart streams, old-version
        features are poison) and counted stale."""
        with self._lock:
            slot = self._slots.get(stream)
            if slot is None:
                self.misses += 1
                return None
            if (slot.key != tuple(key) or slot.seq != seq
                    or slot.version != version):
                del self._slots[stream]
                self.misses += 1
                self.stale += 1
                return None
            self._slots.move_to_end(stream)
            self.hits += 1
            return slot

    # -- write side --------------------------------------------------------

    def store(self, stream: Hashable, key: Tuple[int, int], seq: int,
              version: int, fmap, ctx, flow_low) -> None:
        """Install/replace the stream's slot, then evict LRU slots
        down to ``capacity``. Store-first keeps the JUST-SERVED stream
        warm even under capacity pressure (evicting the newcomer would
        livelock every over-capacity stream into a re-prime loop);
        the transient overshoot is one slot, immediately corrected."""
        with self._lock:
            self._slots[stream] = _Slot(tuple(key), seq, version, fmap,
                                        ctx, flow_low)
            self._slots.move_to_end(stream)
            self.stores += 1
            while len(self._slots) > self.capacity:
                self._slots.popitem(last=False)
                self.evictions += 1

    def invalidate(self, stream: Hashable) -> bool:
        """Drop one stream's slot (session teardown hygiene). True if
        a slot was present."""
        with self._lock:
            return self._slots.pop(stream, None) is not None

    def flush(self) -> int:
        """Drop EVERY slot (weight swap, promote/rollback, close) —
        features from the old weight tree must never feed the new one.
        Returns how many slots were dropped. The caller owns the
        ``cache_flush`` metrics event (it knows the model/version to
        stamp)."""
        with self._lock:
            n = len(self._slots)
            self._slots.clear()
            self.flushes += 1
            return n

    # -- observability -----------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._slots)

    def snapshot(self) -> Dict:
        """The metrics.jsonl ``feature_cache`` block: counters plus
        the occupancy gauge."""
        with self._lock:
            looked = self.hits + self.misses
            return {
                "capacity": self.capacity,
                "occupancy": len(self._slots),
                "hits": self.hits,
                "misses": self.misses,
                "stale": self.stale,
                "evictions": self.evictions,
                "flushes": self.flushes,
                "stores": self.stores,
                "hit_rate": (round(self.hits / looked, 4) if looked
                             else 0.0),
            }
