"""SLO guardian: automated canary judgment + registry-wide admission.

The registry (serving/registry.py) can roll a model out and back, but
every rollout decision is a human call and every overload decision is
per-variant: a bad canary keeps serving its hash fraction until an
operator notices, and one model's batch flood can exhaust the
aggregate queue capacity another model's interactive traffic needs.
Production flow-serving front-ends (the TensorRT path the reference
targets, Clipper-style adaptive model selection — PAPERS.md) treat
automated rollback and admission control as the baseline for
unattended operation. This module closes those two loops, jax-free:

:class:`SLOGuardian`
    A control loop over the per-variant metrics the registry already
    emits. When a model grows a canary, the guardian opens a **bake
    window**: it freezes a baseline snapshot of the live and canary
    variants and, on every tick, compares the two *windows* (deltas of
    the cumulative counters and latency-histogram counts — not
    lifetime aggregates, which would dilute a fresh regression under
    an old variant's history). A canary that breaches the
    :class:`GuardianPolicy` SLOs — p99 latency beyond the live
    variant's with margin, error rate beyond live's with margin, any
    wedge verdict or breaker trip beyond the allowance — is
    auto-``rollback()``ed the moment the breach is statistically
    admissible (``min_requests``); a canary that bakes clean through
    the window is auto-``promote()``d. Both land through the
    registry's consequences-before-futures discipline: routing off
    first, drains settle every accepted future, and the decision event
    (``guardian_promote`` / ``guardian_rollback``) carries the
    deciding evidence windows into metrics.jsonl. The clock and the
    metrics reader are injectable, so bake drills run deterministically
    with a synthetic clock and synthetic snapshots; the
    ``guardian.decide`` fault site (testing/faults) arms the chaos
    question — a guardian that raises or hangs mid-decision must leave
    routing exactly as it found it (the site fires *before* the
    registry mutates anything).

:class:`AdmissionBudget`
    A shared token bucket across every model in a registry
    (``ModelRegistry(admission_budget=N)``), gating ``submit()``
    *before* the per-variant queues. Each admitted request holds one
    token until its future settles; with no token free the submit
    fails fast with the scheduler's ``BackpressureError`` (counted per
    model as ``admission_rejected``). Priority-aware draw: the last
    ``interactive_reserve`` tokens are interactive-only — a batch
    flood on one model can saturate its own queue but can never take
    the whole registry's headroom, so another model's interactive
    traffic still admits. Defaults OFF: with no budget configured the
    registry's submit path is bitwise the PR-9 stack.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from raft_tpu.serving.metrics import LatencyHistogram, ServingMetrics
from raft_tpu.serving.scheduler import PRIORITY_BATCH
from raft_tpu.testing.faults import fault_point

#: graftthread T3: a tick serializes under ``_tick_lock`` and, inside
#: it, reads the registry (snapshot) and executes verdicts (promote/
#: rollback take the registry lock) plus the bake/decision state under
#: the guardian's own lock — ``_tick_lock`` is strictly outermost.
#: The admission budget's lock is a leaf.
LOCK_ORDER = (
    ("guardian.SLOGuardian._tick_lock", "guardian.SLOGuardian._lock"),
    ("guardian.SLOGuardian._tick_lock",
     "registry.ModelRegistry._lock"),
    ("guardian.AdmissionBudget._lock",),
)

#: ``_decided`` is a Condition OVER ``_lock`` (same underlying lock,
#: not a second one): declare it lockish and alias it so the graph
#: sees one node and the T1 same-receiver wait exemption applies.
GRAFTTHREAD = {"locks": ("_decided",), "aliases": {"_decided": "_lock"}}


class GuardianPolicy:
    """The SLO contract a canary must hold through its bake window.

    ``bake_window_s``
        Minimum bake time before a clean canary promotes.
    ``max_bake_s``
        Hard ceiling on the bake (default ``4 * bake_window_s``): a
        canary that still hasn't seen ``min_requests`` by then rolls
        back as ``insufficient_traffic`` — an unjudgeable canary must
        not serve a hash fraction forever.
    ``min_requests``
        Requests (completed + failed) the canary window needs before
        any verdict; breaches are judged as soon as it is met, clean
        promotion additionally waits out ``bake_window_s``. The
        relative SLOs (p99 ratio, err-rate margin) additionally need
        the LIVE window to hold this many requests — an empty
        baseline judges nothing (its p99/err_rate read 0 and the
        bounds would collapse to the bare margins).
    ``p99_ratio`` / ``p99_slack_ms``
        Latency SLO relative to live: breach when canary window p99 >
        live window p99 * ratio + slack (the slack absorbs histogram
        quantization at fast-SLO scales).
    ``p99_ceiling_ms``
        Optional absolute canary p99 bound (None = off) — the
        ``--slo p99_ms:...`` knob for deployments with a hard latency
        contract independent of live's current behavior.
    ``err_rate_margin``
        Breach when canary window error rate > live window error rate
        + margin (failed / (completed + failed)).
    ``max_wedged`` / ``max_breaker_opens``
        Allowance for wedge verdicts and breaker ``open`` transitions
        in the canary window (default 0: any wedge or trip is a
        breach — those are the scheduler's own SLO alarms).
    """

    __slots__ = ("bake_window_s", "max_bake_s", "min_requests",
                 "p99_ratio", "p99_slack_ms", "p99_ceiling_ms",
                 "err_rate_margin", "max_wedged", "max_breaker_opens")

    def __init__(self, bake_window_s: float = 30.0,
                 max_bake_s: Optional[float] = None,
                 min_requests: int = 20, p99_ratio: float = 1.5,
                 p99_slack_ms: float = 50.0,
                 p99_ceiling_ms: Optional[float] = None,
                 err_rate_margin: float = 0.02, max_wedged: int = 0,
                 max_breaker_opens: int = 0):
        if bake_window_s <= 0:
            raise ValueError(f"bake_window_s={bake_window_s}: must be > 0")
        if min_requests < 1:
            raise ValueError(f"min_requests={min_requests}: must be >= 1")
        if p99_ratio <= 0:
            raise ValueError(f"p99_ratio={p99_ratio}: must be > 0")
        if not 0.0 <= err_rate_margin <= 1.0:
            raise ValueError(f"err_rate_margin={err_rate_margin}: "
                             "must be in [0, 1]")
        self.bake_window_s = float(bake_window_s)
        self.max_bake_s = (float(max_bake_s) if max_bake_s is not None
                           else 4.0 * self.bake_window_s)
        if self.max_bake_s < self.bake_window_s:
            raise ValueError(
                f"max_bake_s={self.max_bake_s} below bake_window_s="
                f"{self.bake_window_s}: the bake could never finish")
        self.min_requests = int(min_requests)
        self.p99_ratio = float(p99_ratio)
        self.p99_slack_ms = float(p99_slack_ms)
        self.p99_ceiling_ms = (float(p99_ceiling_ms)
                               if p99_ceiling_ms is not None else None)
        self.err_rate_margin = float(err_rate_margin)
        self.max_wedged = int(max_wedged)
        self.max_breaker_opens = int(max_breaker_opens)


def window_stats(cur: Dict, base: Dict) -> Dict:
    """One variant's bake-window view: the delta of two cumulative
    variant snapshots (serving/metrics.py schema). Counters subtract;
    the latency histogram subtracts COUNTS bucket-by-bucket, so the
    window p99 is the window's, not the variant lifetime's. With
    request tracing armed the variant snapshot carries
    ``tail_exemplars`` — the window view keeps the exemplar refs NEW
    since the baseline, so a guardian decision's evidence names the
    exact trace ids behind the p99 it judged (walk them with
    ``raft_tpu.cli.serve_trace``)."""
    completed = cur["completed"] - base["completed"]
    failed = cur["failed"] - base["failed"]
    requests = completed + failed
    h = LatencyHistogram()
    h.counts = [c - b for c, b in zip(cur["latency"]["counts"],
                                      base["latency"]["counts"])]
    h.count = sum(h.counts)
    h.max = cur["latency"]["max_ms"]   # lifetime max: pessimistic tail
    cur_r, base_r = cur["resilience"], base["resilience"]
    out = {
        "requests": requests,
        "completed": completed,
        "failed": failed,
        "err_rate": round(failed / requests, 4) if requests else 0.0,
        "p99_ms": h.quantile(0.99),
        "wedged": cur_r["wedged"] - base_r["wedged"],
        "breaker_opens": (cur_r["breaker_transitions"]["open"]
                          - base_r["breaker_transitions"]["open"]),
    }
    refs = (cur.get("tail_exemplars") or {}).get("refs")
    if refs:
        seen = {e["trace_id"]
                for e in (base.get("tail_exemplars")
                          or {}).get("refs", [])}
        out["exemplars"] = [dict(e) for e in refs
                            if e["trace_id"] not in seen][-8:]
    reps = cur.get("replicas")
    if reps:
        # replica-fleet variant: carry each lane's OWN window (counts
        # subtracted against the baseline's same-lane block; a lane
        # activated mid-bake subtracts zeros) AND the fleet-merged
        # histogram p99. The merge is the honest aggregate; the
        # per-lane windows are what _breaches judges so one sick
        # replica cannot hide inside N-1 healthy ones.
        base_reps = base.get("replicas") or {}
        per, merged = {}, LatencyHistogram()
        for k in sorted(reps):
            cur_r, base_r = reps[k], base_reps.get(k)
            base_counts = (base_r["latency"]["counts"]
                           if base_r is not None
                           else [0] * len(cur_r["latency"]["counts"]))
            rh = LatencyHistogram()
            rh.counts = [c - b for c, b in
                         zip(cur_r["latency"]["counts"], base_counts)]
            rh.count = sum(rh.counts)
            rh.max = cur_r["latency"]["max_ms"]
            merged.merge(rh)
            done = (cur_r["completed"]
                    - (base_r["completed"] if base_r is not None else 0))
            per[str(k)] = {"requests": done, "completed": done,
                           "p99_ms": rh.quantile(0.99)}
        out["replicas"] = per
        out["p99_merged_ms"] = merged.quantile(0.99)
    return out


class _Bake:
    """One canary's bake in progress: start time + frozen baselines."""

    __slots__ = ("version", "t0", "live0", "canary0")

    def __init__(self, version: str, t0: float, live0: Dict,
                 canary0: Dict):
        self.version = version
        self.t0 = t0
        self.live0 = live0
        self.canary0 = canary0


class SLOGuardian:
    """Autonomous canary judgment over a :class:`ModelRegistry`.

    ``registry`` needs the registry surface only (``snapshot()``,
    ``promote()``, ``rollback()``, ``metrics_path``) — drills run it
    against fakes. ``reader`` overrides the metrics source (defaults
    to ``registry.snapshot``; must return the registry-snapshot
    shape); ``clock`` overrides time (``time.monotonic``). Both are
    the determinism knobs the bake drills inject.

    Drive it either way:

    - ``start()`` / ``stop()``: a daemon thread calls :meth:`tick`
      every ``poll_s`` — the unattended mode. A tick that raises is
      recorded (``guardian_error``) and the loop survives; a tick that
      hangs (the ``guardian.decide`` chaos site) leaves routing
      untouched — the site fires before any registry mutation — and
      ``stop()`` times out rather than blocking shutdown.
    - :meth:`tick` directly: deterministic drills advance the injected
      clock and tick by hand.

    Every decision is appended to :attr:`decisions` and emitted as a
    ``guardian_promote`` / ``guardian_rollback`` event carrying the
    deciding evidence (both windows + thresholds) into the registry's
    metrics.jsonl.
    """

    def __init__(self, registry, policy: Optional[GuardianPolicy] = None,
                 *, poll_s: float = 0.5,
                 clock: Callable[[], float] = time.monotonic,
                 reader: Optional[Callable[[], Dict]] = None,
                 metrics: Optional[ServingMetrics] = None):
        self.registry = registry
        self.policy = policy or GuardianPolicy()
        self.poll_s = float(poll_s)
        self._clock = clock
        self._reader = reader if reader is not None else registry.snapshot
        self._metrics = metrics or ServingMetrics(
            getattr(registry, "metrics_path", None), namespace="guardian")
        #: _lock guards bake/decision state; _tick_lock serializes
        #: whole ticks (a manual tick racing the loop must not judge
        #: the same window twice)
        self._lock = threading.Lock()
        self._decided = threading.Condition(self._lock)
        self._tick_lock = threading.Lock()
        self._bakes: Dict[str, _Bake] = {}
        self.decisions: List[Dict] = []
        self.errors = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- judgment ----------------------------------------------------------

    def _breaches(self, live_w: Dict, can_w: Dict) -> List[str]:
        """Which SLOs the canary window breaches vs the live window.
        The RELATIVE checks (vs live's window) only judge when the
        live window itself holds ``min_requests`` — against an empty
        or near-empty baseline, live's p99/err_rate read as 0 and the
        bounds would collapse to the bare margins, rolling back a
        healthy canary (think canary_fraction 0.9, or a live-traffic
        lull). The absolute checks (ceiling, wedges, breaker trips)
        need no baseline and always judge."""
        p = self.policy
        out = []
        live_judgeable = live_w["requests"] >= p.min_requests
        if (live_judgeable and can_w["err_rate"]
                > live_w["err_rate"] + p.err_rate_margin):
            out.append(f"err_rate {can_w['err_rate']} > live "
                       f"{live_w['err_rate']} + {p.err_rate_margin}")
        bound = live_w["p99_ms"] * p.p99_ratio + p.p99_slack_ms
        if live_judgeable and can_w["p99_ms"] > bound:
            out.append(f"p99_ms {can_w['p99_ms']} > live "
                       f"{live_w['p99_ms']} * {p.p99_ratio} + "
                       f"{p.p99_slack_ms}")
        if (p.p99_ceiling_ms is not None
                and can_w["p99_ms"] > p.p99_ceiling_ms):
            out.append(f"p99_ms {can_w['p99_ms']} > ceiling "
                       f"{p.p99_ceiling_ms}")
        if can_w["wedged"] > p.max_wedged:
            out.append(f"wedged {can_w['wedged']} > {p.max_wedged}")
        if can_w["breaker_opens"] > p.max_breaker_opens:
            out.append(f"breaker_opens {can_w['breaker_opens']} > "
                       f"{p.max_breaker_opens}")
        # replica-fleet canary: judge each lane's OWN window against
        # the SAME live-derived bound. The merged p99 already feeds
        # can_w["p99_ms"]-style aggregates, but a breach confined to
        # one replica of N dilutes 1/N in the merge — per-lane
        # judgment is the anti-dilution guarantee (one sick replica
        # with enough traffic rolls the canary back, however healthy
        # its siblings look).
        for rk, rw in sorted((can_w.get("replicas") or {}).items()):
            if rw["requests"] < p.min_requests:
                continue
            if live_judgeable and rw["p99_ms"] > bound:
                out.append(
                    f"canary_replica_p99 r{rk} {rw['p99_ms']} > live "
                    f"{live_w['p99_ms']} * {p.p99_ratio} + "
                    f"{p.p99_slack_ms}")
            if (p.p99_ceiling_ms is not None
                    and rw["p99_ms"] > p.p99_ceiling_ms):
                out.append(f"canary_replica_p99 r{rk} {rw['p99_ms']} "
                           f"> ceiling {p.p99_ceiling_ms}")
        return out

    @staticmethod
    def _canary_version(canary_blk: Dict) -> str:
        # the canary snapshot is namespaced "model@version"
        ns = str(canary_blk.get("model", ""))
        return ns.rpartition("@")[2] or "?"

    def tick(self) -> List[Dict]:
        """One guardian pass over every model; returns the decisions
        it executed (possibly empty). Safe to call concurrently with
        the polling loop (whole ticks are serialized)."""
        with self._tick_lock:
            return self._tick_locked()

    def _tick_locked(self) -> List[Dict]:
        snap = self._reader()
        now = self._clock()
        decisions: List[Dict] = []
        for name in sorted(snap):
            blk = snap[name]
            canary = blk.get("canary")
            if canary is None:
                # no rollout (or the operator resolved it themselves):
                # any bake we were tracking is over
                with self._lock:
                    self._bakes.pop(name, None)
                continue
            version = self._canary_version(canary)
            with self._lock:
                bake = self._bakes.get(name)
                if bake is None or bake.version != version:
                    bake = _Bake(version, now, blk["live"], canary)
                    self._bakes[name] = bake
                    new_bake = True
                else:
                    new_bake = False
            if new_bake:
                self._metrics.record_event(
                    "guardian_bake_start", model=name, version=version,
                    bake_window_s=self.policy.bake_window_s)
                continue
            window_s = now - bake.t0
            live_w = window_stats(blk["live"], bake.live0)
            can_w = window_stats(canary, bake.canary0)
            evidence = {"window_s": round(window_s, 3), "live": live_w,
                        "canary": can_w}
            breaches = (self._breaches(live_w, can_w)
                        if can_w["requests"] >= self.policy.min_requests
                        else [])
            if breaches:
                decisions.append(self._decide(
                    name, version, "rollback",
                    "; ".join(breaches), evidence))
            elif (window_s >= self.policy.bake_window_s
                    and can_w["requests"] >= self.policy.min_requests):
                decisions.append(self._decide(
                    name, version, "promote", "clean bake", evidence))
            elif window_s >= self.policy.max_bake_s:
                decisions.append(self._decide(
                    name, version, "rollback",
                    f"insufficient_traffic ({can_w['requests']} < "
                    f"{self.policy.min_requests} requests in "
                    f"{round(window_s, 1)}s)", evidence))
            # else: still baking — hold, judge again next tick
        return decisions

    def _decide(self, model: str, version: str, action: str,
                reason: str, evidence: Dict) -> Dict:
        """Execute one verdict through the registry. The chaos site
        fires FIRST: a guardian that raises or hangs here has mutated
        nothing — canary routing, drains and futures are exactly as
        the registry left them (never half-rolled)."""
        fault_point("guardian.decide")
        decision = {"model": model, "version": version,
                    "action": action, "reason": reason,
                    "evidence": evidence}
        try:
            if action == "promote":
                out = self.registry.promote(model)
                decision["mode"] = out.get("mode")
            else:
                self.registry.rollback(model)
        except Exception as exc:
            # raced an operator's own promote/rollback/close: the
            # registry refused — record, drop the bake, move on. The
            # failed decision still lands in self.decisions (and wakes
            # wait_decision): the rollout IS resolved, and a waiter
            # sleeping out its full timeout to report "undecided"
            # would be strictly less true
            decision["action"] = "failed"
            decision["intended"] = action
            decision["error"] = f"{type(exc).__name__}: {exc}"
            with self._decided:
                self._bakes.pop(model, None)
                self.decisions.append(decision)
                self._decided.notify_all()
            self._metrics.record_event(
                "guardian_decision_failed", model=model, version=version,
                intended=action, error=decision["error"])
            return decision
        with self._decided:
            self._bakes.pop(model, None)
            self.decisions.append(decision)
            self._decided.notify_all()
        self._metrics.record_event(
            f"guardian_{action}", model=model, version=version,
            reason=reason, evidence=evidence)
        return decision

    def wait_decision(self, model: Optional[str] = None,
                      timeout: float = 30.0) -> Optional[Dict]:
        """Block until the guardian resolves a verdict (for ``model``
        if given) — an executed promote/rollback, or a ``failed`` one
        the registry refused (the rollout was resolved either way);
        returns it, or None on timeout — the caller's wedged-guardian
        escape hatch."""
        deadline = time.monotonic() + timeout
        with self._decided:
            while True:
                for d in reversed(self.decisions):
                    if model is None or d["model"] == model:
                        return d
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._decided.wait(remaining)

    # -- the unattended loop -----------------------------------------------

    def start(self) -> "SLOGuardian":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="SLOGuardian", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.tick()
            except Exception as exc:  # a failed tick must not kill the
                self.errors += 1      # loop — record and keep watching
                self._metrics.record_event(
                    "guardian_error",
                    error=f"{type(exc).__name__}: {exc}")

    def stop(self, timeout: float = 10.0) -> bool:
        """Stop the loop; returns False when the thread failed to exit
        (a tick wedged mid-hang — daemon, it leaks accountably like a
        quarantined dispatch thread; routing is untouched because the
        fault site precedes every registry mutation)."""
        self._stop.set()
        t = self._thread
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()

    def __enter__(self) -> "SLOGuardian":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()


class AdmissionBudget:
    """Registry-wide overload control: a token bucket shared by every
    model, gating ``submit()`` before the per-variant queues.

    ``capacity`` tokens bound the admitted-but-unsettled requests
    across ALL models; a request holds its token from admission until
    its future settles (the registry releases on the future's done
    callback). With no token available the submit fails fast with
    ``BackpressureError`` — the same shed contract as a full queue,
    one layer up.

    Priority-aware draw (“interactive draws before batch”): the last
    ``interactive_reserve`` tokens are off-limits to batch-class
    requests. A batch flood can therefore hold at most ``capacity -
    interactive_reserve`` tokens however many models it spreads over,
    and interactive (or priority-less — default traffic is a user
    waiting) work always finds headroom. Reserve defaults to a quarter
    of capacity (min 1).
    """

    def __init__(self, capacity: int,
                 interactive_reserve: Optional[int] = None):
        capacity = int(capacity)
        if capacity < 1:
            raise ValueError(f"capacity={capacity}: must be >= 1")
        if interactive_reserve is None:
            interactive_reserve = max(1, capacity // 4)
        interactive_reserve = int(interactive_reserve)
        if not 0 <= interactive_reserve <= capacity:
            raise ValueError(
                f"interactive_reserve={interactive_reserve}: must be "
                f"in [0, capacity={capacity}]")
        self.capacity = capacity
        self.interactive_reserve = interactive_reserve
        self._lock = threading.Lock()
        self.in_use = 0
        self.admitted = {"interactive": 0, "batch": 0}
        self.rejected = {"interactive": 0, "batch": 0}

    def try_acquire(self, priority: Optional[str] = None) -> bool:
        """Take one token; False = budget exhausted for this class
        (batch-class requests additionally respect the interactive
        reserve). Never blocks — admission control sheds, it does not
        queue."""
        cls = ("batch" if priority == PRIORITY_BATCH else "interactive")
        floor = (self.interactive_reserve if cls == "batch" else 0)
        with self._lock:
            if self.capacity - self.in_use <= floor:
                self.rejected[cls] += 1
                return False
            self.in_use += 1
            self.admitted[cls] += 1
            return True

    def release(self) -> None:
        with self._lock:
            if self.in_use > 0:
                self.in_use -= 1

    def snapshot(self) -> Dict:
        with self._lock:
            return {"capacity": self.capacity,
                    "interactive_reserve": self.interactive_reserve,
                    "in_use": self.in_use,
                    "admitted": dict(self.admitted),
                    "rejected": dict(self.rejected)}
