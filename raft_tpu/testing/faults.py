"""Deterministic fault injection for crash-safety drills.

The robustness layer (watchdog, atomic checkpoints, supervisor, loader
policies) is only trustworthy if its failure paths actually run — in CI
and in scheduled drills, not just in outages. This module arms *named*
fault sites in production code paths from a deterministic JSON plan::

    RAFT_FAULT_PLAN='[{"site": "ckpt.msgpack_write", "at": 2,
                       "kind": "crash"}]' python -m raft_tpu.cli.train ...

Plan entries (a list of dicts, or ``{"faults": [...]}``):

``site``
    Named injection point. In-repo sites: ``loader.sample`` (per-sample
    decode in PrefetchLoader workers), ``trainer.step`` (top of the
    training loop, once per step), ``ckpt.msgpack_write`` (weights-only
    msgpack writes — ``kind="crash"`` dies in the window between the
    fsync'd tmp file and the rename, ``kind="corrupt"`` smashes the
    completed file on disk, i.e. post-save bit rot the load-time
    manifest check must catch), ``ckpt.orbax_save`` (full-state saves —
    ``kind="corrupt"`` smashes a file of the just-written step),
    ``serve.request`` (per micro-batch dispatch in the serving
    scheduler's worker, serving/scheduler.py — ``kind="raise"`` fails
    just that batch's futures and the worker survives, ``kind="hang"``
    models a half-up device stalling dispatch until the bounded queue
    sheds and queued deadlines expire), ``serve.dispatch_exec`` (top of
    the supervised dispatch executor's job loop,
    serving/resilience.py — a hang here wedges the executor worker
    itself and drills the watchdog's quarantine-and-replace path),
    ``engine.compile`` (immediately before a real XLA bucket compile in
    ``RAFTEngine._get_executable`` — cache hits never fire it;
    ``raise`` models an uncompilable shape, ``hang`` a compile that
    never returns), ``serve.fetch`` (top of ``PendingBatch.fetch``,
    serving/engine.py — the blocking D2H read; a hang models a device
    whose compute or transfer never completes, which at
    ``pipeline_depth`` > 1 is the COMPLETION stage the scheduler's
    watchdog must verdict across in-flight batches),
    ``registry.load`` (start of a model-variant build in
    ``ModelRegistry`` — ``add_model`` and canary ``deploy``,
    serving/registry.py; ``raise`` models a bad checkpoint artifact or
    an uncompilable arch, and the registry's contract under it is
    auto-rollback: the failed canary is discarded, ``DeployError``
    surfaces to the deployer, and the live model's traffic never
    touches the partial variant), ``guardian.decide`` (the SLO
    guardian's verdict execution point, serving/guardian.py — fires
    AFTER judgment but BEFORE the registry promote/rollback call, so
    a ``raise`` aborts the decision with routing untouched (the loop
    survives and re-judges next tick) and a ``hang`` wedges the
    guardian thread with the canary still fully routed — the drilled
    contract is that a wedged guardian strands no futures and never
    leaves a half-rolled canary), ``aot.load`` (the serialized-
    executable cache's verified load path, serving/aot.py —
    ``kind="corrupt"`` smashes a file of the cache entry on disk
    BEFORE the read (cached-artifact bit rot) and ``kind="raise"``
    fails inside the verification scope; the drilled contract for BOTH
    is a clean MISS-and-recompile — the engine never crashes, never
    strands a future, and no corrupted artifact can serve traffic),
    ``scheduler.swap`` (per-replica weight application inside the
    fleet's quiesced swap epoch,
    ``MicroBatchScheduler.swap_weights`` — fires before EACH lane's
    ``update_weights``, so ``at=k`` models lane k-1 failing mid-fleet
    and the drilled contract is all-or-nothing: the already-swapped
    lanes roll back to the old tree and the error surfaces — a fleet
    is never left half-rolled), ``transport.send`` (inside
    ``Transport.call`` before a request leaves for a remote host,
    serving/transport.py — ``raise``/``hang``/``crash`` model a dead
    or half-up network path, ``kind="corrupt"`` zero-fills the encoded
    request IN TRANSIT via :func:`fault_data`, and the drilled
    contract is a clean ``TransportError`` the caller retries: an
    artifact push re-sends after sha256 verification fails, an infer
    dispatch fails over — corruption never reaches a settle),
    ``transport.recv`` (the reply side of the same seam —
    ``kind="corrupt"`` smashes the reply bytes; same retry contract),
    ``host.heartbeat`` (top of one heartbeat probe in
    ``HostFleet.beat``, serving/hosts.py — ``raise`` models a lost
    beat, ``hang`` a network path that stalls the prober; enough
    consecutive misses walk the host healthy → suspect → dead and the
    dead verdict quarantines its lanes + fails over its in-flight
    batches).
``at``
    1-based occurrence at which the entry becomes eligible (default 1).
    With the defaults below, each entry fires exactly once — the
    original one-shot semantics.
``count``
    Maximum number of fires (default 1; ``0`` = unlimited). With
    ``at``, this scopes an entry to "occurrences N through N+count-1"
    — the nth-call scoping chaos plans randomize.
``p``
    Per-eligible-call fire probability in ``(0, 1]`` (default 1.0).
    Draws come from a plan-scoped ``random.Random`` seeded by the
    plan's top-level ``"seed"`` key (default 0), so a chaos plan is
    bit-reproducible: same plan, same call sequence, same fires.
``kind``
    ``"raise"`` (FaultInjected), ``"hang"`` (sleep ``hang_s``, default
    effectively forever — what a half-up backend looks like),
    ``"crash"`` (``os._exit(CRASH_EXIT_CODE)``: no atexit, no finally —
    simulated power loss / preemption), ``"corrupt"`` (byte corruption
    at sites that write data).
``attempt``
    Optional supervisor attempt index (0-based) this entry arms in,
    matched against $RAFT_SUPERVISOR_ATTEMPT (set by
    ``training.supervisor`` for each child). Entries without it arm in
    every attempt. This is how a drill wedges the first run and lets the
    restarted run recover clean.

Disarmed cost is one module-global ``is None`` check per call — the
plan machinery never touches the hot path unless armed.
"""

from __future__ import annotations

import difflib
import json
import os
import random
import threading
import time
from typing import List, Optional

#: simulated abrupt process death (power-loss / preemption stand-in);
#: distinct from WEDGED_EXIT_CODE so runbooks and the supervisor can
#: tell a drill's injected crash from a real wedge
CRASH_EXIT_CODE = 41

#: the canonical fault-site registry: every dotted ``area.point`` site
#: armed anywhere in raft_tpu/, one line each (the module docstring
#: carries the long-form contracts). Plans validate against this table
#: at parse time — a typo'd site used to arm nothing and silently
#: shrink the drill — and the graftwire W7 tier cross-references it
#: against the chaos plans so every registered site is provably armed
#: AND drawn. Add the row in the same commit that adds the
#: ``fault_point``/``fault_file``/``fault_data`` call.
KNOWN_SITES = {
    "loader.sample": "per-sample decode in PrefetchLoader workers",
    "trainer.step": "top of the training loop, once per step",
    "ckpt.msgpack_write": "weights-only msgpack save (tmp/rename "
                          "window; corrupt = post-save bit rot)",
    "ckpt.orbax_save": "full-state Orbax save (corrupt smashes a "
                       "just-written step file)",
    "serve.request": "per micro-batch dispatch in the serving "
                     "scheduler's worker",
    "serve.dispatch_exec": "top of the supervised dispatch executor's "
                           "job loop (watchdog quarantine drill)",
    "serve.fetch": "PendingBatch.fetch blocking D2H read (completion-"
                   "stage hang)",
    "engine.compile": "immediately before a real XLA bucket compile",
    "registry.load": "start of a model-variant build (deploy auto-"
                     "rollback drill)",
    "guardian.decide": "SLO guardian verdict execution point (after "
                       "judgment, before registry action)",
    "aot.load": "serialized-executable cache verified load (corrupt = "
                "artifact bit rot; contract: clean miss)",
    "scheduler.swap": "per-replica weight application inside the "
                      "quiesced swap epoch (all-or-nothing)",
    "transport.send": "Transport.call request side (corrupt zero-"
                      "fills the encoded request in transit)",
    "transport.recv": "Transport.call reply side (same retry "
                      "contract)",
    "host.heartbeat": "one heartbeat probe in HostFleet.beat (missed-"
                      "beat ladder drill)",
    "host.infer": "remote host worker's infer execution "
                  "(serving/hosts.py — mid-batch host death drill)",
}

_POINT_KINDS = ("raise", "hang", "crash")
_ALL_KINDS = _POINT_KINDS + ("corrupt",)


class FaultInjected(RuntimeError):
    """Raised at an armed ``kind="raise"`` fault site."""


class _Entry:
    __slots__ = ("site", "at", "kind", "hang_s", "p", "count", "seen",
                 "fires")

    def __init__(self, spec: dict):
        self.site = spec["site"]
        # dotted names are the real `area.point` namespace and must be
        # registered; undotted names stay legal — the fault machinery's
        # own unit tests arm synthetic sites ("x", "y") that exist only
        # in the test body
        if "." in self.site and self.site not in KNOWN_SITES:
            near = difflib.get_close_matches(self.site, KNOWN_SITES,
                                             n=1)
            hint = f" — did you mean {near[0]!r}?" if near else ""
            raise ValueError(
                f"unknown fault site {self.site!r}: not in "
                f"faults.KNOWN_SITES{hint} (a typo'd site arms "
                "nothing and the drill silently tests less than it "
                "claims)")
        self.at = int(spec.get("at", 1))
        self.kind = spec["kind"]
        self.hang_s = float(spec.get("hang_s", 3600.0))
        self.p = float(spec.get("p", 1.0))
        self.count = int(spec.get("count", 1))
        if self.kind not in _ALL_KINDS:
            raise ValueError(
                f"fault kind {self.kind!r} at site {self.site!r}: choose "
                f"one of {_ALL_KINDS}")
        if self.at < 1:
            raise ValueError(f"fault at={self.at} at site {self.site!r}: "
                             "occurrence counts are 1-based")
        if not 0.0 < self.p <= 1.0:
            raise ValueError(f"fault p={self.p} at site {self.site!r}: "
                             "must be in (0, 1]")
        if self.count < 0:
            raise ValueError(f"fault count={self.count} at site "
                             f"{self.site!r}: must be >= 0 (0=unlimited)")
        self.seen = 0
        self.fires = 0

    @property
    def exhausted(self) -> bool:
        return self.count > 0 and self.fires >= self.count


_PLAN: Optional[List[_Entry]] = None
_RNG = random.Random(0)
_LOCK = threading.Lock()


def arm(plan) -> None:
    """Arm ``plan`` (list of entry dicts, or ``{"faults": [...],
    "seed": N}``); entries scoped to a different supervisor attempt are
    dropped. ``seed`` (default 0) drives the probabilistic-``p`` draws
    deterministically."""
    global _PLAN, _RNG
    seed = 0
    if isinstance(plan, dict):
        seed = int(plan.get("seed", 0))
        plan = plan.get("faults", [])
    attempt = int(os.environ.get("RAFT_SUPERVISOR_ATTEMPT", "0"))
    entries = [_Entry(spec) for spec in plan
               if int(spec.get("attempt", attempt)) == attempt]
    _RNG = random.Random(seed)
    _PLAN = entries or None


def disarm() -> None:
    global _PLAN
    _PLAN = None


def arm_from_env() -> None:
    """Arm from $RAFT_FAULT_PLAN (inline JSON) or $RAFT_FAULT_PLAN_FILE."""
    raw = os.environ.get("RAFT_FAULT_PLAN")
    if not raw:
        path = os.environ.get("RAFT_FAULT_PLAN_FILE")
        if path:
            with open(path, encoding="utf-8") as fh:
                raw = fh.read()
    if raw:
        arm(json.loads(raw))


def armed(site: str) -> bool:
    """True iff an un-exhausted entry for ``site`` exists. Lets a call
    site gate expensive setup (e.g. waiting out an async save so there
    are bytes on disk to corrupt) on the drill actually being live."""
    if _PLAN is None:
        return False
    with _LOCK:
        return any(e.site == site and not e.exhausted for e in _PLAN)


def _match(site: str, kinds) -> Optional[_Entry]:
    """Count this call against every matching entry; return the first
    (if any) whose occurrence just came due. Each call type counts only
    the kinds it can serve, so a site with both a ``fault_point`` and a
    ``fault_file`` call per event still counts one occurrence per event
    for every entry. Eligible entries (``seen >= at``, not exhausted)
    fire with probability ``p`` from the plan-seeded rng."""
    due = None
    with _LOCK:
        for e in _PLAN or ():
            if e.site != site or e.exhausted or e.kind not in kinds:
                continue
            e.seen += 1
            if due is None and e.seen >= e.at:
                if e.p < 1.0 and _RNG.random() >= e.p:
                    continue
                e.fires += 1
                due = e
    return due


def fault_point(site: str) -> None:
    """crash/hang/raise injection point — no-op unless a plan is armed."""
    if _PLAN is None:
        return
    e = _match(site, _POINT_KINDS)
    if e is None:
        return
    if e.kind == "raise":
        raise FaultInjected(
            f"injected fault at {site} (occurrence {e.seen})")
    if e.kind == "hang":
        time.sleep(e.hang_s)
        return
    # "crash": skip atexit handlers, finally blocks, buffered writes —
    # exactly what power loss or a SIGKILL preemption leaves behind
    os._exit(CRASH_EXIT_CODE)


def fault_file(site: str, path: str) -> Optional[str]:
    """Corruption injection point for a completed on-disk artifact:
    zero-fills ``path``. For a directory, the victim is a ``_METADATA``
    file if one exists (Orbax step dirs), else the largest file under
    it — the one most likely to straddle real bit rot or a torn write.
    Call sites place this AFTER the artifact and any integrity manifest
    are fully written: the drill models damage the loader-side check
    must catch, not damage the writer knew about.

    Size-preserving zero-fill rather than bit flips or truncation, on
    purpose: all three are detected identically by size/hash checks,
    but feeding flipped bytes to a compressed-stream reader
    (tensorstore's zstd path) or short-reading a manifest-declared
    byte range (truncation) corrupts the reader's heap and SIGABRTs
    the process minutes later — the drill must let the fallback path
    run, not poison it.

    The ``_METADATA`` preference exists for the same reason one level
    up: even a *cleanly reported* tensorstore read error against a
    zeroed data file leaves the async read machinery's heap poisoned
    (use-after-free; glibc "corrupted double-linked list" aborts later
    in the very process that must then recover), whereas a zeroed
    ``_METADATA`` fails the restore in pure-Python parsing before any
    tensorstore data read starts. Detection coverage is identical —
    the fallback/quarantine path can't tell which file was bad.

    Returns the victim's path when the entry fired, else None."""
    if _PLAN is None:
        return None
    if _match(site, ("corrupt",)) is None:
        return None
    victim = path
    if os.path.isdir(path):
        victim, size, meta = None, -1, None
        for root, _, files in os.walk(path):
            for f in sorted(files):
                p = os.path.join(root, f)
                if f == "_METADATA" and meta is None:
                    meta = p
                s = os.path.getsize(p)
                if s > size:
                    victim, size = p, s
        victim = meta or victim
        if victim is None:
            return None
    n = os.path.getsize(victim)
    with open(victim, "r+b") as fh:
        fh.write(b"\x00" * n if n else b"\x00")
    return victim


def fault_data(site: str, payload: bytes) -> bytes:
    """Corruption injection point for IN-TRANSIT bytes (the transport
    seam's analog of :func:`fault_file`): returns ``payload``
    zero-filled (size-preserving, same rationale as ``fault_file``)
    when a ``kind="corrupt"`` entry for ``site`` fires, else the
    payload untouched. Call sites place this on the encoded message
    right before it crosses the host boundary — the drill models
    damage the RECEIVER-side decode/verify must catch (undecodable
    request, sha256 mismatch on an artifact blob), and the drilled
    contract is always a clean error the sender retries."""
    if _PLAN is None:
        return payload
    if _match(site, ("corrupt",)) is None:
        return payload
    return b"\x00" * len(payload) if payload else b"\x00"


# a process launched with a plan in its environment is armed on first
# import — no code change needed at the drilled entrypoint
arm_from_env()
