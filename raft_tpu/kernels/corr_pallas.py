"""Pallas TPU kernel: multi-level correlation window lookup.

The per-iteration hot gather of RAFT (corr.py:29-50): for every query pixel,
fetch a (2r+1)² bilinear window from its (Hl, Wl) correlation slice at each
pyramid level. The CUDA reference solves this with per-pixel shared-memory
tiles (correlation_kernel.cu:19-119); XLA solves it with general gathers
(slow on TPU) or one-hot GEMMs (corr_lookup_onehot). This kernel instead
streams each query's integer (2r+2)² window VMEM-ward through an
8-deep ring of async DMAs straight from the volume in HBM — reading ~P²·4 bytes per query
instead of the whole (Hl, Wl) slice — then applies the separable 2-tap lerp
on the VPU.

Bilinear structure exploited (see ``models.corr._window_base``): all taps of
one query share the same fractional offsets, so the kernel never does
scatter/gather arithmetic — one strided window DMA + two lerps per query.

The volume is zero-padded by PAD = 2r+3 on both spatial sides and coords are
clamped to [-(r+2), S+r+1] beforehand, which (a) keeps every window DMA
in-bounds without per-tap masking, and (b) preserves grid_sample's
padding_mode='zeros' semantics exactly — windows of far-out-of-range queries
land entirely in the zero margin.

Training support: forward runs the kernel; the VJP re-expresses the lookup
as two one-hot GEMMs (it is linear in the volume) so the backward pass is
exact without a hand-written scatter kernel — the reference ships no usable
CUDA backward either (its alt path calls ``.forward`` without an autograd
wrapper, corr.py:86, so the backward kernel is dead code; SURVEY.md §2).
"""

from __future__ import annotations

import functools
import math
from typing import Sequence

import jax
import jax.numpy as jnp

try:  # pallas import is gated so CPU-only installs still work
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_OK = True
except Exception:  # pragma: no cover
    _PALLAS_OK = False


# interpret mode runs the kernel in pure XLA — used by CPU tests
_INTERPRET = False


def pallas_available() -> bool:
    if not _PALLAS_OK:
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


_NBUF = 8  # DMA ring depth: each window is ~P²·4 B (~400 B), so single
# transfers are latency-bound, not bandwidth-bound; keeping _NBUF copies in
# flight hides HBM latency the way the CUDA kernel's block-wide coalesced
# loads do (correlation_kernel.cu:56-72).


def _lookup_kernel(base_ref, frac_ref, vol_ref, out_ref, scratch, sems, *,
                   Q: int, K: int):
    """One grid step: Q queries of one (batch, query-tile) block.

    base_ref: SMEM (1, Q, 2) int32 — in-bounds window starts (x0p, y0p)
    frac_ref: SMEM (1, Q, 2) f32 — shared bilinear fracs (wx, wy)
    vol_ref:  ANY  (B, N, Hp, Wp) f32 — padded volume, resident in HBM
    out_ref:  VMEM (1, Q, K²) f32
    scratch:  VMEM (_NBUF, P, P) DMA ring; sems: _NBUF DMA semaphores
    """
    P = K + 1
    b = pl.program_id(0)
    t = pl.program_id(1)

    def window_copy(q, slot):
        x0 = base_ref[0, q, 0]
        y0 = base_ref[0, q, 1]
        return pltpu.make_async_copy(
            vol_ref.at[b, t * Q + q, pl.ds(y0, P), pl.ds(x0, P)],
            scratch.at[slot],
            sems.at[slot],
        )

    # prologue: fill all but one ring slot (slot q%_NBUF for query q)
    for q0 in range(min(_NBUF - 1, Q)):
        window_copy(q0, q0 % _NBUF).start()

    def body(q, _):
        slot = jax.lax.rem(q, _NBUF)
        # body q-1 freed slot (q-1)%_NBUF == (q+_NBUF-1)%_NBUF: refill it
        nxt = q + _NBUF - 1

        @pl.when(nxt < Q)
        def _():
            window_copy(nxt, jax.lax.rem(nxt, _NBUF)).start()

        window_copy(q, slot).wait()
        win = scratch[slot]                       # (P, P) [y, x]
        wx = frac_ref[0, q, 0]
        wy = frac_ref[0, q, 1]
        wl = (1.0 - wy) * win[:K, :] + wy * win[1:, :]
        w2 = (1.0 - wx) * wl[:, :K] + wx * wl[:, 1:]
        out_ref[0, q, :] = w2.T.reshape(K * K)    # x-major channel layout
        return 0

    jax.lax.fori_loop(0, Q, body, 0, unroll=False)


def _level_lookup_pallas(vol: jax.Array, x: jax.Array, y: jax.Array,
                         radius: int, q_tile: int = 256) -> jax.Array:
    """(B, N, Hl, Wl) volume + (B, N) coords -> (B, N, K²)."""
    B, N, Hl, Wl = vol.shape
    K = 2 * radius + 1
    P = K + 1
    PAD = 2 * radius + 3

    # clamp far-OOB queries into the zero margin (semantics-preserving:
    # every tap of a clamped query still reads only zeros)
    x = jnp.clip(x, -(radius + 2.0), Wl + radius + 1.0)
    y = jnp.clip(y, -(radius + 2.0), Hl + radius + 1.0)
    xf = jnp.floor(x)
    yf = jnp.floor(y)
    base = jnp.stack(
        [xf.astype(jnp.int32) - radius + PAD,
         yf.astype(jnp.int32) - radius + PAD], axis=-1)      # (B, N, 2)
    frac = jnp.stack([x - xf, y - yf], axis=-1).astype(jnp.float32)

    vol_p = jnp.pad(vol, ((0, 0), (0, 0), (PAD, PAD), (PAD, PAD)))

    n_pad = (-N) % q_tile
    if n_pad:
        base = jnp.pad(base, ((0, 0), (0, n_pad), (0, 0)))
        frac = jnp.pad(frac, ((0, 0), (0, n_pad), (0, 0)))
        vol_p = jnp.pad(vol_p, ((0, 0), (0, n_pad), (0, 0), (0, 0)))
    Np = N + n_pad

    kernel = functools.partial(_lookup_kernel, Q=q_tile, K=K)
    out = pl.pallas_call(
        kernel,
        grid=(B, Np // q_tile),
        in_specs=[
            pl.BlockSpec((1, q_tile, 2), lambda b, t: (b, t, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, q_tile, 2), lambda b, t: (b, t, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, q_tile, K * K), lambda b, t: (b, t, 0)),
        out_shape=jax.ShapeDtypeStruct((B, Np, K * K), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((_NBUF, P, P), jnp.float32),
            pltpu.SemaphoreType.DMA((_NBUF,)),
        ],
        interpret=_INTERPRET,
    )(base, frac, vol_p.astype(jnp.float32))
    return out[:, :N]


def _lookup_fwd_impl(pyramid, x, y, radius: int):
    outs = [_level_lookup_pallas(vol, x / (2 ** i), y / (2 ** i), radius)
            for i, vol in enumerate(pyramid)]
    return jnp.concatenate(outs, axis=-1)


def _lookup_onehot_impl(pyramid, x, y, radius: int):
    """XLA reference math for the VJP (linear in the volume)."""
    from raft_tpu.models.corr import _separable_lerp, _window_base

    P = 2 * radius + 2
    outs = []
    for i, vol in enumerate(pyramid):
        Hl, Wl = vol.shape[-2:]
        x0, y0, wx, wy = _window_base(x / (2 ** i), y / (2 ** i), radius)
        taps = jnp.arange(P, dtype=jnp.int32)
        sel_y = ((y0[..., None] + taps)[..., None]
                 == jnp.arange(Hl)).astype(jnp.float32)
        sel_x = ((x0[..., None] + taps)[..., None]
                 == jnp.arange(Wl)).astype(jnp.float32)
        hi = jax.lax.Precision.HIGHEST  # fp32 island, as in the forward
        tmp = jnp.einsum("bnph,bnhw->bnpw", sel_y, vol, precision=hi)
        win = jnp.einsum("bnpw,bnqw->bnpq", tmp, sel_x, precision=hi)
        outs.append(_separable_lerp(win, wx, wy, radius))
    return jnp.concatenate(outs, axis=-1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _lookup(pyramid, x, y, radius: int):
    return _lookup_fwd_impl(pyramid, x, y, radius)


def _lookup_fwd(pyramid, x, y, radius: int):
    return _lookup_fwd_impl(pyramid, x, y, radius), (pyramid, x, y)


def _lookup_bwd(radius, res, g):
    pyramid, x, y = res
    # exact adjoint via the one-hot formulation; coords get no gradient
    # (the model stop-gradients the coordinate chain anyway, raft.py:123)
    _, vjp = jax.vjp(
        lambda vols: _lookup_onehot_impl(vols, x, y, radius), pyramid)
    (d_pyramid,) = vjp(g)
    return d_pyramid, None, None


_lookup.defvjp(_lookup_fwd, _lookup_bwd)


def corr_lookup_pallas(pyramid: Sequence[jax.Array], coords: jax.Array,
                       radius: int) -> jax.Array:
    """Drop-in for ``models.corr.corr_lookup`` backed by the Pallas kernel.

    pyramid: list of (B, N, Hl, Wl) fp32 volumes; coords (B, H, W, 2).
    Returns (B, H, W, levels·K²) fp32.
    """
    B, H, W, _ = coords.shape
    N = H * W
    x = coords[..., 0].reshape(B, N).astype(jnp.float32)
    y = coords[..., 1].reshape(B, N).astype(jnp.float32)
    out = _lookup(tuple(pyramid), x, y, radius)
    return out.reshape(B, H, W, -1)
