"""Pallas TPU kernel: multi-level correlation window lookup.

The per-iteration hot gather of RAFT (corr.py:29-50): for every query pixel,
fetch a (2r+1)² bilinear window from its (Hl, Wl) correlation slice at each
pyramid level. The CUDA reference solves this with per-pixel shared-memory
tiles (correlation_kernel.cu:19-119); XLA solves it with general gathers
(slow on TPU) or one-hot GEMMs (corr_lookup_onehot) — measured on a v5e-1
at chairs geometry: 364 / 170 ms per lookup (and a ~4 s gather backward),
versus ~0.3 ms of fundamental HBM traffic.

This kernel's design, arrived at by measuring three shapes on hardware:

- The Pallas grid pipelines whole (query-tile, Hp, Wp) volume blocks
  HBM→VMEM with large contiguous DMAs — HBM traffic is one pass over the
  volume per lookup, no per-query DMA (a per-query window-DMA ring was
  latency-bound; 68k tiny transfers per lookup).
- Window extraction is FULLY VECTORIZED on the VPU: for each of the P=2r+2
  integer row offsets, a broadcasted-iota mask against the per-query row
  start selects one window row across the whole tile at once (a masked
  reduction over Hp); a second pass does the same over columns. No scalar
  per-query loop (a fori_loop doing per-query dynamic slices measured
  163 ms — ~2,000 cycles/query of serialization), no dynamic lane slicing
  (unsupported by Mosaic), no MXU (batched 10×46 GEMMs pad to 128×128 tiles
  at ~1.4% utilization — the one-hot path's failure mode).
- Per-query scalars (window starts, bilinear fracs) arrive pre-shaped as
  (1, Q, 1, 1) blocks so they broadcast directly against (Q, Hp, Wp) —
  Mosaic has no cheap lane→outer relayout, so the reshape happens in XLA
  where it is free.

Bilinear structure exploited (see ``models.corr._window_base``): all taps of
one query share the same fractional offsets, so after the (2r+2)² integer
window is selected, a separable 2-tap lerp vectorized over the tile yields
the (2r+1)² bilinear taps.

The volume is zero-padded by PAD = 2r+3 on both spatial sides and coords are
clamped to [-(r+2), S+r+1] beforehand, which (a) keeps every window row
index in-bounds, and (b) preserves grid_sample's padding_mode='zeros'
semantics exactly — windows of far-out-of-range queries land entirely in
the zero margin.

Training support: the custom VJP runs a second Pallas kernel that scatters
the (adjoint-lerped) window gradients back into the padded volume layout
with the same mask-broadcast structure in reverse. Each query owns its own
(Hp, Wp) slice of the volume, so the scatter has no collisions — no atomics
(the CUDA backward needs atomicAdd for the same computation,
correlation_kernel.cu:237; the reference never calls it from Python anyway,
corr.py:86, SURVEY.md §2).
"""

from __future__ import annotations

import functools
import warnings
from typing import Sequence

import jax
import jax.numpy as jnp

try:  # pallas import is gated so CPU-only installs still work
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_OK = True
except Exception:  # pragma: no cover
    _PALLAS_OK = False


# interpret mode runs the kernel in pure XLA — forced by CPU tests via
# monkeypatch; off-TPU backends fall back to it automatically (Mosaic
# rejects non-interpret pallas_call on CPU, so without the fallback
# ``corr_impl="pallas"`` would be TPU-only — e.g. trained-weights parity
# on the CPU host could not cover this backend)
_INTERPRET = False


def _fallback_interpret() -> bool:
    """True when pallas_call must run in interpret mode because the
    backend has no Mosaic support. Loud on purpose: a trace on a non-TPU
    host (e.g. a StableHLO export destined for TPU) bakes the pure-XLA
    interpret path into the artifact, and that must not happen
    silently."""
    if pallas_available():
        return False
    warnings.warn(
        "pallas kernel lowered in interpret mode (non-TPU backend); an "
        "export/AOT artifact traced here ships the pure-XLA path, not "
        "the Mosaic kernel", stacklevel=3)
    return True


def _interpret() -> bool:
    return _INTERPRET or _fallback_interpret()

# Scoped-VMEM budget for ONE grid step of either kernel, covering
# everything the Mosaic stack allocator charges: pipelined in/out blocks
# (×2 for double buffering), scratch, and kernel-body intermediates.
# The hard limit is 16 MB (observed on-chip: a 17.09 MB scatter step at
# the 27×29 pyramid level was rejected with "scoped allocation ...
# exceeded scoped vmem limit"); 10 MB leaves headroom for Mosaic's own
# spills and for estimate error.
_SCOPED_BUDGET = 10 * 1024 * 1024

_QMAX = 256  # every _q_tile() value is a power of two ≤ this


def pallas_available() -> bool:
    if not _PALLAS_OK:
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def _pad(radius: int) -> int:
    return 2 * radius + 3


def _q_tile(Hp: int, Wp: int, radius: int) -> int:
    """Queries per grid step: largest power of two whose full scoped-VMEM
    footprint fits ``_SCOPED_BUDGET``.

    Models what the Mosaic stack allocator actually charges per grid step
    — every term scales with Q, so the budget divides into a per-query
    cost. VMEM tiling pads each buffer's sublane (second-minor) dim to 8
    and lane (minor) dim to 128 for 4-byte types; in particular a
    (1, Q, 1, 1) scalar block pads to (1, Q, 8, 128) = 4 KB/query, which
    is why the small pyramid levels — not the large ones — used to
    overflow: their spatial term shrank while four padded scalar blocks,
    the window scratch, and the (K, K) in/out blocks didn't.

    Charged at 4 bytes/element regardless of volume dtype: even with a
    bf16 volume the dominant per-query intermediates stay 4-byte (iota
    masks, the scatter's fp32 accumulator), so a smaller itemsize must
    NOT grow the tile — bf16's win is the halved HBM DMA traffic.
    """
    K = 2 * radius + 1
    P = K + 1

    def pad2(sub, lane):
        return (-(-sub // 8) * 8) * (-(-lane // 128) * 128)

    spatial = pad2(Hp, Wp)            # one (Hp, Wp) slice, padded
    per_query_elems = (
        2 * spatial                   # vol / dvol block, double-buffered
        + 3 * spatial                 # iota + masked-select/acc stack temps
        + 2 * pad2(P, Wp)             # rows / drows scratch (+ its temp)
        + 3 * pad2(P, P)              # win / dwin / dwl scratch
        + 2 * 2 * pad2(K, K)          # out / g blocks, double-buffered
        + 4 * pad2(1, 1))             # y0/x0/wy/wx blocks (pad to 8x128)
    q = _SCOPED_BUDGET // (per_query_elems * 4)
    tile = 8
    while tile * 2 <= q and tile < _QMAX:
        tile *= 2
    return tile


def pad_pyramid(pyramid: Sequence[jax.Array], radius: int):
    """Zero-pad each (B, N, Hl, Wl) level for the kernel's margin.

    Pads the spatial dims by the window margin and the query dim N up to a
    multiple of ``_QMAX`` (so any per-level query tile divides it evenly).
    Do this ONCE per forward pass (outside the scanned refinement loop) —
    the lookup is called ``iters`` times on the same loop-invariant pyramid,
    and padding inside the loop would re-copy the whole volume every
    iteration.
    """
    PAD = _pad(radius)
    out = []
    for v in pyramid:
        n_pad = (-v.shape[1]) % _QMAX
        out.append(jnp.pad(
            v, ((0, 0), (0, n_pad), (PAD, PAD), (PAD, PAD))))
    return tuple(out)


def _lookup_kernel(y0_ref, x0_ref, wy_ref, wx_ref, vol_ref, out_ref,
                   rows_ref, win_ref, *, Q: int, K: int):
    """One grid step: Q queries of one (batch, query-tile) block.

    y0/x0_ref: VMEM (1, Q, 1, 1) i32 — in-bounds window starts
    wy/wx_ref: VMEM (1, Q, 1, 1) f32 — shared bilinear fracs
    vol_ref:   VMEM (1, Q, Hp, Wp) f32 — padded volume block (pipelined)
    out_ref:   VMEM (1, Q, K, K) f32 — [y, x] window (x-major swap outside)
    rows_ref:  VMEM scratch (Q, P, Wp); win_ref: VMEM scratch (Q, P, P)
    """
    P = K + 1
    vol = vol_ref[0]                                   # (Q, Hp, Wp)
    Hp, Wp = vol.shape[-2:]
    y0 = y0_ref[0]                                     # (Q, 1, 1)
    x0 = x0_ref[0]
    zero = jnp.zeros((), vol.dtype)

    # row select: for each integer offset p, a mask over the sublane axis.
    # Selection is EXACT in the volume's storage dtype (each output is a
    # sum of zeros plus one entry), so a bf16 volume stays bf16 here —
    # half the HBM traffic — and precision is applied at the fp32 lerp.
    ih = jax.lax.broadcasted_iota(jnp.int32, (Q, Hp, Wp), 1)
    for p in range(P):
        m = (ih == y0 + p)
        rows_ref[:, p:p + 1, :] = jnp.sum(
            jnp.where(m, vol, zero), axis=1, keepdims=True)

    # column select: same over the lane axis of the gathered rows
    rows = rows_ref[:]                                 # (Q, P, Wp)
    iw = jax.lax.broadcasted_iota(jnp.int32, (Q, P, Wp), 2)
    for p in range(P):
        m = (iw == x0 + p)
        win_ref[:, :, p:p + 1] = jnp.sum(
            jnp.where(m, rows, zero), axis=2, keepdims=True)

    win = win_ref[:].astype(jnp.float32)               # (Q, P, P) [y, x]
    wy = wy_ref[0]                                     # (Q, 1, 1)
    wx = wx_ref[0]
    wl = (1.0 - wy) * win[:, :K, :] + wy * win[:, 1:, :]
    out_ref[0] = (1.0 - wx) * wl[:, :, :K] + wx * wl[:, :, 1:]


def _scatter_kernel(y0_ref, x0_ref, wy_ref, wx_ref, g_ref, dvol_ref,
                    dwin_ref, dwl_ref, drows_ref, *, Q: int, K: int):
    """Adjoint of ``_lookup_kernel``: window grads -> padded volume block.

    g_ref: VMEM (1, Q, K, K) f32 — [y, x] cotangent of the window
    dvol_ref: VMEM (1, Q, Hp, Wp) f32 out — zero except the scattered windows
    scratch: dwin (Q, P, P), dwl (Q, K, P), drows (Q, P, Wp)
    """
    P = K + 1
    Hp, Wp = dvol_ref.shape[-2:]
    g = g_ref[0]                                       # (Q, K, K)
    wy = wy_ref[0]
    wx = wx_ref[0]
    y0 = y0_ref[0]
    x0 = x0_ref[0]

    # adjoint of the separable lerp, via overlapping static-slice stores:
    # forward  wl = (1-wy)·win[:K] + wy·win[1:]; out = (1-wx)·wl[:,:K] + wx·wl[:,1:]
    dwl_ref[...] = jnp.zeros_like(dwl_ref)
    dwl_ref[:, :, :K] = (1.0 - wx) * g
    dwl_ref[:, :, 1:] = dwl_ref[:, :, 1:] + wx * g
    dwl = dwl_ref[:]                                   # (Q, K, P)
    dwin_ref[...] = jnp.zeros_like(dwin_ref)
    dwin_ref[:, :K, :] = (1.0 - wy) * dwl
    dwin_ref[:, 1:, :] = dwin_ref[:, 1:, :] + wy * dwl
    dwin = dwin_ref[:]                                 # (Q, P, P)

    # adjoint of column select: place window columns at their lane offsets
    iw = jax.lax.broadcasted_iota(jnp.int32, (Q, P, Wp), 2)
    acc = jnp.zeros((Q, P, Wp), jnp.float32)
    for p in range(P):
        acc = acc + jnp.where(iw == x0 + p, dwin[:, :, p:p + 1], 0.0)
    drows_ref[...] = acc

    # adjoint of row select: broadcast rows to their sublane offsets;
    # cotangent dtype matches the (possibly bf16) volume's
    drows = drows_ref[:]                               # (Q, P, Wp)
    ih = jax.lax.broadcasted_iota(jnp.int32, (Q, Hp, Wp), 1)
    acc = jnp.zeros((Q, Hp, Wp), jnp.float32)
    for p in range(P):
        acc = acc + jnp.where(ih == y0 + p, drows[:, p:p + 1, :], 0.0)
    dvol_ref[0] = acc.astype(dvol_ref.dtype)


def _prep_coords(shape_p, x, y, radius):
    """Clamp coords and build integer window bases + shared fracs.

    Returns (1,1)-trailing-shaped arrays so kernel blocks broadcast
    directly against (Q, Hp, Wp) without any in-kernel relayout.
    """
    PAD = _pad(radius)
    Hl, Wl = shape_p[-2] - 2 * PAD, shape_p[-1] - 2 * PAD
    x = jnp.clip(x, -(radius + 2.0), Wl + radius + 1.0)
    y = jnp.clip(y, -(radius + 2.0), Hl + radius + 1.0)
    xf = jnp.floor(x)
    yf = jnp.floor(y)
    B, N = x.shape

    def sh(a):
        return a.reshape(B, N, 1, 1)

    x0 = sh(xf.astype(jnp.int32) - radius + PAD)
    y0 = sh(yf.astype(jnp.int32) - radius + PAD)
    wx = sh((x - xf).astype(jnp.float32))
    wy = sh((y - yf).astype(jnp.float32))
    return y0, x0, wy, wx


def _scalar_specs(q_tile):
    spec = pl.BlockSpec((1, q_tile, 1, 1), lambda b, t: (b, t, 0, 0))
    return [spec, spec, spec, spec]


def _pad_n(arrs, n_pad):
    if not n_pad:
        return arrs
    return [jnp.pad(a, ((0, 0), (0, n_pad)) + ((0, 0),) * (a.ndim - 2))
            for a in arrs]


def _level_lookup_pallas(vol_p: jax.Array, x: jax.Array, y: jax.Array,
                         radius: int) -> jax.Array:
    """Padded (B, Np, Hp, Wp) volume + (B, N) coords -> (B, N, K²) x-major.

    ``vol_p`` comes from :func:`pad_pyramid`; N (= x.shape[1]) may be less
    than Np, in which case the trailing queries are padding and dropped.
    """
    B, Np, Hp, Wp = vol_p.shape
    N = x.shape[1]
    K = 2 * radius + 1
    y0, x0, wy, wx = _prep_coords(vol_p.shape, x, y, radius)
    q_tile = _q_tile(Hp, Wp, radius)
    assert Np % q_tile == 0, (Np, q_tile)
    y0, x0, wy, wx = _pad_n([y0, x0, wy, wx], Np - N)

    kernel = functools.partial(_lookup_kernel, Q=q_tile, K=K)
    out = pl.pallas_call(
        kernel,
        grid=(B, Np // q_tile),
        in_specs=_scalar_specs(q_tile) + [
            pl.BlockSpec((1, q_tile, Hp, Wp), lambda b, t: (b, t, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, q_tile, K, K), lambda b, t: (b, t, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, Np, K, K), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((q_tile, K + 1, Wp), vol_p.dtype),
            pltpu.VMEM((q_tile, K + 1, K + 1), vol_p.dtype),
        ],
        interpret=_interpret(),
    )(y0, x0, wy, wx, vol_p)
    # [y, x] window -> x-major flat channels (models.corr layout contract)
    out = jnp.swapaxes(out[:, :N], -1, -2).reshape(B, N, K * K)
    return out


def _level_scatter_pallas(g: jax.Array, shape_p, vol_dtype, x: jax.Array,
                          y: jax.Array, radius: int) -> jax.Array:
    """Adjoint: (B, N, K²) x-major cotangent -> padded volume grad.

    Stays in the padded layout — the pad's own VJP (a slice) is applied by
    XLA outside this custom_vjp, once, after the scan sums per-iteration
    cotangents.
    """
    B, Np, Hp, Wp = shape_p
    N = x.shape[1]
    K = 2 * radius + 1
    y0, x0, wy, wx = _prep_coords(shape_p, x, y, radius)
    q_tile = _q_tile(Hp, Wp, radius)

    g = jnp.swapaxes(g.reshape(B, N, K, K), -1, -2)    # x-major -> [y, x]
    y0, x0, wy, wx, g = _pad_n([y0, x0, wy, wx, g], Np - N)

    kernel = functools.partial(_scatter_kernel, Q=q_tile, K=K)
    dvol_p = pl.pallas_call(
        kernel,
        grid=(B, Np // q_tile),
        in_specs=_scalar_specs(q_tile) + [
            pl.BlockSpec((1, q_tile, K, K), lambda b, t: (b, t, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, q_tile, Hp, Wp),
                               lambda b, t: (b, t, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, Np, Hp, Wp), vol_dtype),
        scratch_shapes=[
            pltpu.VMEM((q_tile, K + 1, K + 1), jnp.float32),
            pltpu.VMEM((q_tile, K, K + 1), jnp.float32),
            pltpu.VMEM((q_tile, K + 1, Wp), jnp.float32),
        ],
        interpret=_interpret(),
    )(y0, x0, wy, wx, g)
    return dvol_p


def _lookup_fwd_impl(pyramid_p, x, y, radius: int):
    outs = [_level_lookup_pallas(vol, x / (2 ** i), y / (2 ** i), radius)
            for i, vol in enumerate(pyramid_p)]
    return jnp.concatenate(outs, axis=-1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _lookup(pyramid_p, x, y, radius: int):
    return _lookup_fwd_impl(pyramid_p, x, y, radius)


def _lookup_fwd(pyramid_p, x, y, radius: int):
    # residual leaves must be JAX types: shape as an int tuple, dtype via
    # a zero-size token array
    return _lookup_fwd_impl(pyramid_p, x, y, radius), (
        tuple((v.shape, jnp.zeros((0,), v.dtype)) for v in pyramid_p), x, y)


def _lookup_bwd(radius, res, g):
    shapes, x, y = res
    K2 = (2 * radius + 1) ** 2
    # coords get no gradient (the model stop-gradients the coordinate
    # chain anyway, raft.py:123)
    d_pyramid = tuple(
        _level_scatter_pallas(
            g[..., i * K2:(i + 1) * K2], shape, token.dtype,
            x / (2 ** i), y / (2 ** i), radius)
        for i, (shape, token) in enumerate(shapes))
    return d_pyramid, None, None


_lookup.defvjp(_lookup_fwd, _lookup_bwd)


def corr_lookup_pallas(pyramid: Sequence[jax.Array], coords: jax.Array,
                       radius: int, prepadded: bool = False) -> jax.Array:
    """Drop-in for ``models.corr.corr_lookup`` backed by the Pallas kernel.

    pyramid: list of (B, N, Hl, Wl) volumes in fp32 OR bf16 (a bf16 volume
    flows through unconverted — half the HBM traffic; selection is exact
    in storage dtype and the lerp runs fp32, see ``RAFTConfig.corr_dtype``)
    — or the output of :func:`pad_pyramid` when ``prepadded=True`` (pass
    that from outside the refinement loop so the pad isn't re-done every
    iteration). coords (B, H, W, 2). Returns (B, H, W, levels·K²) fp32.
    """
    B, H, W, _ = coords.shape
    N = H * W
    x = coords[..., 0].reshape(B, N).astype(jnp.float32)
    y = coords[..., 1].reshape(B, N).astype(jnp.float32)
    pyr = tuple(pyramid) if prepadded else pad_pyramid(pyramid, radius)
    out = _lookup(pyr, x, y, radius)
    return out.reshape(B, H, W, -1)
