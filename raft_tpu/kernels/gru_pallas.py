"""Pallas TPU kernels: fused ConvGRU gate math and blend epilogue.

The refinement scan runs the SepConvGRU twice per iteration (horizontal
then vertical), and each half's elementwise tail — ``z = σ(zl)``,
``r = σ(rl)``, ``r·h``, then ``h' = (1-z)·h + z·tanh(ql)`` — is a chain
of small VPU ops between the gate convs. Left to XLA inside the scan
body those intermediates (z, r, r·h, tanh) round-trip HBM between the
conv fusions 12× fwd + 12× bwd per step, at the 46×62-spatial shapes
PROFILE round 5 measured running 20–80 GB/s effective. These kernels
fuse each tail into ONE pass over the operands:

- :func:`gru_gates`: ``(zl, rl, h) -> (z, r·h)`` — both sigmoids and the
  reset-gate product in one read of the three inputs. ``z`` feeds the
  blend; ``r·h`` feeds the candidate conv's input concat.
- :func:`gru_blend`: ``(z, h, ql) -> (1-z)·h + z·tanh(ql)`` — the tanh
  and the convex blend in one pass; no separate q tensor ever lands in
  HBM.

Both are elementwise over ``(B, N, C)`` lane-major operands (N = H·W on
sublanes, C on lanes — the fused update block's native layout, see
``models.layers._apply_conv_lane_major``), gridded over row tiles so
VMEM holds only a slab at a time.

Training support: each op is a ``jax.custom_vjp`` whose backward is a
second fused kernel recomputing the activations from the saved INPUTS
(elementwise recompute is cheaper than storing z/r/tanh per iteration —
the scan would otherwise stack them across all 12 iterations):

- gates: ``dzl = dz·z·(1-z)``, ``drl = drh·h·r·(1-r)``, ``dh = drh·r``
- blend: ``dz = g·(tanh(ql) - h)``, ``dh = g·(1-z)``,
  ``dql = g·z·(1-tanh²(ql))``

Off-TPU the kernels run in interpret mode (pure XLA), same loud-warning
contract as ``corr_pallas`` — a trace on a non-TPU host bakes the
interpret path into any export artifact, and that must not be silent.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

try:  # pallas import is gated so CPU-only installs still work
    from jax.experimental import pallas as pl

    _PALLAS_OK = True
except Exception:  # pragma: no cover
    _PALLAS_OK = False

from raft_tpu.kernels.corr_pallas import (_fallback_interpret,  # noqa: F401
                                          pallas_available)

# interpret mode runs the kernels in pure XLA — forced by CPU tests via
# monkeypatch; off-TPU backends fall back automatically (see
# corr_pallas._interpret for why — and the fused update block must run
# end-to-end on the CPU host for the oracle parity tests)
_INTERPRET = False

#: max rows (of the flattened H·W axis) per grid step. Elementwise
#: kernels with ≤5 operands at 128 lanes: 512 rows × 128 lanes × 4 B =
#: 256 KB per buffer, ~4 MB double-buffered worst case — far under the
#: 16 MB VMEM ceiling, large enough that the DMA engine streams.
_ROWS = 512
#: smallest acceptable exact-divisor tile before padding wins: below
#: this the grid gets long and each DMA small, and one padded copy per
#: operand is cheaper than hundreds of sliver transfers.
_MIN_ROWS = 64


def _interpret() -> bool:
    return _INTERPRET or _fallback_interpret()


def _row_tile(N):
    """(rows per grid step, rows of padding) for an N-row operand.

    Prefers the largest EXACT divisor of N within the VMEM budget: the
    kernels exist to cut HBM round trips, so padding every operand with
    a jnp.pad copy on the hot path (as a fixed power-of-two tile would
    at e.g. the 46·62 = 2852-row production geometry — tile 124 divides
    it) must be the exception, not the rule. Falls back to a padded
    ``_ROWS`` tile only when N is near-prime and the best divisor would
    shred the grid into sliver DMAs.
    """
    if N <= _ROWS:
        return N, 0
    best = max(r for r in range(1, _ROWS + 1) if N % r == 0)
    if best >= _MIN_ROWS:
        return best, 0
    return _ROWS, (-N) % _ROWS


def _gates_kernel(zl_ref, rl_ref, h_ref, z_ref, rh_ref):
    zl = zl_ref[...]
    rl = rl_ref[...]
    h = h_ref[...]
    z_ref[...] = jax.nn.sigmoid(zl)
    rh_ref[...] = jax.nn.sigmoid(rl) * h


def _gates_bwd_kernel(zl_ref, rl_ref, h_ref, dz_ref, drh_ref,
                      dzl_ref, drl_ref, dh_ref):
    z = jax.nn.sigmoid(zl_ref[...])
    r = jax.nn.sigmoid(rl_ref[...])
    dz = dz_ref[...]
    drh = drh_ref[...]
    dzl_ref[...] = dz * z * (1.0 - z)
    drl_ref[...] = drh * h_ref[...] * r * (1.0 - r)
    dh_ref[...] = drh * r


def _blend_kernel(z_ref, h_ref, ql_ref, out_ref):
    z = z_ref[...]
    out_ref[...] = (1.0 - z) * h_ref[...] + z * jnp.tanh(ql_ref[...])


def _blend_bwd_kernel(z_ref, h_ref, ql_ref, g_ref,
                      dz_ref, dh_ref, dql_ref):
    z = z_ref[...]
    g = g_ref[...]
    t = jnp.tanh(ql_ref[...])
    dz_ref[...] = g * (t - h_ref[...])
    dh_ref[...] = g * (1.0 - z)
    dql_ref[...] = g * z * (1.0 - t * t)


def _tiled_call(kernel, inputs, n_out):
    """Run an elementwise kernel over same-shaped (B, N, C) operands,
    gridded in row tiles; outputs mirror the first input's shape/dtype.

    The row tile exactly divides N when a reasonable divisor exists
    (see :func:`_row_tile`); otherwise N is padded up to a tile multiple
    (elementwise: the pad rows compute garbage that the final slice
    drops), so any N works.
    """
    B, N, C = inputs[0].shape
    dt = inputs[0].dtype
    rows, n_pad = _row_tile(N)
    if n_pad:
        inputs = [jnp.pad(a, ((0, 0), (0, n_pad), (0, 0))) for a in inputs]
    Np = N + n_pad
    spec = pl.BlockSpec((1, rows, C), lambda b, t: (b, t, 0))
    out = pl.pallas_call(
        kernel,
        grid=(B, Np // rows),
        in_specs=[spec] * len(inputs),
        out_specs=[spec] * n_out,
        out_shape=[jax.ShapeDtypeStruct((B, Np, C), dt)] * n_out,
        interpret=_interpret(),
    )(*inputs)
    if n_pad:
        out = [o[:, :N] for o in out]
    return tuple(out)


@jax.custom_vjp
def gru_gates(zl, rl, h):
    """Fused update/reset-gate epilogue: ``(σ(zl), σ(rl)·h)``.

    All operands (B, N, C) lane-major, same dtype. Returns ``(z, rh)``:
    ``z`` for :func:`gru_blend`, ``rh`` for the candidate conv's input.
    """
    return _tiled_call(_gates_kernel, [zl, rl, h], n_out=2)


def _gates_fwd(zl, rl, h):
    return gru_gates(zl, rl, h), (zl, rl, h)


def _gates_bwd(res, cts):
    zl, rl, h = res
    dz, drh = cts
    return _tiled_call(_gates_bwd_kernel, [zl, rl, h, dz, drh], n_out=3)


gru_gates.defvjp(_gates_fwd, _gates_bwd)


@jax.custom_vjp
def gru_blend(z, h, ql):
    """Fused candidate/blend epilogue: ``(1-z)·h + z·tanh(ql)``.

    ``ql`` is the candidate conv's PRE-tanh output — the tanh runs in
    here so the q tensor never materializes in HBM.
    """
    (out,) = _tiled_call(_blend_kernel, [z, h, ql], n_out=1)
    return out


def _blend_fwd(z, h, ql):
    return gru_blend(z, h, ql), (z, h, ql)


def _blend_bwd(res, g):
    z, h, ql = res
    return _tiled_call(_blend_bwd_kernel, [z, h, ql, g], n_out=3)


gru_blend.defvjp(_blend_fwd, _blend_bwd)


def gru_cell_lane_major(h, zl, rl, ql_fn):
    """One GRU half in the fused formulation.

    ``ql_fn(rh)`` must produce the candidate conv's pre-activation from
    the fused ``r·h`` (the caller owns the conv so the parameter tree
    stays the update block's). Shared by both SepConvGRU directions.
    """
    z, rh = gru_gates(zl, rl, h)
    ql = ql_fn(rh)
    return gru_blend(z, h, ql)


__all__ = ["gru_gates", "gru_blend", "gru_cell_lane_major",
           "pallas_available"]
