"""Pallas TPU kernel: on-the-fly windowed correlation (alt_cuda_corr).

The reference's one native component computes the correlation lookup
without materializing the (H·W)² volume: for each query pixel, dot fmap1's
feature vector against the bilinearly-sampled fmap2 features in a (2r+1)²
window around the current coords (alt_cuda_corr/correlation_kernel.cu:19-119,
tiled shared-memory dot products). This is the memory regime for large
resolutions — at the TRT envelope max 1024² the level-0 volume alone is
~1 GB·B fp32 (SURVEY.md §5), while this path stores only the fmap2 pyramid.

Kernel design (contrast with ``corr_pallas.py``, the materialized-pyramid
lookup): there each query owns a private (Hl, Wl) slice, so block-streaming
the volume is the only bandwidth-efficient option and per-query DMAs
(~400 B) are latency-bound. Here fmap2 is SHARED across queries and a
query's window spans all C channels — (2r+2)²·C ≈ 100 KB at C=256 — so
per-query async copies are bandwidth-efficient. The kernel keeps a ring of
window DMAs in flight from HBM, dots each arrival against the query's
fmap1 row on the VPU (multiply + lane reduction over C — a matvec, which
the MXU would waste a 128×128 tile on), and applies the separable 2-tap
lerp vectorized over the query tile, exploiting that correlation is linear
in fmap2: interpolate-then-dot ≡ sampling the true volume, exactly the
identity the CUDA kernel's bilinear scatter form uses
(correlation_kernel.cu:56-99).

DMA alignment (learned on-chip): in the (B, Hp, Wp, C) layout the tiled
dims are (Wp, C) — sublane and lane — and Mosaic rejects DMA slices whose
W span isn't a multiple of the 8-row sublane tile ("Slice shape along
dimension 2 must be aligned to tiling (8), but is 10"). So the copy takes
an 8-ALIGNED W span: the window's W start rounds down to a multiple of 8
and the span widens to ``_wspan(P)`` (24 for P=10); H spans are untiled
and stay exact. The true P columns are selected AFTER the channel
reduction — once C is reduced away, W is the lane axis of the (P, WSPAN)
correlation patch, where a per-offset iota mask (corr_pallas's trick)
extracts column j = sub-offset + j without any unaligned slicing.

fmap2 levels are zero-padded by PAD = 2r+3 and coords clamped as in
``corr_pallas`` — every window DMA is in-bounds and far-out-of-range
queries read zeros (grid_sample padding_mode='zeros' semantics).

Training: the reference's alt path is inference-only (its CUDA backward is
never reachable from Python — ``core/corr.py:86`` calls ``.forward``
directly; SURVEY.md §2 caveat a). Ours IS differentiable: a custom VJP
delegates the backward to the XLA formulation (``models.corr
.alt_corr_lookup``), which is algebraically identical, so training with
``alternate_corr=True`` works without a hand-written scatter kernel.
"""

from __future__ import annotations

import functools
import math
from typing import Sequence

import jax
import jax.numpy as jnp

try:  # pallas import is gated so CPU-only installs still work
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_OK = True
except Exception:  # pragma: no cover
    _PALLAS_OK = False

from raft_tpu.kernels.corr_pallas import (_fallback_interpret, _pad,  # noqa: F401
                                          pallas_available)

# interpret mode runs the kernel in pure XLA — forced by CPU tests via
# monkeypatch; off-TPU backends fall back automatically (see
# corr_pallas._interpret for why)
_INTERPRET = False


def _interpret() -> bool:
    return _INTERPRET or _fallback_interpret()

_NBUF = 8    # window-DMA ring depth; each transfer is ~(2r+2)·WSPAN·C·4 B
_QTILE = 128  # queries per grid step


def _wspan(P: int) -> int:
    """8-aligned W extent covering a P-wide window at any sub-offset < 8."""
    return -(-(P + 7) // 8) * 8


def _wextra(radius: int) -> int:
    """Extra right-W zeros pad_f2_pyramid adds beyond the 2·PAD halo so the
    widened `_wspan` DMA stays in-bounds. Every site that pads, unpads, or
    recovers the true level width from a padded buffer MUST use this one
    expression — the three are coupled."""
    P = 2 * radius + 2
    return _wspan(P) - P


def _alt_kernel(base_ref, wy_ref, wx_ref, f1_ref, f2_ref, out_ref,
                ring, sems, win_ref, *, Q: int, K: int):
    """One grid step: Q queries of one batch element.

    base_ref: SMEM (1, Q, 3) i32 — x0a/8 (the 8-aligned W start divided by
             8; the kernel multiplies back so Mosaic can prove tile
             alignment), H start y0, and the sub-offset off = x0 - x0a
    wy/wx_ref: VMEM (1, Q, 1, 1) f32 — shared bilinear fracs
    f1_ref:  VMEM (1, Q, C) f32 — query feature rows
    f2_ref:  ANY (B, Hp, Wp, C) f32 — padded fmap2 levels, resident in HBM.
             Passed WHOLE (trivial index map): Mosaic only lowers
             ANY-space operands unblocked, so the batch index comes from
             ``program_id`` inside the DMA slice instead of a BlockSpec.
    out_ref: VMEM (1, Q, K, K) f32 — [y, x] window (x-major swap outside)
    ring:    VMEM scratch (_NBUF, P, WSPAN, C) DMA ring; sems: DMA sems
    win_ref: VMEM scratch (Q, P, P)
    """
    P = K + 1
    WSPAN = _wspan(P)
    b = pl.program_id(0)

    def window_copy(q, slot):
        # base_ref stores x0a/8: multiplying by 8 HERE is how Mosaic can
        # PROVE the W slice start is tile-aligned — a runtime SMEM value
        # alone fails its divisibility check ("Failed to prove that a tile
        # index in dimension 2 is divisible by the tiling (8)", on-chip
        # session C) even though the host computed it as (x0//8)*8.
        x0a = base_ref[0, q, 0] * 8
        y0 = base_ref[0, q, 1]
        return pltpu.make_async_copy(
            f2_ref.at[b, pl.ds(y0, P), pl.ds(x0a, WSPAN), :],
            ring.at[slot],
            sems.at[slot],
        )

    for q0 in range(min(_NBUF - 1, Q)):
        window_copy(q0, q0 % _NBUF).start()

    def body(q, _):
        slot = jax.lax.rem(q, _NBUF)
        nxt = q + _NBUF - 1

        @pl.when(nxt < Q)
        def _():
            window_copy(nxt, jax.lax.rem(nxt, _NBUF)).start()

        window_copy(q, slot).wait()
        f2win = ring[slot]                       # (P, WSPAN, C)
        f1q = f1_ref[0, q, :]                    # (C,) on lanes
        patch = jnp.sum(f2win * f1q, axis=-1)    # lane reduce -> (P, WSPAN)
        # select the true P window columns at the sub-offset: after the C
        # reduction W is the lane axis, so an iota mask per column offset
        # replaces the unaligned slice the DMA couldn't do
        off = base_ref[0, q, 2]
        iw = jax.lax.broadcasted_iota(jnp.int32, (P, WSPAN), 1)
        for j in range(P):
            col = jnp.sum(jnp.where(iw == off + j, patch, 0.0),
                          axis=1, keepdims=True)      # (P, 1)
            win_ref[q, :, j:j + 1] = col
        return 0

    jax.lax.fori_loop(0, Q, body, 0, unroll=False)

    win = win_ref[:]                             # (Q, P, P) [y, x]
    wy = wy_ref[0]                               # (Q, 1, 1)
    wx = wx_ref[0]
    wl = (1.0 - wy) * win[:, :K, :] + wy * win[:, 1:, :]
    out_ref[0] = (1.0 - wx) * wl[:, :, :K] + wx * wl[:, :, 1:]


def pad_f2_pyramid(f2_pyramid: Sequence[jax.Array], radius: int):
    """Zero-pad each (B, Hl, Wl, C) level's spatial dims by the margin.

    W gets ``_wspan`` extra zeros on the right so the kernel's 8-aligned,
    widened window DMA stays in bounds for the rightmost queries.
    Do this once per forward pass, outside the scanned refinement loop.
    """
    PAD = _pad(radius)
    extra = _wextra(radius)  # DMA-end bound: x0a + WSPAN <= Wl + 2*PAD + extra
    return tuple(
        jnp.pad(f2, ((0, 0), (PAD, PAD), (PAD, PAD + extra), (0, 0)))
        for f2 in f2_pyramid)


def _prep_coords(Hl, Wl, x, y, radius):
    PAD = _pad(radius)
    x = jnp.clip(x, -(radius + 2.0), Wl + radius + 1.0)
    y = jnp.clip(y, -(radius + 2.0), Hl + radius + 1.0)
    xf = jnp.floor(x)
    yf = jnp.floor(y)
    B, N = x.shape
    x0 = xf.astype(jnp.int32) - radius + PAD
    x0a = (x0 // 8) * 8                          # 8-aligned DMA start
    # stored as x0a/8 (kernel multiplies back) so Mosaic can prove the
    # slice start divisible by the (8,128) tile — see window_copy
    base = jnp.stack(
        [x0a // 8, yf.astype(jnp.int32) - radius + PAD, x0 - x0a],
        axis=-1)                                 # (B, N, 3)
    wy = (y - yf).astype(jnp.float32).reshape(B, N, 1, 1)
    wx = (x - xf).astype(jnp.float32).reshape(B, N, 1, 1)
    return base, wy, wx


def _level_alt_pallas(f1: jax.Array, f2_p: jax.Array, x: jax.Array,
                      y: jax.Array, radius: int) -> jax.Array:
    """f1 (B, N, C) + padded f2 (B, Hp, Wp, C) + coords -> (B, N, K²)."""
    B, N, C = f1.shape
    _, Hp, Wp, _ = f2_p.shape
    K = 2 * radius + 1
    PAD = _pad(radius)
    # Wp carries pad_f2_pyramid's `_wextra` right-margin zeros on top of
    # the 2·PAD halo; subtract BOTH to recover the true level width, else
    # the x clamp admits coords whose 8-aligned window DMA (x0a + WSPAN)
    # runs past the padded buffer — an OOB HBM read on real Mosaic DMAs
    # (XLA interpret mode hides it by clamping dynamic_slice).
    base, wy, wx = _prep_coords(
        Hp - 2 * PAD, Wp - 2 * PAD - _wextra(radius), x, y, radius)

    n_pad = (-N) % _QTILE
    if n_pad:
        pads = ((0, 0), (0, n_pad))
        f1 = jnp.pad(f1, pads + ((0, 0),))
        base = jnp.pad(base, pads + ((0, 0),))
        wy = jnp.pad(wy, pads + ((0, 0), (0, 0)))
        wx = jnp.pad(wx, pads + ((0, 0), (0, 0)))
    Np = N + n_pad

    kernel = functools.partial(_alt_kernel, Q=_QTILE, K=K)
    scalar = pl.BlockSpec((1, _QTILE, 1, 1), lambda b, t: (b, t, 0, 0))
    out = pl.pallas_call(
        kernel,
        grid=(B, Np // _QTILE),
        in_specs=[
            pl.BlockSpec((1, _QTILE, 3), lambda b, t: (b, t, 0),
                         memory_space=pltpu.SMEM),
            scalar,
            scalar,
            pl.BlockSpec((1, _QTILE, C), lambda b, t: (b, t, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, _QTILE, K, K), lambda b, t: (b, t, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, Np, K, K), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((_NBUF, K + 1, _wspan(K + 1), C), jnp.float32),
            pltpu.SemaphoreType.DMA((_NBUF,)),
            pltpu.VMEM((_QTILE, K + 1, K + 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(base, wy, wx, f1.astype(jnp.float32), f2_p.astype(jnp.float32))
    # [y, x] window -> x-major flat channels (models.corr layout contract)
    return jnp.swapaxes(out[:, :N], -1, -2).reshape(B, N, K * K)


def _alt_fwd_impl(fmap1, f2_pyramid_p, x, y, radius: int):
    B, N, C = fmap1.shape
    outs = [
        _level_alt_pallas(fmap1, f2_p, x / (2 ** i), y / (2 ** i), radius)
        for i, f2_p in enumerate(f2_pyramid_p)]
    return jnp.concatenate(outs, axis=-1) / math.sqrt(C)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _alt_lookup(fmap1, f2_pyramid_p, x, y, radius: int):
    return _alt_fwd_impl(fmap1, f2_pyramid_p, x, y, radius)


def _alt_fwd(fmap1, f2_pyramid_p, x, y, radius: int):
    return (_alt_fwd_impl(fmap1, f2_pyramid_p, x, y, radius),
            (fmap1, f2_pyramid_p, x, y))


def _alt_bwd(radius, res, g):
    """Backward via the XLA formulation — algebraically identical math
    (models.corr.alt_corr_lookup), so the adjoint is exact; no scatter
    kernel needed (the reference's CUDA backward is dead code anyway)."""
    from raft_tpu.models.corr import alt_corr_lookup

    fmap1, f2_pyramid_p, x, y = res
    B, N, C = fmap1.shape
    PAD = _pad(radius)
    extra = _wextra(radius)  # pad_f2_pyramid's extra right-W margin

    def xla_fwd(f1, f2s, xq, yq):
        # alt_corr_lookup takes (B,H,W,C) fmap1 and unpadded f2 pyramid +
        # (B,H,W,2) coords; rebuild those shapes from the flat layout
        f2_unpadded = [f2[:, PAD:-PAD, PAD:-(PAD + extra), :] for f2 in f2s]
        coords = jnp.stack([xq, yq], axis=-1).reshape(B, 1, N, 2)
        out = alt_corr_lookup(
            f1.reshape(B, 1, N, C), f2_unpadded, coords, radius)
        return out.reshape(B, N, -1)

    _, vjp = jax.vjp(xla_fwd, fmap1, tuple(f2_pyramid_p), x, y)
    return vjp(g)


_alt_lookup.defvjp(_alt_fwd, _alt_bwd)


def alt_corr_lookup_pallas(fmap1: jax.Array,
                           f2_pyramid: Sequence[jax.Array],
                           coords: jax.Array, radius: int,
                           prepadded: bool = False) -> jax.Array:
    """Drop-in for ``models.corr.alt_corr_lookup`` backed by Pallas.

    fmap1 (B, H, W, C); f2_pyramid: (B, Hl, Wl, C) levels — or the output
    of :func:`pad_f2_pyramid` when ``prepadded=True`` (pass that from
    outside the refinement loop). coords (B, H, W, 2).
    Returns (B, H, W, levels·K²) fp32.
    """
    B, H, W, C = fmap1.shape
    N = H * W
    f1 = fmap1.astype(jnp.float32).reshape(B, N, C)
    x = coords[..., 0].reshape(B, N).astype(jnp.float32)
    y = coords[..., 1].reshape(B, N).astype(jnp.float32)
    f2p = (tuple(f2_pyramid) if prepadded
           else pad_f2_pyramid(f2_pyramid, radius))
    out = _alt_lookup(f1, f2p, x, y, radius)
    return out.reshape(B, H, W, -1)
