"""Pallas TPU kernels — the ``alt_cuda_corr`` extension's successor.

The reference's one native component is a CUDA correlation kernel
(alt_cuda_corr/correlation_kernel.cu). Its TPU equivalents live here as
Pallas kernels; selection between XLA paths and Pallas is a config knob
(``RAFTConfig.corr_impl``) benchmarked by ``raft_tpu.cli.corr_bench``.
"""

from raft_tpu.kernels.corr_alt_pallas import (alt_corr_lookup_pallas,
                                              pad_f2_pyramid)
from raft_tpu.kernels.corr_pallas import (corr_lookup_pallas, pad_pyramid,
                                          pallas_available)
from raft_tpu.kernels.corr_ragged_pallas import (RaggedDescriptor,
                                                 build_corr_pyramid_ragged,
                                                 corr_lookup_ragged,
                                                 make_descriptor,
                                                 mask_features)
from raft_tpu.kernels.gru_pallas import (gru_blend, gru_cell_lane_major,
                                         gru_gates)

__all__ = ["RaggedDescriptor", "alt_corr_lookup_pallas",
           "build_corr_pyramid_ragged", "corr_lookup_pallas",
           "corr_lookup_ragged", "gru_blend", "gru_cell_lane_major",
           "gru_gates", "make_descriptor", "mask_features",
           "pad_f2_pyramid", "pad_pyramid", "pallas_available"]
