"""Ragged-batch correlation: one program for mixed spatial shapes.

Sibling of ``corr_pallas.py`` for the ragged serving path (the TPU
lesson of *Ragged Paged Attention*, arXiv 2604.15464: ONE compiled
program walks a per-row batch descriptor instead of compiling per
shape). A ragged micro-batch packs requests of DIFFERENT ``(h, w)``
into one ``(B, Hcap, Wcap)`` capacity box; each row's descriptor says
how much of the box is real. The PR-2 lane-major ``(B, H·W, C)``
layout already made the correlation hot loops shape-agnostic in H·W —
this module adds the one missing piece, the per-row validity mask, and
the key observation that makes the LOOKUP kernels ragged for free:

**Self-masking.** Every lookup backend in ``models/corr.py`` (and the
Mosaic kernel in ``corr_pallas.py``) implements grid_sample's
``padding_mode='zeros'``: window taps outside the volume read zeros.
So once the per-row feature tails are zeroed (``mask_features``), the
correlation volume of row *i* is EXACTLY the row's own
``(h_i/8, w_i/8)`` volume zero-padded to the capacity box, and any
window that drifts past the row's valid extent reads the same zeros an
out-of-bounds tap would have read on the row's own volume. No new
gather kernel is needed — the ragged path rides the SAME measured
kernels (onehot/softsel/pallas, each with its own interpret-mode CPU
fallback), which is why this file carries masks and descriptors, not a
second Mosaic lookup. ``tests/test_ragged.py`` pins the equivalence
bitwise at pyramid level 0 (and across all levels at pool-aligned
extents).

Masked-tail semantics, precisely:

- target pixels past a row's valid extent contribute NOTHING to any
  query's window (their correlation entries are exactly 0.0);
- query pixels past the valid extent produce garbage rows that the
  serving layer crops away (they never ship to a caller);
- a full-extent row's mask is the identity (``jnp.where`` on an
  all-true mask returns the operand's exact bits), so a request whose
  padded shape equals the capacity box is BITWISE the bucketed path —
  the oracle pin the serving tests hold the ragged engine to.

The descriptor also carries the flat-view bookkeeping the ISSUE's
``(B, HW_cap, C)`` form names (``hw_offset``/``valid_len``): row *i*
of the flattened buffer starts at ``i * Hcap * Wcap`` and its first
``h8_i * Wcap`` lanes hold the row-major valid plane — the occupancy
accounting the scheduler's capacity-fill gauge reports.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp


class RaggedDescriptor(NamedTuple):
    """Per-row validity of one ragged micro-batch at 1/8 feature
    resolution, inside a shared ``(Hcap/8, Wcap/8)`` capacity box.

    ``h8``/``w8``: (B,) int32 valid extents (``hp_i/8``, ``wp_i/8`` of
    the row's ÷8-padded request; 0 for batch-fill rows, which masks the
    whole row — padded rows contribute nothing).
    ``hw_offset``/``valid_len``: (B,) int32 flat-view bookkeeping —
    where row *i* starts in the flattened ``(B·HW_cap,)`` lane order
    and how many of its ``HW_cap`` entries are real.
    """

    h8: jax.Array
    w8: jax.Array
    hw_offset: jax.Array
    valid_len: jax.Array


def make_descriptor(shapes8: Sequence[Tuple[int, int]],
                    cap_hw8: Tuple[int, int],
                    batch: int) -> RaggedDescriptor:
    """Build the descriptor for ``len(shapes8)`` real rows padded to
    ``batch`` total rows of a ``cap_hw8 = (Hcap/8, Wcap/8)`` box.

    ``shapes8``: per-row valid (h8, w8); every extent must fit the box
    (raising here beats an out-of-range mask silently zeroing a real
    request's features).
    """
    ch, cw = cap_hw8
    if len(shapes8) > batch:
        raise ValueError(f"{len(shapes8)} rows > batch {batch}")
    h8 = [0] * batch
    w8 = [0] * batch
    for i, (h, w) in enumerate(shapes8):
        if h > ch or w > cw:
            raise ValueError(
                f"row {i} extent ({h}, {w}) exceeds the capacity box "
                f"({ch}, {cw})")
        h8[i], w8[i] = int(h), int(w)
    hw = ch * cw
    return RaggedDescriptor(
        h8=jnp.asarray(h8, jnp.int32),
        w8=jnp.asarray(w8, jnp.int32),
        hw_offset=jnp.asarray([i * hw for i in range(batch)], jnp.int32),
        valid_len=jnp.asarray([h8[i] * cw for i in range(batch)],
                              jnp.int32))


def mask_features(fmap: jax.Array, valid_h: jax.Array,
                  valid_w: jax.Array) -> jax.Array:
    """Zero a (B, H, W, C) feature map past each row's valid extent.

    ``valid_h``/``valid_w``: (B,) int32. Pure vectorized select against
    broadcasted iotas — shape-agnostic in (H, W), lane-clean in C, and
    cheap enough that XLA fuses it into the producing conv's epilogue
    (no Mosaic kernel warranted; measured as noise next to the
    all-pairs GEMM it feeds). The select is EXACT: an all-true mask
    returns the operand's bits unchanged — the identity the full-extent
    bitwise parity pin rests on.
    """
    B, H, W, _ = fmap.shape
    ih = jax.lax.broadcasted_iota(jnp.int32, (B, H, W), 1)
    iw = jax.lax.broadcasted_iota(jnp.int32, (B, H, W), 2)
    valid = ((ih < valid_h[:, None, None])
             & (iw < valid_w[:, None, None]))
    return jnp.where(valid[..., None], fmap, jnp.zeros((), fmap.dtype))


def build_corr_pyramid_ragged(fmap1: jax.Array, fmap2: jax.Array,
                              valid_h: jax.Array, valid_w: jax.Array,
                              num_levels: int = 4):
    """Masked all-pairs pyramid: each row's volume is its own smaller
    volume zero-embedded in the capacity box.

    Masking BOTH maps makes tail targets contribute exact zeros to
    every window (fmap2) and tail queries produce zero rows (fmap1 —
    cropped by the serving layer either way). Pyramid levels pool the
    box; a row's valid extent at level l is its extent/2^l, and pooled
    cells straddling the valid boundary average real values against
    zeros — the zero-padding semantics of the row's own volume embedded
    in the box (exactly the plain pyramid's behavior at ITS boundary).
    """
    from raft_tpu.models.corr import build_corr_pyramid

    return build_corr_pyramid(mask_features(fmap1, valid_h, valid_w),
                              mask_features(fmap2, valid_h, valid_w),
                              num_levels)


def corr_lookup_ragged(pyramid, coords: jax.Array, radius: int,
                       impl: str = "gather") -> jax.Array:
    """Window lookup over a MASKED pyramid — ragged by self-masking.

    Every backend already implements zeros-outside-the-volume, and the
    masked volume is zero outside each row's valid extent, so the plain
    lookups ARE the ragged lookups: a window drifting past a row's
    boundary reads the same zeros in the capacity box that it would
    have read out-of-bounds on the row's own volume.
    ``impl='pallas'`` routes through the Mosaic kernel
    (``corr_pallas``), inheriting its interpret-mode CPU fallback; the
    XLA backends need no fallback at all.
    """
    if impl == "pallas":
        from raft_tpu.kernels.corr_pallas import corr_lookup_pallas

        return corr_lookup_pallas(pyramid, coords, radius)
    from raft_tpu.models.corr import (corr_lookup, corr_lookup_onehot,
                                      corr_lookup_softsel)

    fn = {"gather": corr_lookup, "onehot": corr_lookup_onehot,
          "softsel": corr_lookup_softsel}[impl]
    return fn(pyramid, coords, radius)
